"""Block-pool allocator for the paged KV cache.

The decode engine's original cache gave every slot a contiguous
``[T, D]`` strip sized for the worst case ``max_prompt + max_new`` — a
short sequence wasted almost its whole strip, so concurrency was capped
by slot geometry rather than by actual KV bytes. The paged layout
(vLLM/PagedAttention) carves the same memory into fixed-size **blocks**
of ``block_size`` token positions each; a sequence owns
``ceil((prompt_len + max_new) / block_size)`` blocks, recorded in a
per-slot **block table** the jitted programs consume as traced data.

This module is the host-side half: a free-list allocator over block ids.
Device memory itself lives in the engine (``[L, n_blocks + 1,
block_size, D]`` pools); the allocator only hands out integer ids and
keeps the books honest:

* block id ``0`` is the reserved **scratch block** — never allocated.
  Block tables pad with it (the sentinel), dead decode lanes park their
  K/V writes in it, and pad-position scatter garbage lands in it, so
  every write in the jitted programs has a defined, in-bounds target
  that no live attention mask ever reads.
* ``alloc``/``free`` are guarded: allocating past the free list or
  freeing an id that is not live raises — a leak or double-allocation
  is a bug in the engine's admission/completion bookkeeping, not a
  condition to limp through (the property test churns this).
* occupancy is observable: ``KV_BLOCKS_FREE[name]``/
  ``KV_BLOCKS_LIVE[name]`` gauges and ``BLOCK_ALLOC[name]``/
  ``BLOCK_FREE[name]`` counters land in the Dashboard next to the
  engine's slot metrics (docs/OBSERVABILITY.md).

Capacity math lives here too (:func:`kv_bytes_per_block`,
:func:`blocks_for_bytes`): the ``-kv_pool_blocks`` flag sizes the pool
in blocks, and the bench's equal-KV-bytes A/B converts a bytes budget
into the equivalent block count.
"""

from __future__ import annotations

import threading
from ..analysis import lockwatch
from typing import Iterable, List, Optional

import numpy as np

from ..dashboard import Dashboard

# block id 0: reserved scratch — the block-table pad sentinel and the
# parking target for dead-lane / pad-position writes. Never allocated.
SCRATCH_BLOCK = 0


def kv_bytes_per_block(n_layers: int, d_model: int, block_size: int,
                       dtype=np.float32) -> int:
    """Device bytes one block costs across BOTH pools (K and V)."""
    return 2 * n_layers * block_size * d_model * np.dtype(dtype).itemsize


def blocks_for_bytes(budget_bytes: int, n_layers: int, d_model: int,
                     block_size: int, dtype=np.float32) -> int:
    """Usable blocks a KV-bytes budget buys (scratch block excluded:
    its bytes ride along, but it holds no sequence).

    Raises for a budget too small for scratch + one usable block: the
    result feeds ``kv_pool_blocks``, where ``0`` means AUTO-size — a
    silent 0 here would turn "tiny budget" into "contiguous-equivalent
    pool", a many-fold device-memory overshoot."""
    per = kv_bytes_per_block(n_layers, d_model, block_size, dtype)
    n = budget_bytes // per - 1
    if n < 1:
        raise ValueError(
            f"KV budget {budget_bytes} B buys no usable block: need >= "
            f"{2 * per} B (scratch + 1 block of {per} B at block_size "
            f"{block_size})")
    return int(n)


class BlockPool:
    """Free-list allocator over ``n_blocks`` usable KV-cache blocks.

    Block ids run ``1 .. n_blocks`` (id 0 is the scratch block). The
    engine allocates a sequence's whole reservation up front at
    admission (``prompt + max_new`` worth of positions) and frees it at
    eos/completion, so pool occupancy — not slot geometry — is what
    bounds concurrency.
    """

    def __init__(self, n_blocks: int, block_size: int,
                 name: str = "") -> None:
        if n_blocks < 1:
            raise ValueError(f"BlockPool needs >= 1 usable block, "
                             f"got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.capacity = int(n_blocks)
        self.block_size = int(block_size)
        self._free: List[int] = list(range(n_blocks, 0, -1))  # pop() -> 1 first
        self._live: set = set()
        self._lock = lockwatch.lock("serving.BlockPool._lock")
        self.allocs = 0                # blocks handed out (monotonic)
        self.frees = 0                 # blocks returned (monotonic)
        label = name or "pool"
        self.free_gauge = Dashboard.get_or_create_gauge(
            f"KV_BLOCKS_FREE[{label}]")
        self.live_gauge = Dashboard.get_or_create_gauge(
            f"KV_BLOCKS_LIVE[{label}]")
        self.alloc_counter = Dashboard.get_or_create_counter(
            f"BLOCK_ALLOC[{label}]")
        self.free_counter = Dashboard.get_or_create_counter(
            f"BLOCK_FREE[{label}]")
        self.free_gauge.set(float(n_blocks))
        self.live_gauge.set(0.0)

    # -- sizing -------------------------------------------------------------
    def blocks_needed(self, n_tokens: int) -> int:
        """Blocks covering ``n_tokens`` cache positions."""
        return -(-int(n_tokens) // self.block_size)

    def covers(self, n_tokens: int) -> bool:
        """Whether the pool could EVER hold ``n_tokens`` positions
        (capacity check — the submit-time shed gate)."""
        return self.blocks_needed(n_tokens) <= self.capacity

    # -- allocation ---------------------------------------------------------
    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_live(self) -> int:
        with self._lock:
            return len(self._live)

    def can_alloc(self, n: int) -> bool:
        with self._lock:
            return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Hand out ``n`` block ids; raises if the free list is short
        (callers gate on :meth:`can_alloc` — running dry mid-admission
        is an accounting bug, not an overload condition)."""
        with self._lock:
            if n > len(self._free):
                raise RuntimeError(
                    f"BlockPool: alloc({n}) with only {len(self._free)} "
                    f"free of {self.capacity}")
            blocks = [self._free.pop() for _ in range(n)]
            self._live.update(blocks)
            self.allocs += n
            self._update_gauges_locked()
        self.alloc_counter.inc(n)
        return blocks

    def free(self, blocks: Iterable[int]) -> None:
        """Return blocks to the pool; double-free or foreign ids raise."""
        blocks = list(blocks)
        with self._lock:
            for b in blocks:
                if b not in self._live:
                    raise RuntimeError(
                        f"BlockPool: freeing block {b} that is not live "
                        f"(double-free or foreign id)")
                self._live.discard(b)
                self._free.append(b)
            self.frees += len(blocks)
            self._update_gauges_locked()
        self.free_counter.inc(len(blocks))

    def _update_gauges_locked(self) -> None:
        self.free_gauge.set(float(len(self._free)))
        self.live_gauge.set(float(len(self._live)))

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "block_size": self.block_size,
                "free": len(self._free),
                "live": len(self._live),
                "allocs": self.allocs,
                "frees": self.frees,
            }

    def drift(self) -> Optional[str]:
        """Invariant scan -> violation description, or None when the
        books balance. The watchdog's poll entry point: unlike
        :meth:`check` it never raises (and never depends on ``assert``
        surviving ``-O``), so a corrupted pool yields a diagnosis
        instead of an exception inside the health thread."""
        with self._lock:
            free = set(self._free)
            if len(free) != len(self._free):
                return (f"duplicate ids in free list "
                        f"({len(self._free)} entries, {len(free)} unique)")
            both = free & self._live
            if both:
                return f"{len(both)} id(s) both free and live: {sorted(both)[:8]}"
            if len(free) + len(self._live) != self.capacity:
                return (f"leak: {len(free)} free + {len(self._live)} live "
                        f"!= capacity {self.capacity}")
            if SCRATCH_BLOCK in free or SCRATCH_BLOCK in self._live:
                return "scratch block entered circulation"
        return None

    def check(self) -> None:
        """Invariant check (tests): free + live == capacity, disjoint.
        Raises ``AssertionError`` on the first violation."""
        msg = self.drift()
        if msg is not None:
            raise AssertionError(f"BlockPool: {msg}")
