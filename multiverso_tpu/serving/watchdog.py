"""Stall/leak watchdog: the engine must produce evidence, not silence.

Everything observability built so far is *passive* — spans, counters and
the flight recorder wait for someone to look. The watchdog is the first
component that looks on its own: a daemon thread polling one engine's
public health surface (``engine.health()`` / ``engine.pool_drift()`` —
never private loop state) and tripping when the engine has stopped
behaving like an engine:

* **stall** — no iteration progress (``last_iter_age_s``) for longer
  than ``stall_s`` while sequences are live (slots occupied or an
  admission mid-prefill). A healthy engine with live work iterates
  every few milliseconds; a frozen one means a wedged device call, a
  deadlocked loop, or a blocked host sync.
* **queue-age breach** — the oldest queued request has waited past
  ``queue_age_s`` (0 disables). Distinct from stall: the loop may be
  iterating happily while admission starves.
* **block-pool drift** — the paged-KV allocator's books stopped
  balancing (``BlockPool.drift()``: double-frees, leaks, scratch-block
  circulation, refcount/content-index skew) or live blocks exist with
  zero live sequences. Refcounted prefix sharing is NOT drift: a
  shared block counts as live exactly once however many sequences
  hold it, and refcount-0 cached blocks sit in the pool's cached tier
  — outside ``n_live`` — awaiting reuse or eviction, so a drained
  engine with a warm prefix cache reads clean. Sampled racily against
  the running loop, so a drift verdict must hold for two consecutive
  polls before it trips (a mid-admission snapshot is not a leak).
* **lock-order violation** — the runtime lock-order witness
  (:mod:`~multiverso_tpu.analysis.lockwatch`, ``-lockwatch``) recorded
  a new acquisition-order cycle anywhere in the process: two threads
  disagree about lock order, a deadlock waiting for the right
  interleaving. Unlike the health checks this is level-independent —
  every NEW violation since the last poll trips once (the condition
  never "clears": a cycle certificate is permanent evidence).

On trip: a diagnostic bundle — flight-recorder ring, ``engine.stats()``,
``Dashboard.snapshot()``, and every thread's stack via
``sys._current_frames()`` — is written under ``dump_dir`` (when set),
the ``WATCHDOG_TRIPS[<engine>]`` counter increments, and the
``on_trip(reason, bundle_dir)`` callback fires (test-visible; a fleet
router's health probe in the ROADMAP's multi-replica future). Each
trigger kind trips once per episode: it re-arms only after the
condition clears, so a wedged engine produces one bundle, not one per
poll — and a condition *flapping* around its threshold (each
clear/re-breach cycle is a new episode) is bounded too: bundle writes
stop at ``max_bundles`` and the trip list keeps only the newest 64
entries, while the counter and ``on_trip`` keep reporting.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Deque, List, Optional, Tuple

from ..analysis import lockwatch
from ..dashboard import Dashboard
from ..log import Log


def thread_stacks() -> str:
    """Every live thread's current stack, formatted — the part of a
    hang report you cannot reconstruct after the process is dead."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts: List[str] = []
    for ident, frame in sorted(sys._current_frames().items()):
        parts.append(f"--- thread {names.get(ident, '?')} (ident {ident}) "
                     f"---")
        parts.append("".join(traceback.format_stack(frame)))
    return "\n".join(parts)


@dataclass
class WatchdogConfig:
    interval_s: float = 0.25     # poll period (trip latency <= ~2 polls)
    stall_s: float = 10.0        # no-progress deadline while work is live
    queue_age_s: float = 30.0    # oldest-queued-request limit; 0 disables
    dump_dir: str = ""           # bundle target; "" = count + log only
    # bundle-write ceiling per watchdog: a condition FLAPPING around its
    # threshold re-trips every clear/re-breach cycle, and each bundle is
    # a full ring + snapshot + stacks — without a cap, the degraded
    # replica being diagnosed fills its own disk. Past the cap, trips
    # still count, log, and fire on_trip.
    max_bundles: int = 16
    on_trip: Optional[Callable[[str, Optional[str]], None]] = None


class EngineWatchdog:
    """One engine's self-diagnosis thread (daemon; ``engine.stop()`` and
    ``Dashboard.reset()`` both retire it)."""

    def __init__(self, engine: Any, config: Optional[WatchdogConfig] = None,
                 start: bool = True) -> None:
        self.engine = engine
        self.config = config or WatchdogConfig()
        self.trip_counter = Dashboard.get_or_create_counter(
            f"WATCHDOG_TRIPS[{engine.name}]")
        self.on_trip = self.config.on_trip
        # (kind, reason, bundle_dir) per trip, oldest first (test
        # surface); bounded so a flapping condition in a long-lived
        # process cannot grow it without limit — trip_count keeps the
        # true total
        self.trips: Deque[Tuple[str, str, Optional[str]]] = (
            collections.deque(maxlen=64))
        # sequence-stamped twin of `trips` for the fleet plane's
        # exactly-once forwarding (`trips_since`); same bound
        self._trip_log: Deque[Tuple[int, str, str, Optional[str]]] = (
            collections.deque(maxlen=64))
        self._trips_total = 0
        self.bundles = 0
        self.checks = 0
        self._armed = {"stall": True, "queue_age": True, "pool_drift": True}
        self._drift_streak = 0
        # violations that predate this watchdog are another component's
        # story — only NEW cycles observed on our polls trip
        self._lock_order_seen = lockwatch.violation_count()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    @property
    def trip_count(self) -> int:
        return self._trips_total

    def trips_since(self, cursor: int):
        """``(new_cursor, trips newer than cursor)`` — the fleet plane's
        incremental read: the per-node ``ObsAgent`` forwards every trip
        to the ``ObsCollector`` exactly once by passing back the cursor
        a previous call returned (start at 0). Trips come back oldest
        first as ``(kind, reason, bundle_dir)`` tuples. Each trip is
        sequence-stamped AT APPEND (``_trip_log``), so the read never
        double-reports a trip that lands mid-call; the log keeps only
        the newest 64 — a cursor further back than that gets the
        retained suffix while ``trip_count`` carries the true total."""
        log: List[Tuple[int, str, str, Optional[str]]] = []
        for _ in range(8):
            try:
                log = list(self._trip_log)
                break
            except RuntimeError:
                # the watchdog thread appended mid-copy (deque iterators
                # detect concurrent mutation); retry — the next copy
                # simply includes the new trip
                continue
        new = [(k, r, b) for seq, k, r, b in log if seq > cursor]
        if new:
            cursor = log[-1][0]      # same copy the filter saw
        return cursor, new

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "EngineWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"mv-watchdog-{self.engine.name}",
            daemon=True)
        self._thread.start()
        Dashboard.attach_reporter(self)
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
            self._thread = None
        Dashboard.detach_reporter(self)

    def detach(self) -> None:
        """``Dashboard.reset()`` hook."""
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.check_once()
            except Exception as exc:    # pragma: no cover - defensive
                Log.error("watchdog[%s]: health check failed: %s",
                          self.engine.name, exc)

    # -- the checks ---------------------------------------------------------
    def check_once(self) -> List[str]:
        """One health evaluation (also the tests' direct entry point).
        Returns the reasons that NEWLY tripped this check (empty when
        healthy or already tripped for the same episode)."""
        self.checks += 1
        health = self.engine.health()
        fired: List[str] = []
        if health.get("stopped"):
            # a retired engine is not a stalled one; re-arm everything
            for kind in self._armed:
                self._armed[kind] = True
            self._drift_streak = 0
            return fired

        live = health.get("live_seqs", 0)
        age = health.get("last_iter_age_s", 0.0)
        stalled = live > 0 and age > self.config.stall_s
        reason = (f"engine stall: no iteration progress for {age:.2f}s "
                  f"with {live} live sequence(s) "
                  f"(deadline {self.config.stall_s:g}s, iteration "
                  f"{health.get('iters_total', 0)})")
        self._gate("stall", stalled, reason, fired)

        q_age = health.get("queue_age_s", 0.0)
        breach = 0 < self.config.queue_age_s < q_age
        reason = (f"queue-age breach: oldest queued request has waited "
                  f"{q_age:.2f}s (limit {self.config.queue_age_s:g}s, "
                  f"depth {health.get('queue_depth', 0)})")
        self._gate("queue_age", breach, reason, fired)

        drift = self.engine.pool_drift()
        # any drift verdict held for two consecutive polls trips — the
        # VERDICT persists, not the exact message (its embedded free/live
        # counts fluctuate under traffic); only a verdict that clears
        # between polls is an admission race
        self._drift_streak = self._drift_streak + 1 if drift is not None else 0
        self._gate("pool_drift", self._drift_streak >= 2,
                   f"block-pool drift: {drift}", fired)

        # lock-order witness: every NEW cycle since the last poll is a
        # permanent deadlock certificate, so this bypasses the edge-
        # trigger re-arm machinery — each batch of new violations is its
        # own episode. ONE consistent list copy: cursor math against a
        # separately-read count raced concurrent forget()/clear() (a
        # test's sanctioned cleanup) into empty or already-reported
        # trip batches
        vs = lockwatch.violations()
        if len(vs) < self._lock_order_seen:
            # forget()/clear() rebased the list; follow it down so the
            # next real violation isn't swallowed
            self._lock_order_seen = len(vs)
        new = vs[self._lock_order_seen:]
        self._lock_order_seen = len(vs)
        if new:
            reason = (f"lock-order violation(s): {len(new)} new cycle(s) "
                      f"— first: {new[0].describe()}")
            self._trip("lock_order", reason)
            fired.append(reason)
        return fired

    def _gate(self, kind: str, condition: bool, reason: str,
              fired: List[str]) -> None:
        """Edge-trigger per kind: trip once when the condition appears,
        re-arm when it clears."""
        if not condition:
            self._armed[kind] = True
            return
        if not self._armed[kind]:
            return
        self._armed[kind] = False
        self._trip(kind, reason)
        fired.append(reason)

    # -- the trip -----------------------------------------------------------
    def _trip(self, kind: str, reason: str) -> None:
        self._trips_total += 1
        bundle = None
        if self.config.dump_dir and self.bundles < self.config.max_bundles:
            try:
                bundle = self.dump(kind, reason)
                self.bundles += 1
                if self.bundles == self.config.max_bundles:
                    Log.error(
                        "watchdog[%s]: bundle cap reached (%d) — further "
                        "trips count and log without dumping",
                        self.engine.name, self.config.max_bundles)
            except Exception as exc:    # pragma: no cover - disk trouble
                Log.error("watchdog[%s]: bundle dump failed: %s",
                          self.engine.name, exc)
        self.trip_counter.inc()
        self.trips.append((kind, reason, bundle))
        self._trip_log.append((self._trips_total, kind, reason, bundle))
        Log.error("watchdog[%s] TRIPPED (%s): %s — bundle: %s",
                  self.engine.name, kind, reason,
                  bundle or "none (-debug_dump_dir unset)")
        callback = self.on_trip
        if callback is not None:
            try:
                callback(reason, bundle)
            except Exception as exc:    # pragma: no cover - defensive
                Log.error("watchdog[%s]: on_trip callback failed: %s",
                          self.engine.name, exc)

    def dump(self, kind: str, reason: str) -> str:
        """Write the diagnostic bundle; returns its directory.

        Layout: ``stats.json`` (trip metadata + ``engine.stats()``),
        ``dashboard.json`` (full instrument snapshot), ``stacks.txt``
        (every thread), ``ring.jsonl`` (flight-recorder dump, when the
        engine carries a recorder) — docs/OBSERVABILITY.md walks a read.
        """
        stamp = time.strftime("%Y%m%d-%H%M%S")
        bundle = os.path.join(
            self.config.dump_dir,
            f"watchdog-{self.engine.name}-{kind}-{stamp}-"
            f"{self._trips_total}")
        os.makedirs(bundle, exist_ok=True)
        with open(os.path.join(bundle, "stats.json"), "w") as f:
            json.dump({"engine": self.engine.name, "kind": kind,
                       "reason": reason, "ts_epoch_s": time.time(),
                       "stats": self.engine.stats()}, f, indent=2)
        with open(os.path.join(bundle, "dashboard.json"), "w") as f:
            json.dump(Dashboard.snapshot(), f, indent=2)
        with open(os.path.join(bundle, "stacks.txt"), "w") as f:
            f.write(thread_stacks())
        recorder = getattr(self.engine, "recorder", None)
        if recorder is not None:
            recorder.export_jsonl(os.path.join(bundle, "ring.jsonl"))
        return bundle
