"""Continuous-batching decode engine: slot KV cache + iteration scheduling.

The micro-batcher's ``lm_decode`` workload locks B requests together
through a full ``greedy_decode`` to ``max_new``: one long generation
holds every short one hostage, and an arriving request waits for the
whole batch to drain before it can even prefill (head-of-line blocking
at completion AND admission). This engine removes both stalls with the
Orca design — iteration-level scheduling over a persistent slotted KV
cache (the fixed-slot precursor to vLLM's PagedAttention):

* **slots** — a slot is one in-flight sequence; the set of live slots
  is an ``active`` lanes vector. With the default **paged KV cache**
  the engine owns one block pool ``[L, n_blocks + 1, block_size, D]``
  plus a host-side allocator (``serving/block_pool.py``) and per-slot
  block tables ``[S, max_blocks_per_seq]`` handed to the jitted
  programs as traced data — a sequence reserves
  ``ceil((prompt + max_new) / block_size)`` blocks at admission and
  frees them at eos/completion, so CAPACITY (KV bytes), not slot
  geometry, bounds concurrency: slots can outnumber what contiguous
  strips would fit, short sequences hold only the blocks they need,
  and a submit whose ``prompt + max_new`` can never fit the pool sheds
  with :class:`OverloadedError` (``kv_block_size=0`` restores the
  contiguous ``[L, S, T, D]`` strips — the A/B baseline). Caches are
  jit-donated so XLA updates them in place off-CPU.
* **content-addressed prefix caching** (``-prefix_cache``, default on;
  paged + chunked only) — every FULL block a prefill writes is
  registered under a hash-chained identity (``block_pool.chain_hashes``
  seeded by the pinned snapshot version); admission looks up the
  longest cached prefix of an arriving prompt, splices the matched
  blocks into the new slot's table with a refcount bump, and starts
  chunked prefill at the first uncached token. A fully cached prompt
  skips prefill entirely: its slot goes live at ``P - 1`` and the first
  token falls out of the next fused step (one copy-on-write of the last
  matched block first — writes never land in shared blocks). Completed
  sequences ``decref``; refcount-0 content-addressed blocks park in a
  cached-LRU tier that allocation pressure evicts, so shared system
  prompts/templates prefill once and multiply both effective KV
  capacity and TTFT (vLLM automatic prefix caching / SGLang
  RadixAttention). All placement still rides the block tables as traced
  data — one compiled trace per program, cache hits or not.
* **one fused step per iteration** — every iteration runs ONE jitted
  :func:`models.transformer.decode_step` over all S slots, live or
  dead. Shapes never depend on the request mix, so the step compiles
  exactly once per engine config.
* **tensor-parallel decode mesh** (``-decode_tp``, default 1) — with
  ``decode_tp > 1`` the engine owns a decode-SPECIFIC mesh over the
  first ``tp`` devices: attention heads and the MLP hidden dim shard
  Megatron-style, the paged K/V pools shard over the head slice of
  ``D``, and every serving program is built ONCE at construction with
  matched ``in/out_shardings``
  (:func:`models.transformer.make_sharded_decode_programs`) so the spmd
  partitioner runs at compile time and never in the hot loop. Snapshot
  pins reshard the params onto the mesh
  (:func:`snapshot.shard_for_decode`) instead of replicating them onto
  one device — models whose params + KV pool exceed a single device's
  memory serve by splitting over the mesh, which removes the PR 2
  single-device gate (now just the ``tp=1`` default, not a hard
  limit). Block tables / tokens / positions stay replicated
  traced-as-data, so the one-trace invariant holds per mesh, and
  outputs are token-identical to the replicated path.
* **chunked, budget-bounded admission** — an arriving prompt prefills
  in fixed-size chunks (:func:`models.transformer.prefill_chunk`, K/V
  written straight into its reserved slot), AT MOST ONE chunk per
  iteration interleaved with the fused decode step. Inter-token latency
  for in-flight generations is therefore bounded by one budget-sized
  chunk of work regardless of the arriving prompt's length (the
  Sarathi-Serve stall-free schedule), and a long prompt's TTFT
  amortizes across iterations instead of blocking the world. The chunk
  size is the ``prefill_token_budget`` config knob; its fixed shape
  adds exactly ONE compiled trace per engine config. Setting the
  budget to 0 restores **monolithic admission**: arrivals batched per
  iteration through the bucketed :func:`models.transformer.prefill` +
  fused :func:`models.transformer.cache_insert` (one synchronous
  whole-prompt prefill between decode iterations — cheapest for
  uniformly short prompts, and the A/B baseline the chunked path is
  benched against in ``tools/serving_bench.py``). Either way the first
  token falls out of the (last chunk of the) prefill, so TTFT is one
  prefill — not one full batch drain.
* **speculative decoding** (``-spec_k``, default 0 = off) — the engine
  emits up to ``spec_k + 1`` tokens per iteration: a host-side n-gram
  **prompt-lookup** drafter (Saxena; no draft model) proposes up to K
  continuation guesses per live slot from the sequence's own history
  (prompt + emitted tokens, indexed incrementally per accept), and ONE
  fused :func:`models.transformer.verify_step_paged` scores all K + 1
  positions against the paged pool in a single forward. Greedy
  verification accepts the longest drafted prefix matching the model's
  own argmax chain plus one correction token, so outputs are
  **token-identical to plain greedy decode** — speculation changes the
  schedule, never the tokens. K is fixed per engine config (the
  ``[S, K + 1]`` window is the only new static shape; drafts, valid
  counts and the accepted length are traced data), so the feature adds
  exactly ONE compiled verify trace next to the one fused step. Drafts
  clamp to the request's remaining budget, so speculative writes never
  escape the admission-time block reservation (rejected positions need
  no device rollback — the next window rewrites them before any mask
  can reach them), and a full-hit shared block is CoW'd at admission
  *before* speculation, preserving the prefix-cache one-write-site
  contract. ``spec_k=0`` is today's one-token path, bit-for-bit.
* **iteration-granular completion** — a slot frees the moment its
  sequence emits ``eos_id`` or reaches its per-request ``max_new``;
  the finished tokens resolve the caller's Future immediately and the
  slot is reusable on the next iteration.
* **overload-graceful scheduling** (``-preempt``, default on; paged +
  chunked only) — requests carry a tenant ``priority`` class and an
  optional ``deadline_s``. The queue is a set of per-priority FIFO
  lanes under a stride (weighted-fair) scheduler with bounded
  lookahead past a block-starved head, and expired-deadline requests
  are dropped at POP time (:class:`DeadlineExceededError`) before any
  prefill is burned on them. Paged admission turns OPTIMISTIC: a
  sequence reserves its PROMPT's blocks only and grows the reservation
  block-by-block at decode time; on pool exhaustion the lowest-
  priority/youngest victim is **preempted** — its blocks decref
  (tail-first, so its prefix-cache chain stays hittable), it re-enters
  the front of its lane, and on re-admission it recomputes from
  ``prompt + emitted tokens``, making the final output bit-identical
  to an un-preempted run (greedy decode is a deterministic function of
  the token prefix + pinned params, and the recompute is nearly free
  under the prefix cache). Anti-livelock: a per-request preemption
  budget (past it the request re-admits pessimistically with its full
  worst-case reservation) and a guaranteed-progress floor (the OLDEST
  live sequence is never preempted). Preemption is host-side
  scheduling only — block tables stay traced data, one compiled trace
  per program (docs/SERVING.md "Overload and preemption").

Snapshot pinning: an admission pins the engine's current params
snapshot for the whole generation. The pinned snapshot only moves when
the engine is EMPTY (no live slots), so a generation never spans two
parameter versions — concurrent ``train_batch`` calls can't tear an
in-flight sequence (the copy-on-publish guarantee extended from one
flush to one generation). The trade is surfaced, not hidden: replies
carry the pinned ``snapshot_version``/``staleness_s``, and a saturated
engine serves the admission-time version until it next drains.

Metrics: decode tokens/sec and slot occupancy land in Dashboard gauges
(``DECODE_TPS[name]``, ``SLOT_OCC[name]``); time-to-first-token and
inter-token latency land in histograms (``SERVE_TTFT[name]``,
``SERVE_ITL[name]``) next to the micro-batcher's ``SERVE_LAT``.
"""

from __future__ import annotations

import collections
import itertools
import threading
from ..analysis import lockwatch
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import trace
from ..dashboard import Dashboard
from ..log import Log
from .batcher import (DeadlineExceededError, OverloadedError, bucket_for,
                      shape_buckets)
from . import accounting
from . import kv_transfer
from .block_pool import (SCRATCH_BLOCK, BlockPool, chain_hashes,
                         kv_bytes_per_block)
from .flight_recorder import FlightRecorder
from .snapshot import (SnapshotManager, quantize_decode_params,
                       replicate_for_decode, shard_for_decode)
from .watchdog import EngineWatchdog, WatchdogConfig
from .workloads import _jit_cache_size


@dataclass
class DecodeEngineConfig:
    slots: int = 8              # S: concurrent sequences (fused-step width)
    max_prompt: int = 64        # longest admissible prompt
    max_new: int = 32           # per-request cap AND default generation length
    eos_id: Optional[int] = None
    max_queue: int = 256        # admission queue depth before shedding
    max_staleness_s: float = 0.05
    # prompt pad buckets (powers of two up to max_prompt by default):
    # one compiled prefill/insert per bucket, step compiles ONCE regardless
    # (monolithic admission only; chunked admission needs no buckets)
    prompt_buckets: Optional[Tuple[int, ...]] = None
    # per-iteration chunked-prefill token budget; None = the
    # -prefill_token_budget flag, 0 = monolithic whole-prompt admission
    prefill_token_budget: Optional[int] = None
    # paged KV cache: block size in token positions (None = the
    # -kv_block_size flag, 0 = contiguous per-slot strips) and usable
    # pool blocks (None = the -kv_pool_blocks flag, <= 0 = auto-size to
    # the contiguous-equivalent capacity slots * ceil(T / block_size))
    kv_block_size: Optional[int] = None
    kv_pool_blocks: Optional[int] = None
    # tensor-parallel decode mesh width (None = the -decode_tp flag).
    # 1 reduces exactly to the single-device replicated path; > 1 builds
    # a decode-specific mesh over the first decode_tp devices, shards
    # attention heads / the MLP hidden dim / the head slice of the paged
    # K/V pools over a "tp" axis, and compiles every serving program
    # once against matched in/out_shardings (needs the paged KV cache)
    decode_tp: Optional[int] = None
    # content-addressed prefix caching over the paged pool (None = the
    # -prefix_cache flag; needs paged KV AND chunked prefill, silently
    # inert otherwise). False is the A/B baseline: same pool bytes,
    # every prompt prefills from token zero.
    prefix_cache: Optional[bool] = None
    # sequence-parallel long-prompt prefill over the decode mesh (None =
    # the matching -prefill_sp* flags): prompts at/above the threshold
    # prefill in budget * tp token chunks with the chunk's rows sharded
    # over the decode mesh's tp axis ("ring" ppermute rotations or
    # "ulysses" all_to_all head resharding); shorter prompts keep the
    # single-lane chunk program bit-for-bit. Paged + chunked only;
    # incompatible with kv_quant=int8.
    prefill_sp: Optional[bool] = None
    prefill_sp_backend: Optional[str] = None
    prefill_sp_threshold: Optional[int] = None
    # speculative decoding draft length (None = the -spec_k flag).
    # 0 = off (today's one-token path, bit-for-bit); > 0 drafts up to
    # spec_k tokens per live slot via n-gram prompt lookup and verifies
    # them in one fused fixed-K step (needs the paged KV cache)
    spec_k: Optional[int] = None
    # int8 per-block-scaled paged KV pools (None = the -kv_quant flag).
    # "none" is today's fp pools bit-for-bit; "int8" stores the pools
    # as int8 with per-(layer, block) fp32 scales riding every program
    # as traced data — ~4x KV capacity at equal bytes, lossy (the bench
    # archives the argmax-match rate against the fp32 oracle). Needs
    # the paged KV cache.
    kv_quant: Optional[str] = None
    # int8 decode param snapshot pins (None = the -decode_param_quant
    # flag): pins quantize host-side once per version (~4x smaller
    # replica copies) and the compiled programs fold the dequant in
    decode_param_quant: Optional[str] = None
    # overload-graceful serving (None = the matching flags): optimistic
    # prompt-only reservation + grow-at-decode + preemption-with-
    # recompute (paged + chunked only; False = worst-case up-front
    # reservation, the A/B baseline), the per-request preemption
    # budget, and the bounded admission lookahead past a block-starved
    # queue head (0 = strict FIFO within a priority class)
    preempt: Optional[bool] = None
    preempt_budget: Optional[int] = None
    sched_lookahead: Optional[int] = None
    # black-box layer (None = the matching flag): always-on flight
    # recorder ring, stall/leak watchdog, trip-bundle target, and the
    # rolling-window latency SLOs registered in the Dashboard
    flight_recorder: Optional[bool] = None
    flight_recorder_capacity: Optional[int] = None
    watchdog: Optional[bool] = None
    watchdog_interval_s: Optional[float] = None
    watchdog_stall_s: Optional[float] = None
    watchdog_queue_age_s: Optional[float] = None
    debug_dump_dir: Optional[str] = None
    slo_ttft_ms: Optional[float] = None
    slo_itl_ms: Optional[float] = None
    # per-tenant cost attribution (None = the -cost_ledger flag): a
    # host-only CostLedger accumulating each request's resource vector
    # at the existing instrumentation sites (serving/accounting.py;
    # False = today's metrics surface byte-for-byte)
    cost_ledger: Optional[bool] = None

    def _resolved(self, field: str, flag: Optional[str] = None):
        value = getattr(self, field)
        if value is None:
            from ..config import get_flag

            value = get_flag(flag or field)
        return value

    def resolved_prompt_buckets(self) -> Tuple[int, ...]:
        if self.prompt_buckets:
            return tuple(self.prompt_buckets)
        return shape_buckets(self.max_prompt)

    def resolved_prefill_budget(self) -> int:
        if self.prefill_token_budget is not None:
            return int(self.prefill_token_budget)
        from ..config import get_flag

        return int(get_flag("prefill_token_budget"))

    def resolved_kv_block_size(self) -> int:
        if self.kv_block_size is not None:
            return int(self.kv_block_size)
        from ..config import get_flag

        return int(get_flag("kv_block_size"))

    def resolved_kv_pool_blocks(self, blocks_per_seq: int) -> int:
        n = self.kv_pool_blocks
        if n is None:
            from ..config import get_flag

            n = int(get_flag("kv_pool_blocks"))
        if n <= 0:                   # auto: contiguous-equivalent capacity
            n = self.slots * blocks_per_seq
        return int(n)

    def resolved_watchdog_config(self) -> WatchdogConfig:
        return WatchdogConfig(
            interval_s=float(self._resolved("watchdog_interval_s")),
            stall_s=float(self._resolved("watchdog_stall_s")),
            queue_age_s=float(self._resolved("watchdog_queue_age_s")),
            dump_dir=str(self._resolved("debug_dump_dir")))


# process-unique small request ids: the flight recorder's admitted/
# completed columns join ring records to requests without holding refs
_RIDS = itertools.count(1)

# tenant priority classes: small ints, higher = more important. The
# admission scheduler weights class p by 2**p, so under contention
# class p receives 2**p admissions for every one class 0 gets — and
# every non-empty class keeps a POSITIVE share (the starvation bound
# the tests assert; strict priority would starve class 0 forever).
MAX_PRIORITY = 7
DEFAULT_PRIORITY = 1

# disaggregated serving: cap on the chain hashes health() advertises
# (the decode side's dedup advertisement rides replica heartbeats — at
# 16 bytes/hash this bounds the heartbeat cost to ~8 KB of hex). A
# capped advertisement is weaker, never wrong: an unadvertised cached
# block crosses the wire and dedups on arrival instead.
_CHAIN_ADVERT_CAP = 256

# prompt-lookup n-gram width: the drafter keys on the sequence's last
# _SPEC_NGRAM tokens. 2 is the sweet spot for the repetitive tails
# speculation targets (templated/looping continuations re-enter their
# cycle within a couple of tokens); a larger n only delays the first
# match without improving the greedy-verified acceptance contract.
_SPEC_NGRAM = 2


class _PromptLookup:
    """Per-slot n-gram prompt-lookup index (Saxena, "Prompt Lookup
    Decoding"): maps every :data:`_SPEC_NGRAM`-gram of the sequence so
    far (prompt + emitted tokens) to the position right after its most
    recent earlier occurrence. A proposal reads the continuation that
    followed the last time the sequence's current tail was seen — free
    drafts with high acceptance on the repetitive tails of real traffic
    (templates, code, multi-turn echoes), and by construction the tail
    n-gram itself is never indexed until a later token gives it a
    continuation, so a proposal never self-matches. Pure host state,
    O(1) amortized per token (the index extends incrementally with each
    accepted token), so drafting can never add a compiled trace."""

    __slots__ = ("toks", "index")

    def __init__(self) -> None:
        self.toks: List[int] = []
        self.index: dict = {}

    def extend(self, tokens) -> None:
        """Append tokens; each one gives the n-gram ENDING just before
        it a continuation, which is when that n-gram becomes usable."""
        for t in tokens:
            p = len(self.toks)
            self.toks.append(int(t))
            if p >= _SPEC_NGRAM:
                self.index[tuple(self.toks[p - _SPEC_NGRAM: p])] = p

    def propose(self, limit: int) -> List[int]:
        """Up to ``limit`` draft tokens continuing the current tail, or
        ``[]`` when the tail n-gram has no earlier occurrence.

        The lookup FOLLOWS THROUGH its own extension: when the matched
        continuation runs out before ``limit`` (a tight cycle whose
        period is shorter than the draft window), the tail of (sequence
        + draft-so-far) is looked up again — so a period-2 loop still
        fills a K=4 window instead of stalling at the match boundary,
        which is exactly where greedy generations spend their
        repetitive tails."""
        if limit <= 0 or len(self.toks) < _SPEC_NGRAM:
            return []
        out: List[int] = []
        key = tuple(self.toks[-_SPEC_NGRAM:])
        while len(out) < limit:
            start = self.index.get(key)
            if start is None:
                break
            take = self.toks[start: start + (limit - len(out))]
            if not take:
                break
            out.extend(take)
            key = tuple((list(key) + take)[-_SPEC_NGRAM:])
        return out


class _PrioQueue:
    """Per-priority FIFO lanes under a stride (weighted-fair) scheduler.

    Each admission decision picks the non-empty lane with the smallest
    *pass* value, then advances that lane's pass by ``1 / 2**p``
    (stride scheduling): class ``p`` receives a ``2**p`` share of
    admissions under contention, ties break toward the higher class,
    and an idle lane re-activates at the current pass frontier so it
    cannot hoard credit and burst. Within a lane order is FIFO, with
    two exceptions the overload design needs:

    * **bounded lookahead** — when the lane head's block reservation
      does not fit the pool right now, up to ``lookahead`` younger
      requests of the SAME lane are scanned for one that does (a huge
      request at the head must not starve small admissible ones). The
      bypass bound is GLOBAL: the head accumulates one skip per
      admission that jumps it — same-lane candidates and other lanes'
      requests alike — and at ``lookahead`` skips ALL admission
      freezes until the head fits (see :meth:`pop_admissible`), which
      keeps every head's wait finite.
    * **preempted re-enqueue** (:meth:`appendleft`) — a preempted
      sequence returns to the FRONT of its lane: it is the oldest
      work its class has, and re-admitting it first is what makes the
      preemption budget a real churn bound.

    Expired-deadline requests are dropped AT POP TIME, whenever the
    scheduler's scan touches them — the caller receives them in the
    second return slot and fails their futures before any prefill
    runs. Per-lane depth rides ``QUEUE_DEPTH[name.pN]`` gauges.
    Callers hold the engine lock; this class does no locking itself.
    """

    def __init__(self, name: str, lookahead: int) -> None:
        self._name = name
        self._lookahead = int(lookahead)
        self._lanes: Dict[int, Deque["_Request"]] = {}
        self._passes: Dict[int, float] = {}
        self._gauges: Dict[int, object] = {}
        self._n = 0
        # queued requests that were preempted mid-generation and await
        # resume: while any exist the engine HOLDS its snapshot pin
        # (a pin move between preemption and resume would recompute
        # the tail under different params and break the bit-identical
        # contract) — maintained by _add and every removal path
        self.n_resumed = 0

    def __len__(self) -> int:
        return self._n

    def _gauge(self, p: int):
        g = self._gauges.get(p)
        if g is None:
            g = Dashboard.get_or_create_gauge(
                f"QUEUE_DEPTH[{self._name}.p{p}]")
            self._gauges[p] = g
        return g

    def _min_pass(self) -> float:
        active = [self._passes[p] for p, lane in self._lanes.items()
                  if lane]
        return min(active) if active else 0.0

    def _charge(self, p: int) -> None:
        self._passes[p] += 1.0 / (1 << min(p, MAX_PRIORITY))

    def _add(self, req: "_Request", front: bool) -> None:
        lane = self._lanes.get(req.priority)
        if lane is None:
            lane = self._lanes[req.priority] = collections.deque()
            self._passes.setdefault(req.priority, 0.0)
        if not lane:
            self._passes[req.priority] = max(
                self._passes[req.priority], self._min_pass())
        (lane.appendleft if front else lane.append)(req)
        self._n += 1
        if req.resumed:
            self.n_resumed += 1
        self._gauge(req.priority).set(float(len(lane)))

    def _removed(self, req: "_Request") -> "_Request":
        self._n -= 1
        if req.resumed:
            self.n_resumed -= 1
        return req

    def append(self, req: "_Request") -> None:
        self._add(req, front=False)

    def appendleft(self, req: "_Request") -> None:
        """Preempted re-enqueue: the front of the request's lane."""
        self._add(req, front=True)

    def oldest_t_enq(self) -> Optional[float]:
        heads = [lane[0].t_enq for lane in self._lanes.values() if lane]
        return min(heads) if heads else None

    def lowest_priority(self) -> Optional[int]:
        lanes = [p for p, lane in self._lanes.items() if lane]
        return min(lanes) if lanes else None

    def pop_admissible(self, now: float, covers):
        """One scheduling decision: ``(request or None, expired)``.

        ``covers(req)`` is the admission gate (block coverage); every
        queued request the scan touches is first deadline-checked and
        dropped into ``expired`` when past it — fail-fast BEFORE any
        prefill, the pop-time contract.

        The bypass bound is GLOBAL: a block-starved head accumulates
        one skip per admission that jumps it — same-lane lookahead
        candidates AND other lanes' requests alike — and once any head
        reaches the bound, admission freezes fleet-wide until that
        head fits (only bound-reaching heads may admit). Per-lane-only
        accounting would let the other lanes' small optimistic
        admissions re-consume every block a completion frees, starving
        a pessimistic (budget-exhausted worst-case) waiter forever;
        freezing lets freed blocks ACCUMULATE for it, so its wait is
        bounded by the live sequences' drain."""
        expired: List["_Request"] = []

        def dead(r: "_Request") -> bool:
            return r.deadline is not None and r.deadline <= now

        def sweep(p) -> None:
            lane = self._lanes[p]
            while lane and dead(lane[0]):
                expired.append(self._removed(lane.popleft()))

        thresh = self._lookahead if self._lookahead > 0 else 1
        order = sorted((p for p, lane in self._lanes.items() if lane),
                       key=lambda p: (self._passes[p], -p))
        # starved heads first: one at its bypass bound freezes every
        # other admission until it goes through
        for p in list(order):
            sweep(p)
        starved = [p for p in order
                   if self._lanes[p] and self._lanes[p][0].skips >= thresh]
        scan = starved or [p for p in order if self._lanes[p]]
        frozen = bool(starved)
        checked: List["_Request"] = []   # heads found non-coverable
        try:
            for p in scan:
                lane = self._lanes[p]
                head = lane[0]
                if covers(head):
                    self._removed(lane.popleft())
                    self._charge(p)
                    for h in checked:
                        h.skips += 1
                    return head, expired
                checked.append(head)
                if frozen or self._lookahead <= 0 \
                        or head.skips >= self._lookahead:
                    continue
                i, scanned = 1, 0
                while i < len(lane) and scanned < self._lookahead:
                    cand = lane[i]
                    if dead(cand):
                        del lane[i]
                        expired.append(self._removed(cand))
                        continue
                    scanned += 1
                    if covers(cand):
                        del lane[i]
                        self._removed(cand)
                        self._charge(p)
                        for h in checked:
                            h.skips += 1
                        return cand, expired
                    i += 1
            return None, expired
        finally:
            for p in order:
                self._gauge(p).set(float(len(self._lanes[p])))

    def drain(self) -> List["_Request"]:
        """Remove and return everything (the failure path)."""
        out: List["_Request"] = []
        for p, lane in self._lanes.items():
            out.extend(lane)
            lane.clear()
            self._gauge(p).set(0.0)
        self._n = 0
        self.n_resumed = 0
        return out


class _Request:
    __slots__ = ("prompt", "max_new", "future", "t_enq", "t_last",
                 "slot", "out", "version", "ctx", "pf_off", "pf_chunks",
                 "t_admit", "blocks", "rid", "hashes", "hash_seed",
                 "n_hit", "full_hit", "saved", "pf_reg", "ttft_pending",
                 "drafter", "priority", "deadline", "preempts",
                 "resumed", "skips", "prompt0", "pf_only", "known",
                 "xfer", "tenant", "usage", "sp")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 ctx: Optional[trace.SpanContext] = None,
                 priority: int = DEFAULT_PRIORITY,
                 deadline: Optional[float] = None,
                 tenant: Optional[str] = None) -> None:
        self.rid = next(_RIDS)
        self.prompt = prompt
        self.max_new = max_new
        self.future: Future = Future()
        self.t_enq = time.monotonic()
        self.t_last = self.t_enq     # last token emission (ITL base)
        self.slot = -1
        self.out: List[int] = []
        self.version = -1
        self.blocks: List[int] = []  # paged KV: the admission's reservation
        # trace handoff token (the submitter's root-span context): the
        # engine thread parents admission/iteration spans under it
        self.ctx = ctx
        # chunked-prefill progress: next chunk's prompt offset, chunks
        # run so far, and when admission began (queue.wait boundary)
        self.pf_off = 0
        self.pf_chunks = 0
        self.t_admit = 0.0
        # sequence-parallel prefill routing (set at _begin_prefill on
        # -prefill_sp engines: prompt length >= the threshold)
        self.sp = False
        # prefix caching: the prompt's full-block hash chain (memoized
        # per seed), blocks matched at admission, whether the WHOLE
        # prompt was cached, prefill tokens skipped, how many prompt
        # blocks are registered so far, and whether the next fused-step
        # token is this request's FIRST (full hit: TTFT lands on the
        # first decode step, not on a prefill chunk)
        self.hashes: Optional[List[bytes]] = None
        self.hash_seed: Optional[bytes] = None
        self.n_hit = 0
        self.full_hit = False
        self.saved = 0
        self.pf_reg = 0
        self.ttft_pending = False
        # speculative decoding: the slot's prompt-lookup draft index
        # (None on spec_k=0 engines — created at admission)
        self.drafter: Optional[_PromptLookup] = None
        # overload-graceful scheduling: tenant class, absolute
        # monotonic deadline (None = none), times preempted (the
        # budget), whether a preemption already interrupted emitted
        # output (resume recomputes, TTFT never re-records), times the
        # admission lookahead bypassed this request at the lane head,
        # and the ORIGINAL prompt (the resume base — ``prompt`` grows
        # to prompt0 + emitted tokens across preemptions)
        self.priority = int(priority)
        self.deadline = deadline
        self.preempts = 0
        self.resumed = False
        self.skips = 0
        self.prompt0 = prompt
        # disaggregated serving (kv_transfer): prefill-only admissions
        # resolve with a transfer payload instead of tokens; ``known``
        # holds the hex chain hashes the receiver advertised (skip
        # shipping those); ``xfer`` carries the splice accounting of the
        # transfer that warmed this request's prefix (decode side) so
        # the admit span can attribute the hit to the wire
        self.pf_only = False
        self.known: frozenset = frozenset()
        self.xfer: Optional[Dict[str, int]] = None
        # per-tenant cost attribution: the submitted tenant id (None =
        # the ledger's default tenant) and the request's host-only
        # resource vector — None on ledger-off engines, so every
        # attribution site is a single is-None check there
        self.tenant = tenant
        self.usage: Optional[accounting.ResourceUsage] = None


class DecodeEngine:
    """One LM's continuous-batching decode loop.

    ``lm`` is a :class:`models.transformer.TransformerLM` (the snapshot
    contract source); ``submit`` enqueues a prompt and returns a Future
    resolving to the reply dict ``{"result", "snapshot_version",
    "staleness_s"}`` where ``result`` is the generated id array
    (truncated at eos, so its length is request-dependent).
    """

    def __init__(self, name: str, lm, config: Optional[DecodeEngineConfig]
                 = None) -> None:
        from ..models.transformer import (admit_insert_paged,
                                          admit_insert_paged_q,
                                          cache_insert, cow_block_copy,
                                          cow_block_copy_q, decode_step,
                                          decode_step_paged,
                                          decode_step_paged_q,
                                          dequantize_decode_params,
                                          make_sharded_decode_programs,
                                          prefill, prefill_chunk,
                                          prefill_chunk_paged,
                                          prefill_chunk_paged_q,
                                          prefill_chunk_paged_sp,
                                          verify_step_paged,
                                          verify_step_paged_q)

        self.name = name
        self.config = config or DecodeEngineConfig()
        cfg = lm.config
        self._model_cfg = cfg
        ec = self.config
        if ec.max_prompt + ec.max_new > cfg.max_seq:
            Log.fatal(f"DecodeEngine {name!r}: max_prompt {ec.max_prompt} + "
                      f"max_new {ec.max_new} exceeds max_seq {cfg.max_seq}")
        self._prompt_buckets = ec.resolved_prompt_buckets()
        if self._prompt_buckets[-1] < ec.max_prompt:
            Log.fatal(f"DecodeEngine {name!r}: largest prompt bucket "
                      f"{self._prompt_buckets[-1]} < max_prompt "
                      f"{ec.max_prompt}")
        # admission-group batch buckets (an admission wave is <= slots)
        self._batch_buckets = shape_buckets(ec.slots)
        S = ec.slots
        L, D = cfg.n_layers, cfg.d_model
        self._cache_len = ec.max_prompt + ec.max_new
        T = self._cache_len

        # -- paged KV cache geometry ----------------------------------------
        # block size 0 = contiguous [L, S, T, D] strips (the pre-paging
        # layout, kept as the A/B baseline); > 0 = one block pool
        # [L, n_blocks + 1, block_size, D] (physical block 0 is the
        # scratch/sentinel block) + per-slot block tables [S, M]
        self._block_size = ec.resolved_kv_block_size()
        if self._block_size < 0:
            Log.fatal(f"DecodeEngine {name!r}: negative kv_block_size "
                      f"{self._block_size}")
        self._paged = self._block_size > 0
        if self._paged:
            Bs = self._block_size
            self._blocks_per_seq = -(-T // Bs)          # M = ceil(T / Bs)
            n_blocks = ec.resolved_kv_pool_blocks(self._blocks_per_seq)
            self._pool: Optional[BlockPool] = BlockPool(
                n_blocks, Bs, name=name)
            # all-sentinel rows: every position maps to scratch until an
            # admission installs its reservation
            self._block_tables = np.full(
                (S, self._blocks_per_seq), SCRATCH_BLOCK, np.int32)
        else:
            self._blocks_per_seq = 0
            self._pool = None
            self._block_tables = None

        # -- quantized serving knobs ----------------------------------------
        # int8 per-(layer, block)-scaled KV pools: the pools store int8
        # and a pair of [L, n_blocks + 1] fp32 scale arrays rides every
        # program call as TRACED data — same one-trace accounting as the
        # block tables. kv_quant="none" (default) keeps the fp pools and
        # is bit-identical to the pre-quant engine.
        self._kv_quant_mode = str(ec._resolved("kv_quant"))
        if self._kv_quant_mode not in ("none", "int8"):
            Log.fatal(f"DecodeEngine {name!r}: kv_quant must be 'none' or "
                      f"'int8', got {self._kv_quant_mode!r}")
        self._kv_quant = self._kv_quant_mode == "int8"
        if self._kv_quant and not self._paged:
            Log.fatal(f"DecodeEngine {name!r}: kv_quant=int8 needs the "
                      f"paged KV cache (kv_block_size > 0) — the scales "
                      f"are per (layer, block), and a contiguous strip "
                      f"has no blocks to scale")
        # int8 decode param pins: the pin quantizes host-side ONCE per
        # snapshot version (snapshot.quantize_decode_params) and the
        # compiled programs fold the dequant in — pin device_put bytes
        # drop ~4x, per-token traces stay 1.
        self._param_quant = str(ec._resolved("decode_param_quant"))
        if self._param_quant not in ("none", "int8"):
            Log.fatal(f"DecodeEngine {name!r}: decode_param_quant must be "
                      f"'none' or 'int8', got {self._param_quant!r}")

        # -- decode mesh (tensor-parallel serving) --------------------------
        # decode_tp=1 (default) reduces exactly to the single-device
        # replicated path; > 1 builds a decode-SPECIFIC mesh over the
        # first tp devices — NOT the train mesh, whose NamedShardings
        # dragged per-token programs through the spmd partitioner
        # (~10x step wall, the PR 2 gate this replaces)
        self._tp = int(ec._resolved("decode_tp"))
        self._decode_mesh = None
        self._param_shardings = None     # decode-mesh pin target (tp > 1)
        self._cache_sharding = None      # device_put target for the pools
        if self._tp < 1:
            Log.fatal(f"DecodeEngine {name!r}: decode_tp must be >= 1, "
                      f"got {self._tp}")
        if self._tp > 1:
            from ..models.transformer import (DECODE_TP_AXIS,
                                              validate_decode_tp)
            from ..topology import make_mesh

            if not self._paged:
                Log.fatal(f"DecodeEngine {name!r}: decode_tp={self._tp} "
                          f"needs the paged KV cache (kv_block_size > 0) "
                          f"— the sharded programs partition the block "
                          f"pools over the head slice of D")
            validate_decode_tp(cfg, self._tp, name=f"DecodeEngine {name!r}")
            ndev = len(jax.devices())
            if self._tp > ndev:
                Log.fatal(f"DecodeEngine {name!r}: decode_tp {self._tp} "
                          f"exceeds the {ndev} visible device(s)")
            if jax.process_count() > 1:
                # fail at construction, not at pin time on the loop
                # thread: in a multi-process mesh jax.devices()[:tp]
                # includes devices this host cannot address, and the
                # pin's cross-mesh device_put would raise mid-serving
                # (replicate_for_decode has the same single-process
                # scope; multi-process decode meshes are the
                # serving-fleet item, not this knob)
                Log.fatal(f"DecodeEngine {name!r}: decode_tp > 1 is "
                          f"single-process only — a multi-process mesh "
                          f"cannot address jax.devices()[:{self._tp}] "
                          f"from one host")
            self._decode_mesh = make_mesh(
                (self._tp,), axis_names=(DECODE_TP_AXIS,),
                devices=jax.devices()[: self._tp])

        self._manager = SnapshotManager.of(lm, name=name)
        self._snap = None            # pinned while any slot is live
        self._pinned = None          # the pinned snapshot's DECODE params
        self._pinned_version: Optional[int] = None
        # replica/reshard copies actually taken: the pin memoizes on
        # snapshot VERSION, so a drain/re-pin cycle (or a forced
        # re-publish) without a version move is copy-free (tested)
        self.pin_copies = 0

        # cache donation is real only where XLA implements input aliasing
        # (TPU/GPU). On CPU a donated arg forces a defensive copy AND a
        # second compiled trace — measured 2.4 ms -> 22 ms per fused step
        # — so the engine only donates off-CPU.
        donate = (1, 2) if jax.default_backend() != "cpu" else ()
        # quant programs thread (kc, vc, ksc, vsc) after params — the
        # donate tuple shifts to cover all four pool arrays
        q_donate = (1, 2, 3, 4) if donate else ()

        # -- jitted programs ------------------------------------------------
        # chunked admission budget: a fixed-size chunk prefilled straight
        # into the slot cache at a traced (slot, offset, length) — the
        # chunk shape is the ONLY static, so it is exactly one extra
        # compiled trace per engine config (asserted in the tests)
        self._budget = ec.resolved_prefill_budget()
        if self._budget < 0:
            Log.fatal(f"DecodeEngine {name!r}: negative "
                      f"prefill_token_budget {self._budget}")
        # a chunk never needs more tokens than the longest admissible
        # prompt (and must fit the [.., T, ..] cache): clamp the chunk
        # shape — budgets past max_prompt just mean one-chunk admission
        self._budget = min(self._budget, ec.max_prompt)
        # content-addressed prefix caching: paged blocks + chunked
        # prefill only (monolithic admission writes the WHOLE prompt
        # through the table in one fused insert — it cannot start at the
        # first uncached token, so the cache gates itself off)
        self._prefix = (self._paged and self._budget > 0
                        and bool(ec._resolved("prefix_cache")))
        self._hash_seed = b""        # pinned-version scope for the chain
        # sequence-parallel prefill: prompts at/above the threshold chunk
        # at budget * tp tokens with the rows sharded over the decode
        # mesh — a long prompt admits in tp x fewer iterations while each
        # device still runs one budget's worth of rows per iteration
        # (the ITL bound the budget exists for). Short prompts keep the
        # single-lane chunk program bit-for-bit.
        self._sp = bool(ec._resolved("prefill_sp"))
        self._sp_backend = str(ec._resolved("prefill_sp_backend"))
        self._sp_threshold = int(ec._resolved("prefill_sp_threshold"))
        self._chunk_sp_fn = None
        if self._sp:
            if not (self._paged and self._budget > 0):
                Log.fatal(f"DecodeEngine {name!r}: prefill_sp needs the "
                          f"paged KV cache (kv_block_size > 0) AND "
                          f"chunked prefill (prefill_token_budget > 0) "
                          f"— the seqpar chunk scatters through block "
                          f"tables at a traced offset")
            if self._kv_quant:
                Log.fatal(f"DecodeEngine {name!r}: prefill_sp is "
                          f"incompatible with kv_quant=int8 — the "
                          f"seqpar entry points reproduce the fp chunk "
                          f"math exactly and have no quantized variant")
            if self._sp_backend not in ("ring", "ulysses"):
                Log.fatal(f"DecodeEngine {name!r}: prefill_sp_backend "
                          f"must be 'ring' or 'ulysses', got "
                          f"{self._sp_backend!r}")
            if self._sp_threshold < 0:
                Log.fatal(f"DecodeEngine {name!r}: negative "
                          f"prefill_sp_threshold {self._sp_threshold}")
            if self._sp_backend == "ring" and T % self._tp != 0:
                # the ring rotates the slot's gathered [T, D] view in
                # T/tp-row shards; ulysses keeps T whole (head shards)
                Log.fatal(f"DecodeEngine {name!r}: ring prefill_sp "
                          f"needs the logical cache length {T} "
                          f"divisible by decode_tp {self._tp} — use "
                          f"the ulysses backend or adjust "
                          f"max_prompt/max_new")
        # the seqpar chunk's global size: one budget of rows per DEVICE
        self._sp_chunk = self._budget * self._tp if self._sp else 0
        # speculative decoding: up to spec_k prompt-lookup drafts per
        # live slot, verified by one fused fixed-K step per iteration.
        # Paged-only: the verify window's scatter/rollback contract is
        # written against block tables (dead/pad writes park in scratch;
        # the contiguous strips have no per-position sentinel for a
        # multi-position window), so spec_k > 0 fail-fasts on contiguous
        self._spec = int(ec._resolved("spec_k"))
        if self._spec < 0:
            Log.fatal(f"DecodeEngine {name!r}: negative spec_k "
                      f"{self._spec}")
        if self._spec and not self._paged:
            Log.fatal(f"DecodeEngine {name!r}: spec_k={self._spec} needs "
                      f"the paged KV cache (kv_block_size > 0) — the "
                      f"verify window parks rejected/pad writes in the "
                      f"scratch block")
        # overload-graceful serving: optimistic prompt-only reservation
        # + grow-at-decode + preemption-with-recompute. Paged + chunked
        # only (a contiguous strip has no blocks to release, and
        # monolithic admission can neither grow nor restart mid-prompt)
        # — the knob gates itself off otherwise, the prefix_cache
        # precedent. preempt=False keeps the pre-PR worst-case
        # prompt+max_new up-front reservation (the A/B baseline).
        self._preempt_on = (self._paged and self._budget > 0
                            and bool(ec._resolved("preempt")))
        self._preempt_budget = int(ec._resolved("preempt_budget"))
        if self._preempt_budget < 0:
            Log.fatal(f"DecodeEngine {name!r}: negative preempt_budget "
                      f"{self._preempt_budget}")
        self._lookahead = int(ec._resolved("sched_lookahead"))
        if self._lookahead < 0:
            Log.fatal(f"DecodeEngine {name!r}: negative sched_lookahead "
                      f"{self._lookahead}")

        # fused admission: prefill a group of prompts (padded to a batch
        # bucket x prompt bucket), gather each last REAL position's logits
        # -> first tokens, and insert every prompt's K/V into its free
        # slot, all in ONE dispatch. Placement is traced either way — slot
        # indices for the contiguous DUS chain, per-row block tables for
        # the paged scatter — so there is one trace per (batch bucket,
        # prompt bucket), shared by every slot/block choice.
        def _first_tokens(logits, lengths, dtype):
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
            return jnp.argmax(last, axis=-1).astype(dtype)

        if self._tp > 1:
            # decode-mesh programs, pre-partitioned: every program is
            # jitted ONCE here (construction time — the RT106 contract)
            # with matched in/out_shardings, so the partitioner runs at
            # compile and never again; params arrive resharded by the
            # pin (shard_for_decode) and the pools round-trip with their
            # sharding intact. Copy-on-write rides the same mesh: the
            # one write that can touch a shared block stays one site.
            progs = make_sharded_decode_programs(
                cfg, self._decode_mesh, T, donate=bool(donate),
                kv_quant=self._kv_quant_mode,
                param_quant=self._param_quant,
                prefill_sp=self._sp_backend if self._sp else "none")
            self._param_shardings = progs["param_shardings"]
            self._cache_sharding = progs["pool_sharding"]
            self._admit_fn = progs["admit"]
            self._chunk_fn = progs["chunk"]
            # the seqpar chunk program rides the same builder (same
            # matched in/out_shardings and donation as "chunk"), so the
            # partitioner runs at compile time here too
            self._chunk_sp_fn = progs.get("chunk_sp")
            self._step_fn = progs["step"]
            self._cow_fn = progs["cow"] if self._prefix else None
            # the verify step pins and partitions like the fused step
            # (the builder's in/out_shardings match); K rides the fixed
            # [S, spec_k + 1] window shape, so dispatching it is one
            # compiled trace exactly like the step
            self._verify_fn = progs["verify"] if self._spec else None
        else:
            # param-dequant fold (decode_param_quant=int8): the pinned
            # pytree arrives as {"q": int8, "s": fp32} leaves and every
            # program dequantizes at COMPILE time — the call signatures,
            # donation and trace counts are exactly the fp path's
            pf = ((lambda p: dequantize_decode_params(p, cfg.dtype))
                  if self._param_quant == "int8" else (lambda p: p))
            if self._paged and self._kv_quant:
                # quant admission threads both pools' scale arrays as
                # traced data right after the pools themselves
                def _admit_insert(params, kc, vc, ksc, vsc, bts, toks,
                                  lengths):
                    return admit_insert_paged_q(cfg, pf(params), kc, vc,
                                                ksc, vsc, bts, toks,
                                                lengths)
            elif self._paged:
                # the ONE paged admission body (prefill + last-real-
                # position gather + table-scatter insert) lives in
                # transformer.admit_insert_paged — the sharded variant
                # jits the same function, so the two paths cannot drift
                def _admit_insert(params, kc, vc, bts, toks, lengths):
                    return admit_insert_paged(cfg, pf(params), kc, vc,
                                              bts, toks, lengths)
            else:
                def _admit_insert(params, kc, vc, slots, toks, lengths):
                    logits, ks, vs = prefill(cfg, pf(params), toks)
                    first = _first_tokens(logits, lengths, toks.dtype)
                    kc, vc = cache_insert(kc, vc, slots, ks, vs)
                    return first, kc, vc

            self._admit_fn = jax.jit(
                _admit_insert,
                donate_argnums=q_donate if self._kv_quant else donate)
            if self._prefix:
                # copy-on-write: duplicate one block (both pools) before
                # a write lands in a shared one. src/dst are traced
                # scalars — ONE compiled trace per engine config,
                # dispatched host-side at admission before the table
                # ever reaches the fused step.
                # the lambda is load-bearing: jitting the shared
                # module-level function directly would pool every
                # engine's compile cache on one handle (jit caches key
                # on the function object), breaking the per-engine
                # one-trace accounting
                if self._kv_quant:
                    # the scale columns duplicate WITH the block — a
                    # CoW'd block must dequantize identically to its src
                    self._cow_fn = jax.jit(
                        lambda kc, vc, ksc, vsc, src, dst:
                        cow_block_copy_q(kc, vc, ksc, vsc, src, dst),
                        donate_argnums=(0, 1, 2, 3) if donate else ())
                else:
                    self._cow_fn = jax.jit(
                        lambda kc, vc, src, dst: cow_block_copy(
                            kc, vc, src, dst),
                        donate_argnums=(0, 1) if donate else ())
            else:
                self._cow_fn = None
            if self._paged and self._kv_quant:
                # the quant programs mirror the fp paged ones exactly —
                # block tables AND scale arrays ride as fixed-shape
                # data, so the one-trace-per-config invariant survives
                # quantization the same way it survived paging
                self._chunk_fn = jax.jit(
                    lambda params, kc, vc, ksc, vsc, bt, slot, toks,
                    off, n:
                    prefill_chunk_paged_q(cfg, pf(params), kc, vc, ksc,
                                          vsc, bt, slot, toks, off, n,
                                          t_logical=T),
                    donate_argnums=q_donate)
                self._step_fn = jax.jit(
                    lambda params, kc, vc, ksc, vsc, bt, tok, pos, active:
                    decode_step_paged_q(cfg, pf(params), kc, vc, ksc,
                                        vsc, bt, tok, pos, active,
                                        t_logical=T),
                    donate_argnums=q_donate)
                if self._spec:
                    self._verify_fn = jax.jit(
                        lambda params, kc, vc, ksc, vsc, bt, toks, pos,
                        active, nv:
                        verify_step_paged_q(cfg, pf(params), kc, vc, ksc,
                                            vsc, bt, toks, pos, active,
                                            nv, t_logical=T),
                        donate_argnums=q_donate)
                else:
                    self._verify_fn = None
            elif self._paged:
                # block tables ride every call as DATA ([S, M] int32,
                # fixed shape): which blocks a slot owns never touches an
                # aval, so the one-trace-per-config invariant survives
                # paging. The gathered views are sliced to T inside the
                # kernels, keeping the attention operand (and outputs)
                # bit-identical to the contiguous layout's.
                self._chunk_fn = jax.jit(
                    lambda params, kc, vc, bt, slot, toks, off, n:
                    prefill_chunk_paged(cfg, pf(params), kc, vc, bt, slot,
                                        toks, off, n, t_logical=T),
                    donate_argnums=donate)
                if self._sp:
                    # tp=1 seqpar rides a ONE-device decode mesh: the
                    # collectives degenerate (n=1) but the shard_map
                    # path is genuinely exercised, and the chunk size
                    # equals the budget so the math coincides with the
                    # single-lane program exactly
                    from ..models.transformer import DECODE_TP_AXIS
                    from ..topology import make_mesh

                    sp_mesh = make_mesh(
                        (1,), axis_names=(DECODE_TP_AXIS,),
                        devices=jax.devices()[:1])
                    sp_backend = self._sp_backend
                    self._chunk_sp_fn = jax.jit(
                        lambda params, kc, vc, bt, slot, toks, off, n:
                        prefill_chunk_paged_sp(cfg, pf(params), kc, vc,
                                               bt, slot, toks, off, n,
                                               sp_mesh, sp_backend,
                                               t_logical=T,
                                               tp_axis=DECODE_TP_AXIS),
                        donate_argnums=donate)
                self._step_fn = jax.jit(
                    lambda params, kc, vc, bt, tok, pos, active:
                    decode_step_paged(cfg, pf(params), kc, vc, bt, tok,
                                      pos, active, t_logical=T),
                    donate_argnums=donate)
                if self._spec:
                    # the fixed-K verify step: the [S, spec_k + 1]
                    # window is the only static — drafts, valid counts
                    # and block tables are data, so ONE compiled trace
                    # serves every draft mix and acceptance outcome
                    # (fresh lambda per engine, same as the step)
                    self._verify_fn = jax.jit(
                        lambda params, kc, vc, bt, toks, pos, active, nv:
                        verify_step_paged(cfg, pf(params), kc, vc, bt,
                                          toks, pos, active, nv,
                                          t_logical=T),
                        donate_argnums=donate)
                else:
                    self._verify_fn = None
            else:
                self._verify_fn = None
                self._chunk_fn = jax.jit(
                    lambda params, kc, vc, slot, toks, off, n:
                    prefill_chunk(
                        cfg, pf(params), kc, vc, slot, toks, off, n),
                    donate_argnums=donate)
                # THE fused step: all shapes fixed by the engine config
                # -> exactly one compiled trace no matter which slots
                # are live
                self._step_fn = jax.jit(
                    lambda params, kc, vc, tok, pos, active: decode_step(
                        cfg, pf(params), kc, vc, tok, pos, active),
                    donate_argnums=donate)

        # -- KV transfer plane (disaggregated prefill/decode) ---------------
        # two construction-time programs, prefix-cache engines only (the
        # transfer plane ships chain-addressed FULL blocks, so it rides
        # the same gate): FETCH pulls one block's K/V slices off both
        # pools (prefill side — the result is host-materialized into the
        # wire payload), SPLICE writes one received block into a freshly
        # allocated pool slot (decode side). The block id is a TRACED
        # scalar in both, so each is exactly one compiled trace per
        # engine (transfer_cache_size() asserts 2 after warmup) — a
        # static index would recompile per pool position. Splice donates
        # like the step/CoW (it reassigns both pools); fetch cannot
        # donate (the pools survive it). Fresh lambdas per engine for
        # the same per-engine compile-cache accounting as the CoW above.
        if self._prefix and self._kv_quant:
            # quant fetch/splice move the block's scale columns with its
            # int8 bytes — same traced block id, same one-trace count;
            # the [L] scale row updates in-place via the rank-reduced DUS
            self._fetch_fn = jax.jit(
                lambda kc, vc, ksc, vsc, b: (
                    jax.lax.dynamic_index_in_dim(kc, b, axis=1,
                                                 keepdims=False),
                    jax.lax.dynamic_index_in_dim(vc, b, axis=1,
                                                 keepdims=False),
                    jax.lax.dynamic_index_in_dim(ksc, b, axis=1,
                                                 keepdims=False),
                    jax.lax.dynamic_index_in_dim(vsc, b, axis=1,
                                                 keepdims=False)))
            self._splice_fn = jax.jit(
                lambda kc, vc, ksc, vsc, b, k, v, ks, vs: (
                    jax.lax.dynamic_update_index_in_dim(kc, k, b, axis=1),
                    jax.lax.dynamic_update_index_in_dim(vc, v, b, axis=1),
                    jax.lax.dynamic_update_index_in_dim(ksc, ks, b,
                                                        axis=1),
                    jax.lax.dynamic_update_index_in_dim(vsc, vs, b,
                                                        axis=1)),
                donate_argnums=(0, 1, 2, 3) if donate else ())
        elif self._prefix:
            self._fetch_fn = jax.jit(
                lambda kc, vc, b: (
                    jax.lax.dynamic_index_in_dim(kc, b, axis=1,
                                                 keepdims=False),
                    jax.lax.dynamic_index_in_dim(vc, b, axis=1,
                                                 keepdims=False)))
            self._splice_fn = jax.jit(
                lambda kc, vc, b, k, v: (
                    jax.lax.dynamic_update_index_in_dim(kc, k, b, axis=1),
                    jax.lax.dynamic_update_index_in_dim(vc, v, b, axis=1)),
                donate_argnums=(0, 1) if donate else ())
        else:
            self._fetch_fn = None
            self._splice_fn = None

        # -- device state (owned by the loop thread after start) -------------
        # committed placement from birth: warmup scratch caches use the
        # same put, so the traces warmup compiles ARE the serving traces
        # (an uncommitted zeros here would retrace on the first live call)
        if self._paged:
            cache_shape = (L, self._pool.capacity + 1, self._block_size, D)
        else:
            cache_shape = (L, S, T, D)
        # mesh-aware placement: sharded engines commit the pools to the
        # decode mesh's pool sharding (matching the programs'
        # in_shardings — a plain devices()[0] put would be rejected as
        # an incompatible committed placement); replicated engines keep
        # the single-device put
        self._cache_target = (self._cache_sharding
                              if self._cache_sharding is not None
                              else jax.devices()[0])
        cache_dtype = jnp.int8 if self._kv_quant else cfg.dtype
        self._k_cache = jax.device_put(
            jnp.zeros(cache_shape, cache_dtype), self._cache_target)
        self._v_cache = jax.device_put(
            jnp.zeros(cache_shape, cache_dtype), self._cache_target)
        if self._kv_quant:
            # per-(layer, block) fp32 scales, one array per pool. Zeros
            # from birth: scale 0 marks a never-written block (the
            # kernels' zero-divide guard dequantizes it as exact zeros),
            # which is also what quant_scale_blocks counts against. On a
            # sharded engine the scales REPLICATE — [L, N] has no head
            # slice to shard, and every shard needs every block's scale
            scale_shape = (L, self._pool.capacity + 1)
            if self._cache_sharding is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                self._scale_target = NamedSharding(self._decode_mesh,
                                                   PartitionSpec())
            else:
                self._scale_target = jax.devices()[0]
            self._k_scales = jax.device_put(
                jnp.zeros(scale_shape, jnp.float32), self._scale_target)
            self._v_scales = jax.device_put(
                jnp.zeros(scale_shape, jnp.float32), self._scale_target)
        else:
            self._scale_target = None
            self._k_scales = None
            self._v_scales = None
        # -- host state -----------------------------------------------------
        self._slot_req: List[Optional[_Request]] = [None] * S
        # explicit free-slot set, maintained at admit/complete (the loop
        # used to rebuild it by scanning all S slots every iteration)
        self._free_q: Deque[int] = collections.deque(range(S))
        self._tok = np.zeros(S, np.int32)
        self._pos = np.zeros(S, np.int32)
        self._active = np.zeros(S, bool)
        # the one admission currently prefilling in chunks (its slot is
        # reserved — excluded from the free pool — but not yet live)
        self._pf: Optional[_Request] = None
        # monolithic admission in progress: blocks are reserved at
        # _admit entry but slots go active only after the fused prefill
        # returns (a cold bucket compiles for SECONDS in between) — the
        # watchdog's leaked-reservation heuristic must not read that
        # window as a leak
        self._admitting = False
        # per-priority weighted-fair admission lanes (a plain FIFO when
        # every submit uses the default class)
        self._q = _PrioQueue(name, self._lookahead)
        # chaos/test hook (faultinject pool_squeeze=): block ids held
        # hostage to force pool pressure; excluded from the watchdog's
        # leaked-reservation heuristic
        self._squeezed: List[int] = []
        # inbound KV transfers awaiting the loop thread: the caches are
        # loop-thread-owned (donation reassigns them per dispatch), so
        # splice() parks (payload, done-event, out-dict) triples here
        # and the loop applies them between iterations
        self._splice_q: Deque = collections.deque()
        self._lock = lockwatch.lock("serving.DecodeEngine._lock")
        self._cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        # -- stats ----------------------------------------------------------
        self.ttft_hist = Dashboard.get_or_create_histogram(
            f"SERVE_TTFT[{name}]")
        self.itl_hist = Dashboard.get_or_create_histogram(
            f"SERVE_ITL[{name}]")
        self.tps_gauge = Dashboard.get_or_create_gauge(f"DECODE_TPS[{name}]")
        self.occ_gauge = Dashboard.get_or_create_gauge(f"SLOT_OCC[{name}]")
        # staleness-aware serving: seconds since the served source last
        # moved (SnapshotManager.params_age_s), refreshed on health()
        # polls — the publish-stream-went-silent signal the obs plane
        # ships and -params_stale_after_s turns into a STALE verdict
        self.params_age_gauge = Dashboard.get_or_create_gauge(
            f"SERVE_PARAMS_AGE[{name}]")
        self.shed_counter = Dashboard.get_or_create_counter(
            f"SERVE_SHED[{name}]")
        # overload-graceful instruments: preemption events, expired-
        # deadline drops, and per-class shed counters (created lazily —
        # one per priority class actually shed)
        self.preempt_counter = Dashboard.get_or_create_counter(
            f"PREEMPTIONS[{name}]")
        self.deadline_counter = Dashboard.get_or_create_counter(
            f"DEADLINE_DROPS[{name}]")
        self._shed_class_counters: Dict[int, object] = {}
        self.steps_counter = Dashboard.get_or_create_counter(
            f"DECODE_STEPS[{name}]")
        # token-accounting split: prompt tokens prefilled vs tokens
        # emitted — interval-deltas (MetricsExporter) become the two
        # rates whose ratio says where the engine's FLOPs are going.
        # DECODE_TOKENS counts every EMITTED token (a speculative
        # iteration emits up to spec_k + 1), so DECODE_TPS and the
        # exporter's token rate stay honest under speculation
        self.prefill_tok_counter = Dashboard.get_or_create_counter(
            f"PREFILL_TOKENS[{name}]")
        self.decode_tok_counter = Dashboard.get_or_create_counter(
            f"DECODE_TOKENS[{name}]")
        # speculative decoding instruments, created only on spec engines
        # so a spec_k=0 engine's dashboard/stats surface is byte-for-
        # byte today's (the metrics regression contract)
        self.spec_prop_counter = self.spec_acc_counter = None
        if self._spec:
            self.spec_prop_counter = Dashboard.get_or_create_counter(
                f"SPEC_PROPOSED[{name}]")
            self.spec_acc_counter = Dashboard.get_or_create_counter(
                f"SPEC_ACCEPTED[{name}]")
        # KV-transfer instruments, created only on prefix-cache engines
        # (the transfer plane's gate) so a prefix_cache=off engine's
        # dashboard/stats surface stays byte-for-byte (the metrics
        # regression contract). Bytes are RAW K/V bytes moved — the
        # kv_transfer.payload_bytes unit, not wire encoding.
        self.xfer_bytes_counter = self.xfer_blocks_counter = None
        self.xfer_dedup_counter = None
        if self._prefix:
            self.xfer_bytes_counter = Dashboard.get_or_create_counter(
                f"KV_XFER_BYTES[{name}]")
            self.xfer_blocks_counter = Dashboard.get_or_create_counter(
                f"KV_XFER_BLOCKS[{name}]")
            self.xfer_dedup_counter = Dashboard.get_or_create_counter(
                f"KV_XFER_DEDUP[{name}]")
        # iteration progress: the counter for dashboards/rates, the local
        # mirror + monotonic age for stats()/the watchdog's stall check
        self.iters_counter = Dashboard.get_or_create_counter(
            f"ENGINE_ITERS[{name}]")
        self.iters_total = 0
        self._last_progress = time.monotonic()
        # rolling-window latency SLOs (burn status in every snapshot())
        slo_ttft = float(ec._resolved("slo_ttft_ms"))
        if slo_ttft > 0:
            Dashboard.set_slo(f"SERVE_TTFT[{name}]", slo_ttft)
        slo_itl = float(ec._resolved("slo_itl_ms"))
        if slo_itl > 0:
            Dashboard.set_slo(f"SERVE_ITL[{name}]", slo_itl)
        # always-on flight recorder (the loop writes one record per
        # iteration; pure host state, so it can never add a compiled
        # trace — the one-trace assertions below it stay at 1)
        self.recorder: Optional[FlightRecorder] = None
        if bool(ec._resolved("flight_recorder")):
            self.recorder = FlightRecorder(
                int(ec._resolved("flight_recorder_capacity")), name=name)
            # static mesh facts ride the black box: a post-mortem dump
            # must say which tensor-parallel config produced its records
            self.recorder.meta.update(
                decode_tp=self._tp,
                mesh_devices=(self._decode_mesh.size
                              if self._decode_mesh is not None else 1))
            if self._spec:
                self.recorder.meta["spec_k"] = self._spec
            if self._kv_quant:
                self.recorder.meta["kv_quant"] = self._kv_quant_mode
            if self._sp:
                self.recorder.meta["prefill_sp"] = self._sp_backend
        # admit-span mesh annotation (trace_summary ships the column):
        # only sharded engines carry it, so replicated reports stay flat
        self._mesh_attrs = ({"decode_tp": self._tp} if self._tp > 1
                            else {})
        if self._kv_quant:
            # quant engines annotate every admit span too (the
            # trace_summary quant column; off-quant spans stay flat —
            # the metrics-regression byte-identity contract)
            self._mesh_attrs["kv_quant"] = self._kv_quant_mode
        # per-tenant cost attribution (the -cost_ledger gate): pure
        # host state on the loop thread — attaching it can never add a
        # compiled trace (step/prefill traces stay 1, retraces 0) and
        # off-ledger engines keep today's metrics surface byte-for-byte
        self.ledger: Optional[accounting.CostLedger] = None
        if bool(ec._resolved("cost_ledger")):
            self.ledger = accounting.CostLedger(
                name,
                block_bytes=(kv_bytes_per_block(
                    cfg.n_layers, cfg.d_model, self._block_size,
                    np.dtype(cfg.dtype), quant=self._kv_quant_mode)
                    if self._paged else 0))
        # per-iteration scratch the recorder drains (reused, not realloc'd)
        self._it_admitted: List[int] = []
        self._it_completed: List[int] = []
        self._it_prefill = 0
        self._it_decode = 0
        self._it_spec_proposed = 0
        self._it_spec_accepted = 0
        self._it_sp_chunks = 0
        self.completed = 0
        self.shed = 0
        self.tokens = 0
        # peak concurrent sequences (live slots + the mid-prefill
        # admission): the capacity headline the paged A/B compares —
        # at a fixed KV-bytes budget, paging should hold several times
        # more of these than contiguous strips
        self.peak_live = 0
        # engine-local prefill-token count: the PREFILL_TOKENS Counter is
        # monotonic by contract (MetricsExporter rates), so stats() and
        # reset_stats() read/zero this mirror instead
        self.prefill_tokens = 0
        # prefix-cache mirrors (the pool's PREFIX_* counters stay
        # monotonic; these reset with the bench window)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefill_tokens_saved = 0
        self.cow_copies = 0
        # KV-transfer mirrors (the KV_XFER_* counters stay monotonic;
        # these reset with the bench window): blocks whose bytes crossed
        # this engine's boundary (fetched out OR spliced in), the raw
        # K/V bytes they carried, and blocks deduped away (source-side
        # skip on this engine's fetch, or arrival-side index hit)
        self.xfer_blocks = 0
        self.xfer_bytes = 0
        self.xfer_dedup = 0
        # speculative-decoding mirrors (the SPEC_* counters stay
        # monotonic; these reset with the bench window): drafts
        # proposed/accepted and verify-step dispatches
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_steps = 0
        # sequence-parallel prefill mirror (resets with the bench
        # window): chunks dispatched through the seqpar program
        self.seqpar_chunks = 0
        # overload mirrors (the PREEMPTIONS/DEADLINE_DROPS counters
        # stay monotonic; these reset with the bench window):
        # preemption EVENTS, distinct requests preempted at least
        # once, and expired-deadline queue drops
        self.preemptions = 0
        self.preempted = 0
        self.deadline_drops = 0
        # quant quality headline: argmax-match rate vs an fp32 oracle,
        # measured and recorded by the harness/bench (the engine cannot
        # compute it alone — it needs the oracle's outputs); -1 = never
        # measured. Quant engines surface it in stats() as _info-grade
        # data, off-quant engines' stats stay byte-identical
        self._argmax_match = -1.0
        # window base for the pool's monotonic eviction counter, so
        # stats()["prefix_evictions"] resets with its sibling mirrors
        self._evictions_base = 0
        self.t_first: Optional[float] = None
        self._occ_sum = 0.0          # mean occupancy over iterations
        self._occ_n = 0
        self._thread = threading.Thread(
            target=self._loop, name=f"serve-decode-{name}", daemon=True)
        self._thread.start()
        # the watchdog watches the PUBLIC health surface (health() /
        # pool_drift()), so it starts after the loop thread exists
        self.watchdog: Optional[EngineWatchdog] = None
        if bool(ec._resolved("watchdog")):
            self.watchdog = EngineWatchdog(
                self, ec.resolved_watchdog_config())

    # -- client side --------------------------------------------------------
    def validate(self, prompt: np.ndarray, max_new: Optional[int]) -> None:
        p = np.asarray(prompt, np.int32).ravel()
        if not 1 <= p.shape[0] <= self.config.max_prompt:
            raise ValueError(f"prompt length {p.shape[0]} outside "
                             f"[1, {self.config.max_prompt}]")
        if max_new is not None and not 1 <= int(max_new) <= self.config.max_new:
            raise ValueError(f"max_new {max_new} outside "
                             f"[1, {self.config.max_new}]")

    def _shed_class(self, priority: int) -> None:
        counter = self._shed_class_counters.get(priority)
        if counter is None:
            counter = Dashboard.get_or_create_counter(
                f"SHED_BY_CLASS[{self.name}.p{priority}]")
            self._shed_class_counters[priority] = counter
        counter.inc()

    def submit(self, prompt: np.ndarray, max_new: Optional[int] = None,
               ctx: Optional[trace.SpanContext] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None,
               xfer_info: Optional[Dict[str, int]] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one prompt; fast-rejects at the admission-queue cap,
        and (paged KV) when ``prompt + max_new`` needs more blocks than
        the whole pool holds — such a request could NEVER be admitted
        (``retriable=False``: no amount of retrying changes that), so
        queueing it would deadlock the admission head. ``ctx`` is the
        request's trace handoff token (or None). ``priority`` is the
        tenant class (0..7, higher = more important; None = class 1 —
        admission shares are weighted-fair, docs/SERVING.md "Overload
        and preemption"). ``deadline_s`` (None = none) is seconds from
        now past which the answer is worthless: an expired request is
        dropped at queue-POP time with :class:`DeadlineExceededError`
        before any prefill runs. ``xfer_info`` (disaggregated serving)
        is the :meth:`splice` accounting of the KV transfer that warmed
        this prompt's prefix, threaded onto the admit span so the trace
        attributes the cache hit to the wire. ``tenant`` (None = the
        ``-default_tenant`` fallback) names who pays: on a
        ``-cost_ledger`` engine the request carries a resource vector
        finalized into that tenant's aggregates
        (docs/OBSERVABILITY.md "Tenant accounting")."""
        self.validate(prompt, max_new)
        prio = DEFAULT_PRIORITY if priority is None else int(priority)
        if not 0 <= prio <= MAX_PRIORITY:
            raise ValueError(f"priority {prio} outside "
                             f"[0, {MAX_PRIORITY}]")
        deadline = None
        if deadline_s is not None:
            if float(deadline_s) <= 0:
                raise ValueError(f"deadline_s must be > 0, "
                                 f"got {deadline_s}")
            deadline = time.monotonic() + float(deadline_s)
        p = np.asarray(prompt, np.int32).ravel()
        req = _Request(p, int(max_new or self.config.max_new), ctx,
                       priority=prio, deadline=deadline, tenant=tenant)
        if xfer_info:
            req.xfer = dict(xfer_info)
        if self.ledger is not None:
            req.usage = self.ledger.usage(tenant)
        with self._cv:
            if self._stop.is_set():
                raise RuntimeError(f"decode engine {self.name!r} is stopped")
            if self._paged:
                need = self._pool.blocks_needed(p.shape[0] + req.max_new)
                if need > self._pool.capacity:
                    self.shed += 1
                    self.shed_counter.inc()
                    self._shed_class(prio)
                    if req.usage is not None:
                        self.ledger.finalize(req.usage, "shed")
                    raise OverloadedError(self.name, need,
                                          self._pool.capacity,
                                          what="kv block pool",
                                          retriable=False)
            if len(self._q) >= self.config.max_queue:
                self.shed += 1
                self.shed_counter.inc()
                self._shed_class(prio)
                if req.usage is not None:
                    self.ledger.finalize(req.usage, "shed")
                raise OverloadedError(self.name, len(self._q),
                                      self.config.max_queue)
            if self.t_first is None:
                self.t_first = req.t_enq
            self._q.append(req)
            self._cv.notify()
        return req.future

    # -- disaggregated prefill/decode (kv_transfer) -------------------------
    @property
    def supports_transfer(self) -> bool:
        """Whether this engine can be a disaggregation endpoint. The
        transfer plane moves chain-addressed FULL blocks, so it rides
        exactly the prefix-cache gate (paged + chunked + prefix_cache):
        without the content index there is nothing to splice INTO, and
        without chunked prefill nothing block-granular to fetch FROM."""
        return self._prefix

    def submit_prefill(self, prompt: np.ndarray,
                       known_hashes: Sequence[str] = (),
                       ctx: Optional[trace.SpanContext] = None,
                       tenant: Optional[str] = None) -> Future:
        """Enqueue a PREFILL-ONLY admission (the disaggregated fleet's
        stage 1): the prompt chunk-prefills into paged blocks exactly
        like a normal admission, but instead of going live the request
        resolves with ``{"xfer": payload, "snapshot_version",
        "staleness_s"}`` — the prompt's finished full blocks fetched to
        the host as a :mod:`kv_transfer` payload — and releases its
        reservation (the prefilled blocks stay behind in the CACHED
        tier, so a repeat prompt full-hits locally). ``known_hashes``
        are hex chain hashes the receiver already holds (router-tracked
        shipped set + heartbeat advertisement): those blocks ride as
        metadata only. Sheds like :func:`submit`; fails fast on engines
        without :attr:`supports_transfer`."""
        if not self.supports_transfer:
            raise RuntimeError(
                f"decode engine {self.name!r} cannot serve prefill-only "
                f"admissions (needs paged KV + chunked prefill + "
                f"prefix_cache — the transfer plane's gate)")
        self.validate(prompt, None)
        p = np.asarray(prompt, np.int32).ravel()
        # max_new=1 keeps the reservation arithmetic in-range; the
        # pf_only reservation is prompt-only regardless (nothing decodes)
        req = _Request(p, 1, ctx, tenant=tenant)
        req.pf_only = True
        req.known = frozenset(str(h) for h in known_hashes)
        if self.ledger is not None:
            req.usage = self.ledger.usage(tenant)
        with self._cv:
            if self._stop.is_set():
                raise RuntimeError(f"decode engine {self.name!r} is stopped")
            need = self._pool.blocks_needed(p.shape[0])
            if need > self._pool.capacity:
                self.shed += 1
                self.shed_counter.inc()
                self._shed_class(req.priority)
                if req.usage is not None:
                    self.ledger.finalize(req.usage, "shed")
                raise OverloadedError(self.name, need,
                                      self._pool.capacity,
                                      what="kv block pool",
                                      retriable=False)
            if len(self._q) >= self.config.max_queue:
                self.shed += 1
                self.shed_counter.inc()
                self._shed_class(req.priority)
                if req.usage is not None:
                    self.ledger.finalize(req.usage, "shed")
                raise OverloadedError(self.name, len(self._q),
                                      self.config.max_queue)
            if self.t_first is None:
                self.t_first = req.t_enq
            self._q.append(req)
            self._cv.notify()
        return req.future

    def splice(self, payload: dict, timeout_s: float = 30.0) -> Dict:
        """Splice a :mod:`kv_transfer` payload into this engine's block
        pool (the disaggregated fleet's arrival side) and return the
        accounting ``{"xfer_blocks", "xfer_bytes", "dedup_blocks"}``
        (plus ``"skipped"`` when nothing could apply). BLOCKING and
        thread-safe: the caches are loop-thread-owned, so the payload
        parks on ``_splice_q`` and the loop applies it between
        iterations — callers (the replica's drain thread) wait so the
        follow-up ``submit`` of the same prompt is guaranteed to see
        the warm prefix. Degrades, never raises: an unsupported engine,
        stopped loop, or timeout returns a zero accounting and the
        caller's submit re-prefills locally (correctness by
        construction — the full prompt always rides stage 2)."""
        zero = {"xfer_blocks": 0, "xfer_bytes": 0, "dedup_blocks": 0}
        if not self.supports_transfer:
            return dict(zero, skipped="unsupported")
        done = threading.Event()
        info: Dict = {}
        with self._cv:
            if self._stop.is_set():
                return dict(zero, skipped="stopped")
            self._splice_q.append((payload, done, info))
            self._cv.notify()
        if not done.wait(timeout_s):
            return dict(zero, skipped="timeout")
        out = dict(zero)
        out.update(info)
        return out

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    def health(self) -> dict:
        """The watchdog's poll surface: progress, liveness, and queue
        age WITHOUT the histogram sorts ``stats()`` pays — cheap enough
        to read several times a second against a saturated engine."""
        now = time.monotonic()
        with self._lock:
            depth = len(self._q)
            oldest = self._q.oldest_t_enq()
            age = (now - oldest) if oldest is not None else 0.0
            pinned = self._pinned_version
            snap = self._snap
        from .. import config

        # params staleness: how long since the SERVED source last moved
        # (the trainer's publish stream going silent). The verdict is
        # advisory — the engine keeps serving its frozen snapshot — and
        # clears automatically when a fenced restart republishes.
        params_age = self._manager.params_age_s()
        stale_after = float(config.get_flag("params_stale_after_s"))
        self.params_age_gauge.set(params_age)
        out = {
            "iters_total": self.iters_total,
            "last_iter_age_s": now - self._last_progress,
            "snapshot_version": (-1 if pinned is None else int(pinned)),
            "snapshot_epoch": (0 if snap is None
                               else int(getattr(snap, "epoch", 0))),
            "params_age_s": round(params_age, 4),
            "params_stale": self._manager.params_stale(
                stale_after, age_s=params_age),
            # a monolithic admission in flight counts as live: its
            # requests are already popped from the queue (queue_age_s
            # reads 0) and no slot is active yet, so without it a
            # wedged fused prefill would be invisible to the stall check
            "live_seqs": int(self._active.sum())
            + (1 if self._pf is not None else 0)
            + (1 if self._admitting else 0),
            "active_slots": int(self._active.sum()),
            "queue_depth": depth,
            "queue_age_s": age,
            # rides replica heartbeats -> the router's FLEET_PREEMPTS
            # gauge -> the opscenter replica rows
            "preemptions": self.preemptions,
            "stopped": self._stop.is_set(),
        }
        if self._prefix:
            # dedup ADVERTISEMENT (disaggregated serving): the chain
            # hashes content-addressed here, riding replica heartbeats
            # so the router's prefill stage skips shipping blocks this
            # engine already holds. Capped — a truncated advertisement
            # is weaker (those blocks cross the wire and dedup on
            # arrival), never wrong.
            out["cached_chains"] = [
                h.hex() for h in self._pool.indexed_hashes(
                    limit=_CHAIN_ADVERT_CAP)]
        if self.ledger is not None:
            # per-tenant cost, top-N bounded: rides replica heartbeats
            # so the router (and its replica_rows surface) can see who
            # is burning a replica without an obs-plane round trip
            out["tenants"] = self.ledger.heartbeat_rows()
        return out

    def pool_drift(self) -> Optional[str]:
        """Paged-KV accounting sanity: allocator invariant violations,
        or live blocks held while NOTHING is alive to hold them (no
        active slot, no admission mid-flight — chunked ``_pf`` or
        monolithic ``_admitting``, whose cold-bucket compile can hold
        reservations for seconds — nothing queued). Refcounted sharing
        is NOT a leak: ``n_live`` counts blocks with holders exactly
        once however many sequences share them, and prefix-cached
        blocks whose refcount hit zero sit in the pool's CACHED tier,
        outside ``n_live`` entirely. Sampled racily — the watchdog
        requires the verdict to persist across two polls before
        tripping."""
        if not self._paged:
            return None
        msg = self._pool.drift()
        if msg is not None:
            return msg
        # chaos-squeezed blocks are live-with-no-sequence BY DESIGN —
        # the leak heuristic must not read a staged pool squeeze as a
        # lost reservation
        live_blocks = self._pool.n_live - len(self._squeezed)
        if (live_blocks > 0 and not self._active.any()
                and self._pf is None and not self._admitting
                and not self._q):
            return (f"{live_blocks} live block(s) with zero live "
                    f"sequences (leaked reservation)")
        return None

    # -- engine loop --------------------------------------------------------
    def _req_hashes(self, req: _Request) -> List[bytes]:
        """The prompt's full-block hash chain, memoized per seed (the
        admission gate polls it every loop pass while a request waits
        for blocks; a pin move invalidates the memo)."""
        if req.hashes is None or req.hash_seed != self._hash_seed:
            req.hashes = chain_hashes(req.prompt, self._block_size,
                                      self._hash_seed)
            req.hash_seed = self._hash_seed
        return req.hashes

    def _prefix_usable_hits(self, req: _Request) -> int:
        """Net blocks the prefix cache saves ``req`` against the
        RECLAIMABLE supply (free + cached) the gate checks — a peek, no
        refcounts move. A live-shared hit is a pure saving; a hit on a
        CACHED block saves the prefill but still consumes one unit of
        that supply when lookup reactivates it, so it cancels out of
        the arithmetic (counting it double let an admission pass the
        gate and then run the allocator dry mid-reservation). A FULLY
        cached prompt costs one more fresh block: its last block gets
        copy-on-written so the first decode step can land P-1's K/V.
        Floored at ZERO: the CoW dup's cost is offset by its decref'd
        source returning to the reclaimable pool before the fresh
        allocation runs, so the true supply draw never exceeds the
        plain uncached reservation — without the floor, a block-aligned
        max-context prompt re-hitting its own cached blocks computed
        need = capacity + 1 and deadlocked the FIFO head forever
        (regression-tested)."""
        m, cached = self._pool.peek_counts(self._req_hashes(req))
        usable = m - 1 if (m and m * self._block_size == len(req.prompt)) \
            else m
        return max(0, usable - cached)

    def _reservation_blocks(self, req: _Request) -> int:
        """The admission's reservation size. Worst case by default:
        ``prompt + remaining generation`` worth of blocks (``prompt``
        already folds in any pre-preemption emitted tokens, which
        ``max_new`` also counts — hence the subtraction). With
        ``-preempt`` (optimistic admission) it is the PROMPT's blocks
        only — the generation grows block-by-block at decode time and
        preemption supplies blocks under pressure — EXCEPT for a
        request whose preemption budget is already spent: that one
        re-admits pessimistically, so it can never need growth, never
        be preempted again, and never churn (the anti-livelock
        backstop)."""
        if req.pf_only:
            # prefill-only admissions never decode: the prompt's blocks
            # are the whole reservation (no growth, no CoW headroom)
            return self._pool.blocks_needed(len(req.prompt))
        if self._preempt_on and req.preempts < self._preempt_budget:
            return self._pool.blocks_needed(len(req.prompt))
        return self._pool.blocks_needed(
            len(req.prompt) + req.max_new - len(req.out))

    def _blocks_cover(self, req: _Request, reserved: int) -> bool:
        """Paged-KV admission gate: a request admits only when its
        reservation (:meth:`_reservation_blocks` — worst-case by
        default, prompt-only under ``-preempt``, less what earlier
        arrivals of the same wave will take — and, with prefix
        caching, less the cached blocks it will share instead of
        allocate) fits the reclaimable pool (free list + evictable
        cached blocks). A false verdict leaves it QUEUED — completions
        free blocks at iteration granularity, so it admits as soon as
        enough return; only a request larger than the entire pool could
        wait forever, and ``submit`` shed that case up front (no
        admission deadlock, tested)."""
        if not self._paged:
            return True
        need = self._reservation_blocks(req)
        if self._prefix:
            need -= self._prefix_usable_hits(req)
        return need + reserved <= self._pool.n_free + self._pool.n_cached

    def _drop_expired(self, dropped: List[_Request]) -> None:
        """Deadline enforcement lands at queue-POP time: the scheduler
        hands back every expired request its scan touched, and the
        engine fails them HERE — before a single prefill FLOP is spent
        on an answer whose requester stopped waiting (the pre-PR
        behaviour ran the full prefill first). Futures resolve outside
        the engine lock: their done-callbacks are user code."""
        now = time.monotonic()
        for req in dropped:
            self.deadline_drops += 1
            self.deadline_counter.inc()
            if req.usage is not None:
                # the whole life was queue wait; attribution closes here
                req.usage.queue_wait_ms += (now - req.usage.t_wait0) * 1e3
                self._finalize_usage(req, "deadline", now)
            if trace.enabled() and req.ctx is not None:
                trace.record_span("queue.wait", req.ctx, req.t_enq, now,
                                  cause="deadline")
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(DeadlineExceededError(
                    f"decode request rid {req.rid} missed its deadline "
                    f"after {now - req.t_enq:.3f}s queued "
                    f"(engine {self.name!r})"))

    def _loop(self) -> None:
        chunked = self._budget > 0
        while True:
            splices: List[tuple] = []
            with self._cv:
                while (not self._q and self._pf is None
                       and not self._active.any()
                       and not self._splice_q
                       and not self._stop.is_set()):
                    self._cv.wait()
                if (self._stop.is_set() and not self._q
                        and self._pf is None and not self._active.any()):
                    # release any splice waiters before the loop dies —
                    # a blocked replica drain thread must not hang on a
                    # transfer the loop will never apply
                    while self._splice_q:
                        _, done, info = self._splice_q.popleft()
                        info["skipped"] = "stopped"
                        done.set()
                    return
                if self._splice_q:
                    splices = list(self._splice_q)
                    self._splice_q.clear()
                # admission pops through the weighted-fair lane
                # scheduler (expired deadlines dropped at pop,
                # bounded lookahead past a block-starved head) onto
                # the explicit free-slot set and, when paged, gates on
                # the block pool covering each arrival's reservation
                now = time.monotonic()
                arrivals: List[_Request] = []
                expired: List[_Request] = []
                if chunked:
                    # one admission prefills at a time; the NEXT request
                    # is only picked up once the current one goes live
                    if self._pf is None and self._free_q and self._q:
                        req, expired = self._q.pop_admissible(
                            now, lambda r: self._blocks_cover(r, 0))
                        if req is not None:
                            arrivals.append(req)
                else:
                    reserved = 0
                    while len(arrivals) < len(self._free_q) and self._q:
                        req, exp = self._q.pop_admissible(
                            now,
                            lambda r, res=reserved:
                            self._blocks_cover(r, res))
                        expired.extend(exp)
                        if req is None:
                            break
                        if self._paged:
                            reserved += self._reservation_blocks(req)
                        arrivals.append(req)
            if expired:
                self._drop_expired(expired)
            # the progress clock restarts when the loop picks work up:
            # last_iter_age_s then measures how long THIS pass has been
            # stuck, not how long the engine idled beforehand (an idle
            # engine is not a stalled one — the watchdog's distinction)
            t_work0 = time.monotonic()
            self._last_progress = t_work0
            self._it_admitted.clear()
            self._it_completed.clear()
            self._it_prefill = self._it_decode = 0
            self._it_spec_proposed = self._it_spec_accepted = 0
            self._it_sp_chunks = 0
            step_ms = 0.0
            worked = False
            try:
                # inbound KV transfers apply OUTSIDE the engine lock on
                # this (loop) thread — the only thread allowed to
                # reassign the donated caches. A bad payload degrades
                # (accounting says so); the waiter is released either way
                for payload, done, info in splices:
                    try:
                        info.update(self._apply_splice(payload))
                    except Exception as exc:    # pragma: no cover
                        info["skipped"] = f"splice failed: {exc}"
                    finally:
                        done.set()
                    worked = True
                if chunked:
                    if arrivals:
                        self._begin_prefill(arrivals[0],
                                            self._free_q.popleft())
                    # zero-cost admissions (a full prefix hit goes live
                    # without a single prefill chunk) must not consume
                    # the iteration's one admission slot: keep admitting
                    # until a chunk is actually pending or nothing is
                    # admissible, so a full-hit-heavy trace admits at
                    # slot rate instead of one request per iteration
                    # (the per-iteration chunk budget below is what
                    # bounds ITL, and these admissions cost no chunk)
                    while self._pf is None and self._free_q:
                        with self._cv:
                            if not self._q:
                                break
                            req, exp = self._q.pop_admissible(
                                time.monotonic(),
                                lambda r: self._blocks_cover(r, 0))
                        if exp:
                            self._drop_expired(exp)
                        if req is None:
                            break
                        arrivals.append(req)
                        self._begin_prefill(req, self._free_q.popleft())
                        if req.slot == -1:
                            # the reservation raced a pool claimant and
                            # the request was requeued — retry next
                            # iteration rather than spinning here
                            break
                    if self._pf is not None:
                        # AT MOST one budget-sized chunk per iteration:
                        # the stall an admission can add to every live
                        # generation's next token is one chunk of work
                        self._prefill_one_chunk()
                        worked = True
                else:
                    if arrivals:
                        self._admitting = True
                        try:
                            self._admit(arrivals)
                        finally:
                            self._admitting = False
                        worked = True
                live = int(self._active.sum()) + (self._pf is not None)
                if live > self.peak_live:
                    self.peak_live = live
                if self._active.any():
                    t_step0 = time.monotonic()
                    self._step()
                    step_ms = (time.monotonic() - t_step0) * 1e3
                    worked = True
            except Exception as exc:          # pragma: no cover - defensive
                # arrivals are already popped from the queue but may not
                # be slotted yet — include them so their futures fail too
                self._fail_all(exc, arrivals)
                return
            if worked:
                self._record_iteration(t_work0, step_ms)
            elif not arrivals and not expired:
                # nothing live and nothing admissible: the queue holds
                # only block-starved waiters (a budget-exhausted
                # pessimistic re-admission, or a chaos-squeezed pool) —
                # yield briefly instead of hot-spinning until blocks
                # free
                time.sleep(0.0005)

    def _record_iteration(self, t_work0: float, step_ms: float) -> None:
        """One iteration retired: bump the progress clock/counters and
        append the flight-recorder record. Reads of queue/pool state are
        intentionally lock-light — these are gauge samples for the black
        box, not accounting."""
        now = time.monotonic()
        self.iters_total += 1
        self.iters_counter.inc()
        self._last_progress = now
        it_block_s = 0.0
        if self.ledger is not None:
            # KV residency integrates here: every admitted sequence is
            # charged reserved-blocks x this iteration's wall (host
            # floats only — same cost posture as the recorder itself)
            dt = now - t_work0
            reqs = self._admitted_requests()
            self.ledger.charge_iteration(reqs, dt)
            it_block_s = dt * sum(len(r.blocks) for r in reqs)
        recorder = self.recorder
        if recorder is None:
            return
        try:
            oldest = self._q.oldest_t_enq()
        except (IndexError, RuntimeError):   # racing a concurrent submit
            oldest = None
        recorder.record((
            self.iters_total, now, (now - t_work0) * 1e3, step_ms,
            int(self._active.sum()), 1 if self._pf is not None else 0,
            len(self._q),
            0.0 if oldest is None else (now - oldest) * 1e3,
            self._it_prefill, self._it_decode,
            self._pool.n_free if self._paged else -1,
            self._pool.n_live if self._paged else -1,
            self._pool.n_shared if self._paged else -1,
            self._snap.version if self._snap is not None else -1,
            tuple(self._it_admitted), tuple(self._it_completed),
            self._it_spec_proposed if self._spec else -1,
            self._it_spec_accepted if self._spec else -1,
            (1 if self._kv_quant else 0) if self._paged else -1,
            # written-block occupancy PROXY (live + cached pool blocks)
            # — the real nonzero-scale count lives on the device, and
            # the recorder's cost posture forbids a per-iteration sync
            (self._pool.n_live + self._pool.n_cached)
            if self._kv_quant else -1,
            # tenant accounting tail (FIELDS append at the END; -1 =
            # ledger off): this iteration's KV block-seconds charge and
            # the live tenant cardinality
            round(it_block_s, 6) if self.ledger is not None else -1.0,
            (self.ledger.tenant_count() if self.ledger is not None
             else -1),
            # seqpar tail (FIELDS append at the END; -1 = prefill_sp
            # off): chunks this iteration dispatched through the
            # sequence-parallel program
            self._it_sp_chunks if self._sp else -1))

    def _seed_for(self, version: int) -> bytes:
        """Hash-chain seed for a pinned snapshot version. kv_quant tags
        the seed: cached K/V bytes are a function of (token prefix,
        params version, POOL ENCODING) — an int8 block and an fp block
        for the same prefix hold different bytes, so their chain
        identities must differ. This is also what makes cross-mode KV
        transfer degrade cleanly: a quant payload arriving at a
        kv_quant=none replica fails the seed/dtype checks and the
        receiver re-prefills locally (chaos-tested)."""
        if self._kv_quant:
            return f"{int(version)}/int8".encode()
        return str(int(version)).encode()

    def _maybe_refresh(self, hold: bool = False) -> None:
        """Move the pinned snapshot only while NO generation is in
        flight — neither live slots, nor a mid-prefill admission, nor
        (``-preempt``) a PREEMPTED request awaiting resume anywhere in
        the queue (``hold`` covers the one being re-admitted right
        now, already popped). A resume recomputes its tail from
        prompt + emitted tokens, and that recompute is only
        bit-identical under the SAME params the first life pinned — so
        preemption extends the pin's lifetime across the eviction gap,
        and the surfaced trade is staleness, never a mixed-version
        generation."""
        snap = self._snap
        if snap is None:
            snap = self._manager.current()
        elif (not hold and not self._active.any() and self._pf is None
                and self._q.n_resumed == 0):
            snap = self._manager.ensure_fresh(self.config.max_staleness_s)
        if self._snap is not snap or self._pinned is None:
            # the decode copy memoizes on snapshot VERSION: a drain/
            # re-pin cycle (or a forced re-publish) without an
            # intervening version move reuses the existing replica —
            # the full-tree copy only happens when training actually
            # produced new params
            if self._pinned is None or snap.version != self._pinned_version:
                # one copy per pinned VERSION, amortized over the whole
                # generation stream the pin serves: tp=1 replicates onto
                # one device (snapshot.replicate_for_decode — ~10x
                # per-step wall through the partitioner otherwise,
                # sharded fallback multi-process); tp>1 reshards onto
                # the decode mesh (snapshot.shard_for_decode), matching
                # the pre-partitioned programs' in_shardings exactly
                with trace.span("snapshot.pin", engine=self.name,
                                version=snap.version):
                    # decode_param_quant=int8: quantize HOST-side before
                    # the device_put — the pin ships ~4x fewer bytes and
                    # the programs dequantize at compile time. Host
                    # numpy on purpose: this runs on the loop thread,
                    # where building a jit would be an RT106 hazard.
                    value = (quantize_decode_params(snap.value)
                             if self._param_quant == "int8"
                             else snap.value)
                    if self._tp > 1:
                        self._pinned = shard_for_decode(
                            value, self._decode_mesh,
                            self._param_shardings)
                    else:
                        self._pinned = replicate_for_decode(value)
                self._pinned_version = snap.version
                self.pin_copies += 1
            self._snap = snap
            if self._prefix:
                # the hash chain is scoped to the params the K/V was
                # computed under: when the pin moves, cached blocks are
                # garbage to the new version — flush them (the version
                # seed alone would keep them resident but unreachable,
                # silently shrinking effective capacity)
                seed = self._seed_for(snap.version)
                if seed != self._hash_seed:
                    self._hash_seed = seed
                    self._pool.flush_cache()

    def _reserve_blocks(self, req: _Request, slot: int) -> None:
        """Paged KV: build the admission's reservation
        (:meth:`_reservation_blocks` — ``prompt + max_new`` positions
        worst-case, the prompt's positions only under optimistic
        ``-preempt`` admission) and install it in the slot's block
        table row — the loop's ``_blocks_cover`` gate guaranteed
        coverage, so this cannot fail (a racing chaos pool squeeze is
        the one exception; ``_begin_prefill`` requeues on it).

        With prefix caching the reservation SPLICES: the longest cached
        prefix of the prompt is claimed from the content index (those
        blocks gain a holder instead of being allocated) and only the
        remainder comes off the free list. A fully cached prompt
        additionally copy-on-writes its LAST matched block: the first
        decode step recomputes position ``P - 1`` and writes its K/V
        there, and a write must never land in a shared block — the copy
        happens here, host-dispatched, before the table is ever handed
        to the jitted step."""
        if not self._paged:
            return
        total = self._reservation_blocks(req)
        matched: List[int] = []
        hashes: List[bytes] = []
        full_hit_cow = False
        if self._prefix:
            hashes = self._req_hashes(req)
            matched = self._pool.lookup(hashes)
            req.n_hit = len(matched)
            req.full_hit = bool(matched) and (
                len(matched) * self._block_size == len(req.prompt))
            # claimed blocks land on the request IMMEDIATELY: if an
            # alloc below races a concurrent pool claimant and raises,
            # the requeue path can decref exactly what was taken
            req.blocks = matched
            # a prefill-only full hit skips the CoW: nothing will ever
            # WRITE this sequence (no decode step recomputes P-1), so
            # the last matched block stays shared and the payload
            # fetches straight from the cached blocks
            if req.full_hit and not req.pf_only:
                shared_last = matched[-1]
                dup = self._pool.alloc(1)[0]
                if self._kv_quant:
                    (self._k_cache, self._v_cache, self._k_scales,
                     self._v_scales) = self._cow_fn(
                        self._k_cache, self._v_cache, self._k_scales,
                        self._v_scales, np.int32(shared_last),
                        np.int32(dup))
                else:
                    self._k_cache, self._v_cache = self._cow_fn(
                        self._k_cache, self._v_cache,
                        np.int32(shared_last), np.int32(dup))
                self._pool.decref([shared_last])
                matched[-1] = dup
                full_hit_cow = True
            req.saved = (len(req.prompt) if req.full_hit
                         else req.n_hit * self._block_size)
        req.blocks = matched + self._pool.alloc(total - len(matched))
        # stats commit only once the WHOLE reservation stands: a
        # squeeze-raced alloc raise requeues the request, and its
        # re-admission must not count the same hits/saves twice
        if self._prefix:
            if full_hit_cow:
                self.cow_copies += 1
            self.prefix_hits += req.n_hit
            self.prefix_misses += len(hashes) - req.n_hit
            self.prefill_tokens_saved += req.saved
            if req.usage is not None:
                # same commit point as the engine mirror, so the
                # per-tenant saved sum reconciles exactly (requeue-on-
                # race never reaches here; a preempted resume recommits
                # on both sides alike)
                req.usage.prefill_tokens_saved += req.saved
        row = self._block_tables[slot]
        row[:] = SCRATCH_BLOCK
        row[: total] = req.blocks

    def _release_seq(self, req: _Request) -> None:
        """Completion (eos / max_new / eos-at-first-token): the slot
        returns to the free set and, paged, the reservation's blocks
        drop this holder — at iteration granularity, so a same-
        iteration queued admission can reuse them on the very next
        loop pass (tested). ``decref``, not ``free``: a block shared
        with a live sequence stays live under its remaining holders,
        and a content-addressed block parks in the pool's cached-LRU
        tier instead of losing its identity — the next shared-prefix
        arrival reactivates it without re-prefilling. Decref TAIL
        first: release order is LRU order, and peek/lookup walk the
        hash chain head-first, so eviction must shrink a chain from
        its END — a head-first release would have pressure evict the
        chain's first block and strand every cached suffix block as
        unreachable dead weight (the vLLM eviction convention)."""
        if self._paged and req.blocks:
            self._pool.decref(reversed(req.blocks))
            req.blocks = []
            self._block_tables[req.slot][:] = SCRATCH_BLOCK
        self._free_q.append(req.slot)

    def _begin_prefill(self, req: _Request, slot: int) -> None:
        """Reserve ``slot`` (and its KV blocks) and pin the snapshot for
        one admission; its prompt then prefills one chunk per iteration.
        The reserved-not-live admission keeps its blocks for its whole
        lifetime — a concurrent wave cannot steal a mid-prefill
        sequence's cache out from under it."""
        self._maybe_refresh(hold=req.resumed)
        req.version = self._snap.version
        req.slot = slot
        try:
            self._reserve_blocks(req, slot)
        except RuntimeError:
            # a concurrent pool claimant (the chaos pool squeeze is the
            # one in-contract case) raced the admission gate: requeue
            # the request instead of killing the loop thread — exactly
            # a preemption-before-any-work, minus the accounting
            if req.blocks:
                self._pool.decref(reversed(req.blocks))
                req.blocks = []
            self._block_tables[slot][:] = SCRATCH_BLOCK
            self._free_q.append(slot)
            req.slot = -1
            req.hashes = None
            req.n_hit = 0
            req.full_hit = False
            with self._cv:
                self._q.appendleft(req)
            return
        req.pf_chunks = 0
        req.t_admit = time.monotonic()   # queue.wait ends here
        if req.usage is not None:
            req.usage.queue_wait_ms += (req.t_admit
                                        - req.usage.t_wait0) * 1e3
        if self._spec:
            # prompt-lookup drafting indexes the prompt up front; every
            # emitted token extends the index incrementally from here
            req.drafter = _PromptLookup()
            req.drafter.extend(req.prompt)
        self._it_admitted.append(req.rid)
        if self._prefix and req.full_hit and req.pf_only:
            # prefill-only admission of a fully cached prompt: every
            # block is already resident (and stays shared — reservation
            # skipped the CoW), so the payload fetches immediately and
            # the slot never goes live
            self._pf = None
            self._finish_prefill_only(req, chunks=0)
            return
        if self._prefix and req.full_hit:
            # the WHOLE prompt was cached: no prefill at all. The slot
            # goes live at position P-1 with the prompt's last token as
            # input — the next fused step recomputes that position's
            # K/V (into the block CoW'd at reservation), and its output
            # IS the request's first token (TTFT = one decode step).
            if trace.enabled() and req.ctx is not None:
                now = time.monotonic()
                extra = dict(self._mesh_attrs)
                if req.preempts:
                    extra["preempted"] = req.preempts
                if req.xfer:
                    # the splice that warmed this prefix (disaggregated
                    # stage 2): the trace links the hit to the wire
                    extra["xfer_blocks"] = req.xfer.get("xfer_blocks", 0)
                    extra["xfer_bytes"] = req.xfer.get("xfer_bytes", 0)
                    extra["dedup_blocks"] = req.xfer.get(
                        "dedup_blocks", 0)
                trace.record_span("queue.wait", req.ctx, req.t_enq,
                                  req.t_admit, cause="admission")
                trace.record_span(
                    "decode.admit", req.ctx, req.t_admit, now,
                    slot=slot, prompt_len=len(req.prompt), chunks=0,
                    budget=self._budget, snapshot_version=req.version,
                    blocks=len(req.blocks), pool_free=self._pool.n_free,
                    prefix_hit_blocks=req.n_hit,
                    prefill_tokens_saved=req.saved, **extra)
            # a RESUMED full hit already recorded its TTFT in its first
            # life: the next fused-step token is an inter-token gap
            req.ttft_pending = not req.resumed
            # the ITL base moves to ADMISSION: the next step's first
            # token records TTFT, but a speculative window's extra
            # tokens divide (now - t_last) as ITL samples — left at
            # t_enq, a queued full hit would bleed its whole queue wait
            # into the ITL histogram (review-found, regression-tested)
            req.t_last = req.t_admit
            self._slot_req[slot] = req
            self._tok[slot] = int(req.prompt[-1])
            self._pos[slot] = len(req.prompt) - 1
            self._active[slot] = True
            self._pf = None
            return
        # chunked prefill starts at the first UNCACHED token (block-
        # aligned); the matched prefix blocks are already in the table
        req.pf_off = req.n_hit * self._block_size if self._prefix else 0
        req.pf_reg = req.n_hit
        # seqpar routing decides per REQUEST, once: prompts at/above the
        # threshold take the budget * tp sequence-parallel chunks, the
        # rest keep the single-lane program bit-for-bit
        req.sp = self._sp and len(req.prompt) >= self._sp_threshold
        self._pf = req

    def _prefill_one_chunk(self) -> None:
        """Run ONE budget-sized chunk of the in-flight admission's
        prefill; on the final chunk the first token falls out and the
        slot goes live (or resolves immediately on eos-at-first-token,
        never occupying the slot)."""
        req = self._pf
        sp = req.sp
        C = self._sp_chunk if sp else self._budget
        off = req.pf_off
        n = min(C, len(req.prompt) - off)
        toks = np.zeros(C, np.int32)
        toks[: n] = req.prompt[off: off + n]
        tracing = trace.enabled()
        t0 = time.monotonic() if tracing else 0.0
        if self._paged and self._kv_quant:
            (self._k_cache, self._v_cache, self._k_scales,
             self._v_scales, logits) = self._chunk_fn(
                self._pinned, self._k_cache, self._v_cache,
                self._k_scales, self._v_scales, self._block_tables,
                np.int32(req.slot), toks, np.int32(off), np.int32(n))
        elif self._paged:
            chunk_fn = self._chunk_sp_fn if sp else self._chunk_fn
            self._k_cache, self._v_cache, logits = chunk_fn(
                self._pinned, self._k_cache, self._v_cache,
                self._block_tables, np.int32(req.slot), toks,
                np.int32(off), np.int32(n))
        else:
            self._k_cache, self._v_cache, logits = self._chunk_fn(
                self._pinned, self._k_cache, self._v_cache,
                np.int32(req.slot), toks, np.int32(off), np.int32(n))
        # block per chunk: letting chunk dispatches run ahead
        # asynchronously looks free, but an idle->busy transition can
        # queue several chunks on the device and the NEXT fused step's
        # sync pays for all of them at once — exactly the unbounded ITL
        # spike the budget exists to prevent (measured: p99 went from
        # ~1 chunk+step to >100 ms under ramp). One chunk per iteration,
        # retired per iteration, keeps the bound honest.
        jax.block_until_ready(self._k_cache)
        req.pf_off = off + n
        req.pf_chunks += 1
        if sp:
            self.seqpar_chunks += 1
            self._it_sp_chunks += 1
        self.prefill_tokens += n
        self.prefill_tok_counter.inc(n)
        self._it_prefill += n
        if req.usage is not None:
            req.usage.prefill_tokens += n
            if req.resumed:
                # preemption-with-recompute: a resume life's prefill
                # re-computes work a first life already paid for — the
                # vector carries it separately so showback can see the
                # preemption tax (still counted in prefill_tokens: the
                # conservation identity tracks FLOPs actually spent)
                req.usage.recompute_tokens += n
        if self._prefix:
            # every prompt block this chunk COMPLETED gains its content
            # identity now, not at release: a concurrent same-prefix
            # arrival can share a still-prefilling sequence's blocks
            # (register no-ops when an identical block beat us to it)
            hashes = self._req_hashes(req)
            while (req.pf_reg < len(hashes)
                   and (req.pf_reg + 1) * self._block_size <= req.pf_off):
                self._pool.register(req.blocks[req.pf_reg],
                                    hashes[req.pf_reg])
                req.pf_reg += 1
        final = req.pf_off >= len(req.prompt)
        if tracing and req.ctx is not None:
            # seqpar ENGINES annotate every chunk span (sp=0 marks a
            # below-threshold prompt on the single-lane program); off-sp
            # engines' spans stay flat — the metrics regression contract
            sp_attrs = ({"sp": int(sp), "sp_backend": self._sp_backend}
                        if self._sp else {})
            trace.record_span(
                "decode.prefill_chunk", req.ctx, t0, time.monotonic(),
                slot=req.slot, offset=off, chunk=req.pf_chunks - 1,
                tokens=n, budget=C, **sp_attrs)
        if not final:
            return
        if req.pf_only:
            # prefill-only admission (disaggregated stage 1): no first
            # token — the prompt's finished blocks ARE the result. The
            # logits fall on the floor by design: the decode side
            # recomputes P-1 through its own full-hit CoW step, which
            # is what keeps disaggregated output bit-identical.
            self._pf = None
            self._finish_prefill_only(req, chunks=req.pf_chunks)
            return
        # final chunk: the prompt's last real position's logits are the
        # first generated token (exactly the monolithic prefill's gather)
        tok0 = int(np.argmax(np.asarray(logits)))
        now = time.monotonic()
        if req.resumed:
            # preemption recompute: TTFT already happened in the first
            # life — this token is an inter-token gap, and the sample
            # honestly carries the whole preemption stall (t_last is
            # the last PRE-preemption emission)
            self.itl_hist.record((now - req.t_last) * 1e3)
        else:
            self.ttft_hist.record((now - req.t_enq) * 1e3)
        req.t_last = now
        self.tokens += 1
        self.decode_tok_counter.inc()
        self._it_decode += 1
        if req.usage is not None:
            req.usage.decode_tokens += 1
        req.out.append(tok0)
        if req.drafter is not None:
            req.drafter.extend((tok0,))
        if tracing and req.ctx is not None:
            trace.record_span("queue.wait", req.ctx, req.t_enq,
                              req.t_admit, cause="admission")
            extra = ({"blocks": len(req.blocks),
                      "pool_free": self._pool.n_free}
                     if self._paged else {})
            if self._prefix:
                extra["prefix_hit_blocks"] = req.n_hit
                extra["prefill_tokens_saved"] = req.saved
            if req.preempts:
                extra["preempted"] = req.preempts
            if req.xfer:
                extra["xfer_blocks"] = req.xfer.get("xfer_blocks", 0)
                extra["xfer_bytes"] = req.xfer.get("xfer_bytes", 0)
                extra["dedup_blocks"] = req.xfer.get("dedup_blocks", 0)
            extra.update(self._mesh_attrs)
            trace.record_span(
                "decode.admit", req.ctx, req.t_admit, now, slot=req.slot,
                prompt_len=len(req.prompt), chunks=req.pf_chunks,
                budget=C, snapshot_version=req.version, **extra)
        self._pf = None
        if self._finished(req, tok0):
            # slot never goes live; the inserted K/V is dead weight a
            # later admission overwrites (tested) — slot and blocks
            # return to the free sets immediately
            self._release_seq(req)
            self._resolve(req)
            return
        self._slot_req[req.slot] = req
        self._tok[req.slot] = tok0
        self._pos[req.slot] = len(req.prompt)
        self._active[req.slot] = True

    def _finish_prefill_only(self, req: _Request, chunks: int) -> None:
        """Prefill-only admission complete (disaggregated stage 1): the
        prompt's full blocks are prefilled (or cache-resident), so fetch
        the ones the receiver did NOT advertise to the host, build the
        :mod:`kv_transfer` payload, release the reservation (the blocks
        park in the CACHED tier — a repeat prompt full-hits locally),
        and resolve the future with the payload instead of tokens. Runs
        on the loop thread: the caches are loop-thread-owned."""
        hashes = self._req_hashes(req)
        # a quantized source ships the pool's native int8 bytes + each
        # block's per-layer scale columns; the payload dtype tells the
        # receiver which splice contract applies (the seed check already
        # scoped the hashes to the same encoding)
        payload = kv_transfer.new_payload(
            len(req.prompt), self._block_size, req.version,
            (self._model_cfg.n_layers, self._block_size,
             self._model_cfg.d_model),
            np.int8 if self._kv_quant else self._model_cfg.dtype)
        if req.tenant:
            # the receiving engine's ledger charges the splice-in bytes
            # to the originating tenant; absent key = default tenant
            payload["tenant"] = req.tenant
        shipped = 0
        for i, h in enumerate(hashes):
            hx = h.hex()
            if hx in req.known:
                # source-side dedup: the receiver advertised this chain
                # prefix — the hash rides, the bytes stay home
                kv_transfer.add_block(payload, hx)
                continue
            if self._kv_quant:
                k, v, ks, vs = self._fetch_fn(
                    self._k_cache, self._v_cache, self._k_scales,
                    self._v_scales, np.int32(req.blocks[i]))
                kv_transfer.add_block(payload, hx, np.asarray(k),
                                      np.asarray(v), np.asarray(ks),
                                      np.asarray(vs))
            else:
                k, v = self._fetch_fn(self._k_cache, self._v_cache,
                                      np.int32(req.blocks[i]))
                kv_transfer.add_block(payload, hx, np.asarray(k),
                                      np.asarray(v))
            shipped += 1
        nbytes = kv_transfer.payload_bytes(payload)
        dedup = int(payload["dedup_blocks"])
        self.xfer_blocks += shipped
        self.xfer_bytes += nbytes
        self.xfer_dedup += dedup
        self.xfer_blocks_counter.inc(shipped)
        self.xfer_bytes_counter.inc(nbytes)
        if dedup:
            self.xfer_dedup_counter.inc(dedup)
        if req.usage is not None:
            req.usage.xfer_bytes += nbytes
        now = time.monotonic()
        if trace.enabled() and req.ctx is not None:
            trace.record_span("queue.wait", req.ctx, req.t_enq,
                              req.t_admit, cause="admission")
            trace.record_span(
                "decode.admit", req.ctx, req.t_admit, now,
                slot=req.slot, prompt_len=len(req.prompt), chunks=chunks,
                budget=self._budget, snapshot_version=req.version,
                blocks=len(req.blocks), pool_free=self._pool.n_free,
                prefix_hit_blocks=req.n_hit,
                prefill_tokens_saved=req.saved, prefill_only=True,
                xfer_blocks=shipped, xfer_bytes=nbytes,
                dedup_blocks=dedup, **self._mesh_attrs)
        self._finalize_usage(req, "completed", now)
        self._release_seq(req)
        self.completed += 1
        self._it_completed.append(req.rid)
        if req.future.set_running_or_notify_cancel():
            req.future.set_result({
                "xfer": payload,
                "snapshot_version": req.version,
                "staleness_s": self._manager.staleness_s(self._snap)})

    def _apply_splice(self, payload: dict) -> Dict:
        """Splice one received payload into the pool (loop thread).
        Walks the hash chain head-first: an already-indexed hash is an
        arrival-side dedup hit; a hash with shipped bytes allocates one
        block, writes the K/V via the jitted splice program, registers
        the content identity, and decrefs straight into the CACHED tier
        (claimable by the follow-up admission's lookup, evictable under
        pressure). The walk STOPS at the first gap — chain hashes only
        have meaning as prefixes — so a chaos-dropped payload or a full
        pool degrades to a shorter warm prefix, never a wrong one. A
        payload whose pinned-version seed disagrees is skipped whole
        (splicing stale-params K/V would poison the content index)."""
        info: Dict = {"xfer_blocks": 0, "xfer_bytes": 0,
                      "dedup_blocks": 0}
        why = kv_transfer.validate(payload)
        if why is not None:
            info["skipped"] = why
            return info
        # pin a snapshot if nothing has yet (a fresh decode replica may
        # see its first transfer before its first request), then check
        # the payload's version against OUR hash-chain seed
        self._maybe_refresh()
        if self._seed_for(int(payload["snapshot_version"])) != \
                self._hash_seed:
            info["skipped"] = (
                f"snapshot version {payload['snapshot_version']} != "
                f"pinned {self._pinned_version}")
            return info
        if int(payload["block_size"]) != self._block_size:
            info["skipped"] = (f"block size {payload['block_size']} != "
                               f"{self._block_size}")
            return info
        cfg = self._model_cfg
        shape = tuple(int(d) for d in payload["shape"])
        if shape != (cfg.n_layers, self._block_size, cfg.d_model):
            info["skipped"] = f"block shape {shape} mismatch"
            return info
        dtype = np.dtype(payload["dtype"])
        # the pool's NATIVE dtype, not the model's: an int8 engine
        # splices int8 bytes. The encoding-tagged hash seed means a
        # cross-mode payload normally fails the seed check above; this
        # check is the belt to that suspender (same-version payloads
        # from a differently-configured fleet must still degrade to a
        # local re-prefill, never splice mis-typed bytes)
        expect = (np.dtype(np.int8) if self._kv_quant
                  else np.dtype(cfg.dtype))
        if dtype != expect:
            info["skipped"] = f"dtype {dtype} != {expect}"
            return info
        per_block = kv_transfer.block_nbytes(shape, dtype)
        blocks = payload.get("blocks") or {}
        for hx in payload["hashes"]:
            h = bytes.fromhex(hx)
            if self._pool.peek([h]):
                info["dedup_blocks"] += 1
                continue
            rec = blocks.get(hx)
            if rec is None or not self._pool.can_alloc(1):
                break
            try:
                k, v = kv_transfer.unpack_block(rec, shape, dtype)
                scales = (kv_transfer.unpack_scales(rec, cfg.n_layers)
                          if self._kv_quant else None)
            except ValueError:
                break
            if self._kv_quant and scales is None:
                # int8 bytes without their scales are undecodable —
                # stop the walk (prefix semantics) and re-prefill
                break
            blk = self._pool.alloc(1)[0]
            if self._kv_quant:
                (self._k_cache, self._v_cache, self._k_scales,
                 self._v_scales) = self._splice_fn(
                    self._k_cache, self._v_cache, self._k_scales,
                    self._v_scales, np.int32(blk), k, v,
                    scales[0], scales[1])
            else:
                self._k_cache, self._v_cache = self._splice_fn(
                    self._k_cache, self._v_cache, np.int32(blk), k, v)
            self._pool.register(blk, h)
            self._pool.decref([blk])
            info["xfer_blocks"] += 1
            info["xfer_bytes"] += per_block
        self.xfer_blocks += info["xfer_blocks"]
        self.xfer_bytes += info["xfer_bytes"]
        self.xfer_dedup += info["dedup_blocks"]
        if info["xfer_blocks"]:
            self.xfer_blocks_counter.inc(info["xfer_blocks"])
            self.xfer_bytes_counter.inc(info["xfer_bytes"])
        if info["dedup_blocks"]:
            self.xfer_dedup_counter.inc(info["dedup_blocks"])
        if self.ledger is not None and info["xfer_bytes"]:
            # splice-in bytes charge directly (no request exists yet to
            # carry them): the payload's optional "tenant" tag names
            # who pays, a legacy payload bills the default tenant —
            # same site, same amount as the engine mirror above, so
            # the per-tenant xfer sum reconciles exactly
            self.ledger.charge(payload.get("tenant"),
                               xfer_bytes=info["xfer_bytes"])
        return info

    def _admit(self, arrivals: List[_Request]) -> None:
        t_admit = time.monotonic()     # queue.wait ends / admission begins
        self._maybe_refresh()
        version = self._snap.version
        # phase 1 — dispatch every admission without blocking: arrivals
        # group by PROMPT bucket, each group pads to a power-of-two batch
        # bucket and runs ONE fused prefill+insert. Placement: contiguous
        # pads point their slot at slots[0] (the cache_insert DUS chain
        # overwrites them); paged pads carry all-scratch block-table rows
        # (their scatter lands in the sentinel block nothing reads)
        by_bucket: dict = {}
        for req in arrivals:
            pb = bucket_for(len(req.prompt), self._prompt_buckets)
            by_bucket.setdefault(pb, []).append(req)
        staged = []
        for pb, group in by_bucket.items():
            bb = bucket_for(len(group), self._batch_buckets)
            toks = np.zeros((bb, pb), np.int32)
            lens = np.ones(bb, np.int32)
            slots = np.empty(bb, np.int32)
            bts = (np.full((bb, self._blocks_per_seq), SCRATCH_BLOCK,
                           np.int32) if self._paged else None)
            for i, req in enumerate(group):
                toks[i, : len(req.prompt)] = req.prompt
                lens[i] = len(req.prompt)
                # popleft off the persistent free-slot deque (kept
                # current at admit/complete; list.pop(0) here was
                # O(slots) per admission, O(slots^2) across a wave)
                slot = self._free_q.popleft()
                slots[i] = slot
                req.slot = slot
                self._reserve_blocks(req, slot)
                if self._spec:
                    req.drafter = _PromptLookup()
                    req.drafter.extend(req.prompt)
                if self._paged:
                    bts[i] = self._block_tables[slot]
                self.prefill_tokens += len(req.prompt)
                self.prefill_tok_counter.inc(len(req.prompt))
                self._it_prefill += len(req.prompt)
                self._it_admitted.append(req.rid)
                if req.usage is not None:
                    req.usage.queue_wait_ms += (
                        t_admit - req.usage.t_wait0) * 1e3
                    req.usage.prefill_tokens += len(req.prompt)
                    if req.resumed:
                        req.usage.recompute_tokens += len(req.prompt)
            if self._paged and self._kv_quant:
                (first, self._k_cache, self._v_cache, self._k_scales,
                 self._v_scales) = self._admit_fn(
                    self._pinned, self._k_cache, self._v_cache,
                    self._k_scales, self._v_scales, jnp.asarray(bts),
                    jnp.asarray(toks), jnp.asarray(lens))
            elif self._paged:
                first, self._k_cache, self._v_cache = self._admit_fn(
                    self._pinned, self._k_cache, self._v_cache,
                    jnp.asarray(bts), jnp.asarray(toks), jnp.asarray(lens))
            else:
                slots[len(group):] = slots[0]  # pads: overwritten by row 0
                first, self._k_cache, self._v_cache = self._admit_fn(
                    self._pinned, self._k_cache, self._v_cache,
                    jnp.asarray(slots), jnp.asarray(toks),
                    jnp.asarray(lens))
            staged.append((group, slots, first, pb, bb))
        # phase 2 — read the first tokens back (one sync per group, after
        # every group's dispatch is already in the device queue)
        for group, slots, first, pb, bb in staged:
            first = np.asarray(first)
            now = time.monotonic()
            tracing = trace.enabled()
            for i, req in enumerate(group):
                tok0 = int(first[i])
                slot = int(slots[i])
                req.version = version
                req.t_last = now
                self.ttft_hist.record((now - req.t_enq) * 1e3)
                self.tokens += 1
                self.decode_tok_counter.inc()
                self._it_decode += 1
                if req.usage is not None:
                    req.usage.decode_tokens += 1
                req.out.append(tok0)
                if req.drafter is not None:
                    req.drafter.extend((tok0,))
                if tracing and req.ctx is not None:
                    # the two child spans that explain a slow TTFT: how
                    # long the prompt queued for a free slot, then the
                    # fused prefill+insert with its bucket choice and
                    # the pinned snapshot it was admitted under
                    trace.record_span("queue.wait", req.ctx, req.t_enq,
                                      t_admit, cause="admission")
                    extra = ({"blocks": len(req.blocks),
                              "pool_free": self._pool.n_free}
                             if self._paged else {})
                    extra.update(self._mesh_attrs)
                    trace.record_span(
                        "decode.admit", req.ctx, t_admit, now, slot=slot,
                        prompt_len=len(req.prompt), prompt_bucket=pb,
                        batch_bucket=bb, snapshot_version=version, **extra)
                if self._finished(req, tok0):
                    # slot never goes live; the inserted K/V is dead
                    # weight a later admission overwrites — slot and
                    # blocks return to the free sets immediately
                    self._release_seq(req)
                    self._resolve(req)
                    continue
                self._slot_req[slot] = req
                self._tok[slot] = tok0
                self._pos[slot] = len(req.prompt)
                self._active[slot] = True

    def _propose_drafts(self):
        """Gather this iteration's verification window: up to ``spec_k``
        prompt-lookup drafts per live slot. Drafts clamp to the
        request's REMAINING budget minus one (the correction token
        always fills the final emission), so a valid window write never
        passes position ``prompt + max_new - 2`` — strictly inside the
        worst-case block reservation, which is how the K-token
        overhang is accounted for without reserving a single extra
        block (under optimistic ``-preempt`` admission the same bound
        is what ``_ensure_growth`` sizes each slot's growth to: the
        window length rides ``n_valid``, so speculative writes land in
        grown-and-owned blocks exactly like plain steps' writes do).
        Returns ``(None, None)`` when no slot drafted: the
        iteration then runs the plain fused step, so a spec engine's
        draft-less iterations (and the whole life of a ``spec_k=0``
        engine) stay on today's path bit-for-bit."""
        K = self._spec
        toks = n_valid = None
        for s in range(self.config.slots):
            req = self._slot_req[s]
            if req is None:
                continue
            limit = min(K, req.max_new - len(req.out) - 1)
            if limit <= 0:
                continue
            drafts = req.drafter.propose(limit)
            if not drafts:
                continue
            if toks is None:
                toks = np.zeros((self.config.slots, K + 1), np.int32)
                toks[:, 0] = self._tok
                n_valid = np.ones(self.config.slots, np.int32)
            toks[s, 1: 1 + len(drafts)] = drafts
            n_valid[s] = 1 + len(drafts)
        return toks, n_valid

    def _admitted_requests(self) -> List[_Request]:
        reqs = [r for r in self._slot_req if r is not None]
        if self._pf is not None:
            reqs.append(self._pf)
        return reqs

    def _pick_victim(self, grower: _Request) -> Optional[_Request]:
        """Preemption victim policy: among admitted sequences (live
        slots plus the reserved-not-live mid-prefill admission), pick
        the LOWEST-priority then YOUNGEST one — never the grower
        itself, and NEVER the overall-oldest sequence (the
        guaranteed-progress floor: whatever the churn, the oldest
        admission runs to completion, which is what makes preemption
        terminate). A victim must additionally have preemption budget
        left and rank below the grower (strictly lower class, or the
        same class but younger) — EXCEPT when the grower IS the
        oldest: the floor outranks budget and class, because the
        submit-time shed gate guarantees the oldest's worst case fits
        once every other holder is evicted, and the whole design
        hinges on the oldest always completing."""
        cands = [r for r in self._admitted_requests() if r is not grower]
        if not cands:
            return None
        oldest = min(cands + [grower], key=lambda r: r.t_enq)
        cands = [r for r in cands if r is not oldest]
        if not cands:
            return None
        if oldest is not grower:
            cands = [r for r in cands
                     if r.preempts < self._preempt_budget
                     and (r.priority < grower.priority
                          or (r.priority == grower.priority
                              and r.t_enq > grower.t_enq))]
            if not cands:
                return None
        return min(cands, key=lambda r: (r.priority, -r.t_enq))

    def _preempt(self, req: _Request, why: str = "") -> None:
        """Evict one admitted sequence and free its blocks — host-side
        scheduling only (the block tables are traced DATA; no compiled
        program ever notices). The victim re-enters the FRONT of its
        priority lane and, on re-admission, recomputes from
        ``prompt + emitted tokens``: greedy decode is a deterministic
        function of the token prefix and the pinned params, and the
        paged kernels' attention operand is bit-identical across the
        prefill/decode layouts, so the resumed generation's remaining
        tokens equal the un-preempted run's exactly (oracle-tested).
        Blocks decref TAIL-first (the ``_release_seq`` LRU
        convention), so under the prefix cache the victim's registered
        blocks park in the cached tier and splice straight back at
        resume — recompute is then nearly free."""
        t0 = time.monotonic()
        slot = req.slot
        freed = len(req.blocks)
        if req is self._pf:
            self._pf = None
        else:
            self._active[slot] = False
            self._slot_req[slot] = None
        if req.blocks:
            self._pool.decref(reversed(req.blocks))
            req.blocks = []
        self._block_tables[slot][:] = SCRATCH_BLOCK
        self._free_q.append(slot)
        req.slot = -1
        if req.preempts == 0:
            self.preempted += 1
        req.preempts += 1
        self.preemptions += 1
        self.preempt_counter.inc()
        # resume state: the working prompt becomes the ORIGINAL prompt
        # plus everything emitted so far; prefill-progress/prefix/spec
        # state resets (the drafter rebuilds at re-admission from the
        # same token sequence, so its proposals are identical)
        if req.out:
            req.prompt = np.concatenate(
                [req.prompt0, np.asarray(req.out, np.int32)])
            req.resumed = True
        req.hashes = None
        req.n_hit = 0
        req.full_hit = False
        req.saved = 0
        req.pf_off = req.pf_chunks = req.pf_reg = 0
        req.ttft_pending = False
        req.drafter = None
        if req.usage is not None:
            # a fresh queue-wait interval opens: the victim re-enters
            # its lane and the next admission closes the clock again
            req.usage.t_wait0 = time.monotonic()
        if trace.enabled() and req.ctx is not None:
            trace.record_span(
                "decode.preempt", req.ctx, t0, time.monotonic(),
                victim=req.rid, slot=slot, blocks_freed=freed,
                preempts=req.preempts, priority=req.priority, why=why)
        with self._cv:
            self._q.appendleft(req)

    def _ensure_growth(self, n_valid) -> None:
        """Optimistic admission's decode-time half: before the fused
        step (or verify window) dispatches, every live slot's
        reservation must cover the positions THIS iteration writes —
        ``pos .. pos + window - 1``. Growth is allocator work plus a
        block-table row append (traced data, never a shape). On pool
        exhaustion it preempts via :meth:`_pick_victim`; when no
        admissible victim exists (everyone shielded by the floor/
        budget/class rules, or a chaos squeeze holds the pool) the
        grower itself yields and recomputes later — in normal
        operation that is never the oldest, whose growth the floor
        guarantees. Growers run highest-class-oldest-first, so the
        important/old sequences claim blocks before the preemptible
        ones."""
        order = [s for s in range(self.config.slots)
                 if self._slot_req[s] is not None]
        order.sort(key=lambda s: (-self._slot_req[s].priority,
                                  self._slot_req[s].t_enq))
        for s in order:
            req = self._slot_req[s]
            if req is None:          # victimized by an earlier grower
                continue
            win = 1 if n_valid is None else max(1, int(n_valid[s]))
            need = self._pool.blocks_needed(int(self._pos[s]) + win)
            grow = need - len(req.blocks)
            if grow <= 0:
                continue
            while self._slot_req[s] is req:
                if self._pool.can_alloc(grow):
                    try:
                        blocks = self._pool.alloc(grow)
                    except RuntimeError:
                        # a concurrent claimant (chaos pool squeeze)
                        # raced the check: fall through to preemption
                        continue
                    base = len(req.blocks)
                    req.blocks.extend(blocks)
                    self._block_tables[s][base: base + grow] = blocks
                    break
                victim = self._pick_victim(req)
                if victim is None:
                    self._preempt(req, why="yield: no admissible victim")
                    break
                self._preempt(victim, why=f"growth for rid {req.rid}")

    def _step(self) -> None:
        # ONE branch decides all per-iteration trace work: when tracing
        # is off this loop allocates nothing trace-related (guarded by
        # test_observability's overhead test)
        tracing = trace.enabled()
        ledger_on = self.ledger is not None
        t_it0 = time.monotonic() if (tracing or ledger_on) else 0.0
        spec_toks = n_valid = None
        if self._spec:
            spec_toks, n_valid = self._propose_drafts()
        if self._preempt_on:
            # grow every live reservation to cover this iteration's
            # writes, preempting under pool pressure; a yield can
            # deactivate slots (incl. every drafted one), so re-check
            self._ensure_growth(n_valid if spec_toks is not None
                                else None)
            if not self._active.any():
                return
        # host state (tok/pos/active — and, paged, the block tables)
        # feeds the jit as plain numpy: the same aval signature warmup()
        # uses, so the two share one trace
        if spec_toks is not None:
            # fused verify: ONE forward scores every window position;
            # acceptance is decided below on the host from the argmax
            # chain (traced data in, plain ints out — never a shape)
            self.spec_steps += 1
            if self._kv_quant:
                (self._k_cache, self._v_cache, self._k_scales,
                 self._v_scales, nxt) = self._verify_fn(
                    self._pinned, self._k_cache, self._v_cache,
                    self._k_scales, self._v_scales, self._block_tables,
                    spec_toks, self._pos, self._active, n_valid)
            else:
                self._k_cache, self._v_cache, nxt = self._verify_fn(
                    self._pinned, self._k_cache, self._v_cache,
                    self._block_tables, spec_toks, self._pos,
                    self._active, n_valid)
        elif self._paged and self._kv_quant:
            (self._k_cache, self._v_cache, self._k_scales,
             self._v_scales, nxt, _) = self._step_fn(
                self._pinned, self._k_cache, self._v_cache,
                self._k_scales, self._v_scales, self._block_tables,
                self._tok, self._pos, self._active)
        elif self._paged:
            self._k_cache, self._v_cache, nxt, _ = self._step_fn(
                self._pinned, self._k_cache, self._v_cache,
                self._block_tables, self._tok, self._pos, self._active)
        else:
            self._k_cache, self._v_cache, nxt, _ = self._step_fn(
                self._pinned, self._k_cache, self._v_cache,
                self._tok, self._pos, self._active)
        nxt = np.array(nxt)       # [S] or [S, K+1]; the host sync point
        now = time.monotonic()
        self.steps_counter.inc()
        if ledger_on:
            # device time attributed by active-lane share: the step's
            # wall (dispatch to sync, growth/drafting included) divides
            # evenly over the sequences it served — charged BEFORE the
            # per-slot loop so a sequence completing this very step
            # still pays for it
            self.ledger.charge_step(
                [r for r in self._slot_req if r is not None],
                (now - t_it0) * 1e3)
        n_active = 0
        for s in range(self.config.slots):
            req = self._slot_req[s]
            if req is None:
                continue
            n_active += 1
            if spec_toks is None:
                emitted = [int(nxt[s])]
                accepted = 0
            else:
                # greedy verification: drafts are accepted while they
                # match the model's own argmax chain; entry ``accepted``
                # of the window's outputs is the correction token, so
                # at least the plain step's one token always emits and
                # every emission equals sequential greedy decode
                nv = int(n_valid[s])
                accepted = 0
                while (accepted + 1 < nv
                       and int(spec_toks[s, accepted + 1])
                       == int(nxt[s, accepted])):
                    accepted += 1
                emitted = [int(nxt[s, j]) for j in range(accepted + 1)]
                eos = self.config.eos_id
                if eos is not None and eos in emitted:
                    # an in-window eos truncates the window HERE, so
                    # the accounting credits only REALIZED drafts —
                    # matches accepted past the eos were never emitted,
                    # and accepted_per_step is documented (and gated)
                    # as extra tokens actually bought per dispatch
                    emitted = emitted[: emitted.index(eos) + 1]
                    accepted = len(emitted) - 1
                proposed = nv - 1
                self.spec_proposed += proposed
                self.spec_accepted += accepted
                self._it_spec_proposed += proposed
                self._it_spec_accepted += accepted
                if proposed:
                    self.spec_prop_counter.inc(proposed)
                if accepted:
                    self.spec_acc_counter.inc(accepted)
            # pos/tok mirror host-side (consumed inputs advance the
            # position; rejected window positions are simply never
            # consumed — the next window starts at the first unverified
            # position and rewrites them before any mask reaches them)
            self._pos[s] += len(emitted)
            self._tok[s] = emitted[-1]
            # ITL is per EMITTED token: the step interval divides across
            # this iteration's emissions (spec_k=0 emits one token, so
            # the sample is exactly today's now - t_last)
            share = (now - req.t_last) * 1e3 / len(emitted)
            done = False
            for tok in emitted:
                req.out.append(tok)
                self.tokens += 1
                self.decode_tok_counter.inc()
                self._it_decode += 1
                if req.usage is not None:
                    req.usage.decode_tokens += 1
                if req.ttft_pending:
                    # fully-cached admission: THIS is the request's
                    # first token — it belongs in TTFT, not ITL
                    req.ttft_pending = False
                    self.ttft_hist.record((now - req.t_enq) * 1e3)
                else:
                    self.itl_hist.record(share)
                if self._finished(req, tok):
                    # eos inside the window truncates it: emissions past
                    # eos are dropped exactly as sequential decode would
                    # never have produced them
                    done = True
                    break
            req.t_last = now
            if req.drafter is not None and not done:
                req.drafter.extend(emitted)
            if tracing and req.ctx is not None:
                # one fused step serves every live slot; each request
                # gets the iteration as ITS child span (same interval),
                # so a slow request's trace shows every co-batched
                # iteration it sat through and on which slot. Spec
                # engines annotate how many drafts the window kept
                # (spec_k=0 spans stay flat — today's attrs exactly)
                extra = {"accepted": accepted} if self._spec else {}
                trace.record_span("decode.iter", req.ctx, t_it0, now,
                                  slot=s, token_index=len(req.out),
                                  **extra)
            if done:
                self._active[s] = False
                self._slot_req[s] = None
                self._release_seq(req)
                self._resolve(req)
        self._occ_sum += n_active / self.config.slots
        self._occ_n += 1
        self.occ_gauge.set(int(self._active.sum()) / self.config.slots)
        t_first = self.t_first        # local read: reset_stats() may race
        if t_first is not None and now > t_first:
            self.tps_gauge.set(self.tokens / (now - t_first))

    def _finished(self, req: _Request, tok: int) -> bool:
        eos = self.config.eos_id
        return (eos is not None and tok == eos) or len(req.out) >= req.max_new

    def _finalize_usage(self, req: _Request, outcome: str,
                        now: Optional[float] = None) -> None:
        """Fold one finished request's resource vector into its
        tenant's aggregates, exactly once (the vector detaches here —
        overlapping failure paths cannot double-fold), and record the
        post-hoc ``acct.request`` span carrying tenant + cost + the
        vector: the source of trace_summary's tenant/cost columns."""
        usage = req.usage
        if usage is None:
            return
        req.usage = None
        if now is None:
            now = time.monotonic()
        usage.preemptions = req.preempts
        lat_ms = ((now - req.t_enq) * 1e3 if outcome == "completed"
                  else None)
        cost = self.ledger.finalize(usage, outcome, lat_ms)
        if trace.enabled() and req.ctx is not None:
            trace.record_span(
                "acct.request", req.ctx, req.t_enq, now,
                tenant=usage.tenant, cost=round(cost, 6),
                outcome=outcome,
                prefill_tokens=usage.prefill_tokens,
                prefill_tokens_saved=usage.prefill_tokens_saved,
                decode_tokens=usage.decode_tokens,
                kv_block_s=round(usage.kv_block_s, 6),
                device_step_ms=round(usage.device_step_ms, 3),
                queue_wait_ms=round(usage.queue_wait_ms, 3),
                xfer_bytes=usage.xfer_bytes,
                recompute_tokens=usage.recompute_tokens,
                preemptions=usage.preemptions)

    def _resolve(self, req: _Request) -> None:
        self._finalize_usage(req, "completed")
        self.completed += 1
        self._it_completed.append(req.rid)
        if req.future.set_running_or_notify_cancel():
            # staleness measured at REPLY time (the PR 1 contract): the
            # pin can't move while this request is in flight, so _snap IS
            # the request's snapshot here
            req.future.set_result({
                "result": np.asarray(req.out, np.int32),
                "snapshot_version": req.version,
                "staleness_s": self._manager.staleness_s(self._snap),
            })

    def _fail_all(self, exc: Exception,
                  in_flight: Optional[List[_Request]] = None) -> None:
        with self._cv:
            # the loop thread is dying: flag stop so later submits
            # fast-fail instead of enqueueing futures nobody will drain
            self._stop.set()
            pending = self._q.drain()
            # release splice waiters: the loop will never apply these
            while self._splice_q:
                _, done, info = self._splice_q.popleft()
                info["skipped"] = "engine failed"
                done.set()
        live = [r for r in self._slot_req if r is not None]
        if self._pf is not None:      # mid-prefill admission dies too
            live.append(self._pf)
            self._pf = None
        if self._paged:
            # the dying requests' reservations go back too — including
            # arrivals reserved mid-_admit but not yet slotted. The
            # engine is stopped, but stats()/gauges must not report
            # phantom live blocks (the pool's leak invariant must hold).
            # decref, not free: prefix-shared blocks carry one holder
            # per dying request, and each drops exactly its own
            for req in live + (in_flight or []):
                if req.blocks:
                    self._pool.decref(req.blocks)
                    req.blocks = []
            if self._squeezed:       # staged chaos squeeze dies too
                self._pool.decref(self._squeezed)
                self._squeezed = []
            self._block_tables[:] = SCRATCH_BLOCK
        self._active[:] = False
        self._slot_req = [None] * self.config.slots
        self._free_q = collections.deque(range(self.config.slots))
        seen = set()
        for req in pending + live + (in_flight or []):
            if id(req) in seen or req.future.done():
                continue            # e.g. an arrival already resolved
            seen.add(id(req))
            # whatever this request consumed before the engine died is
            # still attributed (outcome "failed") — the conservation
            # identity survives an engine failure by construction
            self._finalize_usage(req, "failed")
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(exc)

    # -- chaos hooks --------------------------------------------------------
    def squeeze_pool(self, frac: float) -> int:
        """Chaos/test hook (the ``-chaos`` ``pool_squeeze=`` fault):
        take up to ``frac`` of the paged pool's capacity hostage —
        blocks allocate and are simply HELD, so live traffic sees a
        shrunken pool and the preemption machinery gets exercised
        under real pressure. Returns the blocks actually held (capped
        to what is reclaimable right now). The watchdog's
        leaked-reservation heuristic excludes squeezed blocks; release
        with :meth:`unsqueeze_pool` (``stop()``/the failure path
        release automatically)."""
        if not self._paged:
            return 0
        want = int(self._pool.capacity * float(frac))
        take = min(want, self._pool.n_free + self._pool.n_cached)
        if take <= 0:
            return 0
        try:
            self._squeezed.extend(self._pool.alloc(take))
        except RuntimeError:             # raced a concurrent admission
            return 0
        return take

    def unsqueeze_pool(self) -> int:
        """Release a staged :meth:`squeeze_pool`; returns blocks freed."""
        n = len(self._squeezed)
        if n:
            self._pool.decref(self._squeezed)
            self._squeezed = []
        return n

    # -- introspection ------------------------------------------------------
    def step_cache_size(self) -> int:
        """Compiled-trace count of the fused step (1 after warmup: the
        whole point of fixed slots + active-lane masking)."""
        return _jit_cache_size(self._step_fn)

    def prefill_cache_size(self) -> int:
        """Compiled-trace count of the admission path: the single
        fixed-shape chunk program when chunked, or the (batch bucket x
        prompt bucket) fused prefill+insert set when monolithic."""
        if self._budget > 0:
            return _jit_cache_size(self._chunk_fn)
        return _jit_cache_size(self._admit_fn)

    def verify_cache_size(self) -> int:
        """Compiled-trace count of the speculative verify step (1 after
        warmup on a spec engine: the fixed-K window is the whole
        signature; 0 when ``spec_k=0`` — the program doesn't exist)."""
        if self._verify_fn is None:
            return 0
        return _jit_cache_size(self._verify_fn)

    def seqpar_cache_size(self) -> int:
        """Compiled-trace count of the sequence-parallel chunk program
        (1 after warmup on a ``-prefill_sp`` engine — the budget * tp
        token shape is the whole signature; 0 when off — the program
        doesn't exist)."""
        if self._chunk_sp_fn is None:
            return 0
        return _jit_cache_size(self._chunk_sp_fn)

    def transfer_cache_size(self) -> int:
        """Compiled-trace count of the KV transfer plane (2 after
        warmup on a prefix-cache engine — one fetch, one splice; the
        block id is traced, so pool position never recompiles; 0 when
        the plane doesn't exist)."""
        if self._fetch_fn is None:
            return 0
        return (_jit_cache_size(self._fetch_fn)
                + _jit_cache_size(self._splice_fn))

    def warmup(self) -> None:
        """Compile every admission trace (the ONE chunk program when
        chunked, else every (batch bucket, prompt bucket) fused
        prefill+insert) and the fused step before taking traffic,
        against scratch caches — deadline-sensitive deployments call
        this BEFORE submitting so no live request ever pays a compile.
        Pins the snapshot through the serving path itself, so the warmup
        params copy (and placement, hence the compiled traces) IS the
        one the first admission serves.
        """
        self._maybe_refresh()
        params = self._pinned
        S = self.config.slots
        shape = self._k_cache.shape
        dtype = self._k_cache.dtype

        def scratch():
            # the live caches' placement (devices()[0], or the decode
            # mesh's pool sharding when tp > 1): warmup traces only ARE
            # the serving traces if their operands carry the same
            # committed sharding
            return (jax.device_put(jnp.zeros(shape, dtype),
                                   self._cache_target),
                    jax.device_put(jnp.zeros(shape, dtype),
                                   self._cache_target))

        def scratch_scales():
            # quant engines: scratch scale arrays on the scales' own
            # placement (replicated on a sharded engine) — same
            # committed-placement reasoning as scratch()
            sshape = self._k_scales.shape
            return (jax.device_put(jnp.zeros(sshape, jnp.float32),
                                   self._scale_target),
                    jax.device_put(jnp.zeros(sshape, jnp.float32),
                                   self._scale_target))

        if self._paged and self._kv_quant:
            # quant warmup mirrors the fp paged warmup exactly, with
            # the scale arrays threaded through every program — the
            # traces built here ARE the quant serving traces
            M = self._blocks_per_seq
            bt = np.full((S, M), SCRATCH_BLOCK, np.int32)
            if self._budget > 0:
                kc, vc = scratch()
                ks, vs = scratch_scales()
                self._chunk_fn(params, kc, vc, ks, vs, bt, np.int32(0),
                               np.ones(self._budget, np.int32),
                               np.int32(0), np.int32(1))
            else:
                for pb in self._prompt_buckets:
                    for bb in self._batch_buckets:
                        kc, vc = scratch()
                        ks, vs = scratch_scales()
                        self._admit_fn(
                            params, kc, vc, ks, vs,
                            np.full((bb, M), SCRATCH_BLOCK, np.int32),
                            np.ones((bb, pb), np.int32),
                            np.ones(bb, np.int32))
            if self._prefix:
                kc, vc = scratch()
                ks, vs = scratch_scales()
                jax.block_until_ready(self._cow_fn(
                    kc, vc, ks, vs, np.int32(0), np.int32(0)))
                kc, vc = scratch()
                ks, vs = scratch_scales()
                k, v, bks, bvs = self._fetch_fn(kc, vc, ks, vs,
                                                np.int32(0))
                k, v = np.asarray(k), np.asarray(v)
                bks, bvs = np.asarray(bks), np.asarray(bvs)
                jax.block_until_ready(self._splice_fn(
                    kc, vc, ks, vs, np.int32(0), k, v, bks, bvs)[0])
            if self._spec:
                kc, vc = scratch()
                ks, vs = scratch_scales()
                jax.block_until_ready(self._verify_fn(
                    params, kc, vc, ks, vs, bt,
                    np.zeros((S, self._spec + 1), np.int32),
                    np.zeros(S, np.int32), np.zeros(S, bool),
                    np.ones(S, np.int32)))
            kc, vc = scratch()
            ks, vs = scratch_scales()
            jax.block_until_ready(self._step_fn(
                params, kc, vc, ks, vs, bt, np.zeros(S, np.int32),
                np.zeros(S, np.int32), np.zeros(S, bool)))
            return
        if self._paged:
            # all-scratch block tables: warmup writes park in the
            # sentinel block of the scratch pools — placement is data,
            # so these ARE the serving traces for any block assignment
            M = self._blocks_per_seq
            bt = np.full((S, M), SCRATCH_BLOCK, np.int32)
            if self._budget > 0:
                kc, vc = scratch()
                self._chunk_fn(params, kc, vc, bt, np.int32(0),
                               np.ones(self._budget, np.int32),
                               np.int32(0), np.int32(1))
                if self._chunk_sp_fn is not None:
                    # the seqpar chunk program compiles here too (its
                    # budget * tp token shape is the only static), so no
                    # long prompt ever pays the trace — and the
                    # partitioner runs now, not on the loop thread
                    kc, vc = scratch()
                    self._chunk_sp_fn(params, kc, vc, bt, np.int32(0),
                                      np.ones(self._sp_chunk, np.int32),
                                      np.int32(0), np.int32(1))
            else:
                for pb in self._prompt_buckets:
                    for bb in self._batch_buckets:
                        kc, vc = scratch()
                        self._admit_fn(
                            params, kc, vc,
                            np.full((bb, M), SCRATCH_BLOCK, np.int32),
                            np.ones((bb, pb), np.int32),
                            np.ones(bb, np.int32))
            if self._prefix:
                # the CoW block copy is part of the serving path (a
                # full-prompt cache hit dispatches it at admission):
                # compile it here so no live request pays the trace
                kc, vc = scratch()
                jax.block_until_ready(self._cow_fn(
                    kc, vc, np.int32(0), np.int32(0)))
                # the KV transfer plane's two programs likewise (a
                # disaggregated fleet dispatches fetch at stage-1
                # completion and splice at arrival): warm both so no
                # transfer pays a compile. The host round-trip mirrors
                # serving — fetch materializes before splice donates
                # the pools away.
                kc, vc = scratch()
                k, v = self._fetch_fn(kc, vc, np.int32(0))
                k, v = np.asarray(k), np.asarray(v)
                jax.block_until_ready(self._splice_fn(
                    kc, vc, np.int32(0), k, v)[0])
            if self._spec:
                # the verify step pins like the step programs: compiled
                # here against the pinned params + scratch pools, so
                # the trace warmup builds IS the serving trace (the
                # [S, K + 1] window shape is the whole signature)
                kc, vc = scratch()
                jax.block_until_ready(self._verify_fn(
                    params, kc, vc, bt,
                    np.zeros((S, self._spec + 1), np.int32),
                    np.zeros(S, np.int32), np.zeros(S, bool),
                    np.ones(S, np.int32)))
            kc, vc = scratch()
            jax.block_until_ready(self._step_fn(
                params, kc, vc, bt, np.zeros(S, np.int32),
                np.zeros(S, np.int32), np.zeros(S, bool)))
            return
        if self._budget > 0:
            kc, vc = scratch()
            self._chunk_fn(params, kc, vc, np.int32(0),
                           np.ones(self._budget, np.int32), np.int32(0),
                           np.int32(1))
        else:
            for pb in self._prompt_buckets:
                for bb in self._batch_buckets:
                    kc, vc = scratch()
                    self._admit_fn(params, kc, vc,
                                   np.arange(bb, dtype=np.int32) % S,
                                   np.ones((bb, pb), np.int32),
                                   np.ones(bb, np.int32))
        kc, vc = scratch()
        jax.block_until_ready(self._step_fn(
            params, kc, vc, np.zeros(S, np.int32), np.zeros(S, np.int32),
            np.zeros(S, bool)))

    def reset_stats(self) -> None:
        """Zero counters/histograms (benches: measure past jit warmup)."""
        self.ttft_hist.reset()
        self.itl_hist.reset()
        self.completed = 0
        self.shed = 0
        self.tokens = 0
        self.peak_live = 0
        self.prefill_tokens = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefill_tokens_saved = 0
        self.cow_copies = 0
        self.xfer_blocks = 0
        self.xfer_bytes = 0
        self.xfer_dedup = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_steps = 0
        self.seqpar_chunks = 0
        self.preemptions = 0
        self.preempted = 0
        self.deadline_drops = 0
        self._argmax_match = -1.0
        if self._paged:
            self._evictions_base = self._pool.evictions
        if self.ledger is not None:
            self.ledger.reset()
        self.t_first = None
        self._occ_sum = 0.0
        self._occ_n = 0

    def record_argmax_match(self, rate: float) -> None:
        """Attach an externally measured argmax-match rate (quant output
        vs an fp32 oracle on the same prompts) to this engine's stats
        surface — the quant quality headline the bench archives. The
        harness computes it because only the harness holds both
        engines' outputs."""
        self._argmax_match = float(rate)

    def stats(self) -> dict:
        t_first = self.t_first
        elapsed = (time.monotonic() - t_first) if t_first else 0.0
        ttft = self.ttft_hist.percentiles((50, 99))
        itl = self.itl_hist.percentiles((50, 99))
        issued = self.completed + self.shed
        # paged-KV pool occupancy: capacity is what bounds concurrency
        # now, so the pool's free/live split (and the peak sequence
        # count it allowed) belongs next to slot occupancy
        pool = ({"kv_block_size": self._block_size,
                 "kv_pool_blocks": self._pool.capacity,
                 # mesh-aware capacity: the pools (scratch included)
                 # shard over the head slice of D, so each device holds
                 # 1/tp of the KV bytes — the number that decides
                 # whether a model + pool fits the hardware
                 # quant-aware: an int8 pool's per-block cost counts its
                 # int8 K/V bytes PLUS the per-(layer, block) fp32
                 # scales — the footprint must not flatter quantization
                 "kv_bytes_per_device": (
                     (self._pool.capacity + 1) * kv_bytes_per_block(
                         self._model_cfg.n_layers, self._model_cfg.d_model,
                         self._block_size, np.dtype(self._model_cfg.dtype),
                         quant=self._kv_quant_mode)
                     // self._tp),
                 "kv_blocks_free": self._pool.n_free,
                 "kv_blocks_live": self._pool.n_live,
                 "kv_blocks_cached": self._pool.n_cached,
                 "blocks_shared": self._pool.n_shared,
                 "block_allocs": self._pool.allocs,
                 "block_frees": self._pool.frees}
                if self._paged else {"kv_block_size": 0})
        if self._paged:
            lookups = self.prefix_hits + self.prefix_misses
            pool.update({
                "prefix_cache": int(self._prefix),
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_hit_rate": (self.prefix_hits / lookups
                                    if lookups else 0.0),
                "prefill_tokens_saved": self.prefill_tokens_saved,
                "prefix_evictions": self._pool.evictions
                - self._evictions_base,
                "cow_copies": self.cow_copies,
            })
        if self._kv_quant:
            # quant surface, present only on kv_quant=int8 engines (an
            # off-quant engine's stats dict stays byte-for-byte — the
            # metrics regression contract). quant_scale_blocks here IS
            # the real device count (one sync, stats are not the hot
            # loop); the per-iteration recorder uses the pool proxy
            try:
                nz = int((np.maximum(
                    np.asarray(self._k_scales),
                    np.asarray(self._v_scales)).max(axis=0) > 0).sum())
            except RuntimeError:
                # donated-away buffer (stats raced a dispatch): the
                # count is a diagnostic, not an invariant — degrade
                nz = -1
            pool.update({
                "kv_quant": self._kv_quant_mode,
                "quant_scale_blocks": nz,
                "argmax_match_rate": self._argmax_match,
            })
        if self._param_quant == "int8":
            pool["decode_param_quant"] = self._param_quant
        if self.ledger is not None:
            # tenant-accounting surface, present only on -cost_ledger
            # engines (off-ledger stats stay byte-for-byte — the
            # metrics regression contract). accounting_drift is the
            # conservation residual |sum over tenants - engine mirror|
            # over the integer fields: exactly zero at quiescence, and
            # the bench's zero-baseline gate holds it there
            pool.update({
                **self.ledger.stats(),
                "accounting_drift": self.ledger.drift(
                    self.prefill_tokens, self.tokens, self.xfer_bytes),
            })
        if self._prefix:
            # KV transfer plane (disaggregated serving), prefix-cache
            # engines only — the plane's gate, so a prefix_cache=off
            # engine's stats surface stays byte-for-byte today's.
            # kv_bytes_moved is RAW K/V bytes that crossed this
            # engine's boundary (fetched out or spliced in); the dedup
            # hit rate is blocks-deduped over blocks-considered
            moved = self.xfer_blocks + self.xfer_dedup
            pool.update({
                "kv_bytes_moved": self.xfer_bytes,
                "xfer_blocks": self.xfer_blocks,
                "xfer_dedup_blocks": self.xfer_dedup,
                "xfer_dedup_hit_rate": (self.xfer_dedup / moved
                                        if moved else 0.0),
            })
        if self._sp:
            # sequence-parallel prefill surface, present only on
            # -prefill_sp engines (an off-sp engine's stats dict stays
            # byte-for-byte today's — the metrics regression contract).
            # seqpar_traces is the one-trace gate for the sp chunk
            # program, exactly like step_traces/prefill_traces
            pool.update({
                "prefill_sp": self._sp_backend,
                "prefill_sp_threshold": self._sp_threshold,
                "prefill_sp_chunk": self._sp_chunk,
                "seqpar_chunks": self.seqpar_chunks,
                "seqpar_traces": self.seqpar_cache_size(),
            })
        if self._spec:
            # speculative-decoding surface, present only on spec
            # engines (a spec_k=0 engine's stats dict stays byte-for-
            # byte today's — the metrics regression contract).
            # accepted_per_step is the amortization headline: mean
            # EXTRA tokens each verify dispatch bought; acceptance_rate
            # is the drafter-quality diagnostic (archived _info in the
            # bench — trace-dependent, so it never gates)
            pool.update({
                "spec_k": self._spec,
                "spec_steps": self.spec_steps,
                "spec_proposed": self.spec_proposed,
                "spec_accepted": self.spec_accepted,
                "acceptance_rate": (self.spec_accepted
                                    / self.spec_proposed
                                    if self.spec_proposed else 0.0),
                "accepted_per_step": (self.spec_accepted
                                      / self.spec_steps
                                      if self.spec_steps else 0.0),
                "verify_traces": self.verify_cache_size(),
            })
        health = self.health()
        return {
            **pool,
            "decode_tp": self._tp,
            "mesh_devices": (self._decode_mesh.size
                             if self._decode_mesh is not None else 1),
            # the zero-baseline hot-loop gate: any repartition/retrace
            # of the fused step past warmup shows up here (the PR 2
            # ~10x partitioner drag, now asserted gone)
            "decode_step_retraces": max(0, self.step_cache_size() - 1),
            "pin_copies": self.pin_copies,
            "iters_total": health["iters_total"],
            "last_iter_age_s": health["last_iter_age_s"],
            "live_seqs": health["live_seqs"],
            "watchdog_trips": (self.watchdog.trip_count
                               if self.watchdog is not None else 0),
            "flight_records": (self.recorder.total
                               if self.recorder is not None else 0),
            "peak_live_seqs": self.peak_live,
            # overload-graceful scheduling: preemption EVENTS, distinct
            # requests preempted at least once, and expired-deadline
            # queue drops (docs/SERVING.md "Overload and preemption")
            "preempt": int(self._preempt_on),
            "preemptions": self.preemptions,
            "preempted": self.preempted,
            "deadline_drops": self.deadline_drops,
            "completed": self.completed,
            "shed": self.shed,
            "shed_rate": self.shed / issued if issued else 0.0,
            "tokens": self.tokens,
            "tokens_per_s": self.tokens / elapsed if elapsed > 0 else 0.0,
            "ttft_p50_ms": ttft[50],
            "ttft_p99_ms": ttft[99],
            "itl_p50_ms": itl[50],
            "itl_p99_ms": itl[99],
            "slot_occupancy": (self._occ_sum / self._occ_n
                               if self._occ_n else 0.0),
            "active_slots": int(self._active.sum()),
            "queue_depth": self.queue_depth(),
            "snapshot_publishes": self._manager.publishes,
            "step_traces": self.step_cache_size(),
            "prefill_traces": self.prefill_cache_size(),
            "prefill_token_budget": self._budget,
            "prefill_tokens": self.prefill_tokens,
        }

    # -- lifecycle ----------------------------------------------------------
    def stop(self) -> None:
        """Drain queued + in-flight generations, then retire the loop
        (and its watchdog — a watchdog outliving its engine would keep
        polling a corpse)."""
        with self._cv:
            self._stop.set()
            self._cv.notify_all()
        self._thread.join(timeout=60)
        if self._paged:
            # a staged chaos squeeze must not outlive the engine (the
            # pool's books would report phantom live blocks forever)
            self.unsqueeze_pool()
        if self.watchdog is not None:
            self.watchdog.stop()
