"""Always-on flight recorder: a bounded ring of per-iteration engine records.

Tracing (``trace.py``) explains one request, and only when it was ON.
The flight recorder is the black box that is ALWAYS running: every
decode-engine iteration appends one small record — what the engine was
doing, how long the fused step took, who was admitted/completed, how
deep and how old the queue was, what the block pool held — into a
preallocated ring. When something wedges, leaks, or a replica dies, the
last ``capacity`` iterations of evidence are already in memory: the
watchdog dumps them, the bench archives their summary, and
``tools/engine_timeline.py`` renders utilization/bubble analysis from a
dump after the fact.

Cost posture: ONE tuple + one short-lock ring append per iteration (the
iteration itself allocates numpy arrays and syncs the device — the
record is noise next to that), and strictly host-side state, so it can
never add a compiled trace. Nothing is serialized until someone asks
(``export_jsonl`` / ``chrome_counter_events``).

Record schema (:data:`FIELDS`, positional):

======================  =====================================================
``it``                  iteration index (1-based, monotonic per engine)
``ts``                  ``time.monotonic()`` at record time (iteration end)
``busy_ms``             wall of this loop pass's work (admit + chunk + step)
``step_ms``             the fused decode step's share of ``busy_ms`` (0 if
                        the pass ran no step)
``live``                live slots after the pass
``reserved``            mid-prefill admissions (reserved-not-live slots)
``queue``               admission-queue depth after the pass
``queue_age_ms``        age of the OLDEST queued request (0 if empty)
``prefill_toks``        prompt tokens prefilled THIS pass
``decode_toks``         tokens emitted THIS pass (first tokens included)
``pool_free``           paged-KV pool free blocks (-1 when contiguous)
``pool_live``           paged-KV pool live blocks (-1 when contiguous)
``pool_shared``         prefix-cache shared blocks — live blocks held by
                        >= 2 sequences (-1 when contiguous)
``version``             pinned snapshot version (-1 before the first pin)
``admitted``            request ids admitted this pass (tuple, usually empty)
``completed``           request ids completed this pass (tuple)
``spec_proposed``       speculative drafts verified this pass (-1 when
                        ``spec_k=0`` — the engine isn't speculating)
``spec_accepted``       speculative drafts ACCEPTED this pass (-1 when
                        ``spec_k=0``); accepted/proposed per time bucket
                        is the acceptance-rate strip
                        ``tools/engine_timeline.py`` renders
``kv_quant``            1 when the paged pools are int8-quantized, 0 for
                        fp paged pools, -1 for contiguous caches
``quant_scale_blocks``  pool blocks carrying a nonzero quant scale (a
                        written-block occupancy proxy; -1 when
                        ``kv_quant`` != 1)
``kv_block_s``          KV block-seconds charged to tenant usage vectors
                        THIS pass (the cost ledger's residency integral;
                        -1 when ``-cost_ledger`` is off)
``tenants_live``        live tenant cardinality in the cost ledger's
                        aggregate table (-1 when ``-cost_ledger`` is off)
``sp_chunks``           prefill chunks dispatched through the sequence-
                        parallel program THIS pass (-1 when
                        ``-prefill_sp`` is off)
======================  =====================================================

Timestamps are monotonic; the recorder captures a wall/mono anchor at
construction so exports rebase to epoch microseconds — the same
timebase the span export uses, which is what lets
``chrome_counter_events`` merge into a ``trace.export_chrome`` document
as counter tracks under the request spans (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import threading
import time

try:
    from ..analysis import lockwatch
except ImportError:
    # Loaded standalone by file path (tools/engine_timeline.py keeps its
    # digest math jax-free by exec'ing this module outside the package).
    # A second lockwatch copy would fork the witness registry, so fall
    # back to plain locks — the witness only matters in-package.
    class _PlainLocks:
        @staticmethod
        def lock(name):
            return threading.Lock()

    lockwatch = _PlainLocks()  # type: ignore[assignment]
from typing import Any, Dict, List, Optional

# new columns append at the END: readers index the stable prefix
# positionally, and a pre-PR-11 dump (15/16-field records) still zips
# cleanly against the longer FIELDS — consumers read the tail columns
# with .get() defaults (the PR 8 pool_shared pattern)
FIELDS = ("it", "ts", "busy_ms", "step_ms", "live", "reserved", "queue",
          "queue_age_ms", "prefill_toks", "decode_toks", "pool_free",
          "pool_live", "pool_shared", "version", "admitted", "completed",
          "spec_proposed", "spec_accepted", "kv_quant",
          "quant_scale_blocks", "kv_block_s", "tenants_live", "sp_chunks")


def window_digest(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Whole-window utilization digest over dict records (oldest first) —
    the ONE copy of the wall/busy/gap math shared by
    :meth:`FlightRecorder.summary` and ``tools/engine_timeline.py``
    (which loads this stdlib-only module by file path to stay jax-free).

    The window opens when the first retained iteration's work began
    (``ts - busy_ms``) and closes at the last record. ``gaps`` lists
    every idle bubble — time between consecutive records net of the
    later iteration's own work — sorted largest first."""
    if not records:
        return {"wall_s": 0.0, "busy_frac": 0.0, "idle_frac": 0.0,
                "prefill_tokens": 0, "decode_tokens": 0,
                "prefill_share": 0.0, "steps": 0, "mean_step_ms": 0.0,
                "max_idle_gap_ms": 0.0, "peak_live": 0, "gaps": []}
    t0 = records[0]["ts"] - records[0]["busy_ms"] / 1e3
    wall = max(records[-1]["ts"] - t0, 1e-9)
    busy_s = sum(r["busy_ms"] for r in records) / 1e3
    steps = [r["step_ms"] for r in records if r["step_ms"] > 0.0]
    prefill = sum(r["prefill_toks"] for r in records)
    decode = sum(r["decode_toks"] for r in records)
    gaps = []
    for i in range(1, len(records)):
        gap = ((records[i]["ts"] - records[i - 1]["ts"]) * 1e3
               - records[i]["busy_ms"])
        if gap > 0.0:
            gaps.append({"t_s": round(records[i]["ts"] - t0, 6),
                         "gap_ms": round(gap, 3),
                         "it": records[i]["it"]})
    gaps.sort(key=lambda g: g["gap_ms"], reverse=True)
    return {
        "wall_s": wall,
        "busy_frac": min(1.0, busy_s / wall),
        "idle_frac": max(0.0, 1.0 - busy_s / wall),
        "prefill_tokens": prefill,
        "decode_tokens": decode,
        "prefill_share": (prefill / (prefill + decode)
                          if prefill + decode else 0.0),
        "steps": len(steps),
        "mean_step_ms": sum(steps) / len(steps) if steps else 0.0,
        "max_idle_gap_ms": gaps[0]["gap_ms"] if gaps else 0.0,
        "peak_live": max(r["live"] + r["reserved"] for r in records),
        "gaps": gaps,
    }


class FlightRecorder:
    """Bounded ring of per-iteration records (oldest overwritten)."""

    def __init__(self, capacity: int = 4096, name: str = "") -> None:
        if capacity < 1:
            raise ValueError(f"FlightRecorder capacity must be >= 1, "
                             f"got {capacity}")
        self.name = name
        self.capacity = int(capacity)
        # static engine facts (decode_tp, mesh_devices, ...) the owner
        # attaches once; ride every summary() and the JSONL meta line so
        # a post-mortem dump identifies its mesh config
        self.meta: Dict[str, Any] = {}
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._pos = 0
        self._n = 0
        self.total = 0                     # records ever written
        self._lock = lockwatch.lock("serving.FlightRecorder._lock")
        # monotonic->epoch anchor (export timebase, merges with spans)
        self._anchor_wall = time.time()
        self._anchor_mono = time.monotonic()

    # -- write (the engine loop) --------------------------------------------
    def record(self, rec: tuple) -> None:
        """Append one record (a tuple in :data:`FIELDS` order)."""
        with self._lock:
            self._buf[self._pos] = rec
            self._pos = (self._pos + 1) % self.capacity
            self._n = min(self._n + 1, self.capacity)
            self.total += 1

    # -- read ---------------------------------------------------------------
    def _tuples(self) -> List[tuple]:
        with self._lock:
            if self._n < self.capacity:
                out = self._buf[: self._n]
            else:
                out = self._buf[self._pos:] + self._buf[: self._pos]
        return [r for r in out if r is not None]

    def records(self) -> List[Dict[str, Any]]:
        """Retained records as dicts, oldest first."""
        return [dict(zip(FIELDS, r)) for r in self._tuples()]

    def to_epoch_us(self, t_mono: float) -> float:
        return (self._anchor_wall + (t_mono - self._anchor_mono)) * 1e6

    def summary(self) -> Dict[str, Any]:
        """Whole-ring utilization digest (the bench's ``_info`` archive
        and the watchdog bundle's headline numbers).

        ``idle_frac`` is 1 - busy/wall over the retained window; the
        biggest single idle gap rides along because a mean hides exactly
        the bubble an operator is hunting."""
        recs = self.records()
        out: Dict[str, Any] = {
            "name": self.name, "iterations": self.total,
            "retained": len(recs), "capacity": self.capacity,
            "wrapped": self.total > self.capacity,
            **self.meta,
        }
        digest = window_digest(recs)
        # the per-bubble list is timeline_report's concern; the digest
        # here rides in bench JSON lines, so keep it scalar-only
        digest.pop("gaps")
        digest.pop("peak_live")
        out.update(digest)
        return out

    # -- export -------------------------------------------------------------
    def export_jsonl(self, path: str) -> int:
        """One meta line, then one JSON line per retained record (oldest
        first) — the dump format ``tools/engine_timeline.py`` consumes.
        Returns the record count written."""
        recs = self.records()
        with open(path, "w") as f:
            f.write(json.dumps({"flight_recorder": {
                "name": self.name, "capacity": self.capacity,
                "total": self.total, "retained": len(recs),
                "anchor_epoch_s": self._anchor_wall,
                "anchor_mono_s": self._anchor_mono,
                "fields": list(FIELDS),
                **self.meta,
            }}) + "\n")
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
        return len(recs)

    def chrome_counter_events(self) -> List[dict]:
        """Chrome ``ph: "C"`` counter samples, one track family per
        engine, on the span export's epoch-µs timebase — load the merged
        document in Perfetto and the engine's occupancy/queue/token
        counters render directly under the request spans."""
        pid = os.getpid()
        events: List[dict] = []
        prefix = f"fr/{self.name or 'engine'}"
        for r in self._tuples():
            ts = self.to_epoch_us(r[1])
            events.append({"name": f"{prefix}/slots", "ph": "C", "ts": ts,
                           "pid": pid, "tid": 0,
                           "args": {"live": r[4], "reserved": r[5]}})
            events.append({"name": f"{prefix}/queue", "ph": "C", "ts": ts,
                           "pid": pid, "tid": 0,
                           "args": {"depth": r[6]}})
            events.append({"name": f"{prefix}/tokens", "ph": "C", "ts": ts,
                           "pid": pid, "tid": 0,
                           "args": {"prefill": r[8], "decode": r[9]}})
            if r[10] >= 0:
                events.append({"name": f"{prefix}/kv_blocks", "ph": "C",
                               "ts": ts, "pid": pid, "tid": 0,
                               "args": {"free": r[10], "live": r[11],
                                        "shared": max(0, r[12])}})
            # speculative-decoding track: only spec engines emit it
            # (len guard: pre-PR-11 tuples are 16 fields)
            if len(r) > 17 and r[16] >= 0:
                events.append({"name": f"{prefix}/spec", "ph": "C",
                               "ts": ts, "pid": pid, "tid": 0,
                               "args": {"proposed": r[16],
                                        "accepted": r[17]}})
            # tenant-accounting track: only cost-ledger engines emit it
            # (len guard: pre-ledger tuples are 20 fields)
            if len(r) > 21 and r[21] >= 0:
                events.append({"name": f"{prefix}/tenants", "ph": "C",
                               "ts": ts, "pid": pid, "tid": 0,
                               "args": {"kv_block_s": r[20],
                                        "live": r[21]}})
        return events

    def merge_chrome(self, doc: dict) -> dict:
        """Merge this recorder's counter tracks into a span-export
        document (``trace.export_chrome()``), keeping the event list
        time-sorted (a stable sort preserves B/E emission order at equal
        timestamps, which the export's nesting contract relies on)."""
        events = list(doc.get("traceEvents", []))
        events.extend(self.chrome_counter_events())
        events.sort(key=lambda e: e["ts"])
        doc["traceEvents"] = events
        return doc

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"capacity": self.capacity, "retained": self._n,
                    "total": self.total}
