"""Deterministic, seedable fault injection for the serving fleet.

A fault-tolerance claim that was never exercised is a comment, not a
property. This module is the exercise plane: a :class:`FaultPlan` is a
parsed, *seeded* schedule of named failure points that the replica and
its wire publisher consult at well-defined places — the same plan drives
the chaos unit tests, the 3-process acceptance test, and the
``serving_bench`` ``lm_fleet_chaos`` A/B, so "recovery works" is a
number (``requests_lost == 0``, ``recovery_time_s``) the perf gate
watches, not a belief.

Named failure points (the ``-chaos`` spec grammar; directives are
comma-separated, all optional)::

    kill_at_request=K        exit the replica process (exit code 43) the
                             moment it dequeues its K-th targeted
                             request (1-based) — mid-trace, before the
                             reply exists
    wedge_at_request=K:T     sleep T seconds before executing request K
                             (a wedged engine step: the process stays
                             alive and heartbeating while making no
                             request progress)
    wire_delay=T:P           before each outbound wire record, sleep T
                             seconds with probability P (seeded)
    wire_drop=P              suppress each outbound NON-ESSENTIAL wire
                             record (heartbeats) with probability P
                             (seeded); request/response records are
                             never dropped — TCP already owns payload
                             integrity, the interesting failure is the
                             *liveness signal* going quiet
    slow_heartbeat=X         multiply the replica's heartbeat interval
                             by X (a replica that looks dead without
                             being dead — the router must not lose its
                             requests when it flags it)
    burst=K:N                as the replica dequeues its K-th targeted
                             request, submit N EXTRA copies of that
                             prompt straight into its local engine —
                             a one-replica traffic spike that drives
                             the priority scheduler and (with a tight
                             pool) the preemption machinery under
                             real pressure
    pool_squeeze=K:F[:R]     at request K, hold fraction F (0..1) of
                             the replica engine's KV block pool
                             hostage (``engine.squeeze_pool``) so
                             live traffic sees a shrunken pool and
                             growth must preempt; release at request
                             R (omitted = held until engine stop)
    kv_xfer_drop=K           drop the K-th (1-based) outbound KV-block
                             transfer mid-flight: the prefill replica
                             strips the payload's K/V bytes
                             (``kv_transfer.drop_blocks``) before
                             publishing, keeping the header + hash
                             chain so the loss is observable. The
                             decode side splices nothing new and
                             re-prefills the prompt locally — a
                             dropped transfer must cost latency,
                             never tokens (``output_mismatches`` 0,
                             ``requests_lost`` 0)

Trainer-side failure points (PR 14 — the durability pipeline's chaos):

    kill_trainer_at_publish=K   exit the trainer (exit code 43) at its
                             K-th parameter publish (1-based), BEFORE
                             the record hits the wire — the
                             acknowledged-and-journaled update whose
                             publish never happened is exactly what
                             checkpoint+WAL recovery must not lose
    wal_torn_tail            at the kill, tear the journal's LAST
                             record in half (the crash caught the
                             append mid-write) — recovery must
                             truncate it deterministically
    wal_bad_crc              at the kill, flip a payload bit in the
                             journal's last record — same recovery
                             path, different corruption
    zombie_epoch=K:E         from the K-th publish on, stamp records
                             with stale epoch E — the
                             paused-then-resumed zombie trainer whose
                             publishes the fleet's epoch fence must
                             reject

Determinism: every probabilistic decision draws from one
``random.Random(seed)`` stream in consultation order, so a given
``(spec, seed)`` pair replays the identical fault schedule — a flaky
chaos test is a real bug, not an unlucky roll. Kills go through
``kill_fn`` so in-process fleets (the bench, the unit tests) can
substitute an abrupt in-process death for ``os._exit``; subprocess
replicas get the real thing.
"""

from __future__ import annotations

import os
import random
from typing import Any, Callable, Dict, Optional

from ..log import Log

#: replica exit code for an injected kill — distinguishable from a crash
KILL_EXIT = 43


def _default_kill() -> None:    # pragma: no cover - subprocess-only path
    # os._exit, not sys.exit: the point is an ABRUPT death (no atexit,
    # no transport drain, no engine stop) — the failure mode the fleet
    # must survive, not a graceful shutdown it could negotiate with
    os._exit(KILL_EXIT)


class FaultPlan:
    """One parsed ``-chaos`` spec: the schedule a replica consults.

    All methods are cheap and safe to call with no faults configured
    (``FaultPlan("")`` is the always-healthy plan); ``counts`` records
    every fault actually fired, and rides ``ReplicaServer.stats()`` so
    a chaos run's report says what the plan *did*, not just what it
    said.
    """

    def __init__(self, spec: str = "", seed: int = 0,
                 kill_fn: Optional[Callable[[], None]] = None) -> None:
        self.spec = spec or ""
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._kill_fn = kill_fn or _default_kill
        self.kill_at: int = 0                 # 0 = never
        self.wedge_at: int = 0
        self.wedge_s: float = 0.0
        self.delay_s: float = 0.0
        self.delay_p: float = 0.0
        self.drop_p: float = 0.0
        self.heartbeat_scale: float = 1.0
        self.kill_trainer_at: int = 0         # 0 = never
        self.wal_fault: str = ""              # "", torn_tail, bad_crc
        self.zombie_at: int = 0               # 0 = never
        self.zombie_epoch: int = 0
        self.burst_at: int = 0                # 0 = never
        self.burst_count: int = 0
        self.squeeze_at: int = 0              # 0 = never
        self.squeeze_fraction: float = 0.0
        self.squeeze_release_at: int = 0      # 0 = never released
        self.xfer_drop_at: int = 0            # 0 = never
        self._wal = None                      # attach_wal() target
        self.counts: Dict[str, int] = {
            "kills": 0, "wedges": 0, "wire_delays": 0, "wire_drops": 0,
            "trainer_kills": 0, "wal_faults": 0, "zombie_publishes": 0,
            "bursts": 0, "pool_squeezes": 0, "kv_xfer_drops": 0}
        for directive in filter(None,
                                (d.strip() for d in self.spec.split(","))):
            key, _, val = directive.partition("=")
            if not val and key.strip() in ("wal_torn_tail",
                                           "wal_bad_crc"):
                val = "1"       # valueless flag directives, as documented
            if not val:
                raise ValueError(f"chaos directive {directive!r} needs "
                                 f"KEY=VALUE")
            try:
                self._apply(key.strip(), val.strip())
            except (TypeError, ValueError) as exc:
                raise ValueError(
                    f"bad chaos directive {directive!r}: {exc}") from None

    def _apply(self, key: str, val: str) -> None:
        if key == "kill_at_request":
            self.kill_at = int(val)
        elif key == "wedge_at_request":
            k, _, t = val.partition(":")
            self.wedge_at, self.wedge_s = int(k), float(t or 0.0)
        elif key == "wire_delay":
            t, _, p = val.partition(":")
            self.delay_s = float(t)
            self.delay_p = float(p) if p else 1.0
        elif key == "wire_drop":
            self.drop_p = float(val)
        elif key == "slow_heartbeat":
            self.heartbeat_scale = float(val)
            if self.heartbeat_scale < 1.0:
                raise ValueError("slow_heartbeat scale must be >= 1")
        elif key == "kill_trainer_at_publish":
            self.kill_trainer_at = int(val)
        elif key == "wal_torn_tail":
            if val not in ("1", "true"):
                raise ValueError("wal_torn_tail takes =1")
            self.wal_fault = "torn_tail"
        elif key == "wal_bad_crc":
            if val not in ("1", "true"):
                raise ValueError("wal_bad_crc takes =1")
            self.wal_fault = "bad_crc"
        elif key == "zombie_epoch":
            k, _, e = val.partition(":")
            self.zombie_at, self.zombie_epoch = int(k), int(e or 0)
            if self.zombie_at < 1:
                raise ValueError("zombie_epoch needs K >= 1 (K:E)")
        elif key == "burst":
            k, _, n = val.partition(":")
            self.burst_at, self.burst_count = int(k), int(n or 0)
            if self.burst_at < 1 or self.burst_count < 1:
                raise ValueError("burst needs K >= 1 and N >= 1 (K:N)")
        elif key == "pool_squeeze":
            k, _, rest = val.partition(":")
            f, _, r = rest.partition(":")
            self.squeeze_at = int(k)
            self.squeeze_fraction = float(f or 0.0)
            self.squeeze_release_at = int(r) if r else 0
            if self.squeeze_at < 1:
                raise ValueError("pool_squeeze needs K >= 1 (K:F[:R])")
            if not 0.0 < self.squeeze_fraction <= 1.0:
                raise ValueError("pool_squeeze fraction F must be in "
                                 "(0, 1]")
            if (self.squeeze_release_at
                    and self.squeeze_release_at <= self.squeeze_at):
                raise ValueError("pool_squeeze release R must come "
                                 "after K")
        elif key == "kv_xfer_drop":
            self.xfer_drop_at = int(val)
            if self.xfer_drop_at < 1:
                raise ValueError("kv_xfer_drop needs K >= 1")
        else:
            raise ValueError(f"unknown failure point {key!r}")

    @classmethod
    def from_flags(cls, kill_fn: Optional[Callable[[], None]] = None
                   ) -> "FaultPlan":
        """The ``-chaos`` / ``-chaos_seed`` flag pair as a plan."""
        from .. import config

        return cls(config.get_flag("chaos"),
                   seed=int(config.get_flag("chaos_seed")),
                   kill_fn=kill_fn)

    # -- failure points ------------------------------------------------------
    def on_request(self, k: int) -> float:
        """Consulted as the replica dequeues its ``k``-th (1-based)
        targeted request. Fires the kill (does not return) or returns
        the seconds to wedge before executing (0.0 = healthy)."""
        if self.kill_at and k == self.kill_at:
            self.counts["kills"] += 1
            Log.error("chaos: killing replica at request %d "
                      "(kill_at_request)", k)
            self._kill_fn()
            return 0.0          # in-process kill_fn substitutes may return
        if self.wedge_at and k == self.wedge_at and self.wedge_s > 0:
            self.counts["wedges"] += 1
            Log.error("chaos: wedging request %d for %.3f s", k,
                      self.wedge_s)
            return self.wedge_s
        return 0.0

    def attach_wal(self, wal) -> None:
        """Point the WAL-corruption faults at a journal (anything with
        ``corrupt_tail(kind)``); the trainer bootstrap wires the
        session's :class:`~multiverso_tpu.io.wal.DeltaWAL` here."""
        self._wal = wal

    def on_trainer_publish(self, k: int) -> None:
        """Consulted as the trainer issues its ``k``-th (1-based)
        parameter publish, BEFORE the record hits the wire. Fires the
        trainer kill (does not return) — first staging the armed WAL
        corruption, so the crash leaves exactly the torn/bad tail the
        recovery path must truncate."""
        if self.kill_trainer_at and k == self.kill_trainer_at:
            if self.wal_fault and self._wal is not None:
                self.counts["wal_faults"] += 1
                Log.error("chaos: corrupting WAL tail (%s) before the "
                          "trainer kill", self.wal_fault)
                self._wal.corrupt_tail(self.wal_fault)
            self.counts["trainer_kills"] += 1
            Log.error("chaos: killing trainer at publish %d "
                      "(kill_trainer_at_publish)", k)
            self._kill_fn()

    def publish_epoch(self, k: int, epoch: int) -> int:
        """Epoch to stamp the ``k``-th publish with: the claimed
        ``epoch``, or the stale zombie epoch once ``zombie_epoch=K:E``
        is in effect (the fence-rejection the acceptance test counts)."""
        if self.zombie_at and k >= self.zombie_at:
            self.counts["zombie_publishes"] += 1
            return self.zombie_epoch
        return epoch

    def burst_n(self, k: int) -> int:
        """Consulted as the replica dequeues request ``k``: how many
        EXTRA copies of it to submit to the local engine (0 = none)."""
        if self.burst_at and k == self.burst_at:
            self.counts["bursts"] += 1
            Log.error("chaos: bursting %d extra request(s) at request "
                      "%d", self.burst_count, k)
            return self.burst_count
        return 0

    def squeeze_frac(self, k: int) -> Optional[float]:
        """Pool fraction to squeeze at request ``k`` (None = none)."""
        if self.squeeze_at and k == self.squeeze_at:
            self.counts["pool_squeezes"] += 1
            Log.error("chaos: squeezing %.0f%% of the KV pool at "
                      "request %d", self.squeeze_fraction * 100, k)
            return self.squeeze_fraction
        return None

    def squeeze_release(self, k: int) -> bool:
        """True when the staged squeeze releases at request ``k``."""
        return bool(self.squeeze_release_at
                    and k == self.squeeze_release_at)

    def wire_delay_s(self) -> float:
        """Consulted before each outbound wire record: seconds to stall
        the send (0.0 = send now)."""
        if self.delay_s > 0 and self._rng.random() < self.delay_p:
            self.counts["wire_delays"] += 1
            return self.delay_s
        return 0.0

    def drop_kv_xfer(self, k: int) -> bool:
        """Consulted as the prefill replica publishes its ``k``-th
        (1-based) KV-block transfer: True = strip the payload's K/V
        bytes (``kv_transfer.drop_blocks``) before it hits the wire."""
        if self.xfer_drop_at and k == self.xfer_drop_at:
            self.counts["kv_xfer_drops"] += 1
            Log.error("chaos: dropping KV transfer %d mid-flight "
                      "(kv_xfer_drop)", k)
            return True
        return False

    def drop_heartbeat(self) -> bool:
        """Consulted per heartbeat: True = suppress this one."""
        if self.drop_p > 0 and self._rng.random() < self.drop_p:
            self.counts["wire_drops"] += 1
            return True
        return False

    def active(self) -> bool:
        return bool(self.kill_at or self.wedge_at or self.delay_s
                    or self.drop_p or self.heartbeat_scale != 1.0
                    or self.kill_trainer_at or self.wal_fault
                    or self.zombie_at or self.burst_at
                    or self.squeeze_at or self.xfer_drop_at)

    def stats(self) -> Dict[str, Any]:
        return {"spec": self.spec, "seed": self.seed, **self.counts}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FaultPlan({self.spec!r}, seed={self.seed})"
