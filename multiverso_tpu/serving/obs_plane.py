"""Fleet observability plane: cross-process metrics/trace/health shipping.

Every instrument this repo has built so far terminates inside one
process: the Dashboard aggregates, the trace collector rings, the flight
recorder records, the watchdog trips — all per-node. The ROADMAP's next
structural step (N decode-engine replicas behind a router) is
unbuildable blind: a degraded replica is indistinguishable from an idle
one unless some plane carries each node's evidence to a place that can
compare them. Dapper's core lesson is that the cross-process collection
plane must exist *before* the fleet does; the Prometheus model says
fleet truth is mergeable rollups, not per-node log files. This module
is both halves:

* :class:`ObsAgent` — one per node (``-obs_plane`` / ``-obs_report_ms``;
  a daemon thread). Every interval it builds ONE bounded delta report —
  changed ``Dashboard.snapshot()`` rows, the shared-helper interval
  deltas (``dashboard.snapshot_deltas`` — the SAME semantics the JSONL
  ``MetricsExporter`` uses), log-bucketed ``Histogram.buckets()``
  exports for every changed histogram, per-engine
  ``stats()``/``health()``/watchdog-trip/flight-recorder summaries, and
  the tail-kept spans recorded since the last report — and ships it over
  the existing :class:`~multiverso_tpu.parallel.p2p.P2PTransport` wire
  (label ``mvobs``) to the collector node (rank 0). Single-process
  sessions run the same agent in LOOPBACK: reports ingest into a local
  collector with no sockets, which is also what the bench A/B prices.
* :class:`ObsCollector` — keys state per node, sums counters exactly
  (latest cumulative value per node, summed across nodes — deltas never
  compound error), merges bucketed histograms into fleet-wide
  p50/p95/p99 (documented ``dashboard.BUCKET_REL_ERROR`` log-bucket
  bound, ~9.05%), computes fleet SLO burn from the merged buckets, and
  flags degraded/silent nodes by last-report age with the same
  edge-triggered re-arm semantics as ``EngineWatchdog`` (one event per
  episode; a node that reports again re-arms). It also assembles the
  per-node span shipments into ONE merged Chrome/Perfetto document with
  one process track per node — the cross-process traces that today only
  link by id become one openable timeline.

Wire schema (one JSON object per transport record, ``v`` = 1)::

    {"v": 1, "node": <rank>, "seq": <per-node counter>,
     "ts": <epoch s>, "mono": <sender monotonic s>, "interval_s": <dt>,
     "rows":   {name: snapshot row, ...}      # CHANGED rows only
     "deltas": {name: {field: d, field_per_s: r}}   # shared helper
     "buckets": {hist_name: Histogram.buckets()},   # changed hists only
     "engines": {engine: {"stats", "health", "watchdog", "flight"}},
     "spans": [Span.to_dict(), ...], "spans_missed": n,
     "trace_anchor": [epoch_s, mono_s]}

Reports are BOUNDED: only changed rows/buckets ship, spans cap at
``ObsAgent.MAX_SPANS`` per report (overflow counted, never silent), and
the publish window caps at ``MAX_OUTSTANDING`` un-acked reports — past
it the agent drops whole reports and counts ``dropped_reports`` (the
bench gates it at zero) instead of growing the retained window without
bound. The collector acks consumed sequence numbers through the
coordination-service KV (``mvobs/ack/<rank>``), which is what lets the
agent release replayed records; a collector reconnect resumes from its
next expected sequence exactly like the async bus.

docs/OBSERVABILITY.md "Fleet plane" walks the schema, the merge
semantics, the bucket error bound, and the degraded-node lifecycle;
``tools/opscenter.py`` renders the fleet table / merged Prometheus /
merged Perfetto doc from agent report archives (``-obs_jsonl``).
"""

from __future__ import annotations

import collections
import json
import threading
from ..analysis import lockwatch
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import config, trace
from ..dashboard import (BUCKET_REL_ERROR, Dashboard, bucket_breach_frac,
                         bucket_percentile, merge_buckets,
                         render_prometheus, snapshot_deltas)
from ..log import Log

WIRE_VERSION = 1

# the cost ledger's keyed per-tenant instruments
# (``TENANT_REQUESTS[engine.tenant]`` etc, serving/accounting.py):
# counter prefix -> tenant_rows() field
_TENANT_COUNTER_FIELDS = (
    ("TENANT_REQUESTS[", "requests"),
    ("TENANT_PREFILL_TOKENS[", "prefill_tokens"),
    ("TENANT_DECODE_TOKENS[", "decode_tokens"),
    ("TENANT_XFER_BYTES[", "xfer_bytes"),
    ("TENANT_KV_BLOCK_S[", "kv_block_s"),
    ("TENANT_COST[", "cost"),
)


def _slo_source(name: str) -> str:
    """``SLO_P99[SERVE_TTFT[lm]]`` -> ``SERVE_TTFT[lm]`` (the histogram
    the objective watches; the bracket convention is load-bearing)."""
    if "[" in name and name.endswith("]"):
        return name[name.index("[") + 1:-1]
    return name


class ObsCollector:
    """Fleet-side aggregation state: per-node registries, exact counter
    sums, bucket-merged fleet percentiles, SLO burn, degraded flags,
    and the merged cross-process trace document. Pure host state — no
    wire of its own (the collector node's :class:`ObsAgent` drains the
    transport and calls :meth:`ingest`/:meth:`check`; tests and
    ``tools/opscenter.py`` drive it directly)."""

    MAX_SPANS_PER_NODE = 16384
    MAX_TRIPS_PER_NODE = 256
    MAX_EVENTS = 256

    def __init__(self, degraded_after_s: float = 0.0,
                 on_degraded: Optional[Callable[[int, float], None]] = None,
                 name: str = "obs",
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self._degraded_after_s = float(degraded_after_s)
        self._on_degraded = on_degraded
        self._clock = clock
        self._lock = lockwatch.lock("serving.ObsCollector._lock")
        self._nodes: Dict[int, Dict[str, Any]] = {}
        self._armed: Dict[int, bool] = {}
        self._degraded: set = set()
        # (node, "degraded"/"recovered", age_s) transitions, oldest first
        self.events: collections.deque = collections.deque(
            maxlen=self.MAX_EVENTS)
        self.reports = 0

    # -- ingest -------------------------------------------------------------
    def _node_state(self, node: int) -> Dict[str, Any]:
        st = self._nodes.get(node)
        if st is None:
            st = self._nodes[node] = {
                "rows": {}, "buckets": {}, "engines": {},
                "trips": collections.deque(maxlen=self.MAX_TRIPS_PER_NODE),
                "spans": collections.deque(maxlen=self.MAX_SPANS_PER_NODE),
                "spans_missed": 0, "anchor": None, "reports": 0,
                "last_seq": -1, "last_ts": 0.0, "last_ingest": 0.0,
            }
        return st

    def expect_nodes(self, nodes) -> None:
        """Seed the fleet roster: every expected rank appears in the
        table (0 reports) immediately and starts its silence clock at
        seeding time — a replica that never manages a FIRST report
        (boot wedge) ages past ``degraded_after_s`` and flags like any
        other silent node, instead of being invisible."""
        now = self._clock()
        with self._lock:
            for node in nodes:
                st = self._node_state(int(node))
                if st["reports"] == 0 and st["last_ingest"] == 0.0:
                    st["last_ingest"] = now

    def ingest(self, node: int, report: Dict[str, Any]) -> None:
        """Fold one node report into the per-node state. Counters and
        every other snapshot row arrive as CURRENT cumulative values
        (the delta report ships only rows that changed), so fleet sums
        are exact regardless of lost or coalesced reports — deltas ride
        along for rate display, they are never integrated."""
        node = int(node)
        now = self._clock()
        rows = report.get("rows") or {}
        engines = report.get("engines") or {}
        with self._lock:
            st = self._node_state(node)
            st["rows"].update(rows)
            st["buckets"].update(report.get("buckets") or {})
            for ename, eng in engines.items():
                st["engines"][ename] = eng
                for kind, reason in (eng.get("watchdog") or {}).get(
                        "new_trips", []):
                    st["trips"].append((ename, kind, reason,
                                        report.get("ts", 0.0)))
            st["spans"].extend(report.get("spans") or [])
            st["spans_missed"] += int(report.get("spans_missed", 0))
            if report.get("trace_anchor"):
                st["anchor"] = report["trace_anchor"]
            st["reports"] += 1
            st["last_seq"] = int(report.get("seq", st["last_seq"] + 1))
            st["last_ts"] = float(report.get("ts", st["last_ts"]))
            st["last_ingest"] = now
            self.reports += 1

    # -- degraded/silent detection ------------------------------------------
    def check(self, now: Optional[float] = None) -> List[Tuple[int, float]]:
        """One liveness evaluation over every known node (the collector
        agent runs it once per report interval; tests call it
        directly). A node whose last report is older than
        ``degraded_after_s`` is flagged DEGRADED — edge-triggered with
        the ``EngineWatchdog`` re-arm semantics: one event per episode,
        re-armed when the node reports again (its age drops below the
        threshold), a recovery recorded as its own event. Returns the
        ``(node, age_s)`` pairs that NEWLY tripped this check."""
        if self._degraded_after_s <= 0:
            return []
        now = self._clock() if now is None else now
        fired: List[Tuple[int, float]] = []
        with self._lock:
            for node, st in self._nodes.items():
                age = now - st["last_ingest"]
                if age <= self._degraded_after_s:
                    if not self._armed.get(node, True):
                        self.events.append((node, "recovered", age))
                    self._armed[node] = True
                    self._degraded.discard(node)
                    continue
                self._degraded.add(node)
                if self._armed.get(node, True):
                    self._armed[node] = False
                    self.events.append((node, "degraded", age))
                    fired.append((node, age))
        # counter + user callback OUTSIDE the registry lock (locklint
        # LK202/LK204 — a callback must never run under a plane lock)
        for node, age in fired:
            Dashboard.get_or_create_counter(f"OBS_DEGRADED[node{node}]"
                                            ).inc()
            Log.error("obs plane: node %d silent for %.2fs (threshold "
                      "%.2fs) — flagged DEGRADED", node, age,
                      self._degraded_after_s)
            cb = self._on_degraded
            if cb is not None:
                try:
                    cb(node, age)
                except Exception as exc:    # pragma: no cover - defensive
                    Log.error("obs plane: on_degraded callback failed: %s",
                              exc)
        return fired

    def degraded(self) -> List[int]:
        with self._lock:
            return sorted(self._degraded)

    def nodes(self) -> List[int]:
        with self._lock:
            return sorted(self._nodes)

    def node_state(self, node: int) -> Dict[str, Any]:
        """Shallow copy of one node's latest state (test surface)."""
        with self._lock:
            st = self._nodes[int(node)]
            return {**st, "rows": dict(st["rows"]),
                    "buckets": dict(st["buckets"]),
                    "engines": dict(st["engines"]),
                    "trips": list(st["trips"]),
                    "spans": list(st["spans"])}

    # -- fleet aggregation ---------------------------------------------------
    def merged_buckets(self, hist_name: str) -> Dict[str, Any]:
        """Fleet-wide bucket export for one histogram: per-index counts
        summed across every node's latest window export."""
        with self._lock:
            exports = [st["buckets"].get(hist_name)
                       for st in self._nodes.values()]
        return merge_buckets(exports)

    def fleet(self) -> Dict[str, Any]:
        with self._lock:
            return self._fleet_locked()

    def _fleet_locked(self) -> Dict[str, Any]:
        """Fleet rollup: counters/monitors summed exactly from each
        node's latest cumulative row, histograms merged bucket-wise
        (percentiles within ``bucket_error`` of the pooled-sample
        truth), SLO burn recomputed over the merged source buckets,
        engines summed per name."""
        counters: Dict[str, float] = {}
        monitors: Dict[str, Dict[str, float]] = {}
        hist_names: set = set()
        slo_rows: Dict[str, Dict[str, Any]] = {}
        engines: Dict[str, Dict[str, float]] = {}
        for st in self._nodes.values():
            for name, row in st["rows"].items():
                kind = row.get("type")
                if kind == "counter":
                    counters[name] = counters.get(name, 0) + row.get(
                        "value", 0)
                elif kind == "monitor":
                    m = monitors.setdefault(name,
                                            {"count": 0, "total_ms": 0.0})
                    m["count"] += row.get("count", 0)
                    m["total_ms"] += row.get("total_ms", 0.0)
                elif kind == "histogram":
                    hist_names.add(name)
                elif kind == "slo":
                    prev = slo_rows.get(name)
                    if prev is None or row.get("target_ms", 0.0) > prev.get(
                            "target_ms", 0.0):
                        slo_rows[name] = row
            for ename, eng in st["engines"].items():
                stats = eng.get("stats") or {}
                e = engines.setdefault(ename, {
                    "nodes": 0, "tokens_per_s": 0.0, "live_seqs": 0,
                    "completed": 0, "shed": 0, "watchdog_trips": 0})
                e["nodes"] += 1
                e["tokens_per_s"] += stats.get("tokens_per_s", 0.0)
                e["live_seqs"] += stats.get("live_seqs", 0)
                e["completed"] += stats.get("completed", 0)
                e["shed"] += stats.get("shed", 0)
                e["watchdog_trips"] += (eng.get("watchdog") or {}).get(
                    "trips_total", stats.get("watchdog_trips", 0))
        for m in monitors.values():
            m["avg_ms"] = m["total_ms"] / m["count"] if m["count"] else 0.0
        hists: Dict[str, Dict[str, float]] = {}
        merged_cache: Dict[str, Dict[str, Any]] = {}
        for name in sorted(hist_names):
            merged = merge_buckets([st["buckets"].get(name)
                                    for st in self._nodes.values()])
            merged_cache[name] = merged
            lifetime = sum(st["rows"].get(name, {}).get("count", 0)
                           for st in self._nodes.values())
            hists[name] = {
                "count": lifetime,
                "window_n": merged["zero"] + sum(
                    merged["counts"].values()),
                "p50_ms": bucket_percentile(merged, 50),
                "p95_ms": bucket_percentile(merged, 95),
                "p99_ms": bucket_percentile(merged, 99),
                "bucket_error": BUCKET_REL_ERROR,
            }
        slos: Dict[str, Dict[str, float]] = {}
        for name, row in slo_rows.items():
            source = _slo_source(name)
            pct = float(row.get("percentile", 99.0))
            target = float(row.get("target_ms", 0.0))
            merged = merged_cache.get(source) or merge_buckets(
                [st["buckets"].get(source) for st in self._nodes.values()])
            breach = bucket_breach_frac(merged, target)
            budget = max(1.0 - pct / 100.0, 1e-9)
            value = bucket_percentile(merged, pct)
            slos[name] = {
                "target_ms": target, "percentile": pct,
                "window": merged["zero"] + sum(merged["counts"].values()),
                "value_ms": value, "breach_frac": breach,
                "burn": breach / budget,
                "ok": 0 if value > target else 1,
            }
        return {
            "nodes": len(self._nodes),
            "reports": self.reports,
            "degraded": sorted(self._degraded),
            "counters": counters,
            "monitors": monitors,
            "histograms": hists,
            "slos": slos,
            "engines": engines,
            "tokens_per_s": sum(e["tokens_per_s"]
                                for e in engines.values()),
            "watchdog_trips": sum(e["watchdog_trips"]
                                  for e in engines.values()),
        }

    # -- exports -------------------------------------------------------------
    def prometheus(self) -> str:
        """Every node's latest registry as ONE Prometheus text
        exposition, each sample carrying a ``node`` label (the
        ``render_prometheus`` pass-through); family ``# TYPE`` lines are
        deduped across nodes so the merged document stays valid."""
        with self._lock:
            per_node = [(node, dict(st["rows"]))
                        for node, st in sorted(self._nodes.items())]
        family_type: Dict[str, str] = {}
        samples: Dict[str, List[str]] = {}
        for node, rows in per_node:
            for line in render_prometheus(rows, labels={
                    "node": str(node)}).splitlines():
                if line.startswith("# TYPE "):
                    _, _, full, kind = line.split(" ")
                    family_type.setdefault(full, kind)
                elif line:
                    full = line.split("{", 1)[0]
                    samples.setdefault(full, []).append(line)
        lines: List[str] = []
        for full in sorted(samples):
            lines.append(f"# TYPE {full} {family_type.get(full, 'gauge')}")
            lines.extend(samples[full])
        return "\n".join(lines) + ("\n" if lines else "")

    def export_chrome(self, path: Optional[str] = None) -> dict:
        """The merged cross-process trace: every node's shipped spans as
        B/E events with ``pid = node rank`` (one process track per node,
        named via ``process_name`` metadata), timestamps rebased onto
        the shared epoch-µs timebase through each node's own clock
        anchor — so a ``bus.publish`` on node 0 and its ``bus.apply``
        child on node 2 (same trace id via the wire header) finally
        render in ONE Perfetto document. Passes
        ``trace.validate_chrome_events``."""
        with self._lock:
            per_node = [(node, st["anchor"], list(st["spans"]),
                         st["spans_missed"])
                        for node, st in sorted(self._nodes.items())]
        events: List[dict] = []
        missed = 0
        for node, anchor, spans, node_missed in per_node:
            missed += node_missed
            wall, mono = anchor if anchor else (0.0, 0.0)
            events.append({"name": "process_name", "ph": "M", "pid": node,
                           "args": {"name": f"node{node}"}})
            tids: Dict[tuple, int] = {}
            for sp in spans:
                t1 = sp.get("t1")
                if t1 is None:
                    continue
                tid = tids.setdefault(
                    (sp.get("trace_id"), sp.get("thread")), len(tids) + 1)
                args = {"trace_id": f"{int(sp['trace_id']):x}",
                        "span_id": f"{int(sp['span_id']):x}",
                        "thread": sp.get("thread", ""),
                        "node": node}
                if sp.get("parent_id") is not None:
                    args["parent_id"] = f"{int(sp['parent_id']):x}"
                args.update(sp.get("attrs") or {})
                ts0 = (wall + (float(sp["t0"]) - mono)) * 1e6
                ts1 = (wall + (float(t1) - mono)) * 1e6
                events.append({"name": sp["name"], "ph": "B", "ts": ts0,
                               "pid": node, "tid": tid, "args": args})
                events.append({"name": sp["name"], "ph": "E", "ts": ts1,
                               "pid": node, "tid": tid})
        # metadata events carry no ts and sort first; the stable sort
        # keeps B-before-E at identical timestamps within a track
        events.sort(key=lambda e: e.get("ts", float("-inf")))
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"clock": "epoch_us", "nodes": len(per_node),
                             "spans_missed": missed}}
        if path:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    # -- rendering (shared by tools/opscenter.py) ----------------------------
    def table(self, silent_after_s: Optional[float] = None) -> str:
        """The fleet table: one row per node (liveness, report count,
        tok/s, live sequences, watchdog trips, worst SLO burn, spans
        held) under a fleet summary line. ``silent_after_s`` adds the
        OFFLINE silence rule (``tools/opscenter.py``): a node whose last
        report wall-timestamp trails the fleet's newest by more than the
        threshold renders SILENT even though no live clock is running."""
        now = self._clock()
        with self._lock:
            fl = self._fleet_locked()
            latest_ts = max((st["last_ts"] for st in self._nodes.values()),
                            default=0.0)
            rows = []
            for node, st in sorted(self._nodes.items()):
                if node in self._degraded:
                    status = "DEGRADED"
                elif (silent_after_s and latest_ts
                        and latest_ts - st["last_ts"] > silent_after_s):
                    status = "SILENT"
                else:
                    status = "ok"
                tok = sum((e.get("stats") or {}).get("tokens_per_s", 0.0)
                          for e in st["engines"].values())
                live = sum((e.get("health") or {}).get("live_seqs", 0)
                           for e in st["engines"].values())
                trips = sum((e.get("watchdog") or {}).get("trips_total", 0)
                            for e in st["engines"].values())
                burn = max((row.get("burn", 0.0)
                            for row in st["rows"].values()
                            if row.get("type") == "slo"), default=0.0)
                rows.append((node, status, now - st["last_ingest"],
                             st["reports"], st["last_seq"], tok, live,
                             trips, burn, len(st["spans"])))
        lines = [
            f"fleet [{self.name}]: {fl['nodes']} node(s), "
            f"{fl['reports']} report(s), {len(fl['engines'])} engine(s); "
            f"tok/s {fl['tokens_per_s']:.1f}; trips "
            f"{fl['watchdog_trips']}; degraded: "
            + (",".join(map(str, fl["degraded"])) or "none"),
            f"{'node':>6} {'status':<9} {'age_s':>7} {'reports':>8} "
            f"{'seq':>6} {'tok/s':>9} {'live':>5} {'trips':>6} "
            f"{'burn':>6} {'spans':>6}",
        ]
        for (node, status, age, reports, seq, tok, live, trips, burn,
                spans) in rows:
            lines.append(
                f"{node:>6} {status:<9} {age:>7.2f} {reports:>8} "
                f"{seq:>6} {tok:>9.1f} {live:>5} {trips:>6} "
                f"{burn:>6.2f} {spans:>6}")
        replica_rows = self.replica_rows()
        if replica_rows:
            lines.append(
                f"{'replica':>12} {'state':<11} {'role':<8} "
                f"{'inflight':>9} {'hb_age_ms':>10} {'snap_v':>7} "
                f"{'preempts':>9} {'node':>5}")
            for row in replica_rows:
                lines.append(
                    f"{row['replica']:>12} {row['state']:<11} "
                    f"{row['role']:<8} "
                    f"{row['inflight']:>9} {row['hb_age_ms']:>10.1f} "
                    f"{row['snapshot_version']:>7} "
                    f"{row['preemptions']:>9} {row['node']:>5}")
        for name, h in sorted(fl["histograms"].items()):
            lines.append(
                f"fleet {name}: p50 {h['p50_ms']:.3f} / p95 "
                f"{h['p95_ms']:.3f} / p99 {h['p99_ms']:.3f} ms over "
                f"{h['window_n']} sample(s) "
                f"(bucketed, ±{h['bucket_error']:.1%})")
        for name, s in sorted(fl["slos"].items()):
            state = "OK" if s["ok"] else "BURNING"
            lines.append(
                f"fleet {name}: p{s['percentile']:g} = "
                f"{s['value_ms']:.3f} ms vs {s['target_ms']:.3f} ms, "
                f"burn {s['burn']:.2f} ({state})")
        return "\n".join(lines)

    def replica_rows(self) -> List[Dict[str, Any]]:
        """Serving-fleet replica rows assembled from the router's
        per-replica gauges (``FLEET_REPLICA_STATE[name.rank]`` +
        ``FLEET_INFLIGHT``/``FLEET_HB_AGE_MS``) wherever a node's
        shipped registry carries them — the :class:`FleetRouter`'s
        state machine rendered into the fleet table (state, in-flight,
        heartbeat age), live or from ``tools/opscenter.py`` archives."""
        from .router import ROLE_CODES, STATE_NAMES

        role_names = {code: role for role, code in ROLE_CODES.items()}

        with self._lock:
            per_node = [(node, dict(st["rows"]))
                        for node, st in sorted(self._nodes.items())]
        out: List[Dict[str, Any]] = []
        for node, rows in per_node:
            for name, row in sorted(rows.items()):
                if not (name.startswith("FLEET_REPLICA_STATE[")
                        and name.endswith("]")
                        and row.get("type") == "gauge"):
                    continue
                key = name[len("FLEET_REPLICA_STATE["):-1]
                state = STATE_NAMES.get(int(row.get("value", 0)),
                                        f"?{row.get('value')}")
                inflight = int(rows.get(f"FLEET_INFLIGHT[{key}]",
                                        {}).get("value", 0))
                hb_age = float(rows.get(f"FLEET_HB_AGE_MS[{key}]",
                                        {}).get("value", 0.0))
                # snapshot_version shipped since PR 14, preempts since
                # PR 15; older archives lack the gauges and render -1
                # (the PR 8/11 tolerance pattern)
                snap_v = int(rows.get(f"FLEET_SNAPSHOT_VERSION[{key}]",
                                      {}).get("value", -1))
                preempts = int(rows.get(f"FLEET_PREEMPTS[{key}]",
                                        {}).get("value", -1))
                # role shipped since PR 16; pre-disaggregation archives
                # lack the gauge and render "-" (same tolerance)
                role_code = int(rows.get(f"FLEET_ROLE[{key}]",
                                         {}).get("value", -1))
                role = role_names.get(role_code, "-")
                out.append({"replica": key, "state": state,
                            "role": role,
                            "inflight": inflight, "hb_age_ms": hb_age,
                            "snapshot_version": snap_v,
                            "preemptions": preempts, "node": node})
        return out

    def tenant_rows(self) -> List[Dict[str, Any]]:
        """Fleet-merged per-tenant accounting rows assembled from the
        engine cost ledgers' keyed instruments
        (``TENANT_*[engine.tenant]`` counters +
        ``TENANT_LAT_MS[engine.tenant]`` latency histograms,
        serving/accounting.py) wherever a node's shipped registry
        carries them: latest cumulative value per node summed across
        nodes (the exact counter contract — deltas never compound
        error), completion-latency p99 and SLO breach fraction from
        the bucket-merged fleet windows against the engine's
        ``TENANT_SLO_MS[engine]`` gauge (``breach_frac`` renders -1.0
        when no SLO is registered or no window samples exist — the
        archive-tolerance convention). Rows sort by cost, biggest
        spender first."""
        with self._lock:
            per_node = [(node, dict(st["rows"]), dict(st["buckets"]))
                        for node, st in sorted(self._nodes.items())]
        agg: Dict[str, Dict[str, Any]] = {}
        slo_ms: Dict[str, float] = {}
        lat_exports: Dict[str, List[Any]] = {}

        def ent_for(key: str) -> Dict[str, Any]:
            ent = agg.get(key)
            if ent is None:
                # bundle keys are "{engine}.{tenant}"; engine names
                # never contain dots (tenant ids may)
                eng, _, ten = key.partition(".")
                ent = agg[key] = {
                    "tenant": ten or key, "engine": eng,
                    "requests": 0, "prefill_tokens": 0,
                    "decode_tokens": 0, "xfer_bytes": 0,
                    "kv_block_s": 0.0, "cost": 0.0, "nodes": set()}
            return ent

        for node, rows, buckets in per_node:
            for name, row in rows.items():
                if not name.endswith("]"):
                    continue
                if (name.startswith("TENANT_SLO_MS[")
                        and row.get("type") == "gauge"):
                    eng = name[len("TENANT_SLO_MS["):-1]
                    slo_ms[eng] = max(slo_ms.get(eng, 0.0),
                                      float(row.get("value", 0.0)))
                    continue
                if (name.startswith("TENANT_LAT_MS[")
                        and row.get("type") == "histogram"):
                    key = name[len("TENANT_LAT_MS["):-1]
                    ent_for(key)["nodes"].add(node)
                    exp = buckets.get(name)
                    if exp is not None:
                        lat_exports.setdefault(key, []).append(exp)
                    continue
                if row.get("type") != "counter":
                    continue
                for prefix, field in _TENANT_COUNTER_FIELDS:
                    if name.startswith(prefix):
                        key = name[len(prefix):-1]
                        ent = ent_for(key)
                        ent[field] += row.get("value", 0)
                        ent["nodes"].add(node)
                        break
        out: List[Dict[str, Any]] = []
        for key, ent in agg.items():
            merged = merge_buckets(lat_exports.get(key) or [])
            window_n = merged["zero"] + sum(merged["counts"].values())
            target = slo_ms.get(ent["engine"], 0.0)
            ent["lat_p99_ms"] = (bucket_percentile(merged, 99)
                                 if window_n else 0.0)
            ent["breach_frac"] = (bucket_breach_frac(merged, target)
                                  if target > 0 and window_n else -1.0)
            ent["nodes"] = len(ent["nodes"])
            for field in ("requests", "prefill_tokens", "decode_tokens",
                          "xfer_bytes"):
                ent[field] = int(ent[field])
            ent["kv_block_s"] = round(float(ent["kv_block_s"]), 6)
            ent["cost"] = float(ent["cost"])
            out.append(ent)
        out.sort(key=lambda r: (-r["cost"], r["engine"], r["tenant"]))
        return out

    def tenants_table(self) -> str:
        """The ``opscenter --tenants`` rendering of
        :meth:`tenant_rows`: one line per (engine, tenant), biggest
        spender first (empty string when no ledger rows shipped)."""
        rows = self.tenant_rows()
        if not rows:
            return ""
        lines = [
            f"{'tenant':<16} {'engine':<10} {'reqs':>7} {'prefill':>9} "
            f"{'decode':>9} {'kvblk_s':>9} {'xfer_B':>10} {'cost':>11} "
            f"{'p99_ms':>8} {'breach':>7} {'nodes':>5}"]
        for r in rows:
            breach = ("-" if r["breach_frac"] < 0
                      else f"{r['breach_frac']:.2f}")
            lines.append(
                f"{r['tenant']:<16} {r['engine']:<10} {r['requests']:>7} "
                f"{r['prefill_tokens']:>9} {r['decode_tokens']:>9} "
                f"{r['kv_block_s']:>9.3f} {r['xfer_bytes']:>10} "
                f"{r['cost']:>11.3f} {r['lat_p99_ms']:>8.2f} "
                f"{breach:>7} {r['nodes']:>5}")
        return "\n".join(lines)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "nodes": len(self._nodes),
                "reports": self.reports,
                "degraded": sorted(self._degraded),
                "events": len(self.events),
                "spans": sum(len(st["spans"])
                             for st in self._nodes.values()),
                "spans_missed": sum(st["spans_missed"]
                                    for st in self._nodes.values()),
            }


class ObsAgent:
    """Per-node shipper: builds the bounded delta report every interval
    and moves it to the collector — loopback in a single process, the
    ``mvobs`` :class:`P2PTransport` stream across processes (collector
    node = rank 0, which also observes itself via loopback and drains +
    acks every peer's stream)."""

    LABEL = "mvobs"
    MAX_SPANS = 2048            # spans per report (overflow counted)
    MAX_OUTSTANDING = 64        # un-acked reports before dropping whole ones

    def __init__(self, rank: int = 0, size: int = 1, client: Any = None,
                 report_ms: Optional[int] = None, collector_rank: int = 0,
                 engines: Optional[Callable[[], Dict[str, Any]]] = None,
                 sink: str = "", degraded_after_s: Optional[float] = None,
                 label: str = LABEL,
                 collector: Optional[ObsCollector] = None,
                 start: bool = True) -> None:
        self._rank = int(rank)
        self._size = int(size)
        self._client = client
        self._label = label
        self._interval = max(
            (int(config.get_flag("obs_report_ms"))
             if report_ms is None else int(report_ms)), 10) / 1000.0
        self._collector_rank = int(collector_rank)
        self._engines_fn = engines or _session_engines
        self._sink = sink
        self.collector: Optional[ObsCollector] = None
        if self._size <= 1 or self._rank == self._collector_rank:
            self.collector = collector or ObsCollector(
                degraded_after_s=(2.0 * self._interval
                                  if degraded_after_s is None
                                  else float(degraded_after_s)),
                name=f"{label}@{self._rank}")
        if self.collector is not None and self._size > 1:
            # the roster is known at construction: seed every fleet rank
            # so a replica that dies BEFORE its first report (boot
            # wedge, crash during warmup) still ages out and flags
            # DEGRADED instead of being invisible to the table
            self.collector.expect_nodes(range(self._size))
        self._transport = None
        if self._size > 1:
            from ..parallel.p2p import P2PTransport

            # hub topology: only the collector rank subscribes (to
            # every publisher); agents publish-only — reports cross the
            # wire exactly once instead of broadcasting full-mesh
            self._transport = P2PTransport(
                self._rank, self._size, client, label=label,
                subscribe_to=(
                    [r for r in range(self._size) if r != self._rank]
                    if self._rank == self._collector_rank else []))
        # serializes report build+commit pairs (the MetricsExporter
        # _report_lock pattern) for direct concurrent tick() callers;
        # the loop-vs-final-report race is excluded STRUCTURALLY —
        # stop() skips the final report when the loop fails to join,
        # because seq assignment + send order can't be lock-protected
        # without blocking I/O under a lock (locklint LK203)
        self._tick_lock = lockwatch.lock("serving.ObsAgent._tick_lock")
        self._last_snap: Optional[Dict[str, Dict[str, Any]]] = None
        self._last_mono: Optional[float] = None
        self._span_cursor = 0
        self._wd_cursor: Dict[str, int] = {}
        self._engines_seen: Dict[str, Any] = {}
        self._seq = 0
        self._released = 0
        self._next_seq: Dict[int, int] = {
            r: 0 for r in range(self._size) if r != self._rank}
        self.reports = 0
        self.dropped_reports = 0
        self.spans_shipped = 0
        self.spans_missed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ObsAgent":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"mv-obs-{self._rank}", daemon=True)
        self._thread.start()
        Dashboard.attach_reporter(self)
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception as exc:    # pragma: no cover - defensive
                Log.error("obs agent[%d]: report failed: %s", self._rank,
                          exc)

    def detach(self) -> None:
        """``Dashboard.reset()`` hook: stop WITHOUT a final report (the
        instruments were just cleared)."""
        self.stop(final_report=False)

    def stop(self, final_report: bool = True) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
            self._thread = None
            if thread.is_alive():
                # a wedged loop may be MID-TICK: running the final
                # report concurrently could assign the same transport
                # seq twice (an out-of-order or overwritten record
                # stalls the collector's in-order pop forever) — skip
                # it; tick() is single-threaded by exclusion, not by
                # locking the send path (locklint LK203)
                Log.error("obs agent[%d]: loop thread failed to join; "
                          "skipping the final report", self._rank)
                final_report = False
        Dashboard.detach_reporter(self)
        if final_report:
            try:
                self.tick()
            except Exception as exc:
                Log.error("obs agent[%d]: final report failed: %s",
                          self._rank, exc)
            if self.collector is None and self._transport is not None:
                # best-effort flush: transport.send only RETAINS the
                # final report and wakes the async sender — closing the
                # sockets immediately would usually lose it. The
                # collector acks after its next drain tick, so wait
                # (bounded) for the ack frontier to cover our last seq.
                deadline = time.monotonic() + min(
                    5.0, max(1.0, 3.0 * self._interval))
                while time.monotonic() < deadline:
                    if self._read_ack() >= self._seq:
                        break
                    time.sleep(0.05)
        if self._transport is not None:
            self._transport.stop()

    # -- one report ---------------------------------------------------------
    def build_report(self) -> Dict[str, Any]:
        """Assemble one bounded delta report (see the module docstring
        for the wire schema). No plane lock is held while the registry
        fans out — ``Dashboard.snapshot()``, ``engine.stats()`` and the
        trace drain all take their own locks."""
        snap = Dashboard.snapshot()
        now = time.time()
        mono = time.monotonic()
        dt = (mono - self._last_mono) if self._last_mono is not None else None
        deltas = snapshot_deltas(self._last_snap, snap, dt)
        prev = self._last_snap or {}
        rows = {name: row for name, row in snap.items()
                if prev.get(name) != row}
        buckets: Dict[str, Any] = {}
        for name, row in rows.items():
            if row.get("type") != "histogram":
                continue
            hist = Dashboard.get_or_create_histogram(name)
            buckets[name] = hist.buckets()
        engines: Dict[str, Any] = {}
        # discovery can go dark before the agent does: Session.stop()
        # empties the server registry BEFORE the teardown ships our
        # final report, but the engine objects themselves are still
        # alive (they stop AFTER the obs agent). Cache the last
        # non-empty discovery so that final report still carries every
        # engine's terminal stats — and the last interval's watchdog
        # trips, whose trips_since cursor is never re-read
        found = self._engines_fn() or {}
        if found:
            self._engines_seen = dict(found)
        for name, engine in (found or self._engines_seen).items():
            try:
                eng: Dict[str, Any] = {"stats": engine.stats(),
                                       "health": engine.health()}
                wd = getattr(engine, "watchdog", None)
                if wd is not None:
                    cursor, new = wd.trips_since(self._wd_cursor.get(name, 0))
                    self._wd_cursor[name] = cursor
                    eng["watchdog"] = {
                        "trips_total": wd.trip_count,
                        "new_trips": [[k, r] for k, r, _ in new]}
                rec = getattr(engine, "recorder", None)
                if rec is not None:
                    eng["flight"] = rec.summary()
                engines[name] = eng
            except Exception as exc:
                Log.error("obs agent[%d]: engine %r report failed: %s",
                          self._rank, name, exc)
        coll = trace.collector()
        self._span_cursor, new_spans, missed = coll.drain_since(
            self._span_cursor)
        if len(new_spans) > self.MAX_SPANS:
            missed += len(new_spans) - self.MAX_SPANS
            new_spans = new_spans[-self.MAX_SPANS:]
        self.spans_shipped += len(new_spans)
        self.spans_missed += missed
        anchor = coll.anchor()
        report = {
            "v": WIRE_VERSION,
            "node": self._rank,
            "seq": self._seq,
            "ts": now,
            "mono": mono,
            "interval_s": dt,
            "rows": rows,
            "deltas": deltas,
            "buckets": buckets,
            "engines": engines,
            "spans": [sp.to_dict() for sp in new_spans],
            "spans_missed": missed,
            "trace_anchor": [anchor[0], anchor[1]],
        }
        self._last_snap, self._last_mono = snap, mono
        return report

    def tick(self) -> Optional[Dict[str, Any]]:
        """Build + ship one report (returns it; ``None`` when the full
        publish window forced a whole-report drop); on the collector
        node also drain and ack every peer stream, then run the
        degraded check. The tests' direct entry point (the loop calls
        it every interval).

        ``_tick_lock`` covers ONLY the build+commit pair (direct
        concurrent callers must commit last-snapshot state in build
        order, the ``MetricsExporter._report_lock`` pattern; the
        loop-vs-final-report race is excluded structurally — ``stop()``
        skips the final report on a failed join). Everything else
        runs OUTSIDE it: the sink write blocks on disk, ingest runs the
        collector's merges, and the transport takes its own locks
        (locklint LK202/LK203)."""
        if self.collector is None and self._transport is not None \
                and not self._release_acked_and_can_ship():
            # the collector stopped consuming: drop BEFORE building, so
            # the delta state (_last_snap, span/trip cursors) is never
            # consumed by a report that can't ship — when capacity
            # frees, the next build diffs against the pre-drop snapshot
            # and every changed row, trip and span still goes out
            # exactly once (the "a lost report never skews a sum" /
            # "every trip forwards once" contracts)
            self.dropped_reports += 1
            self._drain_peers()
            return None
        with self._tick_lock:
            report = self.build_report()
        if self.collector is not None:
            self.collector.ingest(self._rank, report)
            self._seq += 1
            self.reports += 1
        elif self._transport is not None:
            self._ship(report)
        if self._sink:
            # the archive is a convenience sink: it writes AFTER the
            # report shipped and a failure (full disk, bad path) must
            # not cost the live plane the delta state the build just
            # consumed — log and keep reporting
            try:
                with open(self._sink, "a") as f:
                    f.write(json.dumps(report, default=str) + "\n")
            except OSError as exc:
                Log.error("obs agent[%d]: report sink failed: %s",
                          self._rank, exc)
        if self._transport is not None:
            self._drain_peers()
        if self.collector is not None:
            self.collector.check()
        return report

    def _release_acked_and_can_ship(self) -> bool:
        """Advance the release frontier to the collector's ack and say
        whether the publish window has room — the ship/drop decision
        ``tick`` makes BEFORE building a report (a report that can't
        ship must never consume the delta cursors)."""
        ack = self._read_ack()
        while self._released < min(ack, self._seq):
            self._transport.release(self._released)
            self._released += 1
        return self._seq - self._released < self.MAX_OUTSTANDING

    def _ship(self, report: Dict[str, Any]) -> None:
        payload = json.dumps(report, default=str).encode()
        self._transport.send(self._seq, payload)
        self._seq += 1
        self.reports += 1

    def _read_ack(self) -> int:
        key = f"{self._label}/ack/{self._rank}"
        client = self._client
        try:
            if hasattr(client, "key_value_try_get"):
                raw = client.key_value_try_get(key)
            else:
                # jax <= 0.4.x DistributedRuntimeClient has NO try-get
                # (verified: blocking_key_value_get/_set are the whole
                # KV surface) — a short blocking get does the job: a
                # missing key (no ack yet) surfaces as an exception
                # after the timeout instead of wedging the loop
                raw = client.blocking_key_value_get(key, 200)
            return int(str(raw))
        except Exception:
            return self._released

    def _drain_peers(self) -> None:
        """Pop every ready record from every peer stream and ack what
        was consumed. Only the collector rank subscribes (hub
        topology), so on every other node the inboxes stay empty and
        this is a cheap no-op pass."""
        tp = self._transport
        for r in list(self._next_seq):
            consumed = False
            while True:
                payload = tp.pop_ready(r, self._next_seq[r])
                if payload is None:
                    break
                self._next_seq[r] += 1
                consumed = True
                if self.collector is None:
                    continue
                try:
                    rep = json.loads(bytes(payload).decode())
                except ValueError:
                    Log.error("obs agent[%d]: undecodable report from "
                              "node %d (seq %d)", self._rank, r,
                              self._next_seq[r] - 1)
                    continue
                self.collector.ingest(int(rep.get("node", r)), rep)
            if consumed and self.collector is not None:
                try:
                    self._client.key_value_set(
                        f"{self._label}/ack/{r}", str(self._next_seq[r]),
                        allow_overwrite=True)
                except Exception as exc:    # pragma: no cover - kv trouble
                    Log.error("obs agent[%d]: ack for node %d failed: %s",
                              self._rank, r, exc)

    def stats(self) -> Dict[str, Any]:
        return {
            "rank": self._rank,
            "size": self._size,
            "interval_s": self._interval,
            "reports": self.reports,
            "dropped_reports": self.dropped_reports,
            "spans_shipped": self.spans_shipped,
            "spans_missed": self.spans_missed,
            # un-acked wire reports (0 in loopback / on the collector
            # node — nothing is retained when reports ingest locally)
            "outstanding": ((self._seq - self._released)
                            if (self.collector is None
                                and self._transport is not None) else 0),
            "collector": self.collector.stats()
            if self.collector is not None else None,
        }


def _session_engines() -> Dict[str, Any]:
    """Default engine discovery: every decode engine registered on every
    live ``InferenceServer`` of the current Session (by engine name —
    unique per registration)."""
    from ..runtime import Session

    sess = Session._instance
    out: Dict[str, Any] = {}
    if sess is None or not sess.started:
        return out
    for srv in list(sess.servers):
        entries = getattr(srv, "_models", None)
        if entries is None:
            continue
        with srv._lock:
            values = list(entries.values())
        for entry in values:
            engine = getattr(entry, "engine", None)
            if engine is not None:
                out[engine.name] = engine
    return out
