"""Online serving: batched low-latency inference over live parameter tables.

The inference half of the train/serve stack (docs/SERVING.md). Pieces:

* :class:`InferenceServer` — request router; named models, blocking
  ``predict`` / async ``submit``, per-model stats.
* :class:`MicroBatcher` — bounded queue flushed on max-batch-size OR
  deadline, padded to jit-warm shape buckets, load-shedding past the
  queue-depth cap (:class:`OverloadedError`).
* :class:`SnapshotManager` — versioned copy-on-publish read views over
  tables/models; replies carry a staleness bound.
* workloads — jitted inference for the three model families:
  :class:`EmbeddingNeighbors` (word2vec lookup + top-k),
  :class:`LogRegPredict` / :class:`FTRLPredict`, and
  :class:`LMGreedyDecode` (KV-cache greedy decode).
* :class:`DecodeEngine` — continuous-batching LM decode: paged KV
  cache (:class:`BlockPool` block allocator + per-slot block tables
  traced as data; capacity, not slot geometry, bounds concurrency),
  ONE fused jitted step per iteration, iteration-granular
  admission/completion (``InferenceServer.register_decoder``), chunked
  prefill under a per-iteration token budget
  (``prefill_token_budget``) so admissions never stall in-flight
  generations for more than one chunk of work, and content-addressed
  prefix caching (``prefix_cache``: hash-chained block identities via
  :func:`chain_hashes`, refcounted sharing, copy-on-write) so prompts
  sharing a prefix prefill it once (docs/SERVING.md "Prefix caching").
  Under load it degrades BY POLICY (``-preempt``): requests carry
  priority classes and deadlines, the queue is a weighted-fair
  per-class scheduler that drops expired requests at pop time
  (:class:`DeadlineExceededError`) before burning prefill, paged
  admission reserves prompt blocks only and grows at decode time, and
  pool exhaustion preempts the lowest-priority/youngest sequence —
  recomputed on resume to a bit-identical output (docs/SERVING.md
  "Overload and preemption").
* the black box — :class:`FlightRecorder` (always-on bounded ring of
  per-iteration engine records) and :class:`EngineWatchdog`
  (stall/leak/queue-age self-diagnosis; trips dump a diagnostic bundle
  to ``-debug_dump_dir`` and count in ``WATCHDOG_TRIPS``), so a wedged
  or leaking engine produces evidence instead of silence.
* the serving fleet — :class:`FleetRouter` (failure-aware front door:
  least-loaded dispatch with session affinity, per-request deadlines,
  bounded retry with backoff+jitter, heartbeat-observed replica
  liveness with half-open readmission, ``OverloadedError(
  what="fleet")`` shedding) over N :class:`ReplicaServer` decode
  replicas on the ``mvserve`` p2p wire; a killed replica's in-flight
  requests replay bit-identically on survivors, and
  :class:`FaultPlan` (``-chaos``) stages the failures that prove it
  (docs/SERVING.md "Serving fleet"). Replicas can specialize
  (``role="prefill"|"decode"``; default ``unified``): the router's
  two-stage dispatch prefills on one replica, ships the paged KV
  blocks + content chain hashes over the wire (``kv_transfer``) and
  splices them into the decode replica's pool — bit-identical to
  unified serving, with warm prefixes deduped off the wire
  (docs/SERVING.md "Disaggregated prefill/decode").
* the durable train half — :class:`ParamPublisher` /
  :class:`ParamSubscriber` (``mvparam`` wire): the trainer's fenced
  parameter publish stream into serving replicas. Each trainer
  incarnation claims a monotonic epoch, rebases subscribers with one
  STATE record on restart, and lower-epoch (zombie) records are
  rejected by the epoch fence; subscribers flag STALE past
  ``-params_stale_after_s`` when the stream goes silent and recover
  automatically (docs/DISTRIBUTED.md "Durability").
"""

from .batcher import (BatcherConfig, MicroBatcher, OverloadedError,
                      bucket_for, shape_buckets)
from .faultinject import FaultPlan
from .param_plane import ParamPublisher, ParamSubscriber
from .replica import ReplicaServer, serve_replica
from .router import (DeadlineExceededError, FleetConfig, FleetError,
                     FleetRouter, retry_backoff_s)
from .block_pool import (BlockPool, blocks_for_bytes, chain_hashes,
                         kv_bytes_per_block)
from .decode_engine import DecodeEngine, DecodeEngineConfig
from .flight_recorder import FlightRecorder
from .obs_plane import ObsAgent, ObsCollector
from .server import InferenceServer
from .snapshot import Snapshot, SnapshotManager
from .watchdog import EngineWatchdog, WatchdogConfig
from .workloads import (EmbeddingNeighbors, FTRLPredict, LMGreedyDecode,
                        LogRegPredict)

__all__ = [
    "BatcherConfig", "MicroBatcher", "OverloadedError", "bucket_for",
    "shape_buckets", "InferenceServer", "Snapshot", "SnapshotManager",
    "EmbeddingNeighbors", "FTRLPredict", "LMGreedyDecode", "LogRegPredict",
    "DecodeEngine", "DecodeEngineConfig", "BlockPool", "blocks_for_bytes",
    "chain_hashes", "kv_bytes_per_block", "FlightRecorder",
    "EngineWatchdog", "WatchdogConfig", "ObsAgent", "ObsCollector",
    "FaultPlan", "ReplicaServer", "serve_replica", "FleetRouter",
    "FleetConfig", "FleetError", "DeadlineExceededError",
    "retry_backoff_s", "ParamPublisher", "ParamSubscriber",
]
