"""Decode-engine replica: one fleet member's request/response stream.

One :class:`ReplicaServer` wraps one decode engine (any object with the
``submit(prompt, max_new, ctx) -> Future`` / ``health()`` / ``stats()``
/ ``stop()`` surface — a :class:`~.decode_engine.DecodeEngine` in
production, a deterministic fake in the router unit tests) and exposes
it to the :class:`~.router.FleetRouter` over the existing
:class:`~multiverso_tpu.parallel.p2p.P2PTransport` wire under the new
label ``mvserve``. Topology is the obs plane's hub, inverted twice:

* the ROUTER (rank 0) is the only publisher of requests — every replica
  subscribes to its stream and executes the records targeted at it
  (``target`` field; the per-publisher stream is a replay log, so
  non-targets are skipped, not an error);
* every REPLICA publishes its own response stream — the router is its
  only subscriber. Responses, errors and heartbeats ride it in
  publish order.

Liveness is *observed, not assumed*: a heartbeat thread publishes
``engine.health()`` every ``-fleet_heartbeat_ms`` — the router's DEAD
verdict is heartbeat-age over the wire, never a local guess. Requests
carry idempotent ids; a replica replays whatever the stream hands it
and the router dedupes by rid, which is what makes the resume/replay
path after a death boring instead of subtle.

Restart contract (the half-open readmission path): a restarted replica
process re-advertises its endpoint (the KV outlives it), resumes its
SUBSCRIPTION from the router's published stream head
(``{label}/head``) — requests before the head were already drained and
re-dispatched when the router flagged the death, so replaying them
would be wasted work — and resumes its PUBLISH sequence from the
router's ack (``{label}/rack/<rank>``) so the router's in-order
consumer sees one contiguous stream across incarnations.

Fault injection (:mod:`.faultinject`) hooks exactly three places:
request dequeue (kill/wedge), outbound publish (delay), and the
heartbeat (drop/slow) — enough to stage every failure the router
claims to survive, few enough to audit.
"""

from __future__ import annotations

import collections
import json
import threading
from ..analysis import lockwatch
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from .. import config, trace
from ..log import Log
from .batcher import OverloadedError
from .faultinject import FaultPlan

LABEL = "mvserve"
ROUTER_RANK = 0

#: wire message kinds (one JSON object per transport record)
MSG_REQ = "req"        # router -> replica: execute a prompt
MSG_PING = "ping"      # router -> replica: half-open readmission probe
MSG_RSP = "rsp"        # replica -> router: completed generation
MSG_ERR = "err"        # replica -> router: shed / engine failure
MSG_PONG = "pong"      # replica -> router: probe answer
MSG_HB = "hb"          # replica -> router: engine.health() heartbeat
MSG_XFER = "xfer"      # replica -> router: prefilled KV-block payload
                       # (disaggregated stage 1 -> the router carries it
                       # to the chosen decode replica in stage 2)

#: replica roles (disaggregated serving). "unified" is the back-compat
#: default: the replica both prefills and decodes, exactly the pre-PR
#: fleet. A "prefill" replica only serves stage-1 prefill-only
#: admissions; a "decode" replica serves stage-2 (splice + generate) —
#: and either still handles a plain unified request, which is what
#: makes the router's no-prefill-UP fallback safe.
ROLES = ("unified", "prefill", "decode")


def encode_msg(msg: Dict[str, Any]) -> bytes:
    return json.dumps(msg, default=str).encode()


def decode_msg(payload: bytes) -> Dict[str, Any]:
    return json.loads(bytes(payload).decode())


class ReplicaServer:
    """One decode replica on the ``mvserve`` wire (ranks 1..N; rank 0
    is the router). ``engine`` must already be constructed/warm —
    building it is the caller's business (``serve_replica`` below is
    the flag-wired standalone entry the subprocess tests use)."""

    def __init__(self, rank: int, size: int, client: Any, engine: Any,
                 label: str = LABEL, heartbeat_ms: Optional[int] = None,
                 chaos: Optional[FaultPlan] = None,
                 kill_fn: Optional[Callable[[], None]] = None,
                 role: str = "unified") -> None:
        from ..parallel.p2p import P2PTransport

        if not 1 <= rank < size:
            raise ValueError(f"replica rank {rank} outside [1, {size})")
        if role not in ROLES:
            raise ValueError(f"replica role {role!r} not in {ROLES}")
        self.rank = int(rank)
        self.size = int(size)
        self.role = role
        self._client = client
        self._label = label
        self.engine = engine
        hb_ms = (int(config.get_flag("fleet_heartbeat_ms"))
                 if heartbeat_ms is None else int(heartbeat_ms))
        self._hb_interval = max(hb_ms, 5) / 1000.0
        self.chaos = chaos if chaos is not None else FaultPlan(
            "", kill_fn=kill_fn)
        if kill_fn is not None and chaos is not None:
            self.chaos._kill_fn = kill_fn
        # engine capability probe: the priority/deadline keywords only
        # ride when the engine's submit takes them (the router unit
        # tests' deterministic fakes keep the classic 3-arg surface)
        try:
            import inspect

            params = inspect.signature(engine.submit).parameters
            self._engine_prio = "priority" in params
            self._engine_xfer_kw = "xfer_info" in params
            self._engine_tenant = "tenant" in params
        except (TypeError, ValueError):   # builtins/partials: assume new
            self._engine_prio = True
            self._engine_xfer_kw = True
            self._engine_tenant = True
        # transfer-plane capability: an inbound payload only splices
        # when the engine can (the fakes keep the classic surface —
        # the payload is then ignored and the prompt prefills locally;
        # a stage-1 request against an engine without submit_prefill
        # errors through the normal MSG_ERR path)
        self._engine_splice = hasattr(engine, "splice")
        # publish seq resumes from the router's ack so the router's
        # in-order consumer sees ONE contiguous stream across replica
        # incarnations; subscription resumes from the router's stream
        # head — everything before it was drained + re-dispatched when
        # the router flagged our predecessor dead
        self._seq = self._read_kv_int(f"{label}/rack/{rank}", 0)
        self._released = self._seq
        head = self._read_kv_int(f"{label}/head", 0)
        self._transport = P2PTransport(
            self.rank, self.size, client, label=label,
            subscribe_to=[ROUTER_RANK],
            initial_resume={ROUTER_RANK: head})
        self._expect = head
        # ONE publisher thread owns seq allocation + the wire send:
        # the drain loop, the heartbeat thread and the engine's
        # completion callbacks all just enqueue here — no lock is ever
        # held across a send (locklint LK203), and per-publisher wire
        # order is the outbox's FIFO order by construction
        self._out_cv = lockwatch.condition(
            name="serving.ReplicaServer._out_cv")
        self._outbox: "collections.deque" = collections.deque()
        self._stop = threading.Event()
        self.requests_seen = 0          # targeted reqs dequeued (chaos k)
        self.completed = 0
        self.failed = 0
        self.heartbeats = 0
        self.xfers_sent = 0             # stage-1 payloads published
        self.xfers_spliced = 0          # stage-2 payloads applied
        self._threads = [
            threading.Thread(target=self._drain_loop,
                             name=f"mvserve-replica-{rank}", daemon=True),
            threading.Thread(target=self._heartbeat_loop,
                             name=f"mvserve-hb-{rank}", daemon=True),
            threading.Thread(target=self._publish_loop,
                             name=f"mvserve-pub-{rank}", daemon=True),
        ]
        for t in self._threads:
            t.start()
        Log.info("fleet: replica %d/%d up (hb %.0f ms, resume seq %d, "
                 "head %d)", rank, size - 1, self._hb_interval * 1e3,
                 self._seq, head)

    # -- kv helpers ----------------------------------------------------------
    def _read_kv_int(self, key: str, default: int) -> int:
        try:
            if hasattr(self._client, "key_value_try_get"):
                return int(str(self._client.key_value_try_get(key)))
            return int(str(self._client.blocking_key_value_get(key, 200)))
        except Exception:
            return default

    # -- publish side --------------------------------------------------------
    def _publish(self, msg: Dict[str, Any]) -> None:
        with self._out_cv:
            self._outbox.append(msg)
            self._out_cv.notify()

    def _publish_loop(self) -> None:
        while True:
            with self._out_cv:
                while not self._outbox and not self._stop.is_set():
                    self._out_cv.wait(0.2)
                if self._stop.is_set():
                    return
                msg = self._outbox.popleft()
            # chaos wire delay stalls the publisher itself — every
            # record behind the delayed one waits too, which is what a
            # congested/flaky wire actually looks like
            delay = self.chaos.wire_delay_s()
            if delay > 0:
                time.sleep(delay)
            seq = self._seq
            self._seq = seq + 1
            self._transport.send(seq, encode_msg(msg))

    def _release_acked(self) -> None:
        """Drop retained records the router has consumed (its ack in
        the KV) — the obs plane's release frontier, replica-side."""
        ack = self._read_kv_int(f"{self._label}/rack/{self.rank}", 0)
        while self._released < ack:
            self._transport.release(self._released)
            self._released += 1

    # -- request side --------------------------------------------------------
    def _drain_loop(self) -> None:
        consumed = False
        while not self._stop.is_set():
            payload = self._transport.pop_ready(ROUTER_RANK, self._expect)
            if payload is None:
                if consumed:
                    # ack once per DRAINED BATCH, not per record: the
                    # ack only needs to be current when the router
                    # reads it (tick granularity), and a per-record
                    # key_value_set would be R synchronous KV writes
                    # per dispatched request against a real
                    # coordination service
                    self._write_ack()
                    consumed = False
                time.sleep(0.002)
                continue
            self._expect += 1
            consumed = True
            try:
                msg = decode_msg(payload)
            except ValueError:
                Log.error("fleet: replica %d got undecodable record "
                          "(seq %d)", self.rank, self._expect - 1)
                continue
            try:
                self._handle(msg)
            except Exception as exc:    # pragma: no cover - defensive
                Log.error("fleet: replica %d handler failed: %s",
                          self.rank, exc)

    def _write_ack(self) -> None:
        """Advance the router-visible consume frontier (also where a
        restarted successor resumes its publish seq from)."""
        try:
            self._client.key_value_set(
                f"{self._label}/ack/{self.rank}", str(self._expect),
                allow_overwrite=True)
        except Exception:               # pragma: no cover - kv trouble
            pass

    def _handle(self, msg: Dict[str, Any]) -> None:
        kind = msg.get("t")
        if msg.get("target") != self.rank:
            return                       # another replica's record
        if kind == MSG_PING:
            self._publish({"t": MSG_PONG, "node": self.rank,
                           "rid": msg.get("rid")})
            return
        if kind != MSG_REQ:
            return
        self.requests_seen += 1
        wedge_s = self.chaos.on_request(self.requests_seen)
        if self._stop.is_set():
            # an in-process kill_fn (replica.die) RETURNS instead of
            # os._exit'ing — honor the death here: the fatal request
            # must not still be submitted to the "dead" replica's
            # engine (it would burn slots concurrently with the
            # survivor's replay, which a real process death never does)
            return
        if wedge_s > 0:
            time.sleep(wedge_s)
        rid = msg["rid"]
        parent = None
        if msg.get("trace"):
            tid, sid = msg["trace"]
            parent = trace.SpanContext(int(tid), int(sid))
        sp = trace.start_span("replica.exec", parent=parent,
                              replica=self.rank, rid=rid)
        prompt = np.asarray(msg["prompt"], np.int32)
        # the router ships the REMAINING deadline budget (clocks are
        # per-process): re-anchor it on our monotonic clock so the
        # engine's pop-time check measures the same instant
        deadline_ms = msg.get("deadline_ms")
        deadline_s = (None if not deadline_ms
                      else float(deadline_ms) / 1e3)
        # chaos traffic faults staged at dequeue: a burst submits N
        # extra copies of this prompt straight into the local engine
        # (a one-replica traffic spike), a pool squeeze holds part of
        # the engine's KV pool hostage so preemption runs under real
        # pressure
        for _ in range(self.chaos.burst_n(self.requests_seen)):
            try:
                self.engine.submit(prompt, msg.get("max_new"))
            except Exception:            # sheds are part of the chaos
                pass
        squeeze = self.chaos.squeeze_frac(self.requests_seen)
        if squeeze is not None and hasattr(self.engine, "squeeze_pool"):
            self.engine.squeeze_pool(squeeze)
        if (self.chaos.squeeze_release(self.requests_seen)
                and hasattr(self.engine, "unsqueeze_pool")):
            self.engine.unsqueeze_pool()
        if msg.get("stage") == "prefill":
            # disaggregated stage 1: chunk-prefill the prompt into
            # paged blocks and reply with the transfer payload instead
            # of tokens ("known" = chain hashes the decode side already
            # holds — those ride as metadata, zero bytes)
            try:
                pkw = ({"tenant": msg.get("tenant")}
                       if self._engine_tenant else {})
                fut = self.engine.submit_prefill(
                    prompt, msg.get("known") or (),
                    ctx=sp.context if parent else None, **pkw)
            except Exception as exc:
                sp.end(error=type(exc).__name__)
                self.failed += 1
                err = {"t": MSG_ERR, "node": self.rank, "rid": rid,
                       "kind": "error", "what": type(exc).__name__,
                       "msg": str(exc)}
                if isinstance(exc, OverloadedError):
                    err.update(kind="overloaded", what=exc.what,
                               depth=exc.depth, cap=exc.cap,
                               retriable=exc.retriable)
                self._publish(err)
                return
            fut.add_done_callback(
                lambda f, rid=rid, sp=sp: self._reply_xfer(rid, f, sp))
            return
        xfer_info = None
        if msg.get("xfer") is not None and self._engine_splice:
            # disaggregated stage 2: splice the carried payload into
            # the local pool BEFORE submitting the prompt, so admission
            # sees the warm prefix (full hit -> CoW -> live at P-1).
            # splice degrades instead of raising — a bad/stale/dropped
            # payload just means the prompt re-prefills locally
            xfer_info = self.engine.splice(msg["xfer"])
            self.xfers_spliced += 1
        kw = {}
        if self._engine_prio:
            kw = {"priority": msg.get("prio"), "deadline_s": deadline_s}
        if xfer_info is not None and self._engine_xfer_kw:
            kw["xfer_info"] = xfer_info
        if self._engine_tenant:
            # absent on the wire (old router, archived payload) decodes
            # as None -> the engine ledger's -default_tenant
            kw["tenant"] = msg.get("tenant")
        try:
            fut = self.engine.submit(prompt, msg.get("max_new"),
                                     ctx=sp.context if parent else None,
                                     **kw)
        except OverloadedError as exc:
            sp.end(error="OverloadedError")
            self.failed += 1
            self._publish({"t": MSG_ERR, "node": self.rank, "rid": rid,
                           "kind": "overloaded", "what": exc.what,
                           "depth": exc.depth, "cap": exc.cap,
                           "retriable": exc.retriable,
                           "msg": str(exc)})
            return
        except Exception as exc:
            sp.end(error=type(exc).__name__)
            self.failed += 1
            self._publish({"t": MSG_ERR, "node": self.rank, "rid": rid,
                           "kind": "error", "what": type(exc).__name__,
                           "msg": str(exc)})
            return
        fut.add_done_callback(
            lambda f, rid=rid, sp=sp: self._reply(rid, f, sp))

    def _reply(self, rid: str, fut, sp) -> None:
        if self._stop.is_set():
            # died mid-generation: no reply — but the span still
            # closes (an unclosed span is an invariant break, and the
            # trace should SHOW the request dying on this replica)
            sp.end(error="died")
            return
        exc = fut.exception()
        if exc is not None:
            sp.end(error=type(exc).__name__)
            self.failed += 1
            err = {"t": MSG_ERR, "node": self.rank, "rid": rid,
                   "kind": "error", "what": type(exc).__name__,
                   "msg": str(exc)}
            if isinstance(exc, OverloadedError):
                err.update(kind="overloaded", what=exc.what,
                           depth=exc.depth, cap=exc.cap,
                           retriable=exc.retriable)
            self._publish(err)
            return
        reply = fut.result()
        sp.end(ok=True)
        self.completed += 1
        self._publish({
            "t": MSG_RSP, "node": self.rank, "rid": rid,
            "result": np.asarray(reply["result"], np.int32).tolist(),
            "snapshot_version": reply.get("snapshot_version"),
            "staleness_s": reply.get("staleness_s", 0.0)})

    def _reply_xfer(self, rid: str, fut, sp) -> None:
        """Stage-1 completion: publish the KV-block payload as a
        MSG_XFER record for the router to carry to the decode replica.
        The ``kv_xfer_drop`` chaos point fires here — the payload's
        K/V bytes are stripped mid-flight while the header + hash chain
        survive, so the loss is observable and the decode side
        re-prefills (latency, never tokens)."""
        if self._stop.is_set():
            sp.end(error="died")
            return
        exc = fut.exception()
        if exc is not None:
            sp.end(error=type(exc).__name__)
            self.failed += 1
            err = {"t": MSG_ERR, "node": self.rank, "rid": rid,
                   "kind": "error", "what": type(exc).__name__,
                   "msg": str(exc)}
            if isinstance(exc, OverloadedError):
                err.update(kind="overloaded", what=exc.what,
                           depth=exc.depth, cap=exc.cap,
                           retriable=exc.retriable)
            self._publish(err)
            return
        reply = fut.result()
        payload = reply["xfer"]
        self.xfers_sent += 1
        if self.chaos.drop_kv_xfer(self.xfers_sent):
            from . import kv_transfer

            payload = kv_transfer.drop_blocks(payload)
        sp.end(ok=True)
        self.completed += 1
        self._publish({
            "t": MSG_XFER, "node": self.rank, "rid": rid,
            "payload": payload,
            "snapshot_version": reply.get("snapshot_version"),
            "staleness_s": reply.get("staleness_s", 0.0)})

    # -- heartbeat side ------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        # heartbeat_scale is read PER BEAT, not folded in at init: the
        # bench/test idiom assigns replica.chaos after construction,
        # and a slow_heartbeat plan assigned that way must actually
        # slow the beats (not pass vacuously)
        while not self._stop.wait(self._hb_interval
                                  * self.chaos.heartbeat_scale):
            if self.chaos.drop_heartbeat():
                continue
            try:
                health = self.engine.health()
            except Exception as exc:    # pragma: no cover - defensive
                health = {"error": str(exc)}
            self.heartbeats += 1
            self._publish({"t": MSG_HB, "node": self.rank,
                           "n": self.heartbeats, "mono": time.monotonic(),
                           "role": self.role, "health": health})
            self._release_acked()

    # -- lifecycle -----------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "rank": self.rank,
            "role": self.role,
            "requests_seen": self.requests_seen,
            "completed": self.completed,
            "failed": self.failed,
            "heartbeats": self.heartbeats,
            "xfers_sent": self.xfers_sent,
            "xfers_spliced": self.xfers_spliced,
            "chaos": self.chaos.stats(),
        }

    def die(self) -> None:
        """In-process analogue of ``kill_at_request``'s ``os._exit``:
        stop heartbeating and replying IMMEDIATELY and drop the wire
        mid-stream — no drain, no goodbye. The engine object survives
        (the test/bench owns its cleanup); the fleet just sees this
        replica go dark. ``FaultPlan(kill_fn=replica.die)`` wires it."""
        self._stop.set()
        with self._out_cv:
            self._outbox.clear()         # unreplied, like a real crash
            self._out_cv.notify_all()
        self._transport.stop()

    def stop(self, stop_engine: bool = True) -> None:
        """Graceful shutdown (clean exit path): stop accepting, let the
        wire drain briefly, then close. ``stop_engine=False`` leaves
        the (expensive, warm) engine alive for the next incarnation —
        the bench's A/B legs re-wrap the same engines."""
        # let the publisher flush queued replies before it is told off
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._out_cv:
                if not self._outbox:
                    break
            time.sleep(0.01)
        self._stop.set()
        with self._out_cv:
            self._out_cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        self._transport.stop()
        if stop_engine:
            stop = getattr(self.engine, "stop", None)
            if stop is not None:
                stop()


def serve_replica(rank: int, size: int, client: Any, lm,
                  label: str = LABEL, engine_kw: Optional[dict] = None,
                  warm: bool = True, role: str = "unified"
                  ) -> ReplicaServer:
    """Standalone replica bootstrap: build a warm
    :class:`~.decode_engine.DecodeEngine` over ``lm`` and put it on the
    wire, with the ``-chaos`` flag plan armed. ``role`` specializes the
    replica for a disaggregated fleet (``prefill``/``decode``;
    ``unified`` is the symmetric default). The subprocess acceptance
    test and any real deployment entry call this after ``mv.init()``
    (Session bootstrap: flags, topology, tables)."""
    from .decode_engine import DecodeEngine, DecodeEngineConfig

    engine = DecodeEngine(f"replica{rank}", lm,
                          DecodeEngineConfig(**(engine_kw or {})))
    if warm:
        engine.warmup()
    return ReplicaServer(rank, size, client, engine, label=label,
                         chaos=FaultPlan.from_flags(), role=role)
