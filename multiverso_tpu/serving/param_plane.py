"""Fenced parameter publish stream: one trainer, N serving subscribers.

The ROADMAP's train-on-feedback-while-serving loop needs live parameter
publishes flowing from the trainer into every serving replica — and the
durability PR makes that stream *restartable*: a trainer crash must not
leave the fleet wedged on a dead stream, and a paused-then-resumed
zombie trainer must not fold stale deltas into a converged fleet.

Topology (the obs plane's hub, trainer-side): the TRAINER (rank 0 on
the ``mvparam`` labels) is the only publisher; serving subscribers
(ranks 1..N-1) each hold a local table replica and apply the records in
stream order. Records reuse the async-PS wire framing
(:func:`~multiverso_tpu.parallel.async_ps._serialize`) and carry the
**(epoch, version)** pair: epoch is the trainer's incarnation
(:func:`~multiverso_tpu.parallel.async_ps.claim_epoch`), version the
publisher's post-apply table version, so a subscriber's replica tracks
the trainer's version identity exactly.

Restart contract — *the epoch IS the stream generation*: each trainer
incarnation claims the next epoch in the coordination KV and publishes
on a fresh transport label (``mvparam.e<E>``), its FIRST record a
``STATE`` rebase (absolute value + exact version). Subscribers watch
the epoch key; when it moves they drop the dead incarnation's stream
and attach the new one from sequence zero — whatever the dead trainer
published-but-never-delivered is superseded by the rebase, so
re-convergence is one record, not a replay negotiation. On top of the
stream switch, every record's epoch passes an
:class:`~multiverso_tpu.parallel.async_ps.EpochFence` — a zombie
record (stale epoch riding ANY stream, e.g. the ``zombie_epoch`` chaos
directive) is rejected and counted, never applied.

Staleness: subscribers expose ``params_age_s`` (time since the last
applied record) and the STALE verdict past ``-params_stale_after_s`` —
the serving side keeps answering from its frozen replica and recovers
automatically when the fenced restart republishes
(docs/DISTRIBUTED.md "Durability").
"""

from __future__ import annotations

import threading
from ..analysis import lockwatch
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from .. import config, trace
from ..dashboard import Dashboard
from ..log import Log
from ..parallel.async_ps import (DENSE, KEYED, KV, STATE, EpochFence,
                                 _deserialize, _kv_get_int, _serialize,
                                 claim_epoch)
from ..quantization import SparseFilter, dequantize_int8, quantize_int8
from .faultinject import FaultPlan

LABEL = "mvparam"
TRAINER_RANK = 0

# -- wire codec ---------------------------------------------------------------
#
# Delta records cross the DCN, not ICI, so their bytes are the one part
# of the publish stream worth shrinking (the reference's SparseFilter
# motivation unchanged). Two encodings ride behind the existing array
# framing, self-describing by ARRAY COUNT AND TRAILING DTYPE so the
# subscriber needs no flag agreement with the publisher:
#
# * DENSE   raw=[delta]            filtered=[blob, size int64]
#           quant=[q int8, scale fp32]
# * KEYED   raw=[ids, vals]        filtered=[ids, blob, size int64]
#           quant=[ids, q int8, scale fp32]
#
# A filtered payload always ends with the SparseFilter's int64
# size-info blob; the int8 codec always ends with an fp32 scale — the
# two can never collide, and the raw forms have strictly fewer arrays.
# Filtering (``-param_wire_compress``, default on) is lossless;
# ``-param_wire_quant int8`` is LOSSY (symmetric per-tensor int8) and
# therefore default off — it is the knob for delta streams whose
# consumers already tolerate quantization noise (e.g. feeding
# int8-pinned decode replicas). STATE rebases always ship raw: they
# are once-per-restart absolute values, exactly where lossy wire
# encoding must never apply.

_SIZE_INFO_DTYPE = np.dtype(np.int64)
_WIRE_FILTERS: Dict[Any, SparseFilter] = {}


def _filter_for(dtype) -> SparseFilter:
    """Memoized per-dtype :class:`SparseFilter` (filter instances are
    typed, and the publish path must not allocate one per record)."""
    dt = np.dtype(dtype)
    filt = _WIRE_FILTERS.get(dt)
    if filt is None:
        filt = _WIRE_FILTERS[dt] = SparseFilter(dtype=dt)
    return filt


def encode_dense(host: np.ndarray, compress: bool,
                 quant: str) -> list:
    """DENSE delta -> wire arrays (see the codec table above). Pure, so
    benches can measure codec bytes without a transport."""
    if quant == "int8":
        q, s = quantize_int8(host)
        return [q, s]
    if compress:
        return _filter_for(host.dtype).filter_in([host])
    return [host]


def decode_dense(arrays, dtype, shape) -> np.ndarray:
    """Invert :func:`encode_dense` -> the dense delta, table-shaped."""
    if len(arrays) == 1:
        dense = np.asarray(arrays[0], dtype)
    elif np.asarray(arrays[-1]).dtype == _SIZE_INFO_DTYPE:
        dense = np.asarray(
            _filter_for(dtype).filter_out(list(arrays))[0], dtype)
    else:
        dense = dequantize_int8(arrays[0], arrays[1], dtype)
    return dense.reshape(shape)


def encode_keyed(ids: np.ndarray, vals: np.ndarray, compress: bool,
                 quant: str) -> list:
    """KEYED delta -> wire arrays; only ``vals`` is encoded (ids are
    already the sparse half of the record)."""
    if quant == "int8":
        q, s = quantize_int8(vals)
        return [ids, q, s]
    if compress:
        return [ids] + _filter_for(vals.dtype).filter_in([vals])
    return [ids, vals]


def decode_keyed(arrays, dtype):
    """Invert :func:`encode_keyed` -> ``(ids, vals)`` with ``vals``
    row-aligned to ``ids`` (the filtered form ships flat)."""
    ids = np.asarray(arrays[0], np.int32)
    if len(arrays) == 2:
        return ids, np.asarray(arrays[1])
    if np.asarray(arrays[-1]).dtype == _SIZE_INFO_DTYPE:
        vals = np.asarray(
            _filter_for(dtype).filter_out(list(arrays[1:]))[0], dtype)
        if ids.size and vals.size != ids.size:
            vals = vals.reshape(ids.size, -1)
        return ids, vals
    return ids, dequantize_int8(arrays[1], arrays[2], dtype)


class ParamPublisher:
    """Trainer-side publish half (rank 0 of one ``label`` plane).

    Claims the next incarnation epoch (unless given one), advertises it
    in the KV, and publishes on the per-epoch stream label. The chaos
    plan hooks the publish point (``kill_trainer_at_publish``,
    ``zombie_epoch``) — see :mod:`.faultinject`.
    """

    def __init__(self, client: Any, size: int, label: str = LABEL,
                 epoch: Optional[int] = None,
                 chaos: Optional[FaultPlan] = None,
                 kill_fn: Optional[Callable[[], None]] = None,
                 wire_compress: Optional[bool] = None,
                 wire_quant: Optional[str] = None) -> None:
        from ..parallel.p2p import P2PTransport

        self._client = client
        self._label = label
        self.wire_compress = (
            bool(config.get_flag("param_wire_compress"))
            if wire_compress is None else bool(wire_compress))
        self.wire_quant = (
            str(config.get_flag("param_wire_quant"))
            if wire_quant is None else str(wire_quant))
        if self.wire_quant not in ("none", "int8"):
            Log.fatal(f"param plane: unknown param_wire_quant "
                      f"{self.wire_quant!r} (none|int8)")
        # wire-codec ledger: payload bytes actually sent vs what the
        # raw (uncoded) delta arrays would have cost — the
        # wire_compressed_ratio denominator. Delta records only; STATE
        # rebases ship raw by contract and count into both sides
        # equally via publish_record's payload tally.
        self.publish_bytes = 0
        self._delta_raw_bytes = 0
        self._delta_wire_bytes = 0
        self.epoch = (claim_epoch(client, f"{label}/epoch")
                      if epoch is None else int(epoch))
        if epoch is not None:
            # explicit epoch (tests): still advertise it so subscribers
            # attach this stream generation
            client.key_value_set(f"{label}/epoch", str(self.epoch),
                                 allow_overwrite=True)
        self.chaos = chaos if chaos is not None else FaultPlan(
            "", kill_fn=kill_fn)
        if kill_fn is not None and chaos is not None:
            self.chaos._kill_fn = kill_fn
        self._transport = P2PTransport(
            TRAINER_RANK, int(size), client,
            label=f"{label}.e{self.epoch}", subscribe_to=[])
        self._seq = 0
        self.publishes = 0
        self._counter = Dashboard.get_or_create_counter("PARAM_PUBLISHES")
        self._bytes_counter = Dashboard.get_or_create_counter(
            "PARAM_PUBLISH_BYTES")
        Log.info("param plane: publisher up (epoch %d, %d subscriber "
                 "slot(s))", self.epoch, int(size) - 1)

    # -- publish API ---------------------------------------------------------
    def publish_state(self, table) -> None:
        """The rebase record: absolute table value at its exact version
        — a restarted incarnation's FIRST publish, re-converging every
        subscriber in one record. Works for any table implementing the
        STATE protocol (``_state_arrays``: array tables ship one host
        array, KVTable ships keys+vals)."""
        arrays, version = table._state_arrays()
        self.publish_record(STATE, table.table_id, arrays,
                            version=version)

    def publish_delta(self, table, delta, option=None,
                      version: Optional[int] = None) -> None:
        """Publish a dense delta the trainer ALREADY applied locally
        (``version`` defaults to the table's current = post-apply
        version; single-writer trainer contract)."""
        host = np.asarray(delta, dtype=table.dtype).reshape(table.shape)
        arrays = encode_dense(host, self.wire_compress, self.wire_quant)
        self._note_delta_bytes([host], arrays)
        self.publish_record(
            DENSE, table.table_id, arrays, option=option,
            version=table.version if version is None else int(version))

    def publish_keyed(self, table, ids, vals, option=None,
                      version: Optional[int] = None) -> None:
        ids = np.asarray(ids, np.int32).ravel()
        vals = np.asarray(vals)
        arrays = encode_keyed(ids, vals, self.wire_compress,
                              self.wire_quant)
        self._note_delta_bytes([ids, vals], arrays)
        self.publish_record(
            KEYED, table.table_id, arrays, option=option,
            version=table.version if version is None else int(version))

    def _note_delta_bytes(self, raw, encoded) -> None:
        self._delta_raw_bytes += sum(
            np.asarray(a).nbytes for a in raw)
        self._delta_wire_bytes += sum(
            np.asarray(a).nbytes for a in encoded)

    def publish_kv(self, table, keys, vals,
                   version: Optional[int] = None) -> None:
        self.publish_record(
            KV, table.table_id,
            [np.asarray(keys, np.int64), np.asarray(vals, np.float64)],
            version=table.version if version is None else int(version))

    def publish_record(self, kind: int, table_id: int, arrays,
                       option=None, version: int = 0,
                       epoch: Optional[int] = None) -> None:
        """Low-level publish (the zombie tests stamp an explicit stale
        ``epoch`` here). Consults the chaos plan BEFORE the send: a
        ``kill_trainer_at_publish`` trainer dies with the record
        unsent — the journaled-but-unpublished update recovery must
        replay."""
        k = self.publishes + 1
        self.chaos.on_trainer_publish(k)      # may os._exit (chaos)
        if epoch is None:
            epoch = self.chaos.publish_epoch(k, self.epoch)
        sp = trace.start_span("param.publish", table_id=table_id,
                              epoch=epoch, version=version)
        payload = _serialize(kind, table_id, option, arrays, sp.context,
                             epoch=epoch, version=version)
        self._transport.send(self._seq, payload)
        self._seq += 1
        self.publishes = k
        self.publish_bytes += len(payload)
        self._counter.inc()
        self._bytes_counter.inc(len(payload))
        sp.end(bytes=len(payload))

    def stats(self) -> Dict[str, Any]:
        return {"epoch": self.epoch, "publishes": self.publishes,
                "publish_bytes": self.publish_bytes,
                "wire_compressed_ratio": (
                    self._delta_wire_bytes
                    / max(self._delta_raw_bytes, 1)),
                "chaos": self.chaos.stats()}

    def stop(self) -> None:
        self._transport.stop()


class ParamSubscriber:
    """Serving-side apply half: one per replica process.

    Applies the trainer stream into local ``tables`` (a list or
    ``{table_id: table}``) in publish order, fencing every record's
    epoch, and exposes the params-staleness surface serving health
    checks read.
    """

    def __init__(self, client: Any, tables, rank: int, size: int,
                 label: str = LABEL, poll_s: float = 0.02,
                 stale_after_s: Optional[float] = None,
                 start: bool = True) -> None:
        if not 1 <= int(rank) < int(size):
            raise ValueError(f"subscriber rank {rank} outside "
                             f"[1, {size})")
        self._client = client
        self._label = label
        self.rank = int(rank)
        self._size = int(size)
        self._poll_s = float(poll_s)
        if isinstance(tables, dict):
            self._tables = dict(tables)
        else:
            self._tables = {t.table_id: t for t in tables}
        self.stale_after_s = (
            float(config.get_flag("params_stale_after_s"))
            if stale_after_s is None else float(stale_after_s))
        self._fence = EpochFence(f"param.r{self.rank}")
        self._transport = None
        self._expect = 0
        self._cur_epoch = 0
        # epoch-key probe cadence: a restart is a once-per-incident
        # event, so the KV is asked at ~4 Hz, not once per apply poll —
        # 50 RPCs/s/subscriber forever (and, on jax<=0.4 clients whose
        # only read is a 200 ms blocking get, a 5 Hz apply cadence)
        # just to watch a key that almost never moves. Stream-less
        # subscribers probe every poll: attach latency IS their job.
        self._epoch_check_s = max(0.25, self._poll_s)
        self._next_epoch_check = 0.0
        self.applied = 0
        self.states_applied = 0
        self.epoch_switches = 0
        self._lock = lockwatch.lock("serving.ParamSubscriber._lock")
        self._last_apply = time.monotonic()
        self._counter = Dashboard.get_or_create_counter("PARAM_APPLIES")
        self._age_gauge = Dashboard.get_or_create_gauge(
            f"SERVE_PARAMS_AGE[param.r{self.rank}]")
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"mvparam-sub-{self.rank}",
            daemon=True)
        if start:
            self._thread.start()

    # -- stream management ---------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                self.poll_once()
            except Exception as exc:   # pragma: no cover - wire races
                if not self._stop.is_set():
                    Log.error("param plane: subscriber %d poll failed: "
                              "%s", self.rank, exc)

    def poll_once(self) -> int:
        """Attach the current epoch's stream (switching off a dead
        incarnation's) and apply everything ready; returns the applied
        count. Tests drive it directly with ``start=False``."""
        now = time.monotonic()
        if self._transport is None or now >= self._next_epoch_check:
            self._next_epoch_check = now + self._epoch_check_s
            epoch = _kv_get_int(self._client, f"{self._label}/epoch", 0)
            # highest-epoch-wins, like the record fence: a key read that
            # comes back 0/stale (transient KV failure, an operator
            # rewinding the key) must never detach a LIVE stream onto a
            # dead lower-epoch label whose records the fence would then
            # reject — that would wedge the subscriber silently
            if epoch > self._cur_epoch:
                self._attach(epoch)
        if self._transport is None:
            return 0
        applied = 0
        while not self._stop.is_set():
            payload = self._transport.pop_ready(TRAINER_RANK,
                                                self._expect)
            if payload is None:
                break
            self._expect += 1
            self._apply(payload)
            applied += 1
        return applied

    def _attach(self, epoch: int) -> None:
        """Switch to the incarnation's stream: the epoch key moving IS
        the restart signal — the old stream is dead by contract (its
        publisher claimed no successor records), and the new one's
        first record is the STATE rebase, so dropping the old
        subscription loses nothing a rebase doesn't supersede."""
        from ..parallel.p2p import P2PTransport

        old, self._transport = self._transport, None
        if old is not None:
            # tear the dead incarnation's transport down OFF the apply
            # path: its subscriber thread is typically deep in a
            # reconnect backoff against the dead endpoint, and joining
            # it here would stall re-convergence by whole backoff
            # periods (measured ~5s -> ~1s recovery)
            threading.Thread(target=old.stop,
                             name=f"mvparam-reap-{self._cur_epoch}",
                             daemon=True).start()
        Log.info("param plane: subscriber %d attaching epoch-%d stream"
                 " (was %d)", self.rank, epoch, self._cur_epoch)
        self._transport = P2PTransport(
            self.rank, self._size, self._client,
            label=f"{self._label}.e{epoch}",
            subscribe_to=[TRAINER_RANK],
            initial_resume={TRAINER_RANK: 0})
        self._expect = 0
        self._cur_epoch = epoch
        self.epoch_switches += 1

    # -- apply ---------------------------------------------------------------
    def _apply(self, payload: bytes) -> None:
        (kind, table_id, option, arrays, _, ctx, epoch,
         version) = _deserialize(payload)
        sp = (trace.start_span("param.apply", parent=ctx,
                               table_id=table_id)
              if ctx is not None else trace.NULL_SPAN)
        if not self._fence.admit(epoch):
            Log.error("param plane: subscriber %d rejected epoch-%d "
                      "record (fence at %d)", self.rank, epoch,
                      self._fence.epoch)
            sp.end(error="epoch_fenced", epoch=epoch)
            return
        table = self._tables.get(table_id)
        if table is None:
            Log.error("param plane: record for unknown table %d",
                      table_id)
            sp.end(error="unknown_table")
            return
        if kind == STATE:
            table._install_state_arrays(arrays, version, epoch)
            self.states_applied += 1
        elif kind == DENSE:
            table._apply_remote_dense(
                decode_dense(arrays, table.dtype, table.shape), option)
            self._pin_version(table, version, epoch)
        elif kind == KEYED:
            ids, vals = decode_keyed(arrays, table.dtype)
            table._apply_remote_keyed(ids, vals, option)
            self._pin_version(table, version, epoch)
        elif kind == KV:
            table._apply_remote_kv(arrays[0], arrays[1])
            self._pin_version(table, version, epoch)
        else:
            Log.error("param plane: unknown record kind %d", kind)
            sp.end(error="unknown_kind")
            return
        with self._lock:
            self.applied += 1
            self._last_apply = time.monotonic()
        self._counter.inc()
        sp.end(version=version, epoch=epoch)

    @staticmethod
    def _pin_version(table, version: int, epoch: int) -> None:
        """Mirror the publisher's version identity: the replica's state
        after this apply IS the trainer's state at ``version`` (stream
        order + single writer), so serving health reports the fleet's
        true convergence point rather than a rank-local counter."""
        if not version:
            return
        with table._lock:
            table.version = int(version)
            if epoch:
                table.epoch = int(epoch)

    # -- staleness surface ---------------------------------------------------
    def params_age_s(self) -> float:
        """Seconds since the last applied record — the subscriber-side
        publish-stream-silent signal (also shipped as the
        SERVE_PARAMS_AGE gauge)."""
        with self._lock:
            age = time.monotonic() - self._last_apply
        self._age_gauge.set(age)
        return age

    def params_stale(self) -> bool:
        return (self.stale_after_s > 0
                and self.params_age_s() > self.stale_after_s)

    def stats(self) -> Dict[str, Any]:
        versions = {tid: int(t.version)
                    for tid, t in self._tables.items()}
        return {
            "rank": self.rank,
            "epoch": self._cur_epoch,
            "fence_epoch": self._fence.epoch,
            "fence_rejections": self._fence.rejections,
            "applied": self.applied,
            "states_applied": self.states_applied,
            "epoch_switches": self.epoch_switches,
            "params_age_s": self.params_age_s(),
            "params_stale": self.params_stale(),
            "table_versions": versions,
        }

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
        if self._transport is not None:
            self._transport.stop()
