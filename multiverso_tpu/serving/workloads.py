"""Jitted inference paths for the three existing workload families.

Each workload binds a live training source (table or model) and exposes
``run(payloads, bucket, snapshot_value) -> results``: the batcher pads
the flushed batch up to ``bucket`` (a static shape from the bucket set,
so XLA compiles once per bucket and every flush hits a warm cache), the
workload executes ONE jitted program on the snapshot, and slices the
padding back off. Snapshots arrive in the tables' PHYSICAL (padded)
shape; workloads slice to logical rows exactly like the training math
(``TableBase.logical`` semantics).
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp
import numpy as np

from ..log import Log
from .snapshot import DerivedCache, replicate_for_decode


def _jit_cache_size(fn) -> int:
    """Compiled-trace count of a jitted callable (test/bench introspection:
    shape-bucket reuse means this stops growing after warmup)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return -1


class EmbeddingNeighbors:
    """word2vec serving: embedding lookup + top-k nearest neighbors.

    Payload: an ``int`` word id. Reply: ``(neighbor_ids [k], scores [k])``
    by cosine similarity over the input-embedding matrix table — the
    query-time half of the WordEmbedding application (the reference only
    ever wrote vectors to disk; SURVEY §L3's "shared model state serving"
    is this, made live).

    The normalized matrix is a per-snapshot derived artifact: computed
    once per publish (copy-on-publish makes the version a safe cache
    key), reused by every flush until training moves the table.
    """

    def __init__(self, table, k: int = 8) -> None:
        self.source = table
        self.k = int(k)
        rows = table.shape[0]
        if self.k >= rows:
            Log.fatal(f"EmbeddingNeighbors: k={k} >= vocab {rows}")

        logical_rows = rows

        def normalize(arr):
            emb = arr[:logical_rows].astype(jnp.float32)
            norm = jnp.sqrt(jnp.sum(emb * emb, axis=1, keepdims=True))
            return emb / jnp.maximum(norm, 1e-12)

        k_ = self.k

        def neighbors(normed, ids):
            q = jnp.take(normed, ids, axis=0)              # [B, D]
            sims = q @ normed.T                            # [B, V]
            # exclude the query word itself before ranking
            sims = sims.at[jnp.arange(ids.shape[0]), ids].set(-jnp.inf)
            return jax.lax.top_k(sims, k_)

        self._normalize = jax.jit(normalize)
        self._fn = jax.jit(neighbors)
        self._derived = DerivedCache(self._normalize)

    def validate(self, payload) -> None:
        """Host-side id check at SUBMIT time: XLA silently clamps an OOB
        index inside jit (the tables/base.py posture), which would return
        the wrong word's neighbors as a valid-looking reply."""
        wid = int(payload)
        if not 0 <= wid < self.source.shape[0]:
            raise ValueError(f"word id {wid} outside vocab "
                             f"[0, {self.source.shape[0]})")

    def run(self, payloads: List[int], bucket: int, snap) -> List[Any]:
        normed = self._derived.get(snap)
        ids = np.zeros(bucket, np.int32)
        ids[: len(payloads)] = np.asarray(payloads, np.int32)
        scores, nbr = self._fn(normed, jnp.asarray(ids))
        scores, nbr = np.asarray(scores), np.asarray(nbr)
        return [(nbr[i], scores[i]) for i in range(len(payloads))]

    def jit_cache_size(self) -> int:
        return _jit_cache_size(self._fn)


class LogRegPredict:
    """logreg serving: sigmoid/softmax/linear scores for feature vectors.

    Payload: a dense ``[input_size]`` feature vector. Reply: the
    ``[output_size]`` score vector — the model's :meth:`LogReg._forward`
    math (bias column, logical-row slice) run on a snapshot instead of
    the live table, so training minibatches never tear a reply.
    """

    def __init__(self, model) -> None:
        from ..models.logreg import LogReg

        if not isinstance(model, LogReg):
            Log.fatal("LogRegPredict serves a models.logreg.LogReg")
        self.source = model.table
        self.input_size = model.cfg.input_size
        self._fn = model._predict_fn   # the model's own jitted forward

    def validate(self, payload) -> None:
        x = np.asarray(payload)
        if x.shape != (self.input_size,):
            raise ValueError(f"feature vector shape {x.shape} != "
                             f"({self.input_size},)")

    def run(self, payloads: List[np.ndarray], bucket: int, snap) -> List[Any]:
        x = np.zeros((bucket, self.input_size), np.float32)
        for i, p in enumerate(payloads):
            x[i] = np.asarray(p, np.float32)
        out = np.asarray(self._fn(snap.value, jnp.asarray(x)))
        return [out[i] for i in range(len(payloads))]

    def jit_cache_size(self) -> int:
        return _jit_cache_size(self._fn)


class FTRLPredict:
    """FTRL serving: closed-form weight reconstruction + sigmoid score.

    Payload: a dense ``[input_size]`` feature vector. The per-key ``(z,
    n)`` state snapshot is collapsed to weights with the FTRL-proximal
    closed form (the worker-side math of :class:`models.logreg.FTRLLogReg`,
    jitted and batched); the bias key rides as the last weight, matching
    the training layout.
    """

    def __init__(self, table, cfg) -> None:
        self.source = table
        self.input_size = int(cfg.input_size)
        rows = self.input_size + 1   # + bias key
        alpha, beta = float(cfg.ftrl_alpha), float(cfg.ftrl_beta)
        l1, l2 = float(cfg.ftrl_lambda1), float(cfg.ftrl_lambda2)

        def predict(zn, x):
            z = zn[:rows, 0].astype(jnp.float32)
            n = zn[:rows, 1].astype(jnp.float32)
            w = -(z - jnp.sign(z) * l1) / (
                (beta + jnp.sqrt(n)) / alpha + l2)
            w = jnp.where(jnp.abs(z) <= l1, 0.0, w)
            scores = x @ w[:-1] + w[-1]
            return jax.nn.sigmoid(jnp.clip(scores, -35.0, 35.0))

        self._fn = jax.jit(predict)

    def validate(self, payload) -> None:
        x = np.asarray(payload)
        if x.shape != (self.input_size,):
            raise ValueError(f"feature vector shape {x.shape} != "
                             f"({self.input_size},)")

    def run(self, payloads: List[np.ndarray], bucket: int, snap) -> List[Any]:
        x = np.zeros((bucket, self.input_size), np.float32)
        for i, p in enumerate(payloads):
            x[i] = np.asarray(p, np.float32)
        out = np.asarray(self._fn(snap.value, jnp.asarray(x)))
        return [float(out[i]) for i in range(len(payloads))]

    def jit_cache_size(self) -> int:
        return _jit_cache_size(self._fn)


class LMGreedyDecode:
    """LM serving: greedy continuation with a KV cache.

    Payload: a 1-D prompt id array (length in ``[1, max_prompt]``).
    Reply: ``[max_new]`` generated ids. Prompts are right-padded to the
    static ``max_prompt`` so every flush of a bucket reuses one compiled
    prefill+decode program (:func:`models.transformer.greedy_decode`);
    per-example lengths keep padding out of positions, logits, and the
    attention mask.
    """

    def __init__(self, lm, max_prompt: int, max_new: int,
                 eos_id: "int | None" = None) -> None:
        from ..models.transformer import greedy_decode

        cfg = lm.config
        if max_prompt + max_new > cfg.max_seq:
            Log.fatal(f"LMGreedyDecode: max_prompt {max_prompt} + max_new "
                      f"{max_new} exceeds max_seq {cfg.max_seq}")
        self.source = lm
        self.max_prompt = int(max_prompt)
        self.max_new = int(max_new)
        # eos_id freezes finished lanes (pad emissions, frozen pos) — the
        # batch still runs all max_new iterations, it just stops paying
        # attention width for completed sequences
        self._fn = jax.jit(
            lambda params, toks, lens: greedy_decode(
                cfg, params, toks, lens, int(max_new), eos_id))
        # decode serves a replicated single-device params copy (see
        # snapshot.replicate_for_decode: ~2x flush wall otherwise on the
        # CPU harness), derived once per snapshot version
        self._plain = DerivedCache(replicate_for_decode)

    def validate(self, payload) -> None:
        """Submit-time check: a bad prompt must reject ITS request, not
        fail every co-batched request at flush."""
        p = np.asarray(payload, np.int32).ravel()
        if not 1 <= p.shape[0] <= self.max_prompt:
            raise ValueError(f"prompt length {p.shape[0]} outside "
                             f"[1, {self.max_prompt}]")

    def run(self, payloads: List[np.ndarray], bucket: int, snap) -> List[Any]:
        toks = np.zeros((bucket, self.max_prompt), np.int32)
        lens = np.ones(bucket, np.int32)    # pad rows decode garbage, sliced off
        for i, p in enumerate(payloads):
            p = np.asarray(p, np.int32).ravel()
            toks[i, : p.shape[0]] = p
            lens[i] = p.shape[0]
        out = np.asarray(self._fn(self._plain.get(snap),
                                  jnp.asarray(toks), jnp.asarray(lens)))
        return [out[i] for i in range(len(payloads))]

    def jit_cache_size(self) -> int:
        return _jit_cache_size(self._fn)
