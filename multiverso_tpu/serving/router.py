"""Failure-aware fleet router: N decode replicas, one front door.

The :class:`FleetRouter` is the serving fleet's front-end (rank 0 on
the ``mvserve`` wire): it owns the request queue, dispatches to the
least-loaded UP replica with session affinity (a multi-turn session
sticks to the replica holding its prefix-cache blocks), enforces
per-request deadlines, and — the point of the module — keeps every
accepted request alive across replica failures:

* **liveness is observed**: a replica is UP because its heartbeats say
  so; silence past ``-fleet_dead_after_s`` (default 2 heartbeat
  intervals) or a wire-declared death (``P2PTransport.on_dead``) flags
  it DEAD. The verdict is edge-triggered: one transition, one drain.
* **death drains, never drops**: the dead replica's in-flight set moves
  into the retry queue with exponential backoff + jitter
  (:func:`retry_backoff_s`, bounded by ``-fleet_retry_max``). Requests
  carry idempotent ids and decode is deterministic greedy (the PR 11
  invariant), so the replay executes the same prompt from scratch on a
  survivor and produces **bit-identical output** — late duplicate
  replies are deduped by id, and a duplicate whose payload differs
  increments ``fleet_redispatch_output_mismatches`` (gated at zero by
  the bench: determinism is an invariant, not a hope).
* **readmission is half-open**: a DEAD replica that heartbeats again
  (restarted process, healed partition) is PROBED — one ``ping`` must
  round-trip on the wire before any real request is dispatched to it.
* **overload degrades loudly — and BY CLASS**: past
  ``-fleet_shed_depth`` aggregate queue depth (pending + retry +
  in-flight) ``submit`` sheds with :class:`~.batcher.OverloadedError`
  ``(what="fleet", retriable=True)`` instead of queueing unboundedly —
  but it sheds the LOWEST class first: an arriving request of a higher
  priority class evicts the newest queued request of the lowest
  pending class rather than being rejected itself, so paying tenants
  keep flowing while batch traffic absorbs the burst
  (``SHED_BY_CLASS[name.pN]`` counters say who paid). Dispatch pops
  the highest class first (FIFO within a class), requests carry
  ``priority``/``deadline_s`` onto the wire and into the replica
  engines' weighted-fair schedulers, a retry whose backoff would land
  past its deadline fails fast with
  :class:`~.batcher.DeadlineExceededError` instead of burning the
  wait, and a replica's ``retriable=False`` shed (a request bigger
  than its whole KV pool) fails immediately instead of burning the
  retry budget on an impossibility.
* **disaggregated prefill/decode is a routing decision**: when the
  fleet has both a ``prefill``-role and a ``decode``-role replica UP
  (roles ride the heartbeats), dispatch goes two-stage — stage 1 sends
  the prompt to a prefill rank (with the decode rank's ``known`` chain
  hashes, so a warm prefix never crosses the wire), the finished KV
  blocks come back as a ``MSG_XFER`` payload bracketed by a
  ``kv.transfer`` span, and stage 2 lands the request + payload on the
  chosen decode rank, which splices the blocks into its pool and
  admits through the full-hit path. Either role pool going empty (or a
  prefill death mid-stage-1) falls back to classic unified admission —
  the payload is a latency optimization, never a correctness
  dependency (:mod:`.kv_transfer`, docs/SERVING.md "Disaggregated
  prefill/decode").

Observability: ``FLEET_DISPATCH``/``FLEET_RETRIES``/``FLEET_REDISPATCH``
/``FLEET_SHED`` counters, per-replica ``FLEET_REPLICA_STATE``/
``FLEET_INFLIGHT``/``FLEET_HB_AGE_MS`` gauges (the obs plane ships them
and ``tools/opscenter.py`` renders replica rows), and a
``route.dispatch`` span per attempt whose context rides the wire — the
replica's spans join the request's trace across the process boundary
(docs/SERVING.md "Serving fleet").
"""

from __future__ import annotations

import collections
import random
import threading
from ..analysis import lockwatch
import time
import uuid
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import config, trace
from ..dashboard import Dashboard
from ..log import Log
from ..parallel.p2p import reconnect_backoff_s
from . import kv_transfer
from .batcher import DeadlineExceededError, OverloadedError
from .replica import (LABEL, MSG_ERR, MSG_HB, MSG_PING, MSG_PONG, MSG_REQ,
                      MSG_RSP, MSG_XFER, ROUTER_RANK, decode_msg,
                      encode_msg)

# replica lifecycle states; the numeric codes are the
# FLEET_REPLICA_STATE gauge values (ordered by serviceability)
DEAD, CONNECTING, PROBING, UP = 0, 1, 2, 3
STATE_NAMES = {DEAD: "DEAD", CONNECTING: "CONNECTING",
               PROBING: "PROBING", UP: "UP"}

# replica role codes — the FLEET_ROLE gauge values (disaggregated
# serving). Archives written before the gauge existed read -1 in the
# opscenter, same tolerance as the PR 8/11 gauge additions.
ROLE_CODES = {"unified": 0, "prefill": 1, "decode": 2}

# per-decode-rank shipped-hash book cap: past this the book clears and
# rebuilds from heartbeat advertisements (a stale book only costs a
# re-shipped block that dedups on arrival — bounded memory wins)
_SHIPPED_CAP = 8192

# NB DeadlineExceededError lives in .batcher now (both serving tiers
# raise it); the import above keeps `from .router import
# DeadlineExceededError` working.


class FleetError(RuntimeError):
    """The request exhausted its re-dispatch budget (every attempt hit
    a dying or shedding replica)."""


def retry_backoff_s(attempt: int, base_s: float, cap_s: float,
                    rng: Optional[random.Random] = None) -> float:
    """Delay before re-dispatch ``attempt`` (1-based): the capped
    exponential ceiling ``min(cap, base * 2**(attempt-1))``, jittered
    into ``[ceiling/2, ceiling]`` when ``rng`` is given (equal-jitter —
    a burst of redispatches from one death must not re-land as one
    synchronized burst). ``rng=None`` returns the deterministic
    ceiling (the unit-testable schedule). One schedule, one
    implementation: this is the transport's reconnect schedule
    (:func:`~multiverso_tpu.parallel.p2p.reconnect_backoff_s`) with
    1-based indexing."""
    if attempt < 1:
        raise ValueError(f"attempt is 1-based, got {attempt}")
    return reconnect_backoff_s(attempt - 1, base_s, cap_s, rng)


@dataclass
class FleetConfig:
    """Router knobs; ``None`` falls back to the ``-fleet_*`` flags."""

    heartbeat_ms: Optional[int] = None
    dead_after_s: Optional[float] = None      # 0/None -> 2 heartbeats
    retry_max: Optional[int] = None
    backoff_ms: Optional[float] = None
    backoff_cap_ms: Optional[float] = None
    shed_depth: Optional[int] = None
    deadline_s: Optional[float] = None

    def resolved(self) -> "FleetConfig":
        def flag(field, name):
            v = getattr(self, field)
            return config.get_flag(name) if v is None else v

        hb_ms = int(flag("heartbeat_ms", "fleet_heartbeat_ms"))
        dead = float(flag("dead_after_s", "fleet_dead_after_s"))
        if dead <= 0:
            dead = 2.0 * hb_ms / 1000.0
        return FleetConfig(
            heartbeat_ms=hb_ms, dead_after_s=dead,
            retry_max=int(flag("retry_max", "fleet_retry_max")),
            backoff_ms=float(flag("backoff_ms", "fleet_backoff_ms")),
            backoff_cap_ms=float(flag("backoff_cap_ms",
                                      "fleet_backoff_cap_ms")),
            shed_depth=int(flag("shed_depth", "fleet_shed_depth")),
            deadline_s=float(flag("deadline_s", "fleet_deadline_s")))


class _FleetRequest:
    __slots__ = ("rid", "prompt", "max_new", "session", "deadline",
                 "attempts", "future", "replica", "t_enq", "root",
                 "dispatch_span", "redispatched", "exclude", "priority",
                 "stage", "decode_rank", "xfer", "xfer_span", "tenant")

    def __init__(self, prompt: np.ndarray, max_new: Optional[int],
                 session: Optional[str], deadline: float, root,
                 priority: int = 1,
                 tenant: Optional[str] = None) -> None:
        self.rid = uuid.uuid4().hex[:16]
        self.prompt = np.asarray(prompt, np.int32).ravel()
        self.max_new = max_new
        self.session = session
        self.deadline = deadline
        self.priority = int(priority)
        self.tenant = tenant
        self.attempts = 0
        self.future: Future = Future()
        self.replica: Optional[int] = None
        self.t_enq = time.monotonic()
        self.root = root
        self.dispatch_span = None
        self.redispatched = False
        self.exclude: Optional[int] = None   # rank that just failed it
        # disaggregated two-stage dispatch state: stage is None (plain)
        # or "prefill" (stage 1 in flight at a prefill replica);
        # decode_rank is the replica the KV payload is destined for;
        # xfer holds the arrived payload while stage 2 waits to dispatch
        self.stage: Optional[str] = None
        self.decode_rank: Optional[int] = None
        self.xfer: Optional[Dict[str, Any]] = None
        self.xfer_span = None


class _ClassQueue:
    """The router's pending lanes: one FIFO deque per priority class.

    Dispatch is strict-priority (highest class first, FIFO within —
    fairness between tenants lives in the replica engines' weighted-
    fair schedulers; the router's job is just to not let low-class
    work block high-class work at the front door), and overload shed
    evicts from the LOWEST class, newest first (the request that
    waited least loses). Callers hold the router lock."""

    def __init__(self) -> None:
        self._lanes: Dict[int, collections.deque] = {}
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def append(self, req: _FleetRequest) -> None:
        self._lanes.setdefault(req.priority,
                               collections.deque()).append(req)
        self._n += 1

    def appendleft(self, req: _FleetRequest) -> None:
        """Retries re-enter at the FRONT of their class (they are the
        oldest work that class has)."""
        self._lanes.setdefault(req.priority,
                               collections.deque()).appendleft(req)
        self._n += 1

    def peek(self) -> Optional[_FleetRequest]:
        for p in sorted(self._lanes, reverse=True):
            if self._lanes[p]:
                return self._lanes[p][0]
        return None

    def popleft(self) -> Optional[_FleetRequest]:
        for p in sorted(self._lanes, reverse=True):
            if self._lanes[p]:
                self._n -= 1
                return self._lanes[p].popleft()
        return None

    def shed_lowest_below(self, priority: int) -> Optional[_FleetRequest]:
        """Evict the NEWEST queued request of the lowest non-empty
        class strictly below ``priority`` (None = nothing lower is
        queued — the arrival itself sheds)."""
        for p in sorted(self._lanes):
            if p >= priority:
                break
            if self._lanes[p]:
                self._n -= 1
                return self._lanes[p].pop()
        return None

    def expire(self, now: float) -> List[_FleetRequest]:
        """Remove and return every queued request past its deadline."""
        out: List[_FleetRequest] = []
        for lane in self._lanes.values():
            if any(r.deadline <= now for r in lane):
                keep = [r for r in lane if r.deadline > now]
                out.extend(r for r in lane if r.deadline <= now)
                lane.clear()
                lane.extend(keep)
        self._n -= len(out)
        return out

    def drain(self) -> List[_FleetRequest]:
        out: List[_FleetRequest] = []
        for lane in self._lanes.values():
            out.extend(lane)
            lane.clear()
        self._n = 0
        return out


class _Replica:
    __slots__ = ("rank", "state", "last_hb", "health", "inflight",
                 "wire_dead", "probe_rid", "deaths", "readmissions",
                 "state_gauge", "inflight_gauge", "hb_age_gauge",
                 "snap_gauge", "preempt_gauge", "role", "role_gauge")

    def __init__(self, rank: int, router_name: str) -> None:
        self.rank = rank
        self.state = CONNECTING
        self.role = "unified"               # learned from heartbeats
        self.last_hb: Optional[float] = None
        self.health: Dict[str, Any] = {}
        self.inflight: set = set()          # rids currently assigned here
        self.wire_dead = False              # transport-declared: terminal
        self.probe_rid: Optional[str] = None
        self.deaths = 0
        self.readmissions = 0
        self.state_gauge = Dashboard.get_or_create_gauge(
            f"FLEET_REPLICA_STATE[{router_name}.{rank}]")
        self.inflight_gauge = Dashboard.get_or_create_gauge(
            f"FLEET_INFLIGHT[{router_name}.{rank}]")
        self.hb_age_gauge = Dashboard.get_or_create_gauge(
            f"FLEET_HB_AGE_MS[{router_name}.{rank}]")
        # the replica's SERVED snapshot version (from its heartbeat
        # health): a fleet serving divergent or frozen versions is
        # visible at a glance in the opscenter replica rows
        self.snap_gauge = Dashboard.get_or_create_gauge(
            f"FLEET_SNAPSHOT_VERSION[{router_name}.{rank}]")
        # the replica engine's cumulative preemption count (from its
        # heartbeat health): overload churn per replica at a glance in
        # the opscenter replica rows
        self.preempt_gauge = Dashboard.get_or_create_gauge(
            f"FLEET_PREEMPTS[{router_name}.{rank}]")
        # the replica's serving role (from its heartbeat): a
        # disaggregated fleet's prefill/decode split at a glance in
        # the opscenter replica rows (ROLE_CODES)
        self.role_gauge = Dashboard.get_or_create_gauge(
            f"FLEET_ROLE[{router_name}.{rank}]")
        self.state_gauge.set(CONNECTING)
        self.role_gauge.set(ROLE_CODES["unified"])


class FleetRouter:
    """Front door for a replicated decode fleet (``mvserve`` rank 0)."""

    def __init__(self, size: int, client: Any, label: str = LABEL,
                 fleet_config: Optional[FleetConfig] = None,
                 name: str = "fleet") -> None:
        from ..parallel.p2p import P2PTransport

        if size < 2:
            raise ValueError(f"fleet size {size} needs >= 1 replica")
        self.name = name
        self.size = int(size)
        self._client = client
        self._label = label
        self.config = (fleet_config or FleetConfig()).resolved()
        self._lock = lockwatch.lock("serving.FleetRouter._lock")
        self._replicas: Dict[int, _Replica] = {
            r: _Replica(r, name) for r in range(1, size)}
        self._pending = _ClassQueue()
        self.shed_by_class: Dict[int, int] = {}
        self._shed_class_counters: Dict[int, Any] = {}
        self._retry: List[Tuple[float, _FleetRequest]] = []
        self._inflight: Dict[str, _FleetRequest] = {}
        self._affinity: Dict[str, int] = {}
        # completed rids -> result digest, bounded: dedupes the late
        # duplicate replies the replay path makes legitimate, and is
        # what lets a duplicate's payload be CHECKED for bit-identity
        self._done: "collections.OrderedDict[str, Optional[int]]" = \
            collections.OrderedDict()
        self._done_cap = 4096
        self._expect: Dict[int, int] = {r: 0 for r in self._replicas}
        self._acked: Dict[int, int] = {r: 0 for r in self._replicas}
        self._seq = 0
        self._released = 0
        self._head_published = -1       # last head value written to KV
        self._next_ack_poll = 0.0       # ack reads run at hb cadence
        self._probe_n = 0
        self._rng = random.Random(0x466C3374)   # retry jitter stream
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.shed = 0
        self.deadline_failures = 0
        self.duplicate_replies = 0
        self.output_mismatches = 0
        # disaggregated transfer-plane accounting + the per-decode-rank
        # book of KV-block hashes known to be resident there (union of
        # payloads routed to it and its heartbeat advertisements);
        # "known" hashes are told to the prefill side so warm prefixes
        # never cross the wire
        self.kv_xfers = 0
        self.kv_bytes_moved = 0
        self.xfer_blocks = 0
        self.xfer_dedup_blocks = 0
        self._shipped: Dict[int, set] = {}
        self._last_death: Optional[float] = None
        self._last_recovery: Optional[float] = None
        self._dispatch_counter = Dashboard.get_or_create_counter(
            "FLEET_DISPATCH")
        self._retries_counter = Dashboard.get_or_create_counter(
            "FLEET_RETRIES")
        self._redispatch_counter = Dashboard.get_or_create_counter(
            "FLEET_REDISPATCH")
        self._shed_counter = Dashboard.get_or_create_counter("FLEET_SHED")
        self._transport = P2PTransport(
            ROUTER_RANK, self.size, client, label=label,
            subscribe_to=sorted(self._replicas),
            on_dead=self._on_wire_dead)
        self._publish_head()
        self._stop = threading.Event()
        # one loop owns all routing state transitions: drain, liveness,
        # retries, deadlines, dispatch — ticked fast enough that the
        # DEAD verdict lands well inside the 2-heartbeat contract
        self._tick_s = max(0.005, self.config.heartbeat_ms / 4000.0)
        self._thread = threading.Thread(
            target=self._loop, name=f"mvserve-router", daemon=True)
        self._thread.start()
        Log.info("fleet: router up over %d replica(s) (hb %d ms, dead "
                 "after %.3f s, retry_max %d, shed at %d)",
                 size - 1, self.config.heartbeat_ms,
                 self.config.dead_after_s, self.config.retry_max,
                 self.config.shed_depth)

    # -- submit path ---------------------------------------------------------
    def _count_shed(self, priority: int) -> None:
        self.shed += 1
        self.shed_by_class[priority] = \
            self.shed_by_class.get(priority, 0) + 1
        counter = self._shed_class_counters.get(priority)
        if counter is None:
            counter = Dashboard.get_or_create_counter(
                f"SHED_BY_CLASS[{self.name}.p{priority}]")
            self._shed_class_counters[priority] = counter
        counter.inc()

    def submit(self, prompt: np.ndarray, max_new: Optional[int] = None,
               session: Optional[str] = None,
               deadline_s: Optional[float] = None,
               priority: Optional[int] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one prompt for the fleet; resolves to the reply dict
        ``{"result", "snapshot_version", "staleness_s", "replica"}``.
        ``session`` keys affinity (multi-turn conversations hit the
        same replica's prefix cache while it stays UP); ``deadline_s``
        overrides ``-fleet_deadline_s``; ``priority`` is the tenant
        class (0..7, higher = more important; None = class 1), carried
        over the wire into the replica engines' weighted-fair
        schedulers. Past the aggregate queue cap the fleet sheds BY
        CLASS, lowest first: a higher-class arrival evicts the newest
        queued lowest-class request (that one's future fails with the
        ``OverloadedError``) instead of being rejected itself; only
        when nothing lower is queued does the arrival shed
        (``retriable=True`` either way — fleet overload is
        transient). ``tenant`` is the accounting id the replica
        engines' cost ledgers attribute usage to (rides the wire only
        when set — absent keys fall back to each replica's
        ``-default_tenant``, so old replicas keep working)."""
        root = trace.start_span("serve.request", root=True,
                                model=self.name, fleet=True)
        deadline = time.monotonic() + float(
            self.config.deadline_s if deadline_s is None else deadline_s)
        prio = 1 if priority is None else int(priority)
        if not 0 <= prio <= 7:
            root.end(error="ValueError")
            raise ValueError(f"priority {prio} outside [0, 7]")
        req = _FleetRequest(prompt, max_new, session, deadline, root,
                            priority=prio, tenant=tenant)
        victim: Optional[_FleetRequest] = None
        with self._lock:
            stopped = self._stop.is_set()
            depth = -1
            if not stopped:
                depth = (len(self._pending) + len(self._retry)
                         + len(self._inflight))
                if depth >= self.config.shed_depth:
                    # shed by class: the lowest queued class below the
                    # arrival pays; the arrival itself only sheds when
                    # nothing lower is pending
                    victim = self._pending.shed_lowest_below(prio)
                    if victim is not None:
                        self._count_shed(victim.priority)
                        self.submitted += 1
                        self._pending.append(req)
                        depth = -1
                    else:
                        self._count_shed(prio)
                else:
                    self.submitted += 1
                    self._pending.append(req)
                    depth = -1
        if stopped:
            # the root span still closes on the reject path — a raise
            # must never leave an open span in the collector
            root.end(error="stopped")
            raise RuntimeError(f"fleet router {self.name!r} is stopped")
        if victim is not None:
            # the evicted request resolves OUTSIDE the lock (its
            # done-callbacks are user code) — submitted stays counted,
            # failed balances the requests_lost identity
            with self._lock:
                self.failed += 1
            self._shed_counter.inc()
            self._apply_resolutions([(victim, OverloadedError(
                self.name, self.config.shed_depth,
                self.config.shed_depth, what="fleet"))])
        if depth >= 0:
            self._shed_counter.inc()
            root.end(error="OverloadedError")
            raise OverloadedError(self.name, depth,
                                  self.config.shed_depth, what="fleet")
        if root is not trace.NULL_SPAN:
            req.future.add_done_callback(lambda f, sp=root: sp.end(
                ok=(not f.cancelled()) and f.exception() is None))
        return req.future

    def predict(self, prompt: np.ndarray, max_new: Optional[int] = None,
                session: Optional[str] = None,
                timeout_s: float = 60.0,
                priority: Optional[int] = None,
                tenant: Optional[str] = None) -> dict:
        return self.submit(prompt, max_new, session, priority=priority,
                           tenant=tenant).result(timeout=timeout_s)

    # -- wire death hook -----------------------------------------------------
    def _on_wire_dead(self, ranks) -> None:
        """Transport-declared deaths (out-of-contract resume): terminal
        for the rank — the wire itself refuses its streams now, so
        there is no readmission path. Runs on a transport thread,
        outside every router lock."""
        resolutions: List[Tuple[_FleetRequest, Any]] = []
        with self._lock:
            for r in ranks:
                rep = self._replicas.get(int(r))
                if rep is None:
                    continue
                rep.wire_dead = True
                if rep.state != DEAD:
                    self._mark_dead_locked(rep, "wire on_dead",
                                           resolutions)
        self._apply_resolutions(resolutions)

    # -- the routing loop ----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self._tick_s):
            try:
                self.tick()
            except Exception as exc:    # pragma: no cover - defensive
                Log.error("fleet router: tick failed: %s", exc)

    def tick(self) -> None:
        """One routing pass (the loop calls it every few ms; tests call
        it directly). All state mutation happens under ``_lock``;
        future resolutions and wire sends are collected and fired
        OUTSIDE it (locklint LK202/LK203 — a future's done-callbacks
        are user code, and the send path blocks on chaos delays)."""
        now = time.monotonic()
        inbound = self._drain_wire()
        resolutions: List[Tuple[_FleetRequest, Any]] = []
        sends: List[Dict[str, Any]] = []
        with self._lock:
            for node, msg in inbound:
                self._handle_locked(node, msg, now, resolutions)
            self._check_liveness_locked(now, resolutions, sends)
            self._run_retries_locked(now)
            self._check_deadlines_locked(now, resolutions)
            self._dispatch_locked(now, sends)
            for rep in self._replicas.values():
                rep.inflight_gauge.set(len(rep.inflight))
                if rep.last_hb is not None:
                    rep.hb_age_gauge.set((now - rep.last_hb) * 1e3)
                    rep.snap_gauge.set(float(
                        (rep.health or {}).get("snapshot_version", -1)))
                    rep.preempt_gauge.set(float(
                        (rep.health or {}).get("preemptions", 0)))
        self._apply_resolutions(resolutions)
        for msg in sends:
            self._publish(msg)
        self._ack_and_release()

    # -- inbound -------------------------------------------------------------
    def _drain_wire(self) -> List[Tuple[int, Dict[str, Any]]]:
        out: List[Tuple[int, Dict[str, Any]]] = []
        for r in sorted(self._replicas):
            while True:
                payload = self._transport.pop_ready(r, self._expect[r])
                if payload is None:
                    break
                self._expect[r] += 1
                try:
                    out.append((r, decode_msg(payload)))
                except ValueError:
                    Log.error("fleet: undecodable record from replica "
                              "%d (seq %d)", r, self._expect[r] - 1)
        return out

    def _handle_locked(self, node: int, msg: Dict[str, Any], now: float,
                       resolutions) -> None:
        rep = self._replicas[node]
        kind = msg.get("t")
        if kind == MSG_HB:
            rep.last_hb = now
            rep.health = msg.get("health") or {}
            role = msg.get("role") or "unified"
            if role != rep.role and role in ROLE_CODES:
                rep.role = role
                rep.role_gauge.set(ROLE_CODES[role])
            if rep.state == CONNECTING:
                self._set_state_locked(rep, UP)
            return
        if kind == MSG_PONG:
            if rep.state == PROBING and msg.get("rid") == rep.probe_rid:
                rep.probe_rid = None
                rep.readmissions += 1
                self._set_state_locked(rep, UP)
                Log.info("fleet: replica %d readmitted (probe %s "
                         "round-tripped)", node, msg.get("rid"))
            return
        if kind == MSG_XFER:
            # stage-1 complete: a prefill replica finished chunk-
            # prefilling and shipped the paged KV blocks. Release the
            # prefill assignment and re-enqueue the request at the
            # FRONT of its class as stage 2 (payload in tow) — it is
            # the oldest work its class has, and the decode side goes
            # live at P-1 through the full-hit admission path
            rid = msg.get("rid")
            req = self._inflight.get(rid)
            if req is None:
                return          # late duplicate / already re-dispatched
            for holder in self._replicas.values():
                holder.inflight.discard(rid)
            del self._inflight[rid]
            payload = msg.get("payload") or {}
            shipped = kv_transfer.shipped_hashes(payload)
            nbytes = kv_transfer.payload_bytes(payload)
            dedup = int(payload.get("dedup_blocks", 0))
            self.kv_xfers += 1
            self.kv_bytes_moved += nbytes
            self.xfer_blocks += len(shipped)
            self.xfer_dedup_blocks += dedup
            if req.decode_rank is not None and not payload.get("dropped"):
                # every hash in an intact payload is resident at the
                # decode rank after the splice (the dedup'd ones
                # already were) — a chaos-dropped payload's blocks
                # never arrived, so its hashes stay out of the book. A
                # stale book only costs a re-ship that dedups on
                # arrival; correctness never depends on it
                book = self._shipped.setdefault(req.decode_rank, set())
                if len(book) > _SHIPPED_CAP:
                    book.clear()
                book.update(payload.get("hashes") or ())
            xsp = req.xfer_span
            if xsp is not None:
                req.xfer_span = None
                xsp.end(ok=not payload.get("dropped"),
                        xfer_blocks=len(shipped), xfer_bytes=nbytes,
                        dedup_blocks=dedup)
            sp = req.dispatch_span
            if sp is not None:
                req.dispatch_span = None
                sp.end(ok=True)
            req.stage = None
            req.xfer = payload
            req.replica = None
            self._pending.appendleft(req)
            return
        if kind not in (MSG_RSP, MSG_ERR):
            return
        rid = msg.get("rid")
        req = self._inflight.get(rid)
        if req is None:
            # late duplicate (the replay path makes these legitimate):
            # dedupe by rid, and CHECK the payload against the first
            # completion — greedy decode is deterministic, so a
            # mismatch is a real invariant break, counted and gated
            if rid in self._done:
                self.duplicate_replies += 1
                if kind == MSG_RSP:
                    digest = self._digest(msg.get("result"))
                    first = self._done[rid]
                    if first is not None and digest != first:
                        self.output_mismatches += 1
                        Log.error("fleet: duplicate reply for %s from "
                                  "replica %d DIFFERS from the first "
                                  "completion (determinism break)",
                                  rid, node)
            return
        # the reply may come from a previous assignee (re-dispatch
        # raced a slow-but-alive replica): accept it — the output is
        # deterministic — and release both assignments
        for holder in self._replicas.values():
            holder.inflight.discard(rid)
        del self._inflight[rid]
        if kind == MSG_ERR:
            if (msg.get("kind") == "overloaded"
                    and msg.get("retriable", True)):
                self._requeue_locked(req, f"replica {node} shed",
                                     resolutions)
            elif msg.get("kind") == "overloaded":
                # a PERMANENT shed (request bigger than the replica's
                # whole KV pool): retrying cannot change the verdict —
                # fail now instead of burning the retry budget on an
                # impossibility (the retriable hint, not string-
                # matching `what`)
                self.failed += 1
                self._finish_done_locked(rid, None)
                resolutions.append((req, OverloadedError(
                    self.name, int(msg.get("depth", -1)),
                    int(msg.get("cap", -1)),
                    what=msg.get("what", "replica"), retriable=False)))
            elif msg.get("what") == "DeadlineExceededError":
                # the replica engine dropped it at queue-pop time: the
                # caller sees the same typed error the router's own
                # deadline sweep raises
                self.deadline_failures += 1
                self.failed += 1
                self._finish_done_locked(rid, None)
                resolutions.append((req, DeadlineExceededError(
                    f"fleet request {rid} missed its deadline on "
                    f"replica {node}: {msg.get('msg')}")))
            else:
                self.failed += 1
                self._finish_done_locked(rid, None)
                resolutions.append((req, RuntimeError(
                    f"fleet request {rid} failed on replica {node}: "
                    f"{msg.get('what')}: {msg.get('msg')}")))
            return
        reply = {
            "result": np.asarray(msg.get("result"), np.int32),
            "snapshot_version": msg.get("snapshot_version"),
            "staleness_s": msg.get("staleness_s", 0.0),
            "replica": node,
        }
        self.completed += 1
        if req.redispatched:
            self._last_recovery = now
        self._finish_done_locked(rid, self._digest(msg.get("result")))
        resolutions.append((req, reply))

    @staticmethod
    def _digest(result) -> int:
        return hash(tuple(result or ()))

    def _finish_done_locked(self, rid: str, digest: Optional[int]) -> None:
        self._done[rid] = digest
        while len(self._done) > self._done_cap:
            self._done.popitem(last=False)

    # -- liveness ------------------------------------------------------------
    def _set_state_locked(self, rep: _Replica, state: int) -> None:
        rep.state = state
        rep.state_gauge.set(state)

    def _mark_dead_locked(self, rep: _Replica, why: str,
                          resolutions) -> None:
        """One death transition: flag, drain the in-flight set into the
        retry queue (bounded re-dispatch), drop affinity pins."""
        self._set_state_locked(rep, DEAD)
        rep.deaths += 1
        self._last_death = time.monotonic()
        drained = [self._inflight[rid] for rid in sorted(rep.inflight)
                   if rid in self._inflight]
        rep.inflight.clear()
        for session, r in list(self._affinity.items()):
            if r == rep.rank:
                del self._affinity[session]
        # a dead decode rank's KV pool is gone with it: forget what we
        # shipped there (its heartbeat advertisements rebuild the book)
        self._shipped.pop(rep.rank, None)
        Log.error("fleet: replica %d DEAD (%s); re-dispatching %d "
                  "in-flight request(s)", rep.rank, why, len(drained))
        for req in drained:
            req.redispatched = True
            self._redispatch_counter.inc()
            self._requeue_locked(req, why, resolutions)

    def _requeue_locked(self, req: _FleetRequest, why: str,
                        resolutions) -> None:
        """Push one in-flight request back through the bounded
        retry/backoff path (or fail it once the budget is spent)."""
        sp = req.dispatch_span
        if sp is not None:
            sp.end(error=why)
            req.dispatch_span = None
        xsp = req.xfer_span
        if xsp is not None:
            xsp.end(error=why)
            req.xfer_span = None
        self._inflight.pop(req.rid, None)
        req.exclude = req.replica        # prefer a DIFFERENT survivor
        req.replica = None
        # a failed stage-1 re-decides its route at redispatch time: the
        # surviving fleet may have no prefill rank left, in which case
        # the request falls back to unified admission (any role's
        # engine handles a plain request). A carried stage-2 payload
        # (req.xfer) survives — the blocks are still good
        req.stage = None
        if req.attempts > self.config.retry_max:
            self.failed += 1
            self._finish_done_locked(req.rid, None)
            resolutions.append((req, FleetError(
                f"fleet request {req.rid} exhausted "
                f"{self.config.retry_max} re-dispatch attempt(s): {why}")))
            return
        delay = retry_backoff_s(req.attempts,
                                self.config.backoff_ms / 1000.0,
                                self.config.backoff_cap_ms / 1000.0,
                                self._rng)
        now = time.monotonic()
        if now + delay >= req.deadline:
            # the retry queue respects deadlines: a backoff that lands
            # past the deadline is a wait for an answer nobody will
            # read — fail fast instead of burning it
            self.deadline_failures += 1
            self.failed += 1
            self._finish_done_locked(req.rid, None)
            resolutions.append((req, DeadlineExceededError(
                f"fleet request {req.rid} cannot retry within its "
                f"deadline (backoff {delay:.3f}s, "
                f"{max(0.0, req.deadline - now):.3f}s left): {why}")))
            return
        self._retries_counter.inc()
        self._retry.append((now + delay, req))

    def _check_liveness_locked(self, now: float, resolutions,
                               sends) -> None:
        for rep in self._replicas.values():
            age = None if rep.last_hb is None else now - rep.last_hb
            if rep.state == UP:
                if age is not None and age > self.config.dead_after_s:
                    self._mark_dead_locked(
                        rep, f"heartbeat age {age:.3f}s", resolutions)
            elif rep.state == PROBING:
                if age is not None and age > self.config.dead_after_s:
                    # went silent again mid-probe: back to DEAD (no
                    # in-flight to drain — PROBING never dispatches)
                    rep.probe_rid = None
                    self._mark_dead_locked(
                        rep, f"silent during probe ({age:.3f}s)",
                        resolutions)
            elif rep.state == DEAD and not rep.wire_dead:
                if age is not None and age <= self.config.dead_after_s:
                    # heartbeats resumed: half-open — ONE probe must
                    # round-trip before any real request lands here
                    self._probe_n += 1
                    rep.probe_rid = f"probe-{rep.rank}-{self._probe_n}"
                    self._set_state_locked(rep, PROBING)
                    Log.info("fleet: replica %d heartbeating again; "
                             "probing (%s)", rep.rank, rep.probe_rid)
                    sends.append({"t": MSG_PING, "target": rep.rank,
                                  "rid": rep.probe_rid})

    # -- retries / deadlines -------------------------------------------------
    def _run_retries_locked(self, now: float) -> None:
        due = [req for t, req in self._retry if t <= now]
        if due:
            self._retry = [(t, req) for t, req in self._retry if t > now]
            # retries go to the FRONT of their class: they are the
            # oldest requests that class has
            for req in reversed(due):
                self._pending.appendleft(req)

    def _check_deadlines_locked(self, now: float, resolutions) -> None:
        def expire(req: _FleetRequest) -> None:
            self.deadline_failures += 1
            self.failed += 1
            sp = req.dispatch_span
            if sp is not None:
                sp.end(error="deadline")
                req.dispatch_span = None
            xsp = req.xfer_span
            if xsp is not None:
                xsp.end(error="deadline")
                req.xfer_span = None
            self._finish_done_locked(req.rid, None)
            resolutions.append((req, DeadlineExceededError(
                f"fleet request {req.rid} missed its deadline "
                f"({(now - req.t_enq):.3f}s since submit)")))

        expired = self._pending.expire(now)
        for t, req in list(self._retry):
            if req.deadline <= now:
                expired.append(req)
        self._retry = [(t, r) for t, r in self._retry
                       if r.deadline > now]
        for rid, req in list(self._inflight.items()):
            if req.deadline <= now:
                del self._inflight[rid]
                for rep in self._replicas.values():
                    rep.inflight.discard(rid)
                expired.append(req)
        for req in expired:
            expire(req)

    # -- dispatch ------------------------------------------------------------
    def _pick_locked(self, req: _FleetRequest,
                     pool: Optional[List[_Replica]] = None
                     ) -> Optional[_Replica]:
        up = (pool if pool is not None else
              [rep for rep in self._replicas.values()
               if rep.state == UP])
        if not up:
            return None
        # a retried request prefers a DIFFERENT replica than the one
        # that just died/shed it (when any other is up) — re-dispatch
        # exists to escape the failure, not to re-queue behind it
        if req.exclude is not None and len(up) > 1:
            up = [rep for rep in up if rep.rank != req.exclude] or up
        if req.session:
            pin = self._affinity.get(req.session)
            if pin is not None and pin != req.exclude:
                rep = self._replicas.get(pin)
                if rep is not None and rep.state == UP \
                        and (pool is None or rep in up):
                    return rep
        def load(rep: _Replica) -> Tuple[int, int]:
            return (len(rep.inflight)
                    + int((rep.health or {}).get("queue_depth", 0)),
                    rep.rank)
        return min(up, key=load)

    def _role_pools_locked(self) -> Tuple[List[_Replica], List[_Replica]]:
        prefills = [rep for rep in self._replicas.values()
                    if rep.state == UP and rep.role == "prefill"]
        decodes = [rep for rep in self._replicas.values()
                   if rep.state == UP and rep.role == "decode"]
        return prefills, decodes

    def _dispatch_locked(self, now: float, sends) -> None:
        while self._pending:
            req = self._pending.peek()
            # two-stage route decision, re-made at EVERY dispatch (the
            # role pools may have changed since the last attempt):
            #   stage 1 — both role pools populated and no payload yet:
            #     prefill rank computes the KV, decode rank is chosen
            #     NOW so its cached chains can be advertised upstream;
            #   stage 2 — payload in tow: land on the chosen decode
            #     rank (or any survivor — the payload degrades to a
            #     local re-prefill if its blocks cannot splice);
            #   otherwise — classic unified admission (fallback when a
            #   role pool is empty: every role serves plain requests).
            prefills, decodes = self._role_pools_locked()
            stage1 = False
            extra: Dict[str, Any] = {}
            if req.xfer is not None:
                rep = None
                if req.decode_rank is not None:
                    cand = self._replicas.get(req.decode_rank)
                    if cand is not None and cand.state == UP:
                        rep = cand
                if rep is None:
                    rep = self._pick_locked(req, decodes or None)
                extra["xfer"] = req.xfer
            elif prefills and decodes:
                dec = self._pick_locked(req, decodes)
                rep = self._pick_locked(req, prefills)
                if dec is not None and rep is not None:
                    stage1 = True
                    req.stage = "prefill"
                    req.decode_rank = dec.rank
                    # the decode side's known chains (our shipping book
                    # + its own heartbeat advertisement): a warm prefix
                    # never crosses the wire
                    known = set(self._shipped.get(dec.rank, ()))
                    known.update(
                        (dec.health or {}).get("cached_chains") or ())
                    extra["stage"] = "prefill"
                    extra["known"] = sorted(known)
                else:
                    rep = self._pick_locked(req)
            else:
                rep = self._pick_locked(req)
            if rep is None:
                return                   # nobody UP: requests wait
            self._pending.popleft()
            req.attempts += 1
            req.replica = rep.rank
            rep.inflight.add(req.rid)
            self._inflight[req.rid] = req
            if req.session:
                # affinity pins the rank that HOLDS the KV — the
                # decode side of a disaggregated route
                self._affinity[req.session] = (req.decode_rank
                                               if stage1 else rep.rank)
            self._dispatch_counter.inc()
            sp = trace.start_span(
                "route.dispatch",
                parent=req.root.context if req.root is not trace.NULL_SPAN
                else None,
                replica=rep.rank, rid=req.rid, attempt=req.attempts)
            req.dispatch_span = sp
            if stage1 and req.xfer_span is None:
                # the kv.transfer span brackets the whole stage-1 →
                # payload round trip; closed at MSG_XFER (or error'd by
                # the requeue/deadline paths)
                req.xfer_span = trace.start_span(
                    "kv.transfer",
                    parent=req.root.context
                    if req.root is not trace.NULL_SPAN else None,
                    rid=req.rid, prefill_replica=rep.rank,
                    decode_replica=req.decode_rank)
            wire_ctx = None
            if sp is not trace.NULL_SPAN:
                wire_ctx = [sp.trace_id, sp.span_id]
            sends.append({
                "t": MSG_REQ, "target": rep.rank, "rid": req.rid,
                "session": req.session, "prompt": req.prompt.tolist(),
                "max_new": req.max_new, "trace": wire_ctx,
                # priority + REMAINING deadline budget ride the wire
                # (remaining, not absolute: the replica's monotonic
                # clock is not ours) so the replica engine's scheduler
                # sees the same class and the same urgency
                "prio": req.priority,
                "deadline_ms": max(0.0, (req.deadline - now) * 1e3),
                # tenant rides only when set: absent keys decode as
                # the replica's -default_tenant, so pre-ledger
                # replicas (and archived payloads) stay valid
                **({"tenant": req.tenant} if req.tenant else {}),
                **extra})

    # -- outbound ------------------------------------------------------------
    def _publish(self, msg: Dict[str, Any]) -> None:
        payload = encode_msg(msg)
        with self._lock:
            seq = self._seq
            self._seq = seq + 1
        self._transport.send(seq, payload)

    def _publish_head(self) -> None:
        # only when the head MOVED: an idle router must not rewrite an
        # identical value into the coordination service every tick
        if self._seq == self._head_published:
            return
        try:
            self._client.key_value_set(f"{self._label}/head",
                                       str(self._seq),
                                       allow_overwrite=True)
            self._head_published = self._seq
        except Exception:               # pragma: no cover - kv trouble
            pass

    def _ack_and_release(self) -> None:
        """Ack every replica stream we consumed, advance the request
        stream's release frontier to the min ack over serviceable
        replicas (DEAD ranks are excluded — a permanently silent
        replica must not pin the retained window; its successor
        resumes from the published head, not from its ack), and
        re-publish the head for restart bootstraps. The ack READS run
        at heartbeat cadence, not tick cadence: release latency is not
        liveness, and a KV client whose only read is a blocking get
        (the ``_read_ack`` fallback) must never stall the routing
        thread once per replica per tick — that path flagged healthy
        replicas DEAD at boot."""
        for r, rep in self._replicas.items():
            if self._expect[r] > self._acked[r]:
                try:
                    self._client.key_value_set(
                        f"{self._label}/rack/{r}", str(self._expect[r]),
                        allow_overwrite=True)
                    self._acked[r] = self._expect[r]
                except Exception:       # pragma: no cover - kv trouble
                    pass
        now = time.monotonic()
        if now < self._next_ack_poll or self._released >= self._seq:
            self._publish_head()
            return
        self._next_ack_poll = now + self.config.heartbeat_ms / 1000.0
        live_acks = []
        for r, rep in self._replicas.items():
            if rep.state == DEAD or rep.last_hb is None:
                # DEAD ranks and never-connected CONNECTING ranks (a
                # replica that crashed at boot) must not pin the
                # frontier at 0 forever — their (re)incarnations resume
                # from the published head, not from their ack, so
                # releasing past them is in contract
                continue
            live_acks.append(self._read_ack(r))
        if live_acks:
            frontier = min(live_acks)
            while self._released < frontier:
                self._transport.release(self._released)
                self._released += 1
        self._publish_head()

    def _read_ack(self, r: int) -> int:
        key = f"{self._label}/ack/{r}"
        try:
            if hasattr(self._client, "key_value_try_get"):
                return int(str(self._client.key_value_try_get(key)))
            return int(str(self._client.blocking_key_value_get(key, 100)))
        except Exception:
            return 0

    def _apply_resolutions(self, resolutions) -> None:
        """Fire future results/exceptions OUTSIDE every router lock —
        done-callbacks are user code (locklint LK202)."""
        for req, outcome in resolutions:
            sp = req.dispatch_span
            if sp is not None:
                req.dispatch_span = None
                sp.end(ok=not isinstance(outcome, Exception))
            xsp = req.xfer_span
            if xsp is not None:
                req.xfer_span = None
                xsp.end(ok=not isinstance(outcome, Exception))
            if not req.future.set_running_or_notify_cancel():
                continue
            if isinstance(outcome, Exception):
                req.future.set_exception(outcome)
            else:
                req.future.set_result(outcome)

    # -- introspection -------------------------------------------------------
    def replica_rows(self) -> List[Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            return [{
                "rank": rep.rank,
                "state": STATE_NAMES[rep.state],
                "role": rep.role,
                "inflight": len(rep.inflight),
                "hb_age_ms": (None if rep.last_hb is None
                              else round((now - rep.last_hb) * 1e3, 1)),
                "deaths": rep.deaths,
                "readmissions": rep.readmissions,
                "queue_depth": (rep.health or {}).get("queue_depth", 0),
                "snapshot_version": (rep.health or {}).get(
                    "snapshot_version", -1),
                "params_stale": bool((rep.health or {}).get(
                    "params_stale", False)),
                "preemptions": (rep.health or {}).get("preemptions", -1),
            } for rep in sorted(self._replicas.values(),
                                key=lambda x: x.rank)]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            pending = len(self._pending)
            retrying = len(self._retry)
            inflight = len(self._inflight)
            recovery = None
            if self._last_death is not None \
                    and self._last_recovery is not None \
                    and self._last_recovery >= self._last_death:
                recovery = self._last_recovery - self._last_death
            return {
                "replicas": len(self._replicas),
                "up": sum(1 for rep in self._replicas.values()
                          if rep.state == UP),
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "shed": self.shed,
                "shed_by_class": {f"p{p}": n for p, n in
                                  sorted(self.shed_by_class.items())},
                "deadline_failures": self.deadline_failures,
                "pending": pending,
                "retrying": retrying,
                "inflight": inflight,
                "requests_lost": (self.submitted - self.completed
                                  - self.failed - pending - retrying
                                  - inflight),
                "duplicate_replies": self.duplicate_replies,
                "output_mismatches": self.output_mismatches,
                "kv_xfers": self.kv_xfers,
                "kv_bytes_moved": self.kv_bytes_moved,
                "xfer_blocks": self.xfer_blocks,
                "xfer_dedup_blocks": self.xfer_dedup_blocks,
                "xfer_dedup_hit_rate": (
                    self.xfer_dedup_blocks
                    / (self.xfer_blocks + self.xfer_dedup_blocks)
                    if (self.xfer_blocks + self.xfer_dedup_blocks)
                    else 0.0),
                "deaths": sum(rep.deaths
                              for rep in self._replicas.values()),
                "readmissions": sum(rep.readmissions
                                    for rep in self._replicas.values()),
                "recovery_time_s": recovery,
            }

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Block until every accepted request resolved (or timeout):
        the bench/test barrier between "trace submitted" and "verdict
        read"."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not (self._pending or self._retry or self._inflight):
                    return True
            time.sleep(0.005)
        return False

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        resolutions: List[Tuple[_FleetRequest, Any]] = []
        with self._lock:
            leftovers = (self._pending.drain()
                         + [r for _, r in self._retry]
                         + list(self._inflight.values()))
            self._retry = []
            self._inflight.clear()
        for req in leftovers:
            resolutions.append((req, RuntimeError(
                f"fleet router {self.name!r} stopped with request "
                f"{req.rid} unresolved")))
        self._apply_resolutions(resolutions)
        self._transport.stop()
