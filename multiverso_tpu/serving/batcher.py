"""Micro-batching request scheduler: bounded queue -> padded shape buckets.

The latency/throughput trade at the heart of batched serving (the
clipper-style adaptive-batching design): single requests dispatched alone
pay the full host->device dispatch + kernel launch cost per reply;
batching amortises it, but an unbounded wait for a full batch destroys
tail latency. The scheduler therefore flushes on EITHER trigger:

* **size** — ``max_batch`` requests are waiting (throughput bound);
* **deadline** — the OLDEST waiting request has aged ``deadline_ms``
  (latency bound; nothing waits longer than one deadline + one batch
  execution).

Flushed batches are padded up to a small set of **shape buckets**
(powers of two up to ``max_batch``), so XLA compiles one program per
bucket and every later flush of any size reuses a warm cache entry —
arbitrary batch sizes would retrace/recompile on each new size and
torpedo p99.

Overload is handled by **load-shedding, not queueing**: past
``max_queue`` waiting requests, ``submit`` fast-rejects with the typed
:class:`OverloadedError` (the caller can back off / retry elsewhere)
instead of growing an unbounded queue whose every entry would time out
anyway. Per-reply latency lands in a Dashboard histogram
(``SERVE_LAT[name]``) for p50/p95/p99.
"""

from __future__ import annotations

import threading
from ..analysis import lockwatch
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, Deque, List, Optional, Sequence, Tuple

import collections

from .. import trace
from ..dashboard import Dashboard
from ..log import Log


class OverloadedError(RuntimeError):
    """Typed fast-reject: the model is out of a bounded resource.

    ``what`` names the resource — the queue-depth cap here, or the
    decode engine's KV block pool when a request's ``prompt + max_new``
    could never fit it (``depth``/``cap`` then carry blocks needed vs
    pool capacity). ``retriable`` is the retry-policy hint: a
    queue-depth/fleet shed is TRANSIENT (back off and resend — capacity
    frees as requests complete), while a request bigger than the whole
    pool is PERMANENT (no amount of waiting ever admits it; resending
    is a spin loop). Retry paths — the fleet router's requeue, the
    bench's playback — branch on this field, never on string-matching
    ``what``."""

    def __init__(self, model: str, depth: int, cap: int,
                 what: str = "queue depth", retriable: bool = True) -> None:
        super().__init__(
            f"serving {what} for {model!r} at cap ({depth}/{cap}); "
            "request shed")
        self.model = model
        self.depth = depth
        self.cap = cap
        self.what = what
        self.retriable = bool(retriable)


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed before it completed.

    Raised on both serving tiers: the :class:`~.router.FleetRouter`
    expires pending/retrying/in-flight requests against its
    ``deadline_s``, and the :class:`~.decode_engine.DecodeEngine` drops
    expired requests at queue-POP time — before any prefill FLOPs are
    burned on an answer nobody is waiting for."""


def shape_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to ``max_batch`` (``max_batch`` always included)."""
    buckets: List[int] = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (callers guarantee n <= max(buckets))."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


@dataclass
class BatcherConfig:
    max_batch: int = 32
    deadline_ms: float = 2.0
    max_queue: int = 256
    buckets: Optional[Tuple[int, ...]] = None   # default: shape_buckets()
    # rolling-window p99 reply-latency SLO registered in the Dashboard
    # (None = the -slo_lat_ms flag; 0 = no SLO)
    slo_lat_ms: Optional[float] = None

    def resolved_buckets(self) -> Tuple[int, ...]:
        return tuple(self.buckets) if self.buckets else shape_buckets(
            self.max_batch)

    def resolved_slo_lat_ms(self) -> float:
        if self.slo_lat_ms is not None:
            return float(self.slo_lat_ms)
        from ..config import get_flag

        return float(get_flag("slo_lat_ms"))


class _Pending:
    __slots__ = ("payload", "future", "t_enq", "ctx")

    def __init__(self, payload: Any,
                 ctx: Optional[trace.SpanContext] = None) -> None:
        self.payload = payload
        self.future: Future = Future()
        self.t_enq = time.monotonic()
        # trace handoff token: the submitting thread's root-span context,
        # carried across the queue so the flush thread's spans join the
        # request's trace instead of starting orphan ones
        self.ctx = ctx


class MicroBatcher:
    """One model's queue + flush thread.

    ``run_batch(payloads, bucket) -> results`` executes a flushed batch
    (``len(payloads) <= bucket``; the workload pads to ``bucket``) and
    returns one result per payload, in order.
    """

    def __init__(self, name: str, run_batch: Callable[[List[Any], int], List[Any]],
                 config: Optional[BatcherConfig] = None) -> None:
        self.name = name
        self.config = config or BatcherConfig()
        self._buckets = self.config.resolved_buckets()
        if self.config.max_batch > self._buckets[-1]:
            Log.fatal(f"batcher {name!r}: max_batch {self.config.max_batch} "
                      f"exceeds the largest bucket {self._buckets[-1]}")
        self._run_batch = run_batch
        self._q: Deque[_Pending] = collections.deque()
        self._lock = lockwatch.lock("serving.MicroBatcher._lock")
        self._cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        # -- stats ----------------------------------------------------------
        self.hist = Dashboard.get_or_create_histogram(f"SERVE_LAT[{name}]")
        slo_lat = self.config.resolved_slo_lat_ms()
        if slo_lat > 0:
            # burn status for this model's reply latency rides every
            # Dashboard.snapshot() (docs/OBSERVABILITY.md "SLO tracking")
            Dashboard.set_slo(f"SERVE_LAT[{name}]", slo_lat)
        self.shed_counter = Dashboard.get_or_create_counter(
            f"SERVE_SHED[{name}]")
        self.completed = 0
        self.shed = 0
        self.t_first: Optional[float] = None
        # idle-wait returns (tests assert an idle server never wakes:
        # the idle wait is untimed, not a poll)
        self.idle_wakeups = 0
        # recent (n, bucket, cause) flush records, for tests/introspection
        self.flushes: Deque[Tuple[int, int, str]] = collections.deque(
            maxlen=1024)
        self._thread = threading.Thread(
            target=self._loop, name=f"serve-batch-{name}", daemon=True)
        self._thread.start()

    # -- client side --------------------------------------------------------
    def submit(self, payload: Any,
               ctx: Optional[trace.SpanContext] = None) -> Future:
        """Enqueue one request; fast-rejects at the queue-depth cap.
        ``ctx`` is the request's trace handoff token (or None)."""
        if self._stop.is_set():
            raise RuntimeError(f"batcher {self.name!r} is stopped")
        p = _Pending(payload, ctx)
        with self._cv:
            if self._stop.is_set():
                # re-check under the lock: a submit that passed the gate
                # above while stop() drained would enqueue a request no
                # thread will ever flush
                raise RuntimeError(f"batcher {self.name!r} is stopped")
            if len(self._q) >= self.config.max_queue:
                self.shed += 1
                self.shed_counter.inc()
                raise OverloadedError(self.name, len(self._q),
                                      self.config.max_queue)
            if self.t_first is None:
                self.t_first = p.t_enq
            self._q.append(p)
            self._cv.notify()
        return p.future

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    # -- flush thread -------------------------------------------------------
    def _loop(self) -> None:
        deadline_s = self.config.deadline_ms / 1e3
        max_batch = self.config.max_batch
        while True:
            with self._cv:
                # UNTIMED idle wait: submit() and stop() both notify, so a
                # poll here only burned 20 wakeups/s per registered model
                # while idle
                while not self._q and not self._stop.is_set():
                    self._cv.wait()
                    self.idle_wakeups += 1
                if self._stop.is_set() and not self._q:
                    return
                # queue non-empty: wait for a full batch, bounded by the
                # OLDEST request's deadline (submit() notifies on growth)
                cause = "size"
                while len(self._q) < max_batch and not self._stop.is_set():
                    remaining = deadline_s - (
                        time.monotonic() - self._q[0].t_enq)
                    if remaining <= 0:
                        cause = "deadline"
                        break
                    self._cv.wait(remaining)
                if self._stop.is_set():
                    cause = "stop"        # final drain: flush what's left
                batch = [self._q.popleft()
                         for _ in range(min(max_batch, len(self._q)))]
            self._flush(batch, cause)

    def _flush(self, batch: List[_Pending], cause: str) -> None:
        # claim every future FIRST: set_running_or_notify_cancel() returns
        # False for a future the client cancel()'d while queued — skipping
        # it (instead of set_result raising InvalidStateError) keeps one
        # cancelled request from killing the flush thread for good
        live = [p for p in batch if p.future.set_running_or_notify_cancel()]
        bucket = bucket_for(len(batch), self._buckets)
        t_claim = time.monotonic()
        if trace.enabled():
            # per-request queue-wait spans: how long each request sat
            # before THIS flush claimed it, and why the flush fired.
            # `live` only — a cancelled request's root span closed at
            # cancel time; stage spans recorded after it would outlive
            # their parent in the exported tree
            for p in live:
                if p.ctx is not None:
                    trace.record_span("queue.wait", p.ctx, p.t_enq, t_claim,
                                      cause=cause)
        error = None
        try:
            results = self._run_batch([p.payload for p in batch], bucket)
        except Exception as exc:
            error = exc
        now = time.monotonic()
        if trace.enabled():
            # one batch execution -> one child span PER co-batched request
            # (same interval, each under its own trace): a slow request's
            # tree shows exactly which strangers shared its flush and
            # which shape bucket the batch padded into
            err_attr = ({"error": type(error).__name__} if error is not None
                        else {})
            for p in live:
                if p.ctx is not None:
                    trace.record_span("batch.exec", p.ctx, t_claim, now,
                                      bucket=bucket, batch_n=len(batch),
                                      cause=cause, **err_attr)
        if error is not None:
            for p in live:
                p.future.set_exception(error)
            return
        self.flushes.append((len(batch), bucket, cause))
        done = 0
        for p, r in zip(batch, results):
            if p.future.running():          # claimed above, not cancelled
                p.future.set_result(r)
                self.hist.record((now - p.t_enq) * 1e3)
                done += 1
        self.completed += done

    # -- stats / lifecycle --------------------------------------------------
    def stats(self) -> dict:
        elapsed = (time.monotonic() - self.t_first) if self.t_first else 0.0
        issued = self.completed + self.shed
        return {
            "completed": self.completed,
            "shed": self.shed,
            "shed_rate": self.shed / issued if issued else 0.0,
            "qps": self.completed / elapsed if elapsed > 0 else 0.0,
            **{k: v for k, v in self.hist.summary().items() if k != "count"},
        }

    def stop(self) -> None:
        """Flush whatever is queued, then retire the thread."""
        with self._cv:
            self._stop.set()
            self._cv.notify_all()
        self._thread.join(timeout=10)
