"""Request router: named models -> micro-batchers -> jitted workloads.

The front door of the serving subsystem. Each registered model owns a
:class:`MicroBatcher` (bounded queue, size/deadline flush, shape
buckets, load-shedding) and a :class:`SnapshotManager` (versioned
copy-on-publish read view). A flush takes ONE snapshot decision for the
whole batch, executes the workload's jitted program against it, and
stamps every reply with the snapshot version and its staleness bound —
so a client can always tell how far behind live training its answer is.

Lifecycle ties into the Session: a started server registers itself, and
``Session.stop()`` (``mv.shutdown()``) stops serving before tables are
torn down — the reference Zoo's shutdown-order contract extended to the
inference plane.

A fleet deployment scales this out behind :class:`~.router.FleetRouter`
(``mvserve``), optionally with role-specialized replicas — prefill
ranks chunk-prefill prompts and ship the finished paged-KV blocks over
the wire to decode ranks (:mod:`.kv_transfer`, docs/SERVING.md
"Disaggregated prefill/decode"); this in-process server is the
``unified`` role both specializations degrade to.
"""

from __future__ import annotations

import threading
from ..analysis import lockwatch
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from .. import trace
from ..log import Log
from .batcher import BatcherConfig, MicroBatcher
from .decode_engine import DecodeEngine, DecodeEngineConfig
from .snapshot import SnapshotManager


class _DecoderEntry:
    """A continuous-batching LM: requests route to a :class:`DecodeEngine`
    (iteration-level scheduling) instead of a :class:`MicroBatcher`."""

    def __init__(self, name: str, engine: DecodeEngine) -> None:
        self.name = name
        self.engine = engine

    def submit(self, payload: Any,
               ctx: Optional[trace.SpanContext] = None) -> Future:
        """Payload: a 1-D prompt id array, or a dict with ``prompt`` and
        optional per-request ``max_new``, ``priority`` (tenant class,
        0..7, higher = more important), ``deadline_s`` (seconds from
        now past which the reply is worthless — expired requests drop
        at queue-pop time with ``DeadlineExceededError``, before any
        prefill runs) and ``tenant`` (accounting id for the cost
        ledger; absent = the ``-default_tenant`` flag)."""
        if isinstance(payload, dict):
            if "prompt" not in payload:
                raise ValueError("decoder payload dict needs a 'prompt' key")
            return self.engine.submit(payload["prompt"],
                                      payload.get("max_new"), ctx=ctx,
                                      priority=payload.get("priority"),
                                      deadline_s=payload.get("deadline_s"),
                                      tenant=payload.get("tenant"))
        return self.engine.submit(payload, ctx=ctx)


class _ModelEntry:
    def __init__(self, name: str, workload, manager: SnapshotManager,
                 batcher_cfg: BatcherConfig, max_staleness_s: float) -> None:
        self.name = name
        self.workload = workload
        self.manager = manager
        self.max_staleness_s = float(max_staleness_s)
        self.batcher = MicroBatcher(name, self._run, batcher_cfg)

    def _run(self, payloads: List[Any], bucket: int) -> List[dict]:
        # ONE freshness decision per flush: every reply in the batch is
        # built from the same snapshot, and its staleness at flush time
        # is bounded by max_staleness_s (ensure_fresh republishes past it)
        snap = self.manager.ensure_fresh(self.max_staleness_s)
        staleness = self.manager.staleness_s(snap)
        results = self.workload.run(payloads, bucket, snap)
        return [{"result": r, "snapshot_version": snap.version,
                 "staleness_s": staleness} for r in results]


class InferenceServer:
    """Batched low-latency inference over live parameter state."""

    def __init__(self, name: str = "serving") -> None:
        self.name = name
        self._models: Dict[str, _ModelEntry] = {}
        self._lock = lockwatch.lock("serving.InferenceServer._lock")
        self._stopped = False
        from ..runtime import Session

        sess = Session.get()
        if sess.started:
            sess.register_server(self)

    # -- registration -------------------------------------------------------
    def register(self, name: str, workload, max_batch: int = 32,
                 deadline_ms: float = 2.0, max_queue: int = 256,
                 max_staleness_s: float = 0.05,
                 buckets: Optional[tuple] = None) -> None:
        """Attach a workload under ``name``.

        ``workload`` exposes ``source`` (a table or model with the
        snapshot contract) and ``run(payloads, bucket, snap)``; knobs:
        ``max_batch``/``deadline_ms`` set the flush triggers,
        ``max_queue`` the shed threshold, ``max_staleness_s`` the
        snapshot refresh bound.
        """
        cfg = BatcherConfig(max_batch=max_batch, deadline_ms=deadline_ms,
                            max_queue=max_queue, buckets=buckets)
        manager = SnapshotManager.of(workload.source, name=name)
        with self._lock:
            if self._stopped:
                Log.fatal(f"serving: register({name!r}) on a stopped "
                          f"server")
            if name in self._models:
                Log.fatal(f"serving: model {name!r} already registered")
            self._models[name] = _ModelEntry(
                name, workload, manager, cfg, max_staleness_s)
        Log.info("serving: model %r up (max_batch %d, deadline %.1f ms, "
                 "queue cap %d)", name, max_batch, deadline_ms, max_queue)

    def register_decoder(self, name: str, lm, *, slots: int = 8,
                         max_prompt: int = 64, max_new: int = 32,
                         eos_id: Optional[int] = None, max_queue: int = 256,
                         max_staleness_s: float = 0.05,
                         prompt_buckets: Optional[tuple] = None,
                         prefill_token_budget: Optional[int] = None,
                         kv_block_size: Optional[int] = None,
                         kv_pool_blocks: Optional[int] = None,
                         decode_tp: Optional[int] = None,
                         prefix_cache: Optional[bool] = None,
                         prefill_sp: Optional[bool] = None,
                         prefill_sp_backend: Optional[str] = None,
                         prefill_sp_threshold: Optional[int] = None,
                         spec_k: Optional[int] = None,
                         kv_quant: Optional[str] = None,
                         decode_param_quant: Optional[str] = None,
                         preempt: Optional[bool] = None,
                         preempt_budget: Optional[int] = None,
                         sched_lookahead: Optional[int] = None,
                         watchdog: Optional[bool] = None,
                         debug_dump_dir: Optional[str] = None,
                         slo_ttft_ms: Optional[float] = None,
                         slo_itl_ms: Optional[float] = None,
                         cost_ledger: Optional[bool] = None
                         ) -> DecodeEngine:
        """Attach a continuous-batching decode engine under ``name``.

        Unlike :meth:`register`'s micro-batched ``LMGreedyDecode``,
        ``submit`` routes straight into the engine: admission, decode,
        and completion all happen at iteration granularity (no request
        ever waits for a co-batched stranger's generation to finish).
        Payloads are 1-D prompt id arrays, or ``{"prompt": ...,
        "max_new": n}`` for a per-request generation cap.
        ``prefill_token_budget`` bounds the prefill work any single
        iteration interleaves with decode (chunked admission; None =
        the ``-prefill_token_budget`` flag, 0 = monolithic).
        ``kv_block_size``/``kv_pool_blocks`` size the paged KV cache
        (None = the ``-kv_block_size``/``-kv_pool_blocks`` flags;
        block size 0 = contiguous per-slot strips) — with paging, pool
        capacity rather than slot geometry bounds concurrency, and a
        submit whose ``prompt + max_new`` can never fit the pool sheds
        with :class:`OverloadedError` (docs/SERVING.md "Paged KV
        cache"). ``decode_tp`` (None = the ``-decode_tp`` flag, default
        1) sets the tensor-parallel width of the decode mesh: heads/MLP
        shards + head-sharded K/V pools over the first ``decode_tp``
        devices, params resharded once per snapshot pin, per-token
        programs compiled once against matched shardings — the knob
        that serves models bigger than one device (docs/SERVING.md
        "Sharded decode"; 1 = the replicated single-device path).
        ``prefix_cache`` (None = the ``-prefix_cache`` flag,
        default on) turns on content-addressed block reuse over that
        pool: prompts sharing a prefix prefill it once and splice the
        cached blocks refcounted/copy-on-write (docs/SERVING.md
        "Prefix caching"). ``prefill_sp`` (None = the ``-prefill_sp``
        flag, default off; paged + chunked, sharded or single-device)
        turns on sequence-parallel long-prompt prefill: prompts of at
        least ``prefill_sp_threshold`` tokens prefill in
        ``prefill_token_budget * decode_tp`` token chunks whose rows
        shard over the decode mesh via ``prefill_sp_backend`` ("ring"
        ppermute rotations or "ulysses" all_to_all head resharding) —
        a long document admits in ``decode_tp`` x fewer iterations
        while each device still runs one budget of rows per iteration,
        and shorter prompts keep the single-lane chunk program
        bit-for-bit (docs/SERVING.md "Long-context prefill").
        ``spec_k`` (None = the ``-spec_k`` flag,
        default 0 = off) turns on speculative decoding: up to
        ``spec_k`` n-gram prompt-lookup drafts per live slot, verified
        by one fused fixed-K step per iteration — up to ``spec_k + 1``
        tokens per iteration, outputs token-identical to plain greedy
        decode (docs/SERVING.md "Speculative decoding"; needs the
        paged KV cache). ``kv_quant`` (None = the ``-kv_quant`` flag,
        default "none") stores the paged K/V pools as int8 with
        per-(layer, block) fp32 scales — ~4x the KV capacity at equal
        pool bytes, lossy (the bench archives the argmax-match rate);
        "none" keeps today's fp pools bit-for-bit.
        ``decode_param_quant`` (None = the ``-decode_param_quant``
        flag, default "none") pins int8-quantized decode param
        snapshots and folds the dequant into the compiled programs —
        ~4x smaller pin copies (docs/SERVING.md "Quantized KV &
        params"). ``preempt`` (None = the ``-preempt`` flag,
        default on; paged + chunked only) switches paged admission to
        OPTIMISTIC prompt-only reservation with grow-at-decode and
        preemption-with-recompute under pool pressure —
        ``preempt_budget`` bounds how often one request may be
        preempted and ``sched_lookahead`` bounds admission lookahead
        past a block-starved queue head (docs/SERVING.md "Overload
        and preemption"; ``preempt=False`` restores the worst-case
        ``prompt + max_new`` up-front reservation).

        The black-box layer rides along by default: an always-on
        flight recorder (``engine.recorder``) and a stall/leak/queue-age
        watchdog (``watchdog``/``debug_dump_dir`` override the
        ``-watchdog``/``-debug_dump_dir`` flags); ``slo_ttft_ms``/
        ``slo_itl_ms`` register rolling-window p99 SLOs whose burn
        status rides every ``Dashboard.snapshot()``
        (docs/OBSERVABILITY.md "Flight recorder" / "Watchdog").
        ``cost_ledger`` (None = the ``-cost_ledger`` flag, default
        off) attaches a host-only per-tenant :class:`CostLedger`:
        every request accumulates a resource vector (queue wait,
        prefill/decode tokens, KV block-seconds, device step ms,
        transfer bytes, recompute) attributed to its ``tenant``
        payload key and folded into bounded-cardinality per-tenant
        aggregates and cost units at completion
        (docs/OBSERVABILITY.md "Tenant accounting").
        """
        cfg = DecodeEngineConfig(
            slots=slots, max_prompt=max_prompt, max_new=max_new,
            eos_id=eos_id, max_queue=max_queue,
            max_staleness_s=max_staleness_s, prompt_buckets=prompt_buckets,
            prefill_token_budget=prefill_token_budget,
            kv_block_size=kv_block_size, kv_pool_blocks=kv_pool_blocks,
            decode_tp=decode_tp, prefix_cache=prefix_cache,
            prefill_sp=prefill_sp,
            prefill_sp_backend=prefill_sp_backend,
            prefill_sp_threshold=prefill_sp_threshold,
            spec_k=spec_k, kv_quant=kv_quant,
            decode_param_quant=decode_param_quant,
            preempt=preempt, preempt_budget=preempt_budget,
            sched_lookahead=sched_lookahead,
            watchdog=watchdog, debug_dump_dir=debug_dump_dir,
            slo_ttft_ms=slo_ttft_ms, slo_itl_ms=slo_itl_ms,
            cost_ledger=cost_ledger)
        with self._lock:
            if self._stopped:
                Log.fatal(f"serving: register_decoder({name!r}) on a "
                          f"stopped server")
            if name in self._models:
                Log.fatal(f"serving: model {name!r} already registered")
        # engine construction dispatches the params replica copy and the
        # warmup compiles — seconds of work that must happen OUTSIDE the
        # registry lock, or every submit() to every OTHER model wedges
        # behind it (locklint LK203; tests/test_serving.py covers it)
        entry = _DecoderEntry(name, DecodeEngine(name, lm, cfg))
        with self._lock:
            # re-check BOTH races lost during construction: a duplicate
            # registration, and a stop() whose entries snapshot predates
            # this entry (the engine's loop thread would outlive the
            # server, reading tables Session teardown is flushing)
            raced = name in self._models
            stopped = self._stopped
            if not raced and not stopped:
                self._models[name] = entry
        if raced or stopped:
            entry.engine.stop()           # join happens OUTSIDE the lock
            Log.fatal(f"serving: model {name!r} already registered" if raced
                      else f"serving: server stopped during decoder "
                           f"{name!r} registration")
        Log.info("serving: decoder %r up (%d slots, max_prompt %d, "
                 "max_new %d)", name, slots, max_prompt, max_new)
        return entry.engine

    def _entry(self, name: str) -> _ModelEntry:
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            Log.fatal(f"serving: unknown model {name!r} "
                      f"(registered: {sorted(self._models)})")
        return entry

    # -- request path -------------------------------------------------------
    def submit(self, model: str, payload: Any) -> Future:
        """Enqueue one request; raises :class:`OverloadedError` at the
        queue-depth cap and ``ValueError`` for a malformed payload (the
        workload's submit-time ``validate`` — a bad request must reject
        HERE, not poison every co-batched request at flush). The future
        resolves to a reply dict:
        ``{"result", "snapshot_version", "staleness_s"}``.

        When tracing is on (``trace.enable()`` / ``-trace``), each
        request gets a ROOT span ``serve.request`` covering
        submit -> reply; its handoff token rides the queue entry so the
        batcher/engine threads attach queue-wait, admission and decode
        child spans to the same trace id (docs/OBSERVABILITY.md)."""
        entry = self._entry(model)
        root = trace.start_span("serve.request", root=True, model=model)
        try:
            if isinstance(entry, _DecoderEntry):
                fut = entry.submit(payload, ctx=root.context)
            else:
                validate = getattr(entry.workload, "validate", None)
                if validate is not None:
                    validate(payload)
                fut = entry.batcher.submit(payload, ctx=root.context)
        except Exception as exc:
            # shed / validation reject: the root span still closes, so
            # rejected requests are visible in the trace with the reason
            root.end(error=type(exc).__name__)
            raise
        if root is not trace.NULL_SPAN:
            fut.add_done_callback(lambda f, sp=root: sp.end(
                ok=(not f.cancelled()) and f.exception() is None))
        return fut

    def predict(self, model: str, payload: Any,
                timeout_s: float = 30.0) -> dict:
        """Blocking request -> reply dict."""
        return self.submit(model, payload).result(timeout=timeout_s)

    # -- introspection ------------------------------------------------------
    def stats(self, model: str) -> dict:
        entry = self._entry(model)
        if isinstance(entry, _DecoderEntry):
            return entry.engine.stats()
        return {**entry.batcher.stats(),
                "snapshot_publishes": entry.manager.publishes,
                "queue_depth": entry.batcher.queue_depth()}

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    # -- lifecycle ----------------------------------------------------------
    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            entries = list(self._models.values())
        for entry in entries:
            if isinstance(entry, _DecoderEntry):
                entry.engine.stop()
            else:
                entry.batcher.stop()
