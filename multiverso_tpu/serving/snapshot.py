"""Versioned copy-on-publish read views over live parameter state.

The serving read path must satisfy two properties the raw table view
cannot:

* **no torn reads** — training ``Add``s donate the table's device buffer,
  so a reply computed against ``table.array`` can observe state from two
  different versions (or a donated-away buffer). A snapshot is ONE
  ``jnp.copy`` dispatched under the table lock
  (:meth:`tables.base.TableBase.snapshot_array`), so every element of a
  reply comes from the same version by device-stream ordering.
* **bounded staleness, surfaced** — the reference Multiverso serves reads
  from whatever the server shard holds (async contract); here each reply
  carries the snapshot's version and its age, and the batcher refreshes
  the snapshot whenever training moved AND the published copy is older
  than ``max_staleness_s``.

Copy-on-PUBLISH, not copy-on-read: with training idle (version
unchanged) the same device buffer serves indefinitely — zero copies on
the reply hot path.
"""

from __future__ import annotations

import threading
from ..analysis import lockwatch
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from ..log import Log


@dataclass(frozen=True)
class Snapshot:
    """Immutable published view: a device pytree + its source version
    (and, for fenced sources, the trainer incarnation epoch the state
    derives from — pins carry (epoch, version) together so a serving
    reply can be joined to the exact fenced publish that produced it)."""

    value: Any
    version: int
    published_at: float
    epoch: int = 0


class DerivedCache:
    """Per-snapshot-version derived artifact.

    Copy-on-publish makes ``Snapshot.version`` a safe cache key: compute
    ``fn(snap.value)`` once per publish, reuse it for every read until
    training moves the source. One implementation for every workload
    that derives from a snapshot (normalized embedding matrices,
    replicated decode params, ...).
    """

    def __init__(self, fn: Callable[[Any], Any]) -> None:
        self._fn = fn
        self._cached: Tuple[int, Any] = (-1, None)
        # the check-then-compute must be atomic: two readers racing a
        # publish could BOTH miss and recompute fn(snap.value) — a
        # doubled derived-artifact cost (replica copy, normalized
        # matrix) exactly at the publish spike. Serializing get() is
        # the point: one thread computes, the rest wait and reuse.
        self._lock = lockwatch.lock("serving.DerivedCache._lock")

    def get(self, snap: Snapshot) -> Any:
        with self._lock:
            ver, value = self._cached
            if ver != snap.version:
                value = self._fn(snap.value)
                self._cached = (snap.version, value)
            return value


def replicate_for_decode(value: Any) -> Any:
    """Single-device replica of a params/table pytree for decode serving.

    Per-token decode programs are tiny; feeding them the train mesh's
    ``NamedSharding``-carrying snapshot drags every call through the
    spmd partitioner (measured ~10x per-step wall on the CPU harness).
    Only safe single-process — in a multi-process mesh ``devices()[0]``
    may not be addressable from this host (and the model may not fit one
    device), so the sharded snapshot is served directly there.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(value, jax.devices()[0])
    return value


def shard_for_decode(value: Any, decode_mesh, shardings: Optional[Any]
                     = None) -> Any:
    """Reshard a train-mesh params snapshot onto the decode mesh.

    The tensor-parallel analogue of :func:`replicate_for_decode`
    (``decode_tp > 1``): one resharding ``device_put`` per snapshot PIN,
    amortized over the whole generation stream the pin serves. The
    resulting pytree carries exactly the ``NamedSharding``s the engine's
    pre-partitioned programs were compiled against, so per-token
    dispatches never go back through the spmd partitioner — the ~10x
    step wall :func:`replicate_for_decode` was dodging, removed instead
    of avoided (docs/SERVING.md "Sharded decode").

    ``shardings`` is the decode-mesh ``NamedSharding`` pytree matching
    ``value``; ``None`` derives the transformer serving layout
    (:func:`models.transformer.decode_param_shardings`) from
    ``decode_mesh``.
    """
    import jax

    if shardings is None:
        from ..models.transformer import decode_param_shardings

        shardings = decode_param_shardings(decode_mesh)
    return jax.device_put(value, shardings)


def quantize_decode_params(value: Any) -> Any:
    """int8 symmetric snapshot of a params pytree for decode pinning.

    Every leaf becomes ``{"q": int8, "s": fp32 scale}`` — per-COLUMN
    (reduce the input axis, keepdims) for matrices so the Megatron-split
    weights keep per-output-channel resolution, per-tensor for vectors.
    Runs in HOST numpy, deliberately: the pin path
    (``DecodeEngine._maybe_refresh``) is reachable from the engine loop,
    where constructing a jit would be an RT106 hazard — and the quant
    runs ONCE per pinned snapshot version (``pin_copies`` memoization),
    so host arithmetic is off the per-token path entirely. The pinned
    pytree then rides :func:`replicate_for_decode` /
    :func:`shard_for_decode` as ~4x fewer bytes per device_put, and the
    decode programs fold
    :func:`models.transformer.dequantize_decode_params` in at compile
    time."""
    import jax
    import numpy as np

    from ..quantization import quantize_int8

    def quant(leaf) -> Any:
        host = np.asarray(leaf)
        q, s = quantize_int8(host, axis=-2 if host.ndim >= 2 else None)
        return {"q": q, "s": s}

    return jax.tree.map(quant, value)


class SnapshotManager:
    """Publishes/refreshes snapshots of one source (table or model).

    ``read`` returns ``(device pytree copy, version)`` atomically w.r.t.
    the source's mutation lock; ``version_fn`` probes the current version
    without copying (the cheap "did training move?" check).
    """

    def __init__(self, read: Callable[[], Tuple[Any, int]],
                 version_fn: Callable[[], int], name: str = "snapshot",
                 epoch_fn: Optional[Callable[[], int]] = None):
        self._read = read
        self._version_fn = version_fn
        self._epoch_fn = epoch_fn or (lambda: 0)
        self.name = name
        self._lock = lockwatch.lock("serving.SnapshotManager._lock")
        self._snap: Optional[Snapshot] = None
        self.publishes = 0      # copies actually taken (copy-on-publish)
        # params-age tracking (staleness-aware serving): when the
        # source version last MOVED, as observed by any probe through
        # this manager. A silent publish stream shows up as a growing
        # age; health surfaces flag STALE past -params_stale_after_s
        # while replies keep flowing from the frozen snapshot.
        self._seen_version = self._version_fn()
        self._last_move = time.monotonic()

    @classmethod
    def of(cls, source: Any, name: Optional[str] = None) -> "SnapshotManager":
        """Build from anything exposing the snapshot contract: a table
        (``snapshot_array``), a ``TransformerLM`` (``snapshot_params``),
        or a ``(read, version_fn)`` pair."""
        label = name or getattr(source, "name", type(source).__name__)
        epoch_fn = (lambda: int(getattr(source, "epoch", 0)))
        if hasattr(source, "snapshot_array"):
            return cls(source.snapshot_array,
                       lambda: source.version, label, epoch_fn=epoch_fn)
        if hasattr(source, "snapshot_params"):
            return cls(source.snapshot_params,
                       lambda: source.version, label, epoch_fn=epoch_fn)
        if isinstance(source, tuple) and len(source) == 2:
            return cls(source[0], source[1], label)
        Log.fatal(f"SnapshotManager: {type(source).__name__} exposes "
                  "neither snapshot_array nor snapshot_params")

    def publish(self) -> Snapshot:
        """Force a fresh copy (the copy-on-publish event)."""
        with self._lock:
            value, version = self._read()
            self._snap = Snapshot(value, version, time.monotonic(),
                                  epoch=self._epoch_fn())
            self._note_version_locked(version)
            self.publishes += 1
            return self._snap

    def current(self) -> Snapshot:
        with self._lock:
            snap = self._snap
        return snap if snap is not None else self.publish()

    def ensure_fresh(self, max_staleness_s: float) -> Snapshot:
        """The batcher's per-flush gate: republish iff training moved the
        source AND the published copy is older than the bound. Replies
        built from the returned snapshot therefore carry
        ``staleness_s(snap) <= max_staleness_s``."""
        snap = self.current()
        if snap.version != self._version_fn():
            if time.monotonic() - snap.published_at > max_staleness_s:
                return self.publish()
        return snap

    def staleness_s(self, snap: Snapshot) -> float:
        """Reply-visible staleness: 0 while the snapshot IS the live state
        (version unchanged), else the copy's age."""
        if snap.version == self._version_fn():
            return 0.0
        return time.monotonic() - snap.published_at

    # -- params-staleness watchdog surface --------------------------------
    def _note_version_locked(self, version: int) -> None:
        if version != self._seen_version:
            self._seen_version = version
            self._last_move = time.monotonic()

    def params_age_s(self) -> float:
        """Seconds since the SOURCE version last moved (as observed):
        the publish-stream-went-silent signal. Zero while training is
        flowing; grows without bound when the trainer dies; snaps back
        when a fenced restart republishes. Cheap — one version probe
        (taken OUTSIDE the manager lock; it is caller-supplied code,
        LK202)."""
        version = self._version_fn()
        with self._lock:
            self._note_version_locked(version)
            return time.monotonic() - self._last_move

    def params_stale(self, stale_after_s: float,
                     age_s: Optional[float] = None) -> bool:
        """The serving degradation verdict: the source has been frozen
        past the threshold. ``stale_after_s <= 0`` disables it (a
        never-trained static model must not read as degraded).
        ``age_s`` lets a caller that already probed
        :meth:`params_age_s` reuse the sample — one verdict rule, one
        implementation."""
        if age_s is None:
            age_s = self.params_age_s()
        return stale_after_s > 0 and age_s > stale_after_s
