"""KV-block transfer plane for disaggregated prefill/decode serving.

A disaggregated fleet splits the two phases of generation onto
specialized replicas (DistServe / Splitwise): throughput-bound PREFILL
replicas chunk-prefill prompts into paged KV blocks, latency-bound
DECODE replicas run the per-token steps. What crosses between them is
not tokens but *cache state*: the finished KV blocks of the prompt,
shipped over the existing ``mvserve`` wire and spliced into the decode
replica's block pool so admission lands on the PR 8 full-hit path
(lookup -> CoW on the last block -> live at position P-1) and emits
tokens bit-identical to unified serving.

This module is the wire format and the byte accounting — deliberately
small and engine-free, so both ends (and the router, which carries the
payload between stages) agree on one schema:

* **one payload per prefilled prompt** (:func:`new_payload`): header
  (``prompt_len``, ``block_size``, ``snapshot_version``, the per-block
  ``shape``/``dtype``) + the prompt's full-block **chain hashes in
  chain order** + a sparse ``blocks`` map of the hashes whose K/V bytes
  actually ride the wire. Only FULL blocks transfer — a trailing
  partial block has no chain identity (block_pool.chain_hashes) and the
  decode side re-prefills the tail locally, which is also what makes a
  lost transfer a performance event rather than a correctness event.
* **dedup at the source** (:func:`add_block` with ``k=None``): a hash
  the decode side already advertised (router-tracked shipped set +
  heartbeat ``cached_chains``) rides as metadata only — the hash holds
  its place in the chain so arrival-side splicing can still claim the
  warm prefix, but zero K/V bytes move. ``dedup_blocks`` counts them.
* **dedup on arrival**: the decode engine checks its pool's content
  index per hash before splicing; a block that landed since the
  advertisement is skipped there too. Both ends count into the same
  ``KV_XFER_DEDUP`` ledger.

Transfer-unit math: one block costs
``2 * n_layers * block_size * d_model * itemsize`` bytes across both
pools (:func:`block_nbytes` — the same arithmetic as
``block_pool.kv_bytes_per_block``, restated over the payload's shape
tuple so the wire accounting cannot drift from the device accounting).
Bytes are base64 in the JSON record (the ``mvserve`` wire is one JSON
object per transport record); ``payload_bytes`` reports the RAW K/V
bytes moved, which is what ``kv_bytes_moved`` gates on — encoding
overhead is a wire detail, not a capacity number.

Versioning: ``snapshot_version`` scopes the chain hashes (cached K/V
bytes are a function of (token prefix, params version) — the engine
seeds its hash chain with the pinned snapshot version). A payload whose
version disagrees with the receiver's pinned snapshot is dropped whole
at splice time: splicing stale-params KV would poison the receiver's
content index. Correctness survives because stage-2 dispatch always
carries the full prompt — the decode side re-prefills whatever the
splice did not provide (docs/SERVING.md "Disaggregated prefill/decode").
"""

from __future__ import annotations

import base64
from typing import Any, Dict, List, Optional, Sequence, Set

import numpy as np

#: payload schema version; a receiver rejects (skips) other versions
WIRE_VERSION = 1


def block_nbytes(shape: Sequence[int], dtype) -> int:
    """Raw bytes ONE block moves across both pools (K and V) given the
    payload's per-block ``shape`` — ``(n_layers, block_size, d_model)``
    as the engine fetches it. An int8 (quantized-source) payload also
    carries each pool's per-layer fp32 scales, counted here for the
    same reason ``block_pool.kv_bytes_per_block`` counts them: the wire
    accounting must not flatter quantization by forgetting its scales."""
    n = 1
    for d in shape:
        n *= int(d)
    raw = 2 * n * np.dtype(dtype).itemsize
    if np.dtype(dtype) == np.dtype(np.int8):
        raw += 2 * int(shape[0]) * 4          # [L] fp32 scales per pool
    return raw


def pack_block(k: np.ndarray, v: np.ndarray,
               k_scale: Optional[np.ndarray] = None,
               v_scale: Optional[np.ndarray] = None) -> Dict[str, str]:
    """One block's K/V slices as a JSON-safe record: base64 of the raw
    C-order bytes. Shape/dtype ride ONCE in the payload header — every
    block of a payload shares them by construction. A quantized source
    pool additionally ships each pool's per-layer fp32 scale column
    (``k_scale``/``v_scale`` [n_layers]) under ``ks``/``vs`` — the K/V
    bytes themselves stay int8, which is where the ~4x
    ``kv_bytes_moved`` drop comes from."""
    rec = {
        "k": base64.b64encode(
            np.ascontiguousarray(k).tobytes()).decode("ascii"),
        "v": base64.b64encode(
            np.ascontiguousarray(v).tobytes()).decode("ascii"),
    }
    if k_scale is not None:
        rec["ks"] = base64.b64encode(np.ascontiguousarray(
            k_scale, np.float32).tobytes()).decode("ascii")
        rec["vs"] = base64.b64encode(np.ascontiguousarray(
            v_scale, np.float32).tobytes()).decode("ascii")
    return rec


def unpack_block(rec: Dict[str, str], shape: Sequence[int], dtype):
    """Inverse of :func:`pack_block` -> ``(k, v)`` ndarrays shaped per
    the payload header. Raises ``ValueError`` when the byte count does
    not factor into the declared shape (a truncated/corrupt record must
    fail loudly, not splice garbage)."""
    shape = tuple(int(d) for d in shape)
    k = np.frombuffer(base64.b64decode(rec["k"]), dtype=dtype)
    v = np.frombuffer(base64.b64decode(rec["v"]), dtype=dtype)
    want = 1
    for d in shape:
        want *= d
    if k.size != want or v.size != want:
        raise ValueError(
            f"kv_transfer: block record has {k.size}/{v.size} elems, "
            f"shape {shape} wants {want}")
    return k.reshape(shape), v.reshape(shape)


def new_payload(prompt_len: int, block_size: int, snapshot_version: int,
                shape: Sequence[int], dtype) -> Dict[str, Any]:
    """Empty transfer payload (header only); fill with :func:`add_block`
    in chain order."""
    return {
        "v": WIRE_VERSION,
        "prompt_len": int(prompt_len),
        "block_size": int(block_size),
        "snapshot_version": int(snapshot_version),
        "shape": [int(d) for d in shape],
        "dtype": np.dtype(dtype).name,
        "hashes": [],           # every full block's chain hash, in order
        "blocks": {},           # hex hash -> pack_block record (shipped)
        "dedup_blocks": 0,      # source-side skips (receiver had them)
        "dropped": False,       # chaos kv_xfer_drop stripped the bytes
    }


def add_block(payload: Dict[str, Any], hex_hash: str,
              k: Optional[np.ndarray] = None,
              v: Optional[np.ndarray] = None,
              k_scale: Optional[np.ndarray] = None,
              v_scale: Optional[np.ndarray] = None) -> None:
    """Append one full block to the chain. ``k``/``v`` given = ship the
    bytes; ``k=None`` = source-side dedup (the receiver advertised this
    chain prefix) — the hash still holds its chain position so
    arrival-side splicing can claim the warm prefix past it. A
    quantized source passes its per-layer scale columns too
    (:func:`pack_block`)."""
    payload["hashes"].append(hex_hash)
    if k is None:
        payload["dedup_blocks"] += 1
    else:
        payload["blocks"][hex_hash] = pack_block(k, v, k_scale, v_scale)


def unpack_scales(rec: Dict[str, str], n_layers: int):
    """The quantized record's per-layer fp32 scale columns ->
    ``(k_scale, v_scale)`` each ``[n_layers]``, or ``None`` when the
    record shipped unquantized. Size-checked for the same reason
    :func:`unpack_block` is: a truncated scale blob must fail loudly."""
    if "ks" not in rec:
        return None
    ks = np.frombuffer(base64.b64decode(rec["ks"]), dtype=np.float32)
    vs = np.frombuffer(base64.b64decode(rec["vs"]), dtype=np.float32)
    if ks.size != int(n_layers) or vs.size != int(n_layers):
        raise ValueError(
            f"kv_transfer: scale record has {ks.size}/{vs.size} entries, "
            f"expected {int(n_layers)}")
    return ks, vs


def payload_bytes(payload: Dict[str, Any]) -> int:
    """RAW K/V bytes this payload moves (shipped blocks only — dedup'd
    hashes are metadata). The ``kv_bytes_moved`` unit of account."""
    return len(payload.get("blocks") or {}) * block_nbytes(
        payload["shape"], payload["dtype"])


def shipped_hashes(payload: Dict[str, Any]) -> Set[str]:
    """Hex hashes whose bytes ride this payload (the router folds these
    into its per-decode-replica shipped set for future source dedup)."""
    return set(payload.get("blocks") or {})


def drop_blocks(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Chaos ``kv_xfer_drop``: strip every shipped block mid-flight,
    keeping the header + hashes (the metadata that makes the loss
    OBSERVABLE). The receiver splices nothing new and re-prefills — a
    dropped transfer must cost latency, never correctness."""
    payload = dict(payload)
    payload["blocks"] = {}
    payload["dropped"] = True
    return payload


def validate(payload: Dict[str, Any]) -> Optional[str]:
    """Schema check -> reason string, or None when the payload is
    well-formed. The splice path skips (never raises on) a bad payload:
    the full prompt is in the stage-2 request, so degrading to a local
    re-prefill is always available."""
    if not isinstance(payload, dict):
        return "payload is not a dict"
    if payload.get("v") != WIRE_VERSION:
        return f"wire version {payload.get('v')!r} != {WIRE_VERSION}"
    for key in ("prompt_len", "block_size", "snapshot_version",
                "shape", "dtype", "hashes"):
        if key not in payload:
            return f"missing {key!r}"
    if len(payload["shape"]) != 3:
        return f"shape {payload['shape']!r} is not (L, block, D)"
    blocks = payload.get("blocks") or {}
    stray = set(blocks) - set(payload["hashes"])
    if stray:
        return f"{len(stray)} shipped block(s) not in the hash chain"
    return None
