"""Per-tenant cost attribution: the request-level resource ledger.

The metering substrate under quotas/showback (docs/OBSERVABILITY.md
"Tenant accounting"): every request carries a :class:`ResourceUsage`
vector that the engine fills in at its EXISTING instrumentation points
— queue wait, prefill tokens computed vs saved by the prefix cache,
decode tokens (speculative acceptances included), KV-block-seconds
(reserved blocks x wall, integrated per iteration), device step
milliseconds attributed by active-lane share, KV transfer bytes, and
preemption recompute tokens — and :meth:`CostLedger.finalize` folds at
completion into per-tenant rolling aggregates.

Design constraints (both load-bearing, both tested):

* **Pure host state.** The ledger is dicts and floats on the engine
  loop thread — no jax import, no jit, nothing traceable. Attaching it
  cannot add a compiled trace (``step_traces`` stays 1, retraces 0);
  the retrace-lint FP fixture sanctions exactly this shape, and the TP
  fixture shows the one way to get it wrong (a jitted "cost reducer"
  called from the iteration path fires RT106).
* **Exact.** Every integer field increments at the IDENTICAL code
  site as the engine's own global mirror, attributed through
  ``req.usage`` — so the conservation identity holds to the token:
  sum over tenants of prefill/decode/xfer equals the engine's
  ``prefill_tokens``/``tokens``/``xfer_bytes`` exactly, whatever the
  churn (preemption-with-recompute, speculative windows, full-hit
  admissions, deadline drops, engine failure). ``drift()`` computes
  the residual; the bench gates it at zero (``accounting_drift``).

Cardinality is bounded the ``SHED_BY_CLASS[name.pN]`` way: per-tenant
Dashboard instruments (``TENANT_*[engine.tenant]``) are created lazily
on first use, and once ``-tenant_max`` distinct tenants exist, every
new tenant id folds into the :data:`OVERFLOW_TENANT` bucket — a hostile
or buggy client cannot balloon the metrics surface. The monotonic
counters ride obs-plane reports unchanged (``ObsCollector.tenant_rows``
merges them fleet-wide); the resettable aggregates back ``stats()`` and
``reset_stats()`` like every other engine mirror.

The cost model is a configurable linear fold of the vector
(``-cost_token``, ``-cost_token_ms``, ``-cost_block_byte_s``,
``-cost_xfer_byte``): with the defaults, one cost unit == one token,
so cost is deterministic and reconcilable; weights let a deployment
price device time and KV residency instead.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..analysis import lockwatch
from ..dashboard import Dashboard

# the fold bucket for tenant ids past the -tenant_max cardinality cap:
# "~" sorts after every sane tenant id and cannot collide with one (ids
# are stripped; the engine never invents it for a real tenant)
OVERFLOW_TENANT = "~other"

# terminal outcomes finalize() accepts (anything else raises — an
# unknown outcome is an attribution bug, not a new category)
OUTCOMES = ("completed", "shed", "deadline", "failed")


class ResourceUsage:
    """One request's resource vector (host-only, engine-thread-owned).

    Integer fields mirror engine counters 1:1 (the conservation
    identity); float fields are wall-clock attributions. ``t_wait0``
    is the open queue-wait clock base — set at submit, re-armed at
    preemption requeue, closed into ``queue_wait_ms`` at admission."""

    __slots__ = ("tenant", "queue_wait_ms", "prefill_tokens",
                 "prefill_tokens_saved", "decode_tokens", "kv_block_s",
                 "device_step_ms", "xfer_bytes", "recompute_tokens",
                 "preemptions", "t_wait0")

    def __init__(self, tenant: str) -> None:
        self.tenant = tenant
        self.queue_wait_ms = 0.0
        self.prefill_tokens = 0
        self.prefill_tokens_saved = 0
        self.decode_tokens = 0
        self.kv_block_s = 0.0
        self.device_step_ms = 0.0
        self.xfer_bytes = 0
        self.recompute_tokens = 0
        self.preemptions = 0
        self.t_wait0 = time.monotonic()

    def vector(self) -> Dict[str, Any]:
        """The schema'd dict form (trace spans, tests, docs)."""
        return {"tenant": self.tenant,
                "queue_wait_ms": self.queue_wait_ms,
                "prefill_tokens": self.prefill_tokens,
                "prefill_tokens_saved": self.prefill_tokens_saved,
                "decode_tokens": self.decode_tokens,
                "kv_block_s": self.kv_block_s,
                "device_step_ms": self.device_step_ms,
                "xfer_bytes": self.xfer_bytes,
                "recompute_tokens": self.recompute_tokens,
                "preemptions": self.preemptions}


class _TenantAgg:
    """One tenant's resettable rolling aggregate (the stats() mirror —
    the monotonic ``TENANT_*`` Dashboard counters are the obs-plane
    twin, folded at the same finalize)."""

    __slots__ = ("requests", "completed", "shed", "deadline", "failed",
                 "queue_wait_ms", "prefill_tokens",
                 "prefill_tokens_saved", "decode_tokens", "kv_block_s",
                 "device_step_ms", "xfer_bytes", "recompute_tokens",
                 "preemptions", "cost")

    def __init__(self) -> None:
        self.requests = 0
        self.completed = 0
        self.shed = 0
        self.deadline = 0
        self.failed = 0
        self.queue_wait_ms = 0.0
        self.prefill_tokens = 0
        self.prefill_tokens_saved = 0
        self.decode_tokens = 0
        self.kv_block_s = 0.0
        self.device_step_ms = 0.0
        self.xfer_bytes = 0
        self.recompute_tokens = 0
        self.preemptions = 0
        self.cost = 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {s: getattr(self, s) for s in self.__slots__}


class CostLedger:
    """Per-engine tenant accounting (host state only — see module doc).

    The engine owns one when ``-cost_ledger`` is on and calls in from
    its existing instrumentation sites; everything here is dict/float
    arithmetic cheap enough for the iteration path. Thread-safety:
    attribution happens on the engine loop thread; ``finalize``/
    ``charge``/``reset``/readers take the ledger lock (submit-time
    sheds and stats() readers run on client threads)."""

    def __init__(self, engine: str, *, block_bytes: int = 0,
                 default_tenant: Optional[str] = None,
                 max_tenants: Optional[int] = None,
                 weights: Optional[Dict[str, float]] = None,
                 slo_lat_ms: Optional[float] = None) -> None:
        from .. import config
        self.engine = engine
        # per-block K/V bytes (paged engines): what turns kv_block_s
        # into byte-seconds under the -cost_block_byte_s weight
        self.block_bytes = int(block_bytes)
        self.default_tenant = str(
            default_tenant if default_tenant is not None
            else config.get_flag("default_tenant")) or "default"
        self.max_tenants = int(
            max_tenants if max_tenants is not None
            else config.get_flag("tenant_max"))
        if self.max_tenants < 1:
            raise ValueError(f"tenant_max must be >= 1, "
                             f"got {self.max_tenants}")
        w = dict(weights) if weights is not None else {
            "cost_token": float(config.get_flag("cost_token")),
            "cost_token_ms": float(config.get_flag("cost_token_ms")),
            "cost_block_byte_s": float(
                config.get_flag("cost_block_byte_s")),
            "cost_xfer_byte": float(config.get_flag("cost_xfer_byte"))}
        self.weights = w
        self._lock = lockwatch.lock("serving.CostLedger._lock")
        self._agg: Dict[str, _TenantAgg] = {}
        # lazy keyed Dashboard instruments, one bundle per tenant
        # (bounded by max_tenants + the overflow bucket)
        self._instruments: Dict[str, Dict[str, Any]] = {}
        # the global twin of the per-tenant sums: folded ONLY at
        # finalize()/charge() — the same calls, the same amounts — so
        # sum-over-tenants == totals holds by construction (float
        # fields included)
        self.totals = _TenantAgg()
        # the per-request latency SLO the fleet tenant table breaches
        # against (0 = none); published as a gauge so tenant_rows()
        # finds it next to the TENANT_LAT_MS buckets it merges
        slo = float(slo_lat_ms if slo_lat_ms is not None
                    else config.get_flag("slo_lat_ms"))
        self.slo_lat_ms = slo
        if slo > 0:
            Dashboard.get_or_create_gauge(
                f"TENANT_SLO_MS[{engine}]").set(slo)

    # -- attribution (engine instrumentation sites) -------------------------
    def usage(self, tenant: Optional[str]) -> ResourceUsage:
        """A fresh per-request vector for ``tenant`` (None/empty ->
        the default tenant). Cardinality folds happen here, once, so
        every later touch of the vector is a plain attribute add."""
        return ResourceUsage(self._canon(tenant))

    def _canon(self, tenant: Optional[str]) -> str:
        t = str(tenant).strip() if tenant is not None else ""
        if not t:
            t = self.default_tenant
        with self._lock:
            if t in self._agg or len(self._agg) < self.max_tenants:
                return t
        return OVERFLOW_TENANT

    def charge_iteration(self, reqs: List[Any], dt_s: float) -> None:
        """Integrate KV residency over one engine iteration: each
        admitted request is charged ``len(req.blocks) * dt_s``
        block-seconds (``reqs`` are engine ``_Request``s carrying
        ``usage``/``blocks``). Loop thread only; no lock — the per-
        request vectors are loop-thread-owned until finalize."""
        if dt_s <= 0.0:
            return
        for req in reqs:
            u = req.usage
            if u is not None and req.blocks:
                u.kv_block_s += len(req.blocks) * dt_s

    def charge_step(self, reqs: List[Any], step_ms: float) -> None:
        """Attribute one fused step's wall clock by active-lane share:
        each live sequence pays ``step_ms / n_live`` device
        milliseconds (the co-batching cost model — a lane consumed the
        step whether it accepted one token or a speculative window)."""
        live = [r.usage for r in reqs if r.usage is not None]
        if not live or step_ms <= 0.0:
            return
        share = step_ms / len(live)
        for u in live:
            u.device_step_ms += share

    def charge(self, tenant: Optional[str], *, xfer_bytes: int = 0) -> None:
        """Direct tenant charge for resources not tied to a live
        request (today: splice-side KV transfer bytes — a payload
        arrives and warms the pool before any submit exists). Lands in
        the aggregate immediately, same amounts as the engine's
        ``xfer_bytes`` mirror site, so conservation holds."""
        if not xfer_bytes:
            return
        with self._lock:
            t = self._canon_locked(tenant)
            agg = self._agg_for(t)
            agg.xfer_bytes += int(xfer_bytes)
            self.totals.xfer_bytes += int(xfer_bytes)
            b = self._bundle(t)
        b["xfer"].inc(int(xfer_bytes))

    # -- finalize -----------------------------------------------------------
    def finalize(self, usage: ResourceUsage, outcome: str,
                 lat_ms: Optional[float] = None) -> float:
        """Fold one finished request's vector into its tenant's
        aggregates (resettable mirror + monotonic Dashboard counters +
        latency histogram) and return its cost units. ``outcome`` is
        one of :data:`OUTCOMES`; ``lat_ms`` (completed requests) feeds
        the per-tenant latency buckets the fleet SLO-breach fraction
        reads."""
        if outcome not in OUTCOMES:
            raise ValueError(f"unknown outcome {outcome!r}")
        cost = self.cost_of(usage)
        with self._lock:
            tenant = usage.tenant
            if tenant not in self._agg \
                    and len(self._agg) >= self.max_tenants:
                # late fold: the tenant was canonical at submit but the
                # table filled while this request ran
                tenant = OVERFLOW_TENANT
            agg = self._agg_for(tenant)
            agg.requests += 1
            setattr(agg, outcome, getattr(agg, outcome) + 1)
            agg.queue_wait_ms += usage.queue_wait_ms
            agg.prefill_tokens += usage.prefill_tokens
            agg.prefill_tokens_saved += usage.prefill_tokens_saved
            agg.decode_tokens += usage.decode_tokens
            agg.kv_block_s += usage.kv_block_s
            agg.device_step_ms += usage.device_step_ms
            agg.xfer_bytes += usage.xfer_bytes
            agg.recompute_tokens += usage.recompute_tokens
            agg.preemptions += usage.preemptions
            agg.cost += cost
            t = self.totals
            t.requests += 1
            setattr(t, outcome, getattr(t, outcome) + 1)
            t.queue_wait_ms += usage.queue_wait_ms
            t.prefill_tokens += usage.prefill_tokens
            t.prefill_tokens_saved += usage.prefill_tokens_saved
            t.decode_tokens += usage.decode_tokens
            t.kv_block_s += usage.kv_block_s
            t.device_step_ms += usage.device_step_ms
            t.xfer_bytes += usage.xfer_bytes
            t.recompute_tokens += usage.recompute_tokens
            t.preemptions += usage.preemptions
            t.cost += cost
            b = self._bundle(tenant)
        # monotonic obs-plane twins OUTSIDE the ledger lock (Dashboard
        # instruments have their own locks; lock-order hygiene)
        b["requests"].inc()
        if usage.prefill_tokens:
            b["prefill"].inc(usage.prefill_tokens)
        if usage.decode_tokens:
            b["decode"].inc(usage.decode_tokens)
        if usage.xfer_bytes:
            b["xfer"].inc(usage.xfer_bytes)
        if usage.kv_block_s:
            b["block_s"].inc(usage.kv_block_s)
        if cost:
            b["cost"].inc(cost)
        if lat_ms is not None:
            b["lat"].record(lat_ms)
        return cost

    def cost_of(self, usage: ResourceUsage) -> float:
        """The linear cost fold (docs/OBSERVABILITY.md "Tenant
        accounting"): tokens, device milliseconds, KV byte-seconds,
        and transfer bytes, each under its ``-cost_*`` weight."""
        w = self.weights
        return (w["cost_token"] * (usage.prefill_tokens
                                   + usage.decode_tokens)
                + w["cost_token_ms"] * usage.device_step_ms
                + w["cost_block_byte_s"] * usage.kv_block_s
                * self.block_bytes
                + w["cost_xfer_byte"] * usage.xfer_bytes)

    # -- internals ----------------------------------------------------------
    def _canon_locked(self, tenant: Optional[str]) -> str:
        t = str(tenant).strip() if tenant is not None else ""
        if not t:
            t = self.default_tenant
        if t in self._agg or len(self._agg) < self.max_tenants:
            return t
        return OVERFLOW_TENANT

    def _agg_for(self, tenant: str) -> _TenantAgg:
        agg = self._agg.get(tenant)
        if agg is None:
            agg = self._agg[tenant] = _TenantAgg()
        return agg

    def _bundle(self, tenant: str) -> Dict[str, Any]:
        """Lazy per-tenant Dashboard instruments (the SHED_BY_CLASS
        pattern): created on a tenant's first finalize, cached, keyed
        ``TENANT_*[engine.tenant]`` so obs-plane reports ship them and
        ``tenant_rows()`` can split the key back apart."""
        b = self._instruments.get(tenant)
        if b is None:
            key = f"{self.engine}.{tenant}"
            b = self._instruments[tenant] = {
                "requests": Dashboard.get_or_create_counter(
                    f"TENANT_REQUESTS[{key}]"),
                "prefill": Dashboard.get_or_create_counter(
                    f"TENANT_PREFILL_TOKENS[{key}]"),
                "decode": Dashboard.get_or_create_counter(
                    f"TENANT_DECODE_TOKENS[{key}]"),
                "xfer": Dashboard.get_or_create_counter(
                    f"TENANT_XFER_BYTES[{key}]"),
                "block_s": Dashboard.get_or_create_counter(
                    f"TENANT_KV_BLOCK_S[{key}]"),
                "cost": Dashboard.get_or_create_counter(
                    f"TENANT_COST[{key}]"),
                "lat": Dashboard.get_or_create_histogram(
                    f"TENANT_LAT_MS[{key}]"),
            }
        return b

    # -- read side ----------------------------------------------------------
    def tenant_count(self) -> int:
        """Live tenant cardinality (cheap: the flight recorder reads
        it every iteration)."""
        with self._lock:
            return len(self._agg)

    def tenants(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant aggregate dicts (the resettable window)."""
        with self._lock:
            return {t: agg.as_dict() for t, agg in self._agg.items()}

    def drift(self, prefill_tokens: int, decode_tokens: int,
              xfer_bytes: int) -> int:
        """The conservation residual against the engine's own mirrors:
        |sum over tenants - engine counter| over the integer fields.
        Zero whenever every consumed token/byte was attributed AND
        finalized (the bench reads it at quiescence; a mid-flight read
        legitimately shows the live requests' unfinalized usage)."""
        with self._lock:
            pf = sum(a.prefill_tokens for a in self._agg.values())
            dc = sum(a.decode_tokens for a in self._agg.values())
            xf = sum(a.xfer_bytes for a in self._agg.values())
        return (abs(pf - int(prefill_tokens))
                + abs(dc - int(decode_tokens))
                + abs(xf - int(xfer_bytes)))

    def heartbeat_rows(self, limit: int = 8) -> Dict[str, float]:
        """Top-``limit`` tenants by cost, for replica heartbeat rows
        (small by construction — the wire stays bounded even at the
        cardinality cap)."""
        with self._lock:
            items = sorted(self._agg.items(),
                           key=lambda kv: -kv[1].cost)[: limit]
            return {t: round(a.cost, 3) for t, a in items}

    def stats(self) -> Dict[str, Any]:
        """The engine ``stats()`` contribution (gated on the ledger,
        so off-ledger engines' stats stay byte-identical)."""
        with self._lock:
            return {"tenants_live": len(self._agg),
                    "tenant_cost_units": round(self.totals.cost, 6),
                    "tenant_requests": self.totals.requests}

    def reset(self) -> None:
        """Zero the resettable window (``reset_stats`` sibling): per-
        tenant aggregates and totals; the monotonic TENANT_* counters
        keep counting (MetricsExporter-rate contract), and latency
        histograms reset like the engine's own."""
        with self._lock:
            self._agg.clear()
            self.totals = _TenantAgg()
            hists = [b["lat"] for b in self._instruments.values()]
        for h in hists:
            h.reset()
