"""Server-side updaters as jitted device steps.

TPU-native equivalent of the reference updater layer
(``include/multiverso/updater/updater.h:113-132``, ``src/updater/updater.cpp``
in the Multiverso reference). There, updaters are pluggable C++ loops
(OpenMP-parallel over the shard) that fold a worker's delta into server
storage. Here each updater is a pure function ``(data, state, delta, option)
-> (data, state)`` jitted by the table layer and executed on the shard's
device — the shard never leaves HBM, and XLA vectorises what OpenMP looped.

Updater semantics (mirroring the reference formulas):

* ``default`` — ``data += delta`` (``src/updater/updater.cpp:15-22``);
  integer tables always use this (``updater.cpp:33-36``).
* ``sgd`` — ``data -= delta``; the caller pre-scales by the learning rate
  (``include/multiverso/updater/sgd_updater.h:9-27``).
* ``adagrad`` — per-worker accumulators ``G[w] += delta**2``;
  ``data -= rho / sqrt(G[w] + eps) * delta / lr``
  (``include/multiverso/updater/adagrad_updater.h:22-40``; the reference's
  accumulate-by-subtraction and copy-instead-of-reference bugs noted in the
  survey are fixed here, keeping the intended formula).
* ``momentum_sgd`` — ``s = m*s + (1-m)*delta; data -= s``
  (``include/multiverso/updater/momentum_updater.h:17-24``).

``AddOption`` / ``GetOption`` mirror ``updater.h:10-110`` with the same
defaults (lr=.01, momentum=0, rho=.1, lambda=.1).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp

from . import config
from .log import Log

_ADAGRAD_EPS = 1e-6


@dataclass
class AddOption:
    """Per-Add hyperparameters (``updater.h:10-70``)."""

    worker_id: int = 0
    learning_rate: float = 0.01
    momentum: float = 0.0
    rho: float = 0.1
    lam: float = 0.1


@dataclass
class GetOption:
    """Per-Get options (``updater.h:72-110``)."""

    worker_id: int = 0


class Updater:
    """Base updater: stateless accumulate (the ``default`` type).

    ``stateless`` + ``sign`` let the table layer use a direct scatter
    fast-path for row/key adds: when ``stateless`` is True the update is
    ``data += sign * delta`` and needs no dense materialisation. Custom
    subclasses default to ``stateless = False`` so their ``apply`` always
    runs.
    """

    name = "default"
    stateless = True
    sign = 1.0

    def init_state(self, shape: Tuple[int, ...], dtype, num_workers: int) -> Any:
        return ()

    def apply(self, data: jax.Array, state: Any, delta: jax.Array,
              option: AddOption) -> Tuple[jax.Array, Any]:
        return data + delta.astype(data.dtype), state

    def access(self, data: jax.Array, state: Any, option: GetOption) -> jax.Array:
        """Read path (``Updater::Access`` = memcpy, ``updater.cpp:25-29``)."""
        return data


class SGDUpdater(Updater):
    name = "sgd"
    stateless = True
    sign = -1.0

    def apply(self, data, state, delta, option):
        return data - delta.astype(data.dtype), state


class MomentumUpdater(Updater):
    name = "momentum_sgd"
    stateless = False

    def init_state(self, shape, dtype, num_workers):
        return jnp.zeros(shape, dtype=dtype)

    def apply(self, data, state, delta, option):
        m = jnp.asarray(option.momentum, dtype=data.dtype)
        s = m * state + (1.0 - m) * delta.astype(data.dtype)
        return data - s, s


class AdaGradUpdater(Updater):
    name = "adagrad"
    stateless = False

    def init_state(self, shape, dtype, num_workers):
        return jnp.zeros((num_workers,) + tuple(shape), dtype=dtype)

    def apply(self, data, state, delta, option):
        w = option.worker_id
        delta = delta.astype(data.dtype)
        g_sqr = state[w] + delta * delta
        state = state.at[w].set(g_sqr)
        scale = jnp.asarray(option.rho, data.dtype) / jnp.sqrt(g_sqr + _ADAGRAD_EPS)
        lr = jnp.asarray(option.learning_rate, data.dtype)
        return data - scale * delta / lr, state


_UPDATERS: Dict[str, Type[Updater]] = {
    "default": Updater,
    "sgd": SGDUpdater,
    "adagrad": AdaGradUpdater,
    "momentum_sgd": MomentumUpdater,
}


def register_updater(name: str, cls: Type[Updater]) -> None:
    _UPDATERS[name] = cls


def get_updater(name: Optional[str] = None, dtype=None) -> Updater:
    """Factory keyed by the ``updater_type`` flag (``updater.cpp:33-46``).

    Integer tables always get the default accumulate updater, matching the
    reference's type-dispatch (``updater.cpp:33-36``).
    """
    if dtype is not None and jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        return Updater()
    if name is None:
        name = config.get_flag("updater_type")
    try:
        return _UPDATERS[name]()
    except KeyError:
        Log.fatal(f"unknown updater_type {name!r}; expected one of {sorted(_UPDATERS)}")
