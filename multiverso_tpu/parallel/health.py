"""Heartbeat failure detection (SURVEY §5.3 — absent in the reference).

The reference has no failure story at all: registration happens once at
startup, there are no heartbeats, and a dead node hangs the job silently
(``src/controller.cpp:46-80``; SURVEY: "no heartbeats, no server failover").
This module provides the detection half of the recovery loop; the repair
half is checkpoint/resume (``io/checkpoint.restore_latest`` — a restarted
job reloads the newest complete checkpoint and continues).

Mechanism: every process runs a daemon thread bumping a per-rank heartbeat
counter in the coordination-service KV store. ``dead_peers()`` reports
peers whose counter has not advanced within ``timeout_s`` (measured on the
local clock from the last observed change — no clock sync needed).
``start_watchdog()`` turns detection into action: a background check that
invokes a callback (default: ``Log.fatal``) when a peer is declared dead,
so a lost process fails the job loudly within bounded time instead of
deadlocking the next collective.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..log import Log


class FailureDetector:
    """Per-process heartbeat publisher + peer liveness monitor."""

    def __init__(self, interval_s: float = 1.0, session=None) -> None:
        from ..runtime import Session

        sess = session or Session.get()
        if not sess.started:
            Log.fatal("FailureDetector requires an initialised session")
        self._sess = sess
        self._interval = float(interval_s)
        self._client = None
        self._stop = threading.Event()
        self._watch_cb: Optional[Callable[[List[int]], None]] = None
        self._watch_timeout = 0.0
        self._reported: set = set()   # ranks already handed to the callback
        # last observed (counter value, local monotonic time) per peer
        self._seen: Dict[int, tuple] = {}
        if sess.size > 1:
            from jax._src import distributed

            self._client = distributed.global_state.client
            if self._client is None:
                Log.fatal("FailureDetector: no coordination-service client")
            self._key = f"mvhb/{sess.rank}"
            self._client.key_value_increment(self._key, 1)
            now = time.monotonic()
            self._seen = {r: (0, now) for r in range(sess.size)
                          if r != sess.rank}
            self._thread = threading.Thread(
                target=self._beat_loop, name="mvhb", daemon=True)
            self._thread.start()

    # -- publisher ---------------------------------------------------------
    def _beat_loop(self) -> None:
        errors = 0
        first_err: Optional[float] = None
        while not self._stop.wait(self._interval):
            try:
                self._client.key_value_increment(self._key, 1)
                errors = 0
                first_err = None
            except Exception as exc:
                # transient service blips must NOT stop the publisher — a
                # halted heartbeat makes peers declare a HEALTHY process
                # dead. Log sparsely and keep beating; if the service
                # stays unreachable past the watchdog timeout, that IS a
                # failure (the rank-0 coordinator died) — fire.
                errors += 1
                now = time.monotonic()
                first_err = first_err or now
                if not self._stop.is_set() and errors in (1, 10, 100):
                    Log.error("heartbeat publish failed (x%d): %s",
                              errors, exc)
                cb = self._watch_cb
                if (cb is not None and self._watch_timeout > 0
                        and now - first_err > self._watch_timeout
                        and not self._stop.is_set()):
                    self._watch_cb = None
                    cb([0])   # coordination service (rank 0) unreachable
                continue
            cb = self._watch_cb
            if cb is not None:
                try:
                    dead = self.dead_peers(self._watch_timeout)
                except Exception:
                    continue
                # stay armed: each dead rank is reported exactly once, so
                # a survivor-mode callback (AsyncDeltaBus.mark_dead) keeps
                # working through successive failures
                new = [r for r in dead if r not in self._reported]
                if new:
                    self._reported.update(new)
                    cb(new)

    # -- monitor -----------------------------------------------------------
    def _peer_count(self, r: int) -> int:
        try:
            return int(self._client.key_value_try_get(f"mvhb/{r}"))
        except Exception as exc:
            if "NOT_FOUND" in str(exc):
                return 0
            raise

    def _peer_finished(self, r: int) -> bool:
        try:
            self._client.key_value_try_get(f"mvhb/{r}/done")
            return True
        except Exception:
            return False

    def dead_peers(self, timeout_s: float) -> List[int]:
        """Ranks whose heartbeat has not advanced for ``timeout_s``.
        Peers that deregistered via :meth:`stop` (clean exit) are never
        reported — a finished straggler is not a failure."""
        if self._client is None:
            return []
        now = time.monotonic()
        dead = []
        for r in list(self._seen):
            count = self._peer_count(r)
            last_count, last_time = self._seen[r]
            if count != last_count:
                self._seen[r] = (count, now)
            elif now - last_time > timeout_s:
                if self._peer_finished(r):
                    del self._seen[r]       # clean exit, stop watching
                else:
                    dead.append(r)
        return dead

    def start_watchdog(self, timeout_s: float,
                       on_failure: Optional[Callable[[List[int]], None]]
                       = None) -> None:
        """Declare-dead-and-act: when a peer misses heartbeats for
        ``timeout_s``, invoke ``on_failure(newly_dead_ranks)`` (default:
        fatal log naming the dead ranks — crash fast, restart, resume
        from the latest checkpoint). The watchdog stays armed: each rank
        is reported once, successive failures keep firing — so a
        survivor-mode callback (``-failure_timeout_s`` wires
        ``AsyncDeltaBus.mark_dead``) can ride out multiple deaths."""
        if self._client is None:
            return

        def _default(dead: List[int]) -> None:
            # runs on the heartbeat DAEMON thread: an exception here would
            # only kill that thread while the main thread hangs at its next
            # collective — the exact outcome the watchdog exists to
            # prevent. Log, then hard-exit the process.
            import os

            Log.error(f"peer rank(s) {dead} missed heartbeats for "
                      f"{timeout_s:.0f}s — exiting; restart the job and "
                      f"resume via io.checkpoint.restore_latest()")
            os._exit(17)

        self._watch_timeout = float(timeout_s)
        self._watch_cb = on_failure or _default

    def stop(self) -> None:
        """Deregister (clean exit): publish a done marker so peers stop
        watching this rank, then halt the publisher."""
        self._stop.set()
        if self._client is not None:
            try:
                self._client.key_value_set(f"mvhb/{self._sess.rank}/done",
                                           "1")
            except Exception:
                pass   # exiting anyway; peers fall back to the timeout
