"""Parallelism: ICI collectives, BSP train steps, host-side overlap.

Replaces the reference's net/allreduce-engine layer and sync-server machinery
with XLA-native forms — see per-module docstrings for the mapping.
"""

from .allreduce_engine import AllreduceEngine
from .async_buffer import ASyncBuffer, PipelinedGetter, prefetch_iterator
from .collectives import (all_gather, allreduce, allreduce_replicated,
                          reduce_scatter, ring_shift)
from .health import FailureDetector
from .pipeline import (STAGE_AXIS, make_pipeline_mesh, microbatch,
                       pipeline_apply, stack_stage_params)
from .ssp import SSPClock
from .sync_step import make_sync_step

__all__ = [
    "SSPClock",
    "FailureDetector",
    "AllreduceEngine",
    "ASyncBuffer",
    "PipelinedGetter",
    "prefetch_iterator",
    "all_gather",
    "allreduce",
    "allreduce_replicated",
    "reduce_scatter",
    "ring_shift",
    "STAGE_AXIS",
    "make_pipeline_mesh",
    "microbatch",
    "pipeline_apply",
    "stack_stage_params",
    "make_sync_step",
]
