"""Explicit collective algorithms over the ICI ring (AllreduceEngine parity).

The reference ships a from-scratch collective engine over point-to-point
sends (``src/net/allreduce_engine.cpp`` in the Multiverso reference):
payloads under 4KB (or with fewer elements than nodes) are allreduced by
allgather-then-local-reduce (``:31-44,57-77``); large payloads use
recursive-halving **reduce-scatter** (``:120-172``) followed by **Bruck
allgather** (``:90-117``); non-power-of-two node counts are handled by
pairing extras with group leaders (``allreduce_topo.cpp:58-150``).

This module re-expresses those algorithms TPU-natively: the point-to-point
primitive is ``jax.lax.ppermute`` over a mesh axis (each step compiles to one
ICI neighbour exchange), the per-rank topology maps the reference precomputes
(``BruckMap``/``RecursiveHalvingMap``) become step schedules unrolled at trace
time, and instead of the reference's divergent GroupLeader control flow,
non-power-of-two rings use a ring reduce-scatter — uniform SPMD control flow
is what the compiler wants. ``jax.lax.psum`` remains the production path
(``parallel.collectives``); this engine is the framework's drop-in alternative
for custom-topology experiments, exactly the role it plays in the reference.
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..topology import WORKER_AXIS
from .collectives import _mesh, shard_map

from jax.sharding import PartitionSpec as P


# -- step schedules (the reference's BruckMap / RecursiveHalvingMap) ---------

def bruck_schedule(n: int) -> List[Tuple[int, int]]:
    """Bruck allgather steps for an ``n`` ring: list of (distance,
    blocks_to_send). ``ceil(log2 n)`` steps, doubling block counts, with a
    truncated final step when ``n`` is not a power of two
    (``allreduce_topo.cpp:20`` BruckMap::Construct)."""
    steps = []
    m = 1
    while m < n:
        steps.append((m, min(m, n - m)))
        m *= 2
    return steps


def recursive_halving_schedule(n: int) -> List[int]:
    """Pair distances for recursive-halving reduce-scatter; empty when ``n``
    is not a power of two (those sizes take the ring path instead of the
    reference's GroupLeader pairing, ``allreduce_topo.cpp:58-150``)."""
    if n & (n - 1):
        return []
    steps = []
    d = n // 2
    while d >= 1:
        steps.append(d)
        d //= 2
    return steps


class AllreduceEngine:
    """Allgather / ReduceScatter / Allreduce built from ppermute steps
    (``include/multiverso/net/allreduce_engine.h:80-147``).

    Array conventions match ``parallel.collectives``: inputs carry one row
    per ring participant along axis 0, sharded over ``axis``.
    """

    SMALL_PAYLOAD_BYTES = 4096  # reference's allgather-allreduce cutoff

    def __init__(self, axis: str = WORKER_AXIS, mesh=None) -> None:
        self.axis = axis
        self.mesh = _mesh(mesh)
        self.n = int(self.mesh.shape[axis])

    # -- in-SPMD building blocks ------------------------------------------
    def _bruck_gather(self, block):
        """Inside shard_map: gather every participant's ``block`` (leading
        dim ``c``) into ``[n*c, ...]`` ordered by rank."""
        axis, n = self.axis, self.n
        c = block.shape[0]
        idx = jax.lax.axis_index(axis)
        buf = block
        for dist, send_blocks in bruck_schedule(n):
            send = buf[: send_blocks * c]
            perm = [(i, (i - dist) % n) for i in range(n)]
            recv = jax.lax.ppermute(send, axis, perm)
            buf = jnp.concatenate([buf, recv], axis=0)
        # buf rows are blocks [i, i+1, ..., i+n-1]; rotate block b to row b.
        return jnp.roll(buf, idx * c, axis=0)

    def _halving_reduce_scatter(self, vec):
        """Inside shard_map: recursive-halving RS of the full-size ``vec``
        (leading dim divisible by n); returns this rank's reduced chunk."""
        axis, n = self.axis, self.n
        idx = jax.lax.axis_index(axis)
        buf = vec
        for d in recursive_halving_schedule(n):
            half = buf.shape[0] // 2
            pair = buf.reshape((2, half) + buf.shape[1:])
            side = (idx // d) % 2  # my address bit at this distance
            keep = pair[side]
            send = pair[1 - side]
            perm = [(i, i ^ d) for i in range(n)]
            buf = keep + jax.lax.ppermute(send, axis, perm)
        return buf

    def _ring_reduce_scatter(self, vec):
        """Inside shard_map: ring RS for any ring size (n-1 neighbour steps);
        returns this rank's reduced chunk."""
        axis, n = self.axis, self.n
        idx = jax.lax.axis_index(axis)
        c = vec.shape[0] // n
        buf = vec.reshape((n, c) + vec.shape[1:])
        fwd = [(i, (i + 1) % n) for i in range(n)]
        # schedule starts one chunk behind the owner so that after the n-1
        # neighbour steps rank i holds fully-reduced chunk i directly (no
        # extra handoff ppermute)
        for s in range(n - 1):
            outgoing = buf[(idx - s - 1) % n]
            recv = jax.lax.ppermute(outgoing, axis, fwd)
            buf = buf.at[(idx - s - 2) % n].add(recv)
        return buf[idx]

    def _reduce_scatter_shard(self, vec):
        if recursive_halving_schedule(self.n):
            return self._halving_reduce_scatter(vec)
        return self._ring_reduce_scatter(vec)

    # -- public ops --------------------------------------------------------
    def allgather(self, x):
        """[n*c, ...] sharded over axis → same value replicated everywhere
        (``AllreduceEngine::Allgather``, Bruck)."""
        spec = P(self.axis, *(None,) * (np.ndim(x) - 1))

        @partial(shard_map, mesh=self.mesh, in_specs=(spec,),
                 out_specs=P(*(None,) * np.ndim(x)), check_vma=False)
        def _ag(shard):
            return self._bruck_gather(shard)

        return _ag(x)

    def reduce_scatter(self, x):
        """[n, k, ...] (row i = participant i's contribution, k divisible by
        n) → [k, ...] summed, sharded over axis
        (``AllreduceEngine::ReduceScatter``)."""
        n = self.n
        if x.shape[0] != n or x.shape[1] % n != 0:
            raise ValueError(
                f"reduce_scatter expects [n={n}, k*n, ...], got {tuple(x.shape)}")
        in_spec = P(self.axis, *(None,) * (np.ndim(x) - 1))
        out_spec = P(self.axis, *(None,) * (np.ndim(x) - 2))

        @partial(shard_map, mesh=self.mesh, in_specs=(in_spec,),
                 out_specs=out_spec, check_vma=False)
        def _rs(shard):
            return self._reduce_scatter_shard(shard[0])

        return _rs(x)

    def allreduce(self, x):
        """[n, k, ...] (row i = participant i's full-size buffer) → [n, k, ...]
        where every row is the elementwise sum (``AllreduceEngine::Allreduce``).

        Payloads under ``SMALL_PAYLOAD_BYTES`` (or with fewer elements than
        ring participants) take the allgather-allreduce path; larger ones
        reduce-scatter + allgather, both cutoffs as in the reference
        (``allreduce_engine.cpp:31-44``). Element counts that don't divide
        the ring size are zero-padded for the scatter and sliced after.
        """
        n = self.n
        if x.shape[0] != n:
            raise ValueError(f"allreduce expects [n={n}, ...], got {tuple(x.shape)}")
        k = int(np.prod(x.shape[1:]))
        payload = k * x.dtype.itemsize
        spec = P(self.axis, *(None,) * (np.ndim(x) - 1))

        if payload < self.SMALL_PAYLOAD_BYTES or k < n:
            @partial(shard_map, mesh=self.mesh, in_specs=(spec,),
                     out_specs=spec, check_vma=False)
            def _ar_small(shard):
                gathered = self._bruck_gather(shard)  # [n, k...]
                return jnp.sum(gathered, axis=0, keepdims=True)

            return _ar_small(x)

        @partial(shard_map, mesh=self.mesh, in_specs=(spec,), out_specs=spec,
                 check_vma=False)
        def _ar(shard):
            # Ravel so the scatter dimension is the full element count (the
            # trailing dims of a multi-dim payload need not divide n), and
            # zero-pad to a multiple of the ring size.
            flat = shard[0].reshape(-1)
            pad = -flat.shape[0] % n
            if pad:
                flat = jnp.pad(flat, (0, pad))
            chunk = self._reduce_scatter_shard(flat)
            full = self._bruck_gather(chunk)
            if pad:
                full = full[:-pad]
            return full.reshape(shard.shape)

        return _ar(x)
