"""Device-level collectives over the mesh (ICI data plane).

TPU-native replacement for the reference communication backend
(``src/net/mpi_net``/``zmq_net`` point-to-point transports and the hand-rolled
``AllreduceEngine`` — Bruck allgather + recursive-halving reduce-scatter,
``src/net/allreduce_engine.cpp:31-172`` in the Multiverso reference). Every
algorithm there exists to move bytes between processes; here the same
operations are XLA collectives compiled onto ICI links: ``psum`` (allreduce),
``all_gather``, ``psum_scatter`` (reduce-scatter), ``all_to_all`` and
``ppermute`` (the ring primitive). The topology mapping the reference
precomputes per rank (``allreduce_topo.cpp``) is XLA's job.

Functions here wrap ``shard_map`` so callers can allreduce host-shaped arrays
without writing SPMD code; jitted training steps should instead rely on
sharding propagation (see ``parallel.sync_step``) or use ``jax.lax``
collectives directly inside their own ``shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import Session
from ..topology import WORKER_AXIS

from jax.sharding import PartitionSpec as P

from .._compat import shard_map


def _mesh(mesh=None):
    return mesh if mesh is not None else Session.get().mesh


def allreduce(x, axis: str = WORKER_AXIS, mesh=None, mean: bool = False):
    """Sum (or mean) ``x`` across ``axis``; ``x`` is sharded along axis 0.

    The TPU form of ``MV_Aggregate``/``net::Allreduce``
    (``src/multiverso.cpp:47-50``): one ``psum`` riding ICI.
    """
    mesh = _mesh(mesh)
    spec = P(axis, *(None,) * (np.ndim(x) - 1))

    @partial(shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec,
             check_vma=False)
    def _reduce(shard):
        total = jax.lax.psum(shard, axis)
        if mean:
            total = total / mesh.shape[axis]
        return total

    return _reduce(x)


def allreduce_replicated(x, axis: str = WORKER_AXIS, mesh=None, mean: bool = False):
    """Allreduce of a per-device value that is already replicated layout-wise:
    each worker contributes its shard along a new leading axis."""
    mesh = _mesh(mesh)
    all_axes = tuple(mesh.axis_names)
    other = tuple(a for a in all_axes if a != axis)
    spec = P()

    @partial(shard_map, mesh=mesh, in_specs=(P(axis, *(None,) * np.ndim(x)),),
             out_specs=spec, check_vma=False)
    def _reduce(shard):
        total = jax.lax.psum(shard[0], axis)
        if mean:
            total = total / mesh.shape[axis]
        return total

    stacked = jnp.broadcast_to(x, (mesh.shape[axis],) + tuple(np.shape(x)))
    return _reduce(stacked)


def all_gather(x, axis: str = WORKER_AXIS, mesh=None):
    """Gather shards along ``axis`` onto every participant (Bruck allgather
    equivalent, ``allreduce_engine.cpp:90-117``)."""
    mesh = _mesh(mesh)
    spec = P(axis, *(None,) * (np.ndim(x) - 1))

    @partial(shard_map, mesh=mesh, in_specs=(spec,),
             out_specs=P(*(None,) * np.ndim(x)), check_vma=False)
    def _gather(shard):
        return jax.lax.all_gather(shard, axis, axis=0, tiled=True)

    return _gather(x)


def reduce_scatter(x, axis: str = WORKER_AXIS, mesh=None):
    """Reduce-scatter (recursive-halving equivalent,
    ``allreduce_engine.cpp:120-172``): ``x`` is ``[n, k, ...]`` where row i is
    participant i's full-size contribution (``k`` divisible by ``n``); returns
    ``[k, ...]`` — the elementwise sum, laid out sharded over ``axis`` so each
    participant holds its ``k/n`` slice.
    """
    mesh = _mesh(mesh)
    n = mesh.shape[axis]
    if x.shape[0] != n or x.shape[1] % n != 0:
        raise ValueError(
            f"reduce_scatter expects [n={n}, k*n, ...], got {tuple(x.shape)}")
    in_spec = P(axis, *(None,) * (np.ndim(x) - 1))
    out_spec = P(axis, *(None,) * (np.ndim(x) - 2))

    @partial(shard_map, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
             check_vma=False)
    def _rs(shard):
        return jax.lax.psum_scatter(shard[0], axis, scatter_dimension=0,
                                    tiled=True)

    return _rs(x)


def ring_shift(x, axis: str, mesh=None, shift: int = 1):
    """Rotate shards around the ``axis`` ring by ``shift`` (ppermute) — the
    building block ring attention and pipelined collectives share."""
    mesh = _mesh(mesh)
    n = mesh.shape[axis]
    spec = P(axis, *(None,) * (np.ndim(x) - 1))
    perm = [(i, (i + shift) % n) for i in range(n)]

    @partial(shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec,
             check_vma=False)
    def _shift(shard):
        return jax.lax.ppermute(shard, axis, perm)

    return _shift(x)
