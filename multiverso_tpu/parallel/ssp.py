"""SSP — stale-synchronous-parallel clock (bounded staleness).

The reference reserved this spot and never built it: only binary
sync/async modes exist, and the ``-backup_worker_ratio`` flag is dead code
(``src/server.cpp:20-21,229-231`` in the Multiverso reference; SURVEY §2.5
"SSP/bounded staleness ❌"). This module completes the spectrum:

* sync (BSP)  — every round gated (``-sync=true``);
* **SSP**     — rounds may drift up to ``staleness`` apart (this module
  layered on the async bus);
* async      — unbounded drift, eventual delivery (``parallel/async_ps.py``).

Protocol (classic SSP vector clock, re-expressed on the coordination
service): each worker owns a monotonically increasing round counter in the
KV store. ``tick()`` ends the local round: it flushes the worker's deltas
to the bus and bumps the counter. Before starting round ``r`` a worker
calls ``wait()``, which blocks while ``r - min(peer rounds) > staleness``
— the fastest worker can run at most ``staleness`` rounds ahead of the
slowest, so every Get observes peer state at most ``staleness`` rounds old
(plus the bus drain interval). ``staleness=0`` degenerates to per-round
BSP pacing (with async delivery); ``staleness=inf`` is plain async.
"""

from __future__ import annotations

import time
from typing import Optional

from .. import config
from ..log import Log


class SSPClock:
    """Per-process SSP round clock over the coordination-service KV.

    Usage (every process, symmetric)::

        clock = SSPClock(staleness=2)
        for round in range(R):
            clock.wait()          # gate: <= staleness ahead of slowest
            ... compute + table.add(...) ...
            clock.tick()          # publish round completion
        clock.finish()            # release peers forever (like the
                                  # reference SyncServer's FinishTrain
                                  # clock = INT_MAX)
    """

    _FINISHED = 1 << 30

    def __init__(self, staleness: int = 1, poll_s: float = 0.01,
                 session=None) -> None:
        from ..runtime import Session

        sess = session or Session.get()
        if not sess.started:
            Log.fatal("SSPClock requires an initialised session")
        if config.get_flag("sync"):
            Log.fatal("SSPClock is for async mode (-sync=false); BSP "
                      "already gates every round")
        self.staleness = int(staleness)
        self._poll = float(poll_s)
        self._sess = sess
        self._round = 0
        self._client = None
        if sess.size > 1:
            from jax._src import distributed

            self._client = distributed.global_state.client
            if self._client is None:
                Log.fatal("SSPClock: no coordination-service client")
            # round keys are generation-scoped so re-created clocks in one
            # process group don't read stale rounds
            self._gen = self._client.key_value_increment("mvssp/gen", 1) \
                if sess.rank == 0 else None
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("mvssp_init")
            if self._gen is None:
                self._gen = int(self._client.key_value_try_get("mvssp/gen"))
            self._key = f"mvssp/{self._gen}/r{sess.rank}"
            self._client.key_value_increment(self._key, 0)

    @property
    def round(self) -> int:
        return self._round

    def _peer_round(self, r: int) -> int:
        try:
            return int(self._client.key_value_try_get(
                f"mvssp/{self._gen}/r{r}"))
        except Exception as exc:
            if "NOT_FOUND" in str(exc):
                return 0
            raise

    def wait(self, timeout_s: float = 600.0) -> None:
        """Block until this worker is <= ``staleness`` rounds ahead of the
        slowest peer (no-op single-process).

        Round counters are monotonic, so a peer once observed past the
        gate is never re-polled within this wait — the poll load per
        worker is O(still-behind peers), not O(size), and the scan
        short-circuits on the first behind peer.
        """
        if self._client is None:
            return
        gate = self._round - self.staleness
        behind = [r for r in range(self._sess.size) if r != self._sess.rank]
        deadline = time.monotonic() + timeout_s
        while True:
            still = [r for r in behind if self._peer_round(r) < gate]
            if not still:
                return
            if time.monotonic() > deadline:
                Log.fatal(f"SSP wait timed out at round {self._round} "
                          f"(peers {still} behind round {gate}, "
                          f"staleness {self.staleness})")
            behind = still
            time.sleep(self._poll)

    def tick(self) -> None:
        """End the local round and advance the clock. Bus publications made
        during the round are already visible in the KV store (publish is
        synchronous), so a peer released by the bumped clock can drain
        every delta of this round."""
        self._round += 1
        if self._client is None:
            return
        self._client.key_value_increment(self._key, 1)

    def finish(self) -> None:
        """Release peers permanently (``FinishTrain``: clock -> INT_MAX,
        ``src/server.cpp:82-139``)."""
        if self._client is None:
            return
        self._client.key_value_increment(self._key,
                                         self._FINISHED - self._round)
