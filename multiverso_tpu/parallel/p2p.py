"""Peer-to-peer TCP payload transport for the async delta bus.

The reference's data plane is peer-to-peer: the MPI backend keeps a
one-outstanding Isend pipeline per peer (``include/multiverso/net/
mpi_net.h:199-220`` in the Multiverso reference) and the ZMQ backend a
DEALER socket mesh (``zmq_net.h:171-228``). Round 3's bus funneled every
record through the coordination-service KV — a single gRPC server
(~117 MB/s measured at 256 KB values), fine at 2-4 processes but a
funnel for a pod's O(P^2) record streams.

This module moves the PAYLOAD bytes onto direct per-pair TCP sockets;
the coordination-service KV keeps only the CONTROL plane it is good at:
endpoint discovery, publication counters, acks, the GC/backpressure
frontier, and barriers. Topology:

* every rank listens on an ephemeral port and advertises
  ``{label}/ep/{rank} = host:port`` in the KV;
* every rank SUBSCRIBES to each peer (connects to the peer's listener
  and sends its own rank) — records flow publisher -> subscriber down
  that connection, so each pair has one connection per direction and
  ordering per publisher is TCP's;
* frames are ``<QI`` (sequence number, length) + payload; the sequence
  number is authoritative — a gap means the transport invariant broke
  and the bus fails loudly rather than applying around it.

Threads: one accept loop, one sender per subscriber (drains a per-peer
deque, so a slow consumer never blocks publishes to others — the
reference's per-peer send queue, ``mpi_net.h:199`` ``msg_queues_``), one
receiver per subscription (appends to an in-order inbox the bus's drain
thread consumes). All daemon; :meth:`stop` closes sockets and joins.
"""

from __future__ import annotations

import collections
import socket
import struct
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

from ..log import Log

_FRAME = struct.Struct("<QI")   # seq, payload length
_HELLO = struct.Struct("<I")    # subscriber rank


def _local_host() -> str:
    """Advertised host: MV_P2P_HOST overrides; default = the hostname's
    address (localhost setups resolve to 127.x and work either way)."""
    import os

    host = os.environ.get("MV_P2P_HOST")
    if host:
        return host
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


class P2PTransport:
    """Direct-socket record plane between the processes of one bus."""

    def __init__(self, rank: int, size: int, client,
                 label: str = "mvps", connect_timeout_s: float = 60.0
                 ) -> None:
        self._rank = rank
        self._size = size
        self._client = client
        self._label = label
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # publisher side: per-subscriber outboxes + their sender threads
        self._out: Dict[int, Deque[Tuple[int, bytes]]] = {
            r: collections.deque() for r in range(size) if r != rank}
        self._out_cv = threading.Condition(self._lock)
        self._senders: Dict[int, threading.Thread] = {}
        # consumer side: per-publisher in-order inboxes
        self._in: Dict[int, Deque[Tuple[int, bytes]]] = {
            r: collections.deque() for r in range(size) if r != rank}
        self._dead: set = set()
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("", 0))
        self._listener.listen(size)
        port = self._listener.getsockname()[1]
        # allow_overwrite: the KV outlives the Session; a restarted bus
        # re-advertises its (new) endpoint
        client.key_value_set(f"{label}/ep/{rank}",
                             f"{_local_host()}:{port}", allow_overwrite=True)
        self._spawn(self._accept_loop, "p2p-accept")
        for r in self._in:
            self._spawn(self._subscribe, f"p2p-sub-{r}", r,
                        connect_timeout_s)

    def _spawn(self, fn, name, *args) -> None:
        t = threading.Thread(target=fn, name=name, args=args, daemon=True)
        t.start()
        self._threads.append(t)

    # -- publisher side ----------------------------------------------------
    def send(self, seq: int, payload: bytes) -> None:
        """Enqueue one record for every live subscriber (non-blocking; the
        bus's in-flight-bytes watermark bounds total queued memory)."""
        with self._out_cv:
            for r, q in self._out.items():
                if r not in self._dead:
                    q.append((seq, payload))
            self._out_cv.notify_all()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return                       # listener closed by stop()
            try:
                hello = self._read_exact(conn, _HELLO.size)
                (peer,) = _HELLO.unpack(hello)
            except OSError:
                conn.close()
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            with self._lock:
                self._senders[peer] = t = threading.Thread(
                    target=self._send_loop, name=f"p2p-send-{peer}",
                    args=(peer, conn), daemon=True)
            t.start()
            self._threads.append(t)

    def _send_loop(self, peer: int, conn: socket.socket) -> None:
        q = self._out[peer]
        while True:
            with self._out_cv:
                while not q and not self._stop.is_set():
                    self._out_cv.wait(0.2)
                if self._stop.is_set() and not q:
                    return
                seq, payload = q.popleft()
            try:
                # sendmsg scatters header + payload in one syscall without
                # concatenating (the concat alone costs a payload-sized
                # memcpy per subscriber on multi-MB records)
                self._send_frame(conn, seq, payload)
            except OSError as exc:
                if not self._stop.is_set() and peer not in self._dead:
                    Log.error("p2p: send to rank %d failed: %s (peer dead? "
                              "see parallel.FailureDetector)", peer, exc)
                return

    @staticmethod
    def _send_frame(conn: socket.socket, seq: int, payload: bytes) -> None:
        header = _FRAME.pack(seq, len(payload))
        view = memoryview(payload)
        sent = conn.sendmsg([header, view])
        # sendmsg may send partially; finish the remainder with sendall
        if sent < len(header) + len(view):
            if sent < len(header):
                conn.sendall(header[sent:])
                conn.sendall(view)
            else:
                conn.sendall(view[sent - len(header):])

    # -- consumer side -----------------------------------------------------
    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> bytearray:
        # recv_into a preallocated buffer: no per-chunk allocations, no
        # final copy (callers treat the result as read-only bytes-like)
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = conn.recv_into(view[got:], n - got)
            if r == 0:
                raise OSError("connection closed")
            got += r
        return buf

    def _subscribe(self, publisher: int, timeout_s: float) -> None:
        key = f"{self._label}/ep/{publisher}"
        try:
            ep = self._client.blocking_key_value_get(
                key, int(timeout_s * 1000))
        except Exception as exc:
            Log.error("p2p: no endpoint from rank %d within %.0f s: %s",
                      publisher, timeout_s, exc)
            return
        host, _, port = str(ep).rpartition(":")
        deadline = time.monotonic() + timeout_s
        conn = None
        while conn is None and not self._stop.is_set():
            try:
                conn = socket.create_connection((host, int(port)), timeout=5)
            except OSError:
                if time.monotonic() > deadline:
                    Log.error("p2p: cannot connect to rank %d at %s",
                              publisher, ep)
                    return
                time.sleep(0.05)
        if conn is None:
            return
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conns.append(conn)
        try:
            conn.sendall(_HELLO.pack(self._rank))
            inbox = self._in[publisher]
            while not self._stop.is_set():
                hdr = self._read_exact(conn, _FRAME.size)
                seq, length = _FRAME.unpack(hdr)
                payload = self._read_exact(conn, length)
                with self._lock:
                    inbox.append((seq, payload))
        except OSError as exc:
            if not self._stop.is_set() and publisher not in self._dead:
                Log.error("p2p: stream from rank %d broke: %s (peer dead? "
                          "see parallel.FailureDetector)", publisher, exc)

    def pop_ready(self, publisher: int, expected_seq: int
                  ) -> Optional[bytes]:
        """Return the payload for ``expected_seq`` if it is the inbox head.

        TCP preserves per-publisher order, so the head either IS the
        expected record or hasn't arrived yet; anything else is a broken
        transport invariant and fails loudly (same posture as the PART
        reassembly check)."""
        with self._lock:
            inbox = self._in[publisher]
            if not inbox:
                return None
            seq, payload = inbox[0]
            if seq != expected_seq:
                Log.fatal(f"p2p: rank {publisher} stream out of order: "
                          f"seq {seq} at head, expected {expected_seq}")
            inbox.popleft()
            return payload

    # -- failure handling (wired by the bus, driven by FailureDetector) ----
    def mark_dead(self, ranks) -> None:
        """Stop queueing to / expecting from dead peers; drop their queued
        output so a wedged sender can't pin memory."""
        with self._out_cv:
            for r in ranks:
                self._dead.add(r)
                if r in self._out:
                    self._out[r].clear()
            self._out_cv.notify_all()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)
