"""Peer-to-peer TCP payload transport for the async delta bus.

The reference's data plane is peer-to-peer: the MPI backend keeps a
one-outstanding Isend pipeline per peer (``include/multiverso/net/
mpi_net.h:199-220`` in the Multiverso reference) and the ZMQ backend a
DEALER socket mesh (``zmq_net.h:171-228``). Round 3's bus funneled every
record through the coordination-service KV — a single gRPC server
(~117 MB/s measured at 256 KB values), fine at 2-4 processes but a
funnel for a pod's O(P^2) record streams.

This module moves the PAYLOAD bytes onto direct per-pair TCP sockets;
the coordination-service KV keeps only the CONTROL plane it is good at:
endpoint discovery, publication counters, acks, the GC/backpressure
frontier, and barriers. Topology:

* every rank listens on an ephemeral port and advertises
  ``{label}/ep/{rank} = host:port`` in the KV;
* every rank SUBSCRIBES to each peer (connects to the peer's listener
  and sends its own rank + the sequence number it wants to resume
  from) — records flow publisher -> subscriber down that connection,
  so each pair has one connection per direction and ordering per
  publisher is TCP's;
* frames are ``<QI`` (sequence number, length) + payload; the sequence
  number is authoritative — a gap means the transport invariant broke
  and the bus fails loudly rather than applying around it.

Reconnect (r5; the reference's ZMQ mesh reconnects transparently,
``zmq_net.h:171-228``): a broken subscription re-fetches the
publisher's endpoint and reconnects with a hello carrying the next
sequence number it expects; the publisher replays from its RETAINED
window. The retained window holds exactly the publisher's un-GC'd
records — the bus's ack frontier (`async_ps.AsyncDeltaBus._reap_acks`)
calls :meth:`release` as records become fully acknowledged, so a
record any consumer might still need (it has not acked it) is always
replayable, and retained memory is bounded by the bus's in-flight
backpressure watermark. A duplicate subscription from the same peer
REPLACES the old sender (the old connection is closed and its thread
exits) instead of leaking a second thread on the same stream.
Permanent peer death stays the FailureDetector's job (`mark_dead`);
the transport itself retries transient breaks indefinitely.

Threads: one accept loop, one sender per live subscription (a cursor
over the retained window — a slow consumer never blocks publishes to
others; the reference's per-peer send queue, ``mpi_net.h:199``
``msg_queues_``), one receiver per subscription (appends to an
in-order inbox the bus's drain thread consumes). All daemon;
:meth:`stop` closes sockets and joins.
"""

from __future__ import annotations

import collections
import random
import socket
import struct
import threading
from ..analysis import lockwatch
import time
from typing import Deque, Dict, List, Optional, Tuple

from ..log import Log

_FRAME = struct.Struct("<QI")   # seq, payload length
_HELLO = struct.Struct("<IQ")   # subscriber rank, resume-from seq
_HELLO_TIMEOUT_S = 5.0          # accept-loop budget for the 12-byte hello
_BACKOFF_BASE_S = 0.05          # first reconnect delay
_BACKOFF_CAP_S = 2.0            # reconnect delay ceiling


def reconnect_backoff_s(attempt: int, base_s: float = _BACKOFF_BASE_S,
                        cap_s: float = _BACKOFF_CAP_S,
                        rng: Optional[random.Random] = None) -> float:
    """Delay before reconnect ``attempt`` (0-based): the capped
    exponential ceiling ``min(cap, base * 2**attempt)``, jittered into
    ``[ceiling/2, ceiling]`` when ``rng`` is given. The old fixed
    50 ms loop hammered a flapping peer's listener (and the KV
    endpoint lookup in front of it) at 20 Hz per subscriber forever;
    the schedule keeps the first retries prompt and the steady state
    polite, and the jitter keeps a fleet's subscribers from re-landing
    as one synchronized thundering herd."""
    if attempt < 0:
        raise ValueError(f"attempt is 0-based, got {attempt}")
    # clamp the exponent: a peer down for ~35 min would push 2**attempt
    # past float range and the OverflowError would kill the subscriber
    # thread — permanently losing the subscription right when patience
    # was the whole point
    ceiling = min(cap_s, base_s * (2.0 ** min(attempt, 64)))
    if rng is None:
        return ceiling
    return ceiling * (0.5 + 0.5 * rng.random())


def _local_host() -> str:
    """Advertised host: MV_P2P_HOST overrides; default = the hostname's
    address (localhost setups resolve to 127.x and work either way)."""
    import os

    host = os.environ.get("MV_P2P_HOST")
    if host:
        return host
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


class P2PTransport:
    """Direct-socket record plane between the processes of one bus."""

    def __init__(self, rank: int, size: int, client,
                 label: str = "mvps", connect_timeout_s: float = 60.0,
                 initial_resume: Optional[Dict[int, int]] = None,
                 on_dead=None,
                 subscribe_to: Optional[List[int]] = None) -> None:
        self._rank = rank
        self._size = size
        self._client = client
        self._label = label
        # bus hook for TRANSPORT-declared deaths (out-of-contract resume):
        # without it the bus's ack quorum keeps counting the rejected peer
        # and the publisher can only exit via the 600-s backpressure fatal.
        # Invoked WITHOUT _out_cv held — the bus's mark_dead re-enters
        # p2p.mark_dead, which takes the (non-reentrant) lock.
        self._on_dead = on_dead
        self._lock = lockwatch.lock("parallel.P2PTransport._lock")
        self._stop = threading.Event()
        # publisher side: retained un-GC'd records (seq -> payload) + the
        # next seq to be published; per-subscriber senders are cursors
        # over this window (guarded by _lock / signalled via _out_cv)
        self._retained: Dict[int, bytes] = {}
        self._next_seq: Optional[int] = None
        self._out_cv = threading.Condition(self._lock)
        # peer -> sender state dict; identity is the liveness token — a
        # sender whose state is no longer registered has been replaced
        self._senders: Dict[int, dict] = {}
        # consumer side: per-publisher in-order inboxes + next expected seq
        self._in: Dict[int, Deque[Tuple[int, bytes]]] = {
            r: collections.deque() for r in range(size) if r != rank}
        self._expect: Dict[int, int] = {
            r: int((initial_resume or {}).get(r, 0)) for r in self._in}
        self._dead: set = set()
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        # reconnect jitter stream (rank-seeded: deterministic per
        # process, decorrelated across the mesh)
        self._backoff_rng = random.Random(0x9B2C ^ rank)

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("", 0))
        self._listener.listen(size)
        port = self._listener.getsockname()[1]
        # allow_overwrite: the KV outlives the Session; a restarted bus
        # re-advertises its (new) endpoint
        client.key_value_set(f"{label}/ep/{rank}",
                             f"{_local_host()}:{port}", allow_overwrite=True)
        self._spawn(self._accept_loop, "p2p-accept")
        # records flow publisher -> subscriber, so which streams exist
        # is the SUBSCRIBER's choice: the default (None) is the bus's
        # full mesh, while a hub-topology plane (the obs collector is
        # the only consumer) subscribes each rank to exactly the peers
        # it reads — an empty list publishes only, and no redundant
        # copy of any record ever crosses the wire
        subs = list(self._in) if subscribe_to is None else [
            r for r in subscribe_to if r in self._in]
        for r in subs:
            self._spawn(self._subscribe, f"p2p-sub-{r}", r,
                        connect_timeout_s)

    def _spawn(self, fn, name, *args) -> None:
        t = threading.Thread(target=fn, name=name, args=args, daemon=True)
        t.start()
        # prune retired senders so reconnect churn can't grow the join
        # list without bound (under _lock: __init__ and the accept loop
        # spawn concurrently, and a lost append would skip a join)
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _track(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.add(conn)

    def _close(self, conn: Optional[socket.socket]) -> None:
        if conn is None:
            return
        with self._lock:
            self._conns.discard(conn)
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    # -- publisher side ----------------------------------------------------
    def send(self, seq: int, payload: bytes) -> None:
        """Retain one record and wake the per-subscriber senders
        (non-blocking; the bus's in-flight-bytes watermark bounds the
        retained window — see :meth:`release`)."""
        with self._out_cv:
            self._retained[seq] = payload
            self._next_seq = seq + 1
            self._out_cv.notify_all()

    def release(self, seq: int) -> None:
        """Drop a fully-acknowledged record from the retained window.

        Called by the bus's ack-GC frontier (`_reap_acks`) — once every
        live consumer acked ``seq``, no reconnect can legitimately ask
        for it again (a consumer only acks what it consumed, and resumes
        strictly after what it consumed)."""
        with self._out_cv:
            self._retained.pop(seq, None)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return                       # listener closed by stop()
            try:
                # a short hello deadline: a half-open connection (client
                # stalled between connect and sendall) must not wedge the
                # single accept thread — every OTHER peer's reconnect
                # funnels through it. socket.timeout is an OSError, so
                # the silent client lands in the except below.
                conn.settimeout(_HELLO_TIMEOUT_S)
                hello = self._read_exact(conn, _HELLO.size)
                peer, resume = _HELLO.unpack(hello)
                conn.settimeout(None)   # streaming is deadline-free again
            except OSError:
                conn.close()
                continue
            if (peer in self._dead or peer == self._rank
                    or not 0 <= peer < self._size):
                # declared-dead (or out-of-contract resurrected) peers and
                # bogus hellos (port scanner, wrong-label client, own
                # rank) get no stream; closing here keeps the reject
                # bounded instead of granting a replay sender slot
                conn.close()
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._track(conn)
            state = {"peer": peer, "conn": conn, "cursor": resume}
            with self._lock:
                old = self._senders.pop(peer, None)
                self._senders[peer] = state
            # a duplicate subscribe REPLACES the old sender: closing its
            # socket errors out any blocked send; the registry check below
            # exits it even when it was idle-waiting
            if old is not None:
                self._close(old["conn"])
            self._spawn(self._send_loop, f"p2p-send-{peer}", state)

    def _send_loop(self, state: dict) -> None:
        peer: int = state["peer"]
        conn: socket.socket = state["conn"]
        cursor: int = state["cursor"]
        while True:
            with self._out_cv:
                while (not self._stop.is_set()
                       and self._senders.get(peer) is state
                       and peer not in self._dead
                       and (self._next_seq is None
                            or cursor >= self._next_seq)):
                    self._out_cv.wait(0.2)
                if (self._stop.is_set() or peer in self._dead
                        or self._senders.get(peer) is not state):
                    if self._senders.get(peer) is state:
                        self._senders.pop(peer, None)
                    break
                payload = self._retained.get(cursor)
            if payload is None:
                # only reachable for a resurrected peer whose records were
                # GC'd after it was declared dead — out of contract.
                # Mark it dead transport-side so its retry loop gets a
                # bounded reject at accept instead of a fresh sender +
                # error line per attempt.
                Log.error("p2p: rank %d resumed from seq %d which is "
                          "already released (declared dead earlier?); "
                          "rejecting its stream", peer, cursor)
                with self._out_cv:
                    self._dead.add(peer)
                    self._senders.pop(peer, None)
                # surface the death to the bus (outside the lock — see
                # __init__) so its ack quorum shrinks NOW instead of
                # burning the 600-s backpressure deadline into Log.fatal
                if self._on_dead is not None:
                    try:
                        self._on_dead({peer})
                    except Exception as exc:
                        Log.error("p2p: on_dead hook failed for rank %d: "
                                  "%s", peer, exc)
                break
            try:
                # sendmsg scatters header + payload in one syscall without
                # concatenating (the concat alone costs a payload-sized
                # memcpy per subscriber on multi-MB records)
                self._send_frame(conn, cursor, payload)
            except OSError:
                # the subscriber reconnects with its own resume point;
                # this sender just retires
                with self._lock:
                    if self._senders.get(peer) is state:
                        self._senders.pop(peer, None)
                break
            cursor += 1
        # every exit path closes + untracks this connection (a replaced
        # sender's conn was already closed by the accept loop — _close is
        # idempotent)
        self._close(conn)

    @staticmethod
    def _send_frame(conn: socket.socket, seq: int, payload: bytes) -> None:
        header = _FRAME.pack(seq, len(payload))
        view = memoryview(payload)
        sent = conn.sendmsg([header, view])
        # sendmsg may send partially; finish the remainder with sendall
        if sent < len(header) + len(view):
            if sent < len(header):
                conn.sendall(header[sent:])
                conn.sendall(view)
            else:
                conn.sendall(view[sent - len(header):])

    # -- consumer side -----------------------------------------------------
    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> bytearray:
        # recv_into a preallocated buffer: no per-chunk allocations, no
        # final copy (callers treat the result as read-only bytes-like)
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = conn.recv_into(view[got:], n - got)
            if r == 0:
                raise OSError("connection closed")
            got += r
        return buf

    def _endpoint(self, publisher: int, timeout_ms: int) -> Tuple[str, int]:
        ep = self._client.blocking_key_value_get(
            f"{self._label}/ep/{publisher}", timeout_ms)
        host, _, port = str(ep).rpartition(":")
        return host, int(port)

    def _connect(self, publisher: int, first: bool,
                 timeout_s: float) -> Optional[socket.socket]:
        """One connected+hello'd socket to ``publisher``, or None.

        The FIRST subscription bounds endpoint discovery by
        ``timeout_s`` (a peer that never comes up fails the bus
        handshake anyway); reconnects retry indefinitely — transient
        breaks are the transport's job, permanent death is the
        FailureDetector's (`mark_dead` ends the retries). Failed
        attempts back off on the capped-exponential-with-jitter
        schedule (:func:`reconnect_backoff_s`); a successful connect
        resets it."""
        deadline = time.monotonic() + timeout_s
        attempt = 0
        while not self._stop.is_set() and publisher not in self._dead:
            try:
                # re-fetch each attempt: a restarted publisher
                # re-advertises a NEW ephemeral port
                host, port = self._endpoint(publisher, 5_000)
                conn = socket.create_connection((host, port), timeout=5)
            except Exception as exc:
                if first and time.monotonic() > deadline:
                    Log.error("p2p: no endpoint from rank %d within "
                              "%.0f s: %s", publisher, timeout_s, exc)
                    return None
                time.sleep(reconnect_backoff_s(attempt,
                                               rng=self._backoff_rng))
                attempt += 1
                continue
            # create_connection leaves its 5 s connect timeout on the
            # socket; a publisher idle longer than that (jit compile,
            # barrier) must read as silence, not a broken stream
            conn.settimeout(None)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                with self._lock:
                    resume = self._expect[publisher]
                conn.sendall(_HELLO.pack(self._rank, resume))
            except OSError:
                self._close(conn)
                time.sleep(reconnect_backoff_s(attempt,
                                               rng=self._backoff_rng))
                attempt += 1
                continue
            self._track(conn)
            return conn
        return None

    def _subscribe(self, publisher: int, timeout_s: float) -> None:
        first = True
        fails = 0
        while not self._stop.is_set() and publisher not in self._dead:
            conn = self._connect(publisher, first, timeout_s)
            if conn is None:
                return
            first = False
            inbox = self._in[publisher]
            delivered = False
            try:
                while not self._stop.is_set():
                    hdr = self._read_exact(conn, _FRAME.size)
                    seq, length = _FRAME.unpack(hdr)
                    payload = self._read_exact(conn, length)
                    with self._lock:
                        if seq != self._expect[publisher]:
                            # TCP + replay-from-resume preserve per-
                            # publisher order; anything else is a broken
                            # transport invariant (same posture as
                            # pop_ready / the PART reassembly check)
                            Log.fatal(
                                f"p2p: rank {publisher} stream out of "
                                f"order: got seq {seq}, expected "
                                f"{self._expect[publisher]}")
                        inbox.append((seq, payload))
                        self._expect[publisher] = seq + 1
                    delivered = True
            except OSError as exc:
                if self._stop.is_set() or publisher in self._dead:
                    return
                with self._lock:
                    resume = self._expect[publisher]
                Log.info("p2p: stream from rank %d broke (%s); "
                         "reconnecting from seq %d", publisher, exc, resume)
            finally:
                self._close(conn)
            # a stream the publisher keeps closing without delivering
            # anything (out-of-contract reject) backs off instead of
            # spinning the accept loop at ~20 Hz; a delivering stream
            # resets the schedule — its next break reconnects promptly
            fails = 0 if delivered else fails + 1
            time.sleep(reconnect_backoff_s(fails, rng=self._backoff_rng))

    def pop_ready(self, publisher: int, expected_seq: int
                  ) -> Optional[bytes]:
        """Return the payload for ``expected_seq`` if it is the inbox head.

        TCP preserves per-publisher order, so the head either IS the
        expected record or hasn't arrived yet; anything else is a broken
        transport invariant and fails loudly (same posture as the PART
        reassembly check)."""
        with self._lock:
            inbox = self._in[publisher]
            if not inbox:
                return None
            seq, payload = inbox[0]
            if seq != expected_seq:
                Log.fatal(f"p2p: rank {publisher} stream out of order: "
                          f"seq {seq} at head, expected {expected_seq}")
            inbox.popleft()
            return payload

    # -- failure handling (wired by the bus, driven by FailureDetector) ----
    def mark_dead(self, ranks) -> None:
        """Stop queueing to / expecting from / reconnecting to dead peers;
        their senders exit and release any cursor state. Closing the
        conns matters: a sender to a wedged peer is typically blocked in
        ``sendall`` (full TCP buffers), where no cv notify reaches it —
        only erroring the syscall out does."""
        dropped = []
        with self._out_cv:
            for r in ranks:
                self._dead.add(r)
                state = self._senders.pop(r, None)
                if state is not None:
                    dropped.append(state)
            self._out_cv.notify_all()
        for state in dropped:
            self._close(state["conn"])

    def stop(self) -> None:
        self._stop.set()
        # close() alone does not reliably interrupt a thread blocked in
        # accept(); on a busy mesh a peer's reconnect attempt wakes it
        # by accident, but a QUIET topology (param plane, a stopped
        # fleet) left the accept thread parked until the joiner's 5-s
        # timeout expired — one self-connect wakes it deterministically
        # (the dummy conn closes immediately, so the hello read fails
        # fast and the loop observes the stop flag)
        try:
            port = self._listener.getsockname()[1]
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            self._close(c)
        for t in self._threads:
            t.join(timeout=5)
