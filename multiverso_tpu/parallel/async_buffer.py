"""Double-buffered prefetching: compute/communication overlap on the host.

TPU-native equivalent of the reference ``ASyncBuffer``
(``include/multiverso/util/async_buffer.h:11-116`` in the Multiverso
reference) and the LogReg ``GetPipelineTable`` pattern
(``Applications/LogisticRegression/src/model/ps_model.cpp:236``): a
background thread fills the non-ready buffer while the consumer works on the
ready one; ``get()`` waits, swaps, and re-triggers the prefetch.

On TPU the analogous overlap for *device* work comes free from JAX's async
dispatch; this class covers genuinely host-blocking producers (data loading,
host Gets of remote state) exactly like the reference's.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class ASyncBuffer(Generic[T]):
    """Two buffers + one background filler thread."""

    def __init__(self, buffer0: T, buffer1: T,
                 fill_fn: Callable[[T], None]) -> None:
        self._buffers = [buffer0, buffer1]
        self._fill_fn = fill_fn
        self._ready: "queue.Queue[int]" = queue.Queue(maxsize=2)
        self._todo: "queue.Queue[Optional[int]]" = queue.Queue(maxsize=2)
        self._thread = threading.Thread(target=self._main, daemon=True)
        self._consumer_idx: Optional[int] = None
        self._stopped = False
        self._thread.start()
        self._todo.put(0)  # prefetch into buffer 0 immediately

    def _main(self) -> None:
        while True:
            idx = self._todo.get()
            if idx is None:
                return
            self._fill_fn(self._buffers[idx])
            self._ready.put(idx)

    def get(self) -> T:
        """Wait for the prefetched buffer, hand it out, prefetch the other.

        Acquiring buffer ``i`` releases the previously-held buffer, which
        (two buffers) is always ``1 - i`` — so ``1 - i`` becomes the next
        fill target.
        """
        if self._stopped:
            raise RuntimeError("ASyncBuffer is stopped; call restart() first")
        idx = self._ready.get()
        self._consumer_idx = idx
        self._todo.put(1 - idx)
        return self._buffers[idx]

    def join(self) -> None:
        """Stop the filler thread (reference ``Join``); restartable."""
        if self._stopped:
            return
        self._todo.put(None)
        self._thread.join()
        self._stopped = True

    def restart(self) -> None:
        if not self._stopped:
            return
        self._stopped = False
        self._thread = threading.Thread(target=self._main, daemon=True)
        self._thread.start()
        if self._ready.empty() and self._todo.empty():
            # nothing prefetched and nothing scheduled: prime the non-held buffer
            idx = self._consumer_idx
            self._todo.put(1 - idx if idx is not None else 0)


def prefetch_iterator(iterable, depth: int = 2):
    """Background-thread prefetch of an iterator.

    The loader-thread pattern (reference ``BlockQueue`` +
    ``LoadDataFromFile`` thread, ``WE/src/distributed_wordembedding.cpp:33-56``;
    LogReg ``SampleReader`` thread, ``LR/src/reader.cpp:128``): the producer
    runs ``depth`` items ahead on a daemon thread so host parsing overlaps
    device execution. Exceptions in the producer re-raise at the consumer.
    """
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    done = object()
    stop = threading.Event()

    def put(entry) -> bool:
        # bounded put that gives up when the consumer is gone, so an
        # abandoned generator doesn't leak a thread blocked on a full queue
        while not stop.is_set():
            try:
                q.put(entry, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def run():
        try:
            try:
                for item in iterable:
                    if not put((None, item)):
                        return
            except BaseException as exc:  # propagate to consumer
                put((exc, None))
                return
            put((done, None))
        finally:
            close = getattr(iterable, "close", None)
            if close is not None:
                close()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    try:
        while True:
            exc, item = q.get()
            if exc is done:
                return
            if exc is not None:
                raise exc
            yield item
    finally:
        stop.set()


class PipelinedGetter:
    """Double-buffered table Gets keyed by a per-window keyset.

    Mirrors LogReg ``PSModel::GetPipelineTable``
    (``ps_model.cpp:236``): while the consumer trains on window *i*'s
    parameters, the next window's keyset is already being fetched.
    ``get(next_keys)`` returns the previously-prefetched values and starts
    the fetch for ``next_keys``.
    """

    def __init__(self, fetch_fn: Callable[[object], object]) -> None:
        self._fetch_fn = fetch_fn
        self._pending: Optional["queue.Queue"] = None
        self._thread: Optional[threading.Thread] = None
        self._result_q: "queue.Queue" = queue.Queue(maxsize=1)

    def prime(self, keys) -> None:
        """Start the first fetch (blocking fetches happen in background)."""
        self._start(keys)

    def _start(self, keys) -> None:
        def run():
            self._result_q.put(self._fetch_fn(keys))

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def get(self, next_keys=None):
        """Wait on the in-flight fetch; optionally start the next one."""
        if self._thread is None:
            raise RuntimeError("call prime(keys) before get()")
        result = self._result_q.get()
        self._thread.join()
        self._thread = None
        if next_keys is not None:
            self._start(next_keys)
        return result
