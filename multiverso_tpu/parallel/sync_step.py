"""Jitted BSP training step: the TPU-native form of sync parameter serving.

The reference's sync mode is its most intricate machinery — per-worker vector
clocks gating message order so every worker's i-th Get sees identical
parameters (``SyncServer``, ``src/server.cpp:69-222`` in the Multiverso
reference). BSP is XLA's *native* execution model, so all of that collapses
into one jitted SPMD step: the batch arrives sharded over the ``worker`` mesh
axis, the loss reduction makes XLA insert a ``psum`` of gradients over ICI,
and the updater folds the summed delta into the ``server``-sharded table —
every worker's next Get trivially sees identical parameters because there is
exactly one parameter buffer.

``make_sync_step`` is the minimal-harness version operating on one table;
real models thread pytrees through their own jitted steps and only need the
tables' ``.array``/``set_array`` accessors plus shardings.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..tables.base import TableBase, _option_scalars
from ..topology import WORKER_AXIS
from ..updaters import AddOption


def make_sync_step(
    table: TableBase,
    loss_fn: Callable[[jax.Array, Any], jax.Array],
    batch_sharded: bool = True,
) -> Callable[[Any, Optional[AddOption]], jax.Array]:
    """Build ``step(batch, option) -> loss`` folding grads into ``table``.

    ``loss_fn(params, batch)`` returns a scalar mean loss. The returned step:

    * shards ``batch`` over the ``worker`` axis (data parallelism; XLA turns
      the mean-loss gradient into a psum over ICI),
    * computes ``delta = lr * grad`` and applies the table's updater (so
    ``sgd`` performs descent, ``default`` accumulates ``+lr*grad``),
    * updates the table's HBM-resident state in place (donated buffers).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = table.mesh
    batch_spec = (NamedSharding(mesh, P(WORKER_AXIS))
                  if batch_sharded else NamedSharding(mesh, P()))
    updater = table.updater

    def _step(data, ustate, batch, lr, momentum, rho, lam, wid):
        # loss_fn sees the logical view; grads on server-padding rows are 0
        loss, grads = jax.value_and_grad(
            lambda d, b: loss_fn(table.logical(d), b))(data, batch)
        option = AddOption(worker_id=wid, learning_rate=lr,
                           momentum=momentum, rho=rho, lam=lam)
        delta = lr * grads
        data, ustate = updater.apply(data, ustate, delta, option)
        return data, ustate, loss

    jitted = jax.jit(
        _step,
        donate_argnums=(0, 1),
        out_shardings=(table.sharding, table._ustate_sharding, None),
    )

    def step(batch, option: Optional[AddOption] = None) -> jax.Array:
        option = option or AddOption()
        batch = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, batch_spec), batch)
        with table._lock:
            table._data, table._ustate, loss = jitted(
                table._data, table._ustate, batch,
                *_option_scalars(option, table.dtype))
        return loss

    return step
