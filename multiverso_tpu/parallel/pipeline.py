"""GPipe-style pipeline parallelism over a ``stage`` mesh axis.

The reference framework has no pipeline parallelism (SURVEY §2.5 — the word
"pipeline" there means compute/comm double-buffering: ``ASyncBuffer``
``include/multiverso/util/async_buffer.h:11``, LogReg ``GetPipelineTable``
``Applications/LogisticRegression/src/model/ps_model.cpp:236``). Our TPU-first
design generalises the reference's storage-only model parallelism to real
compute parallelism, and pipeline parallelism falls out of the mesh design:

* stages are devices along a ``stage`` mesh axis;
* activations flow stage -> stage over ICI via ``lax.ppermute``;
* the GPipe microbatch schedule is a ``lax.scan`` inside ``shard_map`` —
  tick ``t`` has stage ``s`` working on microbatch ``t - s`` (bubble at the
  ramp-up/ramp-down edges);
* the whole schedule is differentiable end-to-end: the transpose of
  ``ppermute`` is the reverse ring, so reverse-mode AD derives the backward
  pipeline schedule automatically.

Constraints (the usual SPMD pipeline contract): every stage has the same
activation shape and the same ``stage_fn`` signature; per-stage parameters are
stacked on a leading ``n_stages`` dim and sharded over the ``stage`` axis.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from .._compat import shard_map

STAGE_AXIS = "stage"


def make_pipeline_mesh(n_stages: Optional[int] = None,
                       devices: Optional[Sequence] = None):
    """A 1-D mesh whose single axis is the pipeline ``stage`` axis."""
    from ..topology import make_mesh

    if devices is None:
        devices = jax.devices()
    if n_stages is None:
        n_stages = len(devices)
    return make_mesh((n_stages,), axis_names=(STAGE_AXIS,),
                     devices=devices[:n_stages])


def stack_stage_params(per_stage_params: Sequence[Any]):
    """Stack a list of per-stage parameter pytrees on a leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    params: Any,
    xs: jax.Array,
    mesh,
    axis: str = STAGE_AXIS,
) -> jax.Array:
    """Apply ``f_{S-1}(...f_1(f_0(x)))`` pipelined over mesh axis ``axis``.

    Args:
      stage_fn: ``(stage_params, activation) -> activation``; activation
        shape must be invariant across stages.
      params: pytree whose leaves have leading dim ``n_stages``; sharded (or
        shardable) over ``axis``.
      xs: ``[n_micro, micro_batch, ...]`` microbatched input (replicated).
      mesh: mesh containing ``axis``.

    Returns ``[n_micro, micro_batch, ...]`` outputs, replicated across the
    stage axis. Differentiable in ``params`` and ``xs``.
    """
    n_stages = int(mesh.shape[axis])
    n_micro = int(xs.shape[0])
    for leaf in jax.tree.leaves(params):
        if np.ndim(leaf) == 0 or np.shape(leaf)[0] != n_stages:
            raise ValueError(
                f"params leaf has leading dim "
                f"{np.shape(leaf)[0] if np.ndim(leaf) else 'none (scalar)'} "
                f"!= mesh axis {axis}={n_stages}; stack exactly one param "
                f"set per stage")
    param_spec = jax.tree.map(
        lambda leaf: P(axis, *(None,) * (np.ndim(leaf) - 1)), params)

    @partial(shard_map, mesh=mesh,
             in_specs=(param_spec, P()), out_specs=P(),
             check_vma=False)
    def _pipelined(p_shard, xs_rep):
        stage = jax.lax.axis_index(axis)
        p_local = jax.tree.map(lambda leaf: leaf[0], p_shard)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        state0 = jnp.zeros_like(xs_rep[0])
        out0 = jnp.zeros_like(xs_rep)

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 feeds microbatch t (clamped; garbage after the last
            # microbatch never survives long enough to be recorded).
            feed = jax.lax.dynamic_index_in_dim(
                xs_rep, jnp.clip(t, 0, n_micro - 1), keepdims=False)
            inp = jnp.where(stage == 0, feed, state)
            out = stage_fn(p_local, inp)
            # The last stage records microbatch t-(n_stages-1) at tick t.
            rec = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            recorded = jax.lax.dynamic_update_index_in_dim(
                outputs, out.astype(outputs.dtype), rec, axis=0)
            take = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            outputs = jnp.where(take, recorded, outputs)
            state = jax.lax.ppermute(out, axis, perm)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state0, out0), jnp.arange(n_micro + n_stages - 1))
        # Outputs are only valid on the last stage; a masked psum replicates
        # them (and its transpose routes cotangents back in the bwd pass).
        mask = (stage == n_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    return _pipelined(params, xs)


def microbatch(batch: jax.Array, n_micro: int) -> jax.Array:
    """Split ``[B, ...]`` into ``[n_micro, B//n_micro, ...]``."""
    if batch.shape[0] % n_micro != 0:
        raise ValueError(
            f"batch dim {batch.shape[0]} not divisible by n_micro={n_micro}")
    return batch.reshape((n_micro, batch.shape[0] // n_micro) + batch.shape[1:])


def pipeline_value_and_grad(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    params: Any,
    xs: jax.Array,
    aux: jax.Array,
    mesh,
    axis: str = STAGE_AXIS,
):
    """1F1B pipelined training step: ``(mean loss, param grads)``.

    :func:`pipeline_apply` + reverse-mode AD yields the GPipe schedule —
    all forwards, then all backwards — whose activation residency grows
    with ``n_micro`` (every microbatch's residuals live until its
    backward). This hand-scheduled 1F1B form caps residency at
    ``O(n_stages)`` instead: each tick runs ONE forward slot and ONE
    backward slot per stage, activations ``ppermute`` down the ring while
    cotangents ``ppermute`` up it, and a stage stashes only the INPUT of
    each in-flight microbatch (2*n_stages ring slots), recomputing the
    stage forward inside the backward slot (standard 1F1B-with-remat: one
    extra forward per microbatch buys n_micro-independent memory).

    Schedule (stage ``s``, tick ``t``): forward slot runs microbatch
    ``m_f = t - s``; backward slot runs ``m_b = t - (2S - 1 - s)`` — the
    last stage turns a microbatch around one tick after finishing its
    forward, and backwards cascade stage-by-stage in reverse. The scan
    runs ``n_micro + 2*n_stages - 1`` ticks (the last, inclusive tick is
    stage 0's backward of the final microbatch at
    ``t = n_micro + 2*n_stages - 2``); for ``n_micro >> n_stages``
    total compute matches GPipe + one remat forward.

    Args:
      stage_fn: ``(stage_params, activation) -> activation`` (shape
        invariant across stages).
      loss_fn: ``(last_stage_output, aux_microbatch) -> scalar`` (e.g.
        targets packed in ``aux``); the per-microbatch losses are
        averaged.
      params: pytree with leading ``n_stages`` dim (see
        :func:`stack_stage_params`).
      xs: ``[n_micro, micro_batch, ...]`` inputs (replicated).
      aux: ``[n_micro, ...]`` per-microbatch loss side input (replicated).
      mesh: mesh containing ``axis``.

    Returns ``(loss, grads)`` with ``loss`` the mean over microbatches and
    ``grads`` matching ``params`` (each stage's slice is that stage's
    gradient), both replicated/sharded exactly like the inputs.
    """
    n_stages = int(mesh.shape[axis])
    n_micro = int(xs.shape[0])
    for leaf in jax.tree.leaves(params):
        if np.ndim(leaf) == 0 or np.shape(leaf)[0] != n_stages:
            raise ValueError(
                f"params leaf has leading dim "
                f"{np.shape(leaf)[0] if np.ndim(leaf) else 'none (scalar)'} "
                f"!= mesh axis {axis}={n_stages}; stack exactly one param "
                f"set per stage")
    param_spec = jax.tree.map(
        lambda leaf: P(axis, *(None,) * (np.ndim(leaf) - 1)), params)
    slots = 2 * n_stages
    # last tick = stage 0's backward of the final microbatch:
    # t = (2S - 1 - 0) + (n_micro - 1) = n_micro + 2S - 2, inclusive
    n_ticks = n_micro + 2 * n_stages - 1

    @partial(shard_map, mesh=mesh,
             in_specs=(param_spec, P(), P()),
             out_specs=(P(), param_spec),
             check_vma=False)
    def _one_f_one_b(p_shard, xs_rep, aux_rep):
        stage = jax.lax.axis_index(axis)
        last = stage == n_stages - 1
        p_local = jax.tree.map(lambda leaf: leaf[0], p_shard)
        perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        perm_bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        micro_shape = xs_rep.shape[1:]
        state0 = jnp.zeros(micro_shape, xs_rep.dtype)
        stash0 = jnp.zeros((slots,) + micro_shape, xs_rep.dtype)
        dp0 = jax.tree.map(lambda leaf: jnp.zeros(leaf.shape[1:], jnp.float32),
                           p_shard)

        def tick(carry, t):
            fwd_in, cot_in, stash, dp, loss_acc = carry

            # ---- forward slot: microbatch m_f = t - stage --------------
            m_f = t - stage
            valid_f = jnp.logical_and(m_f >= 0, m_f < n_micro)
            feed = jax.lax.dynamic_index_in_dim(
                xs_rep, jnp.clip(m_f, 0, n_micro - 1), keepdims=False)
            x_in = jnp.where(stage == 0, feed, fwd_in)
            y = stage_fn(p_local, x_in)
            # stash the INPUT (remat recomputes the rest in the bwd slot)
            slot_f = jax.lax.rem(jnp.clip(m_f, 0, n_micro - 1) + slots,
                                 slots)
            stashed = jax.lax.dynamic_update_index_in_dim(
                stash, x_in.astype(stash.dtype), slot_f, axis=0)
            stash = jnp.where(valid_f, stashed, stash)

            # ---- backward slot: microbatch m_b = t - (2S - 1 - stage) --
            m_b = t - (2 * n_stages - 1 - stage)
            valid_b = jnp.logical_and(m_b >= 0, m_b < n_micro)
            slot_b = jax.lax.rem(jnp.clip(m_b, 0, n_micro - 1) + slots,
                                 slots)
            x_saved = jax.lax.dynamic_index_in_dim(stash, slot_b,
                                                   keepdims=False)
            aux_b = jax.lax.dynamic_index_in_dim(
                aux_rep, jnp.clip(m_b, 0, n_micro - 1), keepdims=False)
            y_b, vjp = jax.vjp(stage_fn, p_local, x_saved)
            # seed: the last stage differentiates the loss of ITS output;
            # earlier stages consume the cotangent ppermuted from above
            loss_b, dloss_dy = jax.value_and_grad(loss_fn)(y_b, aux_b)
            seed = jnp.where(last, dloss_dy.astype(y_b.dtype),
                             cot_in.astype(y_b.dtype))
            dp_m, dx_m = vjp(seed)
            dp = jax.tree.map(
                lambda acc, g: acc + jnp.where(valid_b,
                                               g.astype(jnp.float32), 0.0),
                dp, dp_m)
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(last, valid_b), loss_b, 0.0)

            # ---- ring moves (activation dtype pinned to the input's) ---
            fwd_out = jax.lax.ppermute(y.astype(xs_rep.dtype), axis,
                                       perm_fwd)
            cot_out = jax.lax.ppermute(dx_m.astype(xs_rep.dtype), axis,
                                       perm_bwd)
            return (fwd_out, cot_out, stash, dp, loss_acc), None

        carry0 = (state0, jnp.zeros(micro_shape, xs_rep.dtype), stash0, dp0,
                  jnp.float32(0.0))
        (_, _, _, dp, loss_acc), _ = jax.lax.scan(
            tick, carry0, jnp.arange(n_ticks))
        # loss lives on the last stage; masked psum replicates it
        loss = jax.lax.psum(
            jnp.where(last, loss_acc, 0.0), axis) / n_micro
        # grads: re-attach each stage's leading dim for the P(stage) spec
        dp = jax.tree.map(lambda g: g[None] / n_micro, dp)
        return loss, dp

    return _one_f_one_b(params, xs, aux)
