"""GPipe-style pipeline parallelism over a ``stage`` mesh axis.

The reference framework has no pipeline parallelism (SURVEY §2.5 — the word
"pipeline" there means compute/comm double-buffering: ``ASyncBuffer``
``include/multiverso/util/async_buffer.h:11``, LogReg ``GetPipelineTable``
``Applications/LogisticRegression/src/model/ps_model.cpp:236``). Our TPU-first
design generalises the reference's storage-only model parallelism to real
compute parallelism, and pipeline parallelism falls out of the mesh design:

* stages are devices along a ``stage`` mesh axis;
* activations flow stage -> stage over ICI via ``lax.ppermute``;
* the GPipe microbatch schedule is a ``lax.scan`` inside ``shard_map`` —
  tick ``t`` has stage ``s`` working on microbatch ``t - s`` (bubble at the
  ramp-up/ramp-down edges);
* the whole schedule is differentiable end-to-end: the transpose of
  ``ppermute`` is the reverse ring, so reverse-mode AD derives the backward
  pipeline schedule automatically.

Constraints (the usual SPMD pipeline contract): every stage has the same
activation shape and the same ``stage_fn`` signature; per-stage parameters are
stacked on a leading ``n_stages`` dim and sharded over the ``stage`` axis.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from .._compat import shard_map

STAGE_AXIS = "stage"


def make_pipeline_mesh(n_stages: Optional[int] = None,
                       devices: Optional[Sequence] = None):
    """A 1-D mesh whose single axis is the pipeline ``stage`` axis."""
    from ..topology import make_mesh

    if devices is None:
        devices = jax.devices()
    if n_stages is None:
        n_stages = len(devices)
    return make_mesh((n_stages,), axis_names=(STAGE_AXIS,),
                     devices=devices[:n_stages])


def stack_stage_params(per_stage_params: Sequence[Any]):
    """Stack a list of per-stage parameter pytrees on a leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    params: Any,
    xs: jax.Array,
    mesh,
    axis: str = STAGE_AXIS,
) -> jax.Array:
    """Apply ``f_{S-1}(...f_1(f_0(x)))`` pipelined over mesh axis ``axis``.

    Args:
      stage_fn: ``(stage_params, activation) -> activation``; activation
        shape must be invariant across stages.
      params: pytree whose leaves have leading dim ``n_stages``; sharded (or
        shardable) over ``axis``.
      xs: ``[n_micro, micro_batch, ...]`` microbatched input (replicated).
      mesh: mesh containing ``axis``.

    Returns ``[n_micro, micro_batch, ...]`` outputs, replicated across the
    stage axis. Differentiable in ``params`` and ``xs``.
    """
    n_stages = int(mesh.shape[axis])
    n_micro = int(xs.shape[0])
    for leaf in jax.tree.leaves(params):
        if np.ndim(leaf) == 0 or np.shape(leaf)[0] != n_stages:
            raise ValueError(
                f"params leaf has leading dim "
                f"{np.shape(leaf)[0] if np.ndim(leaf) else 'none (scalar)'} "
                f"!= mesh axis {axis}={n_stages}; stack exactly one param "
                f"set per stage")
    param_spec = jax.tree.map(
        lambda leaf: P(axis, *(None,) * (np.ndim(leaf) - 1)), params)

    @partial(shard_map, mesh=mesh,
             in_specs=(param_spec, P()), out_specs=P(),
             check_vma=False)
    def _pipelined(p_shard, xs_rep):
        stage = jax.lax.axis_index(axis)
        p_local = jax.tree.map(lambda leaf: leaf[0], p_shard)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        state0 = jnp.zeros_like(xs_rep[0])
        out0 = jnp.zeros_like(xs_rep)

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 feeds microbatch t (clamped; garbage after the last
            # microbatch never survives long enough to be recorded).
            feed = jax.lax.dynamic_index_in_dim(
                xs_rep, jnp.clip(t, 0, n_micro - 1), keepdims=False)
            inp = jnp.where(stage == 0, feed, state)
            out = stage_fn(p_local, inp)
            # The last stage records microbatch t-(n_stages-1) at tick t.
            rec = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            recorded = jax.lax.dynamic_update_index_in_dim(
                outputs, out.astype(outputs.dtype), rec, axis=0)
            take = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            outputs = jnp.where(take, recorded, outputs)
            state = jax.lax.ppermute(out, axis, perm)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state0, out0), jnp.arange(n_micro + n_stages - 1))
        # Outputs are only valid on the last stage; a masked psum replicates
        # them (and its transpose routes cotangents back in the bwd pass).
        mask = (stage == n_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    return _pipelined(params, xs)


def microbatch(batch: jax.Array, n_micro: int) -> jax.Array:
    """Split ``[B, ...]`` into ``[n_micro, B//n_micro, ...]``."""
    if batch.shape[0] % n_micro != 0:
        raise ValueError(
            f"batch dim {batch.shape[0]} not divisible by n_micro={n_micro}")
    return batch.reshape((n_micro, batch.shape[0] // n_micro) + batch.shape[1:])
