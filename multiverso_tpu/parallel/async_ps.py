"""Cross-process ASYNC parameter serving over the coordination-service KV.

The reference's DEFAULT mode: workers push deltas whenever they like and the
shared server shards apply them in arrival order (``src/server.cpp:36-60``,
worker fan-out ``src/worker.cpp:30-92`` in the Multiverso reference) — every
worker's delta is eventually visible to every worker, with no round gating.

TPU re-design. There is no shared server process: every process holds the
full (sharded-in-HBM) table replica and folds deltas with jitted updater
steps. Sync mode makes replicas identical by aggregating each round (BSP —
XLA's native model). For ASYNC mode this module adds the missing
cross-process data plane:

* every local Add is applied to the local replica immediately (zero-latency
  self-visibility, like a worker sharing a process with its server), and
  **published** to the process group through the JAX coordination service's
  key-value store (gRPC over DCN — the same control plane that replaced
  MPI_Init/rank-0 registration);
* a per-process background **drain thread** (the reference's server actor
  thread re-expressed) polls peers' publication counters and applies their
  deltas to the local replica in arrival order, via the same jitted
  updater/scatter paths as local Adds.

Consistency contract (documented bounded staleness):

* every delta is applied exactly once on every process; each process sees
  its own Adds immediately and peers' Adds within one drain interval plus
  transport time (arrival order may differ between replicas, exactly like
  the reference's per-server arrival order);
* with the ``default``/commutative updater, all replicas converge to the
  same state once quiescent — ``drain()`` (a collective) forces that point:
  after it returns, every process has applied every delta published before
  it anywhere, so ``get()`` equals Sigma_workers Sigma_iters delta (the
  invariant the reference's array test asserts, ``Test/main.cpp:87-127``);
* stateful updaters (AdaGrad slots) carry the originating worker_id in the
  record, so per-worker state is exact; only cross-worker apply ORDER is
  replica-dependent (true of the reference too).

Payload hygiene: records are framed numpy buffers (no pickle); dense deltas
ride the ``SparseFilter`` wire compression (``quantization.py``) — the same
>50-percent-small rule the reference applies to cross-process Add payloads
(``include/multiverso/util/quantization_util.h:95``).

Garbage collection: each record is acknowledged by its consumers via an
atomic counter; the PUBLISHER deletes the record (payload + nested ack key,
one directory-semantics delete) once its backpressure frontier observes
size-1 acks, so the KV store stays bounded by the in-flight watermark.
Consumers never delete — the service's recursive delete would take the ack
key with the payload and wedge the publisher's frontier.

Scale (VERDICT r2 item 3): three mechanisms keep the bus viable for real
model sizes rather than test-scale payloads —

* **representation**: :meth:`AsyncDeltaBus.publish_delta` auto-selects
  keyed touched-row publication for row tables on the commutative default
  updater (the native form of a sparse update; dense falls back when most
  rows moved or the updater is stateful, where skipping zero rows would
  skip state decay);
* **wire chunking**: records above ``-async_max_record_kb`` split into
  PART records at consecutive sequence numbers and are reassembled before
  the ONE apply, so transport message-size limits are respected without
  changing apply atomicity/order;
* **backpressure**: the publisher tracks un-acked published bytes and
  blocks once they exceed ``-async_max_inflight_mb``, so a fast worker
  cannot grow the KV store without bound ahead of slow consumers.

Dashboard monitors: ``ASYNC_BUS[PUBLISH]`` (publish wall time incl.
backpressure), ``ASYNC_BUS[APPLY]`` (local apply time) and
``ASYNC_BUS[LATENCY]`` (publish->apply, from the send timestamp carried in
each record — same-host clocks in tests; cross-host numbers inherit NTP
skew). ``AsyncDeltaBus.stats()`` reports bytes and MB/s both ways.
"""

from __future__ import annotations

import io
import struct
import threading
from ..analysis import lockwatch
import time
from typing import Any, Deque, List, Optional, Sequence, Tuple

import numpy as np

from .. import config, trace
from ..log import Log
from ..quantization import SparseFilter

# record kinds (STATE carries the ABSOLUTE table value — the fenced
# restart's rebase record, installed via set-state, not folded via add)
DENSE, KEYED, KV, PART, STATE = 0, 1, 2, 3, 4

_HEADER = struct.Struct("<BBiiffffdQQIQ")  # kind, n_arrays, table_id,
#                          worker_id, lr, momentum, rho, lam, send_ts,
#                          trace_id, span_id (0,0 = untraced publish) —
#                          the cross-process trace link: a consumer's
#                          bus.apply span parents under the publisher's
#                          bus.publish span by these two u64s —
#                          then epoch (u32; trainer incarnation, 0 =
#                          unfenced) and version (u64; publisher-side
#                          post-apply table version, 0 = unknown)
_PART_HEADER = struct.Struct("<BII")   # kind=PART, part_index, n_parts

# Publication/consumption counters survive init/shutdown cycles within one
# process-group lifetime: the coordination service KV outlives the Session,
# so a fresh Session must continue the sequence numbers, not restart them
# (stop() drains collectively, so no record outlives its Session).
_published = 0
_consumed: dict = {}
_state_lock = lockwatch.lock("parallel.async_ps._state_lock")
# the counters above are rank-keyed and process-wide, which is only sound
# for ONE live bus per process (documented lifecycle); a second concurrent
# Session would silently share them — refuse loudly instead
_active_bus: Optional["AsyncDeltaBus"] = None


def _serialize(kind: int, table_id: int, option, arrays: Sequence[np.ndarray],
               ctx: Optional[trace.SpanContext] = None, epoch: int = 0,
               version: int = 0) -> bytes:
    tid, sid = (ctx.trace_id, ctx.span_id) if ctx is not None else (0, 0)
    buf = io.BytesIO()
    buf.write(_HEADER.pack(kind, len(arrays), table_id,
                           int(getattr(option, "worker_id", 0)),
                           float(getattr(option, "learning_rate", 0.0)),
                           float(getattr(option, "momentum", 0.0)),
                           float(getattr(option, "rho", 0.0)),
                           float(getattr(option, "lam", 0.0)),
                           time.time(), tid, sid, int(epoch),
                           int(version)))
    from ..io.stream import write_array

    for arr in arrays:
        write_array(buf, np.ascontiguousarray(arr))
    return buf.getvalue()


def _deserialize(data: bytes):
    from ..updaters import AddOption

    from ..io.stream import read_array

    buf = io.BytesIO(data)
    (kind, n_arrays, table_id, wid, lr, mom, rho, lam, ts, trace_id,
     span_id, epoch, version) = _HEADER.unpack(buf.read(_HEADER.size))
    arrays = [read_array(buf) for _ in range(n_arrays)]
    option = AddOption(worker_id=wid, learning_rate=lr, momentum=mom,
                       rho=rho, lam=lam)
    ctx = trace.SpanContext(trace_id, span_id) if trace_id else None
    return kind, table_id, option, arrays, ts, ctx, epoch, version


def _kv_get_int(client, key: str, default: int = 0) -> int:
    """Best-effort int read covering both KV client generations:
    ``key_value_try_get`` is absent on jax<=0.4.x's
    DistributedRuntimeClient (PR 12 finding), so fall back to a short
    blocking get."""
    try:
        if hasattr(client, "key_value_try_get"):
            return int(str(client.key_value_try_get(key)))
        return int(str(client.blocking_key_value_get(key, 200)))
    except Exception:
        return default


def claim_epoch(client, key: str = "mvps/epoch") -> int:
    """Claim the next trainer incarnation epoch in the coordination KV.

    The monotonic fencing token of the restart contract: every publish
    of the claiming incarnation is stamped with it, appliers track the
    highest epoch seen and reject lower-epoch records, so a
    paused-then-resumed zombie trainer cannot fold stale deltas into a
    converged fleet (Parameter Server's fenced server recovery,
    OSDI '14). One trainer restarts at a time by deployment contract —
    concurrent claimants are a split-brain the fence then resolves in
    favor of whichever claimed LAST.

    A fencing-token read must FAIL LOUDLY on transport errors: silently
    defaulting to 0 would rewind the key and turn the legitimately
    restarted trainer into a permanent zombie (every publish below the
    fleet's fence). Only a genuinely ABSENT key reads as 0."""
    if hasattr(client, "key_value_try_get"):
        try:
            cur = int(str(client.key_value_try_get(key)))
        except Exception as exc:
            if "NOT_FOUND" not in str(exc) \
                    and not isinstance(exc, KeyError):
                Log.fatal(f"claim_epoch: cannot read fence key {key!r} "
                          f"({exc}) — claiming blindly could regress "
                          f"the epoch and fence out this trainer")
            cur = 0
    else:
        # jax<=0.4.x clients: no try_get — a short blocking get whose
        # timeout means "absent" (the first claim). The real
        # DistributedRuntimeClient raises XlaRuntimeError
        # ("DEADLINE_EXCEEDED...") rather than TimeoutError, so match
        # the timeout by MESSAGE too; anything else still fails loudly.
        try:
            cur = int(str(client.blocking_key_value_get(key, 2_000)))
        except Exception as exc:
            msg = str(exc)
            if (isinstance(exc, TimeoutError) or "DEADLINE" in msg
                    or "NOT_FOUND" in msg):
                cur = 0
            else:
                Log.fatal(f"claim_epoch: cannot read fence key {key!r} "
                          f"({exc}) — claiming blindly could regress "
                          f"the epoch and fence out this trainer")
    nxt = cur + 1
    client.key_value_set(key, str(nxt), allow_overwrite=True)
    return nxt


class EpochFence:
    """Highest-epoch-wins admission check for fenced publishes.

    ``admit(epoch)`` returns False for records from a lower incarnation
    than the highest ever seen (and counts the rejection); epoch 0
    (unfenced legacy records) always passes and never advances the
    fence. GIL-atomic int state: callers are single applier threads."""

    def __init__(self, name: str = "fence") -> None:
        from ..dashboard import Dashboard

        self.epoch = 0
        self.rejections = 0
        self._counter = Dashboard.get_or_create_counter(
            f"EPOCH_FENCE_REJECTIONS[{name}]")

    def admit(self, epoch: int) -> bool:
        if not epoch:
            return True
        if epoch < self.epoch:
            self.rejections += 1
            self._counter.inc()
            return False
        self.epoch = epoch
        return True


class AsyncDeltaBus:
    """Per-process async-PS data plane (publish + drain thread)."""

    def __init__(self, sess, client, poll_interval: float) -> None:
        import collections

        from ..dashboard import Dashboard

        self._sess = sess
        self._client = client
        self._rank = sess.rank
        self._size = sess.size
        self._interval = poll_interval
        self._filters: dict = {}   # np.dtype -> SparseFilter (typed wire)
        self._pub_lock = lockwatch.lock("parallel.AsyncDeltaBus._pub_lock")
        self._drain_lock = lockwatch.lock("parallel.AsyncDeltaBus._drain_lock")
        self._stop = threading.Event()
        self._max_record = max(
            int(config.get_flag("async_max_record_kb")), 64) << 10
        self._max_inflight = max(
            int(config.get_flag("async_max_inflight_mb")), 1) << 20
        # ranks declared dead (FailureDetector -> mark_dead): excluded from
        # the ack quorum and the drain targets so survivors keep training.
        # Mutated WITHOUT _pub_lock (GIL-atomic set ops) — a backpressure-
        # blocked publisher HOLDS _pub_lock, and the whole point of the
        # declaration is to release that wait.
        self._dead: set = set()
        # survivor mode active? (drain's KV dead-union costs P-1 RPCs per
        # quiesce; skip it entirely when nothing can ever be declared dead)
        self._survivor_mode = float(
            config.get_flag("failure_timeout_s")) > 0
        self._p2p = None
        if config.get_flag("async_p2p"):
            try:
                from .p2p import P2PTransport

                # a restarted bus in the same process resumes streams from
                # the module-level consumed counters (a graceful restart
                # drained first, so these equal each peer's published count)
                self._p2p = P2PTransport(
                    self._rank, self._size, client,
                    initial_resume={r: _consumed.get(r, 0)
                                    for r in range(self._size)
                                    if r != self._rank},
                    # transport-declared deaths (out-of-contract resume)
                    # must shrink the ACK quorum too, or _reap_acks waits
                    # on a peer that will never consume again and the
                    # publisher exits via the 600-s backpressure fatal
                    on_dead=self.mark_dead)
            except Exception as exc:
                Log.error("async PS: p2p transport unavailable (%s)", exc)
            # the payload plane must be AGREED: one rank silently falling
            # back to KV while peers publish over sockets splits the bus
            # (its records unread by p2p consumers and vice versa). Each
            # rank publishes its outcome; everyone ANDs them.
            # allow_overwrite: the KV outlives the Session, so a restarted
            # bus in the same process-group lifetime re-publishes its vote
            self._client.key_value_set(
                f"mvps/p2p/{self._rank}", "1" if self._p2p else "0",
                allow_overwrite=True)
            all_ok = self._p2p is not None
            for r in range(self._size):
                if r == self._rank:
                    continue
                try:
                    ok = self._client.blocking_key_value_get(
                        f"mvps/p2p/{r}", 120_000)
                except Exception as exc:
                    Log.fatal(f"async PS: no p2p handshake from rank {r}: "
                              f"{exc}")
                all_ok = all_ok and str(ok) == "1"
            if not all_ok and self._p2p is not None:
                Log.error("async PS: a peer lacks p2p; whole group falls "
                          "back to KV payloads")
                self._p2p.stop()
                self._p2p = None
        # (seq, nbytes) of own records not yet acked by all consumers;
        # drives backpressure and ack-key GC (guarded by _pub_lock)
        self._outstanding: Deque[Tuple[int, int]] = collections.deque()
        self._inflight_bytes = 0
        self._parts: dict = {}     # publisher rank -> list of part payloads
        self._t0 = time.perf_counter()
        self.pub_bytes = 0
        self.apply_bytes = 0
        # trainer incarnation epoch: 0 = unfenced (the default); a
        # restarted trainer claims one (claim_epoch) and every publish
        # carries it. The applier-side fence is highest-epoch-wins, so
        # a zombie incarnation's late records are rejected, not folded.
        self.epoch = 0
        self._fence = EpochFence(f"bus.r{self._rank}")
        self._mon_pub = Dashboard.get_or_create("ASYNC_BUS[PUBLISH]")
        self._mon_apply = Dashboard.get_or_create("ASYNC_BUS[APPLY]")
        self._mon_lat = Dashboard.get_or_create("ASYNC_BUS[LATENCY]")
        global _active_bus
        with _state_lock:
            if _active_bus is not None:
                Log.fatal("async PS: a second AsyncDeltaBus in one process "
                          "would share the module-level sequence counters; "
                          "stop() the first bus before starting another")
            _active_bus = self
            for r in range(self._size):
                _consumed.setdefault(r, 0)
        self._thread = threading.Thread(
            target=self._drain_loop, name="mvps-drain", daemon=True)
        self._thread.start()

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def maybe_start(cls, sess) -> Optional["AsyncDeltaBus"]:
        """Start the bus iff this session runs multi-process async PS."""
        if sess.size <= 1:
            return None
        if config.get_flag("sync") or config.get_flag("ma"):
            return None
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:   # no coordination service (shouldn't happen >1p)
            Log.error("async PS: no coordination-service client; "
                      "cross-process deltas will NOT propagate")
            return None
        interval = float(config.get_flag("async_poll_ms")) / 1000.0
        bus = cls(sess, client, interval)
        Log.info("async PS bus up: rank %d/%d, poll %.0f ms",
                 sess.rank, sess.size, interval * 1000)
        return bus

    def stop(self) -> None:
        """Collective: drain everything in flight, then stop the thread."""
        global _active_bus
        try:
            self.drain()
        finally:
            # deregister even when drain() fails (the bus is dead either
            # way and a supervised restart must be able to start a new
            # one) — but ONLY once the drain thread is actually gone: a
            # still-running thread would race a successor bus on the
            # module-level _consumed counters
            self._stop.set()
            self._thread.join(timeout=30)
            if self._p2p is not None:
                self._p2p.stop()
            with _state_lock:
                if self._thread.is_alive():
                    Log.error("async PS: drain thread failed to stop in "
                              "30 s; bus stays registered (a new bus would "
                              "race it on the sequence counters)")
                elif _active_bus is self:
                    _active_bus = None

    # -- publish (worker -> group) ----------------------------------------
    def _acks_for(self, seq: int) -> int:
        try:
            return int(self._client.key_value_try_get(
                f"mvps/{self._rank}/{seq}/a"))
        except Exception as exc:
            if "NOT_FOUND" in str(exc):   # no consumer acked yet
                return 0
            raise

    def _reap_acks(self) -> None:
        """Advance the backpressure frontier: pop fully-acked own records
        and GC payload + ack key. GC is PUBLISHER-side because the
        coordination service's delete has directory semantics — a consumer
        deleting the payload key would recursively delete the nested ack
        key and the publisher would read "no acks" forever (measured
        deadlock, r3). Caller holds ``_pub_lock``."""
        while self._outstanding:
            seq, nbytes = self._outstanding[0]
            # dead peers leave the quorum; a peer that acked before dying
            # only over-satisfies the check
            if self._acks_for(seq) < self._size - 1 - len(self._dead):
                return
            # recursive: also removes the nested ack key
            self._client.key_value_delete(f"mvps/{self._rank}/{seq}")
            if self._p2p is not None:
                # fully acked -> no reconnect can ask for it again; drop
                # it from the transport's retained replay window
                self._p2p.release(seq)
            self._outstanding.popleft()
            self._inflight_bytes -= nbytes

    def _put_record(self, payload: bytes) -> None:
        """One wire record: backpressure gate, write, bump counter. Caller
        holds ``_pub_lock``.

        The ack frontier is only polled once in-flight bytes pass HALF the
        watermark — below that, no RPC rides the publish hot path, and KV
        growth stays bounded by the watermark (drain() reaps the rest)."""
        global _published
        if self._inflight_bytes + len(payload) > self._max_inflight // 2:
            self._reap_acks()
        warned = False
        deadline = time.monotonic() + 600.0
        while (self._outstanding
               and self._inflight_bytes + len(payload) > self._max_inflight):
            if not warned:
                Log.debug("async PS: backpressure at %.1f MB in flight",
                          self._inflight_bytes / 1e6)
                warned = True
            if self._stop.is_set():
                # shutdown raced a blocked publish. Dropping the record
                # would permanently diverge peers that consumed earlier
                # records from this rank, with no hard signal — so this is
                # a caller error (stop() drains collectively first; publish
                # concurrently with shutdown breaks that contract).
                Log.fatal("async PS: publish raced shutdown with "
                          f"{self._inflight_bytes / 1e6:.1f} MB un-acked — "
                          "callers must drain() before stopping the bus")
            if time.monotonic() > deadline:
                # same liveness posture as drain()'s 600 s barriers and
                # the SSP wait: a peer that stops consuming is a failure,
                # not a reason to hang the training thread forever while
                # holding _pub_lock
                Log.fatal(
                    f"async PS backpressure timed out: {self._inflight_bytes / 1e6:.1f} "
                    f"MB un-acked after 600 s (peer dead? see "
                    f"parallel.FailureDetector); oldest seq "
                    f"{self._outstanding[0][0]}")
            time.sleep(self._interval)
            self._reap_acks()
        seq = _published
        if self._p2p is not None:
            # payload rides the direct sockets; only the counter/acks stay
            # on the KV control plane. A consumer may observe the counter
            # before its frame lands — poll_once simply retries until the
            # in-order inbox head matches.
            self._p2p.send(seq, payload)
        else:
            self._client.key_value_set_bytes(
                f"mvps/{self._rank}/{seq}", payload)
        _published = seq + 1
        # counter bump AFTER the payload is visible: readers never see
        # a sequence number without its record
        self._client.key_value_increment(f"mvps/{self._rank}/n", 1)
        self._outstanding.append((seq, len(payload)))
        self._inflight_bytes += len(payload)
        self.pub_bytes += len(payload)

    def _publish(self, payload: bytes) -> None:
        """Publish one logical record, split into PART wire records when it
        exceeds the transport size cap. Parts occupy consecutive sequence
        numbers from this publisher, so consumers reassemble in order and
        apply the logical record ONCE — chunking never changes apply
        atomicity or ordering."""
        self._mon_pub.begin()
        with self._pub_lock:
            maxb = self._max_record
            if self._p2p is not None or len(payload) <= maxb:
                # direct sockets have no gRPC message-size cap: one frame
                # per logical record (chunking would only add copies and
                # per-part counter/ack RPCs — measured 5x throughput cost)
                self._put_record(payload)
            else:
                n_parts = -(-len(payload) // maxb)
                for i in range(n_parts):
                    chunk = payload[i * maxb:(i + 1) * maxb]
                    self._put_record(
                        _PART_HEADER.pack(PART, i, n_parts) + chunk)
        self._mon_pub.end()

    def _filter_for(self, dtype) -> SparseFilter:
        """SparseFilter typed to the table dtype — a filter is
        ``SparseFilter<data_t>`` in the reference too; an f32-typed filter
        would silently downcast f64 deltas on the wire."""
        dtype = np.dtype(dtype)
        f = self._filters.get(dtype)
        if f is None:
            f = self._filters[dtype] = SparseFilter(clip=0.0, dtype=dtype)
        return f

    def set_epoch(self, epoch: int) -> None:
        """Stamp subsequent publishes with a claimed incarnation epoch
        (:func:`claim_epoch`); appliers fence on it."""
        self.epoch = int(epoch)

    def publish_dense(self, table_id: int, delta: np.ndarray, option) -> None:
        delta = np.ascontiguousarray(delta)
        # bus.publish span: its context rides the wire header, so every
        # consumer's bus.apply span joins THIS trace (the one place a
        # single trace id crosses the process boundary)
        sp = trace.start_span("bus.publish", table_id=table_id,
                              wire="dense")
        blobs = self._filter_for(delta.dtype).filter_in([delta.ravel()])
        payload = _serialize(DENSE, table_id, option, blobs, sp.context,
                             epoch=self.epoch)
        self._publish(payload)
        sp.end(bytes=len(payload))

    def publish_keyed(self, table_id: int, ids: np.ndarray,
                      vals: np.ndarray, option) -> None:
        sp = trace.start_span("bus.publish", table_id=table_id,
                              wire="keyed")
        payload = _serialize(KEYED, table_id, option, [ids, vals],
                             sp.context, epoch=self.epoch)
        self._publish(payload)
        sp.end(bytes=len(payload), rows=int(ids.shape[0]))

    def publish_state(self, table) -> None:
        """Publish the ABSOLUTE table value (the fenced restart's rebase
        record): consumers install it via set-state + exact version
        rather than folding a delta, so a replica that missed the dead
        incarnation's tail re-converges in one record."""
        arrays, version = table._state_arrays()
        sp = trace.start_span("bus.publish", table_id=table.table_id,
                              wire="state")
        payload = _serialize(STATE, table.table_id, None, arrays,
                             sp.context, epoch=self.epoch,
                             version=version)
        self._publish(payload)
        sp.end(bytes=len(payload), version=version)

    def publish_delta(self, table, delta: np.ndarray, option) -> None:
        """Publish a whole-table delta in its cheapest sound representation.

        Row tables on the commutative ``default`` updater publish only the
        TOUCHED rows (keyed) — the native form of a sparse update, and the
        path that keeps records proportional to movement rather than table
        size (VERDICT r2 item 3). Dense is kept when (a) the updater is
        stateful (zero rows still decay momentum/adagad state, so skipping
        them would change semantics) or (b) nearly every row moved, where
        keyed would just add the id column on top of the dense payload.
        """
        delta = np.asarray(delta)
        if (delta.ndim == 2 and table.updater.name == "default"
                and hasattr(table, "num_col")):
            # .any(axis=1) reduces without the table-sized `!= 0` temporary
            rows = np.flatnonzero(delta.any(axis=1))
            if rows.size <= 0.9 * delta.shape[0]:
                if rows.size:
                    self.publish_keyed(table.table_id, rows.astype(np.int32),
                                       delta[rows], option)
                return
        self.publish_dense(table.table_id, delta, option)

    def publish_kv(self, table_id: int, keys: np.ndarray,
                   vals: np.ndarray) -> None:
        sp = trace.start_span("bus.publish", table_id=table_id, wire="kv")
        payload = _serialize(KV, table_id, None, [keys, vals], sp.context)
        self._publish(payload)
        sp.end(bytes=len(payload))

    # -- drain (group -> local replica) ------------------------------------
    def _peer_count(self, r: int) -> int:
        try:
            return int(self._client.key_value_try_get(f"mvps/{r}/n"))
        except Exception as exc:
            # Only an absent counter means "no publications yet"; any other
            # transport error must NOT be read as 0 — drain() pins its
            # quiesce frontier on this value, and a swallowed RPC failure
            # would let a barrier pass with peer deltas unapplied.
            if "NOT_FOUND" in str(exc):
                return 0
            raise

    def poll_once(self) -> int:
        """Apply every currently-visible peer delta; returns applied count."""
        applied = 0
        with self._drain_lock:
            for r in range(self._size):
                if r == self._rank or r in self._dead:
                    continue
                n = self._peer_count(r)
                while _consumed[r] < n:
                    seq = _consumed[r]
                    key = f"mvps/{r}/{seq}"
                    if self._p2p is not None:
                        data = self._p2p.pop_ready(r, seq)
                        if data is None:
                            break      # frame still in flight; next poll
                    else:
                        data = self._client.blocking_key_value_get_bytes(
                            key, 60_000)
                    self._consume(r, data)
                    with _state_lock:
                        _consumed[r] = seq + 1
                    applied += 1
                    # consumers only ACK; the publisher GCs payload + ack
                    # once its backpressure frontier passes (deleting the
                    # payload here would recursively delete the nested ack
                    # key — directory semantics — and wedge the publisher)
                    self._client.key_value_increment(f"{key}/a", 1)
        return applied

    def _consume(self, publisher: int, data: bytes) -> None:
        """Reassemble PART records (consecutive seqs from one publisher)
        and apply each completed logical record exactly once."""
        if data[:1] == bytes([PART]) and len(data) >= _PART_HEADER.size:
            _, idx, n_parts = _PART_HEADER.unpack(data[:_PART_HEADER.size])
            buf = self._parts.setdefault(publisher, [])
            if idx != len(buf):
                # parts ride consecutive sequence numbers consumed in order,
                # so an out-of-position part means the transport ordering
                # invariant itself broke — applying around it would silently
                # diverge this replica (the record is gone but peers count
                # it as delivered). Fail loudly instead.
                Log.fatal(f"async PS: part {idx}/{n_parts} from rank "
                          f"{publisher} arrived at position {len(buf)} — "
                          "consecutive-seq reassembly invariant broken")
            buf.append(data[_PART_HEADER.size:])
            if len(buf) < n_parts:
                return
            data = b"".join(buf)
            self._parts[publisher] = []
        self._apply(data)

    def _drain_loop(self) -> None:
        from ..log import FatalError

        while not self._stop.wait(self._interval):
            try:
                self.poll_once()
            except FatalError:
                # invariant violations (e.g. PART reassembly order) are
                # already logged at critical; stop consuming so drain()'s
                # quiesce wedges loudly instead of passing with a missing
                # delta
                raise
            except Exception as exc:   # pragma: no cover - transport races
                if not self._stop.is_set():
                    Log.error("async PS drain error: %s", exc)

    def _apply(self, data: bytes) -> None:
        (kind, table_id, option, arrays, send_ts, ctx, epoch,
         version) = _deserialize(data)
        # the carried context makes this apply a CHILD of the remote
        # publish span: one trace id covers the cross-process hop, so a
        # merged view shows publish->apply as one causal chain
        sp = (trace.start_span("bus.apply", parent=ctx, table_id=table_id)
              if ctx is not None else trace.NULL_SPAN)
        if not self._fence.admit(epoch):
            # a lower-incarnation (zombie) trainer's record: folding it
            # would walk a converged replica backwards — reject, count,
            # and keep the stream position (the record IS consumed)
            Log.error("async PS: rejected epoch-%d record for table %d "
                      "(fence at epoch %d)", epoch, table_id,
                      self._fence.epoch)
            sp.end(error="epoch_fenced", epoch=epoch)
            return
        self._mon_apply.begin()
        table = self._sess.table(table_id)
        if kind == DENSE:
            # the publisher staged the delta in the table dtype, so the
            # receiving replica's table dtype IS the wire value dtype
            flat = self._filter_for(table.dtype).filter_out(arrays)[0]
            table._apply_remote_dense(flat.reshape(table.shape), option)
        elif kind == KEYED:
            table._apply_remote_keyed(arrays[0], arrays[1], option)
        elif kind == KV:
            table._apply_remote_kv(arrays[0], arrays[1])
        elif kind == STATE:
            # fenced-restart rebase: install the absolute value at the
            # publisher's exact (version, epoch)
            table._install_state_arrays(arrays, version, epoch)
        else:
            Log.error("async PS: unknown record kind %d", kind)
        self._mon_apply.end()
        self.apply_bytes += len(data)
        # publish->apply latency from the carried send timestamp (same-host
        # clocks in tests; cross-host numbers inherit NTP skew)
        wire_lat_ms = max(0.0, (time.time() - send_ts) * 1e3)
        self._mon_lat.record(wire_lat_ms)
        sp.end(bytes=len(data), wire_latency_ms=round(wire_lat_ms, 3))

    def stats(self) -> dict:
        """Measured bus rates since this bus started (both directions)."""
        dt = max(time.perf_counter() - self._t0, 1e-9)
        return {
            "published": _published,
            "pub_bytes": self.pub_bytes,
            "apply_bytes": self.apply_bytes,
            "pub_mb_s": self.pub_bytes / 1e6 / dt,
            "apply_mb_s": self.apply_bytes / 1e6 / dt,
            "inflight_bytes": self._inflight_bytes,
            "apply_lat_avg_ms": self._mon_lat.average_ms(),
            "epoch": self.epoch,
            "fence_epoch": self._fence.epoch,
            "fence_rejections": self._fence.rejections,
        }

    # -- failure handling --------------------------------------------------
    def mark_dead(self, ranks) -> None:
        """FailureDetector action hook: survivors keep training.

        A declared-dead rank is (a) dropped from the ack quorum, releasing
        any backpressure debt its silence pinned, (b) dropped from the
        drain/poll targets, and (c) cut from the p2p fan-out. The
        declaration is published to the KV so peers that haven't noticed
        yet converge on the same live set before the next drain barrier.

        Deliberately does NOT take ``_pub_lock``: a backpressure-blocked
        publisher HOLDS that lock, and this call is what lets its next
        ``_reap_acks`` poll pass. Consistency note (documented contract):
        the dead rank's final in-flight records may have reached some
        survivors and not others — bounded by the in-flight watermark,
        exactly the records the reference's async PS also loses when a
        worker dies mid-send (``src/server.cpp:36-60`` has no liveness
        coupling either).
        """
        ranks = {int(r) for r in ranks} - {self._rank}
        new = ranks - self._dead
        if not new:
            return
        self._dead |= new
        for r in new:
            try:
                self._client.key_value_set(f"mvps/dead/{r}", "1",
                                           allow_overwrite=True)
            except Exception:
                pass    # best effort; peers' own detectors still fire
        if self._p2p is not None:
            self._p2p.mark_dead(new)
        Log.error("async PS: rank(s) %s declared dead; continuing with "
                  "%d live peer(s)", sorted(new),
                  self._size - 1 - len(self._dead))

    def _live_ranks(self):
        """Union the KV dead-declarations into the local dead set (so all
        survivors enter the drain barrier with the same participant list)
        and return the live ranks, self included. The KV probe only runs
        in survivor mode (`-failure_timeout_s` > 0) — without a watchdog
        nothing can ever be declared dead, and the probe would add P-1
        RPCs to every quiesce for nothing."""
        if self._survivor_mode:
            for r in range(self._size):
                if r != self._rank and r not in self._dead:
                    try:
                        self._client.key_value_try_get(f"mvps/dead/{r}")
                    except Exception:
                        continue  # NOT_FOUND (or unreadable) -> assume live
                    self.mark_dead({r})
        return [r for r in range(self._size) if r not in self._dead]

    def _live_barrier(self, name: str, live):
        """Rendezvous among ``live``, robust to a peer dying MID-barrier.

        A barrier whose participant list names a peer that dies before
        arriving can never complete — and the death is only DECLARED
        after the watchdog window, typically while survivors already
        wait. In survivor mode each attempt therefore uses a fresh
        single-use id and a watchdog-scaled timeout; on failure the
        live list is re-unioned from the KV declarations and the
        barrier retried. Converges because every live rank spends the
        same per-attempt budget (entry offsets are scheduling jitter,
        far below it), so live ranks meet at the first attempt where
        their lists agree. Returns the (possibly reduced) live list.
        """
        if not self._survivor_mode:
            self._client.wait_at_barrier(name, 600_000, live)
            return live
        deadline = time.monotonic() + 600.0
        per_try_ms = int(max(
            2.0 * float(config.get_flag("failure_timeout_s")), 5.0) * 1000)
        attempt = 0
        win_key = f"{name}/win"
        while True:
            attempt += 1
            try:
                self._client.wait_at_barrier(
                    f"{name}/t{attempt}", per_try_ms, live)
                # Publish the COMPLETED attempt + its participant list. A
                # straggler whose own wait on this attempt timed out
                # client-side just as its arrival completed the barrier
                # server-side (arrival skew ~ the per-try budget, e.g. a
                # long jit compile) would otherwise retry t{attempt+1}
                # where nobody will ever arrive, desyncing the counters
                # permanently until the 600-s Log.fatal.
                try:
                    self._client.key_value_set(
                        win_key,
                        f"{attempt}:{','.join(map(str, live))}",
                        allow_overwrite=True)
                except Exception:
                    pass   # best effort; stragglers fall back to retrying
                return live
            except Exception as exc:
                won = None
                try:
                    won = str(self._client.key_value_try_get(win_key))
                except Exception:
                    pass   # NOT_FOUND (or unreadable): no winner yet
                if won is not None:
                    _, _, members = won.partition(":")
                    winners = {int(r) for r in members.split(",") if r}
                    if self._rank in winners:
                        # the group completed an attempt COUNTING this
                        # rank — its arrival was registered even though
                        # its own wait raised; join the winning attempt
                        # instead of retrying one nobody else will enter
                        Log.info("async PS: barrier %s completed (%s) "
                                 "while this rank's wait timed out; "
                                 "joining the winning attempt", name, won)
                        return live
                    # completed WITHOUT this rank: the survivors dropped
                    # it from their live list (declared dead). Joining
                    # silently would fake synchronization — keep
                    # retrying/re-unioning so the exclusion surfaces in
                    # the timeout diagnostics instead.
                    Log.error("async PS: barrier %s completed excluding "
                              "this rank (%s) — survivors declared it "
                              "dead", name, won)
                if time.monotonic() > deadline:
                    Log.fatal(f"async PS live barrier {name} failed after "
                              f"600 s: {exc}")
                live = [r for r in self._live_ranks() if r in live]

    # -- quiesce -----------------------------------------------------------
    def drain(self, tag: str = "drain") -> None:
        """Collective flush among LIVE processes: after it returns on all
        of them, every delta a live process published before any live
        process entered is applied on every live process.

        Protocol: barrier A pins the publication frontier (everything
        published-before-entry is visible); each process then consumes up to
        the pinned counters; barrier B confirms group-wide completion.
        Both barriers name the live participant set, so survivors of a
        declared-dead peer still quiesce (the declaration is read from the
        KV union first — see :meth:`_live_ranks`).
        """
        global _drain_round
        with _state_lock:
            _drain_round += 1
            rnd = _drain_round
        live = self._live_ranks()
        live = self._live_barrier(f"mvps/{tag}/{rnd}/a", live)
        targets = {r: self._peer_count(r)
                   for r in live if r != self._rank}
        # p2p frames are not durable like KV payloads, so the wait is
        # deadlined: a stream that stops making progress for as long as
        # the KV path's blocking-get timeout is a transport failure, not
        # a slow peer — fail loudly instead of spinning forever
        last_progress = time.monotonic()
        while True:
            # a peer declared dead MID-drain leaves the target set (its
            # unreceived tail can never arrive; waiting would hang forever)
            targets = {r: n for r, n in targets.items()
                       if r not in self._dead}
            missing = {r: n - _consumed[r] for r, n in targets.items()
                       if _consumed[r] < n}
            if not missing:
                break
            if self.poll_once() == 0:
                if time.monotonic() - last_progress > 60.0:
                    Log.fatal(
                        f"async PS drain stalled 60 s waiting on records "
                        f"{missing} (rank->count); peer dead or transport "
                        f"broken — see parallel.FailureDetector")
                time.sleep(0.002)      # p2p frames may still be in flight
            else:
                last_progress = time.monotonic()
        # recompute the participant list: a peer that died MID-drain must
        # not be named in barrier B (it will never arrive). _live_ranks
        # re-unions the KV declarations so survivors converge on the list.
        live = [r for r in self._live_ranks() if r in live]
        self._live_barrier(f"mvps/{tag}/{rnd}/b", live)
        # every own record is now applied (and acked) everywhere live:
        # collect the ack keys and release any backpressure debt
        with self._pub_lock:
            self._reap_acks()


_drain_round = 0
