"""Wire compression for sparse-ish payloads (the reference ``SparseFilter``).

TPU-native re-expression of ``include/multiverso/util/quantization_util.h``
in the Multiverso reference (``SparseFilter`` at ``:25``, ``TryCompress`` at
``:95``, ``DeCompress`` at ``:139``): when more than half of a payload's
values are within ``clip`` of zero, rewrite it as (index, value) pairs before
it crosses a slow link; otherwise ship it dense. On TPU the *device* data
plane never needs this — sharded tables ride ICI and sparse row traffic is
"send only touched rows" by construction (``tables/matrix_table.py``) — so
this filter serves the **host/DCN** paths: cross-process delta aggregation in
sync mode, checkpoint streams, and the C-ABI bridge, where payloads are host
ndarrays ("blobs") and bandwidth is the reference's motivation unchanged.

Blob model: a payload is a list of 1-D contiguous ndarrays. ``filter_in``
compresses each eligible blob and appends one trailing **size-info** blob
(int64; original element count per blob, or -1 when shipped dense — the
reference's extra size blob). ``filter_out`` inverts it. Like the reference
(a ``SparseFilter<data_t, index_t>`` template) a filter instance is typed:
``dtype`` for values, int32 for indices.

The reference also declares a never-implemented ``OneBitsFilter``
(``quantization_util.h:160-161``); we do not reproduce dead code.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .log import Log

_INDEX_DTYPE = np.dtype(np.int32)


class SparseFilter:
    """Sparsity-gated (index, value) wire compression.

    ``clip`` — magnitude at or below which a value is treated as zero (the
    reference's lossy clip threshold). ``skip_option_blob`` — when True the
    final blob of a payload (an Add/GetOption) passes through untouched,
    mirroring ``skip_option_blob_`` in the reference.
    """

    def __init__(self, clip: float = 0.0, skip_option_blob: bool = False,
                 dtype=np.float32) -> None:
        self.clip = float(clip)
        self.skip_option_blob = bool(skip_option_blob)
        self.dtype = np.dtype(dtype)

    # -- single-blob primitives (``TryCompress`` / ``DeCompress``) ---------
    def try_compress(self, blob: np.ndarray) -> Optional[np.ndarray]:
        """Return the compressed pair buffer, or None when the blob is too
        dense to profit (at most half the values are within ``clip``)."""
        flat = np.ascontiguousarray(blob, dtype=self.dtype).ravel()
        keep = np.abs(flat) > self.clip
        n_keep = int(keep.sum())
        # Profitability is measured in wire bytes, not element counts: a pair
        # costs index+value bytes (for float32 this reduces to the
        # reference's ">50% of values small" rule).
        pair_bytes = _INDEX_DTYPE.itemsize + self.dtype.itemsize
        if n_keep * pair_bytes >= flat.nbytes:
            return None
        indices = np.nonzero(keep)[0].astype(_INDEX_DTYPE)
        values = flat[keep]
        out = np.empty(indices.nbytes + values.nbytes, np.uint8)
        out[: indices.nbytes] = indices.view(np.uint8)
        out[indices.nbytes:] = values.view(np.uint8)
        return out

    def decompress(self, comp: np.ndarray, count: int) -> np.ndarray:
        """Inverse of ``try_compress`` given the original element count."""
        pair_bytes = _INDEX_DTYPE.itemsize + self.dtype.itemsize
        if comp.nbytes % pair_bytes:
            Log.fatal(
                f"corrupt compressed blob: {comp.nbytes} bytes not a multiple "
                f"of pair size {pair_bytes}")
        n_pairs = comp.nbytes // pair_bytes
        buf = np.ascontiguousarray(comp).view(np.uint8)
        indices = buf[: n_pairs * _INDEX_DTYPE.itemsize].view(_INDEX_DTYPE)
        values = buf[n_pairs * _INDEX_DTYPE.itemsize:].view(self.dtype)
        if n_pairs and (indices.min() < 0 or indices.max() >= count):
            Log.fatal(
                f"corrupt compressed blob: index out of range for count {count}")
        out = np.zeros(count, self.dtype)
        out[indices] = values
        return out

    # -- payload API (``FilterIn`` / ``FilterOut``) ------------------------
    def filter_in(self, blobs: Sequence[np.ndarray]) -> List[np.ndarray]:
        """Compress a payload; appends the trailing size-info blob."""
        out: List[np.ndarray] = []
        size_info = np.empty(len(blobs), np.int64)
        for i, blob in enumerate(blobs):
            if self.skip_option_blob and i == len(blobs) - 1:
                out.append(np.asarray(blob))
                size_info[i] = -1
                continue
            comp = self.try_compress(blob)
            if comp is None:
                out.append(np.asarray(blob))
                size_info[i] = -1
            else:
                out.append(comp)
                size_info[i] = np.asarray(blob).size
        out.append(size_info)
        return out

    def filter_out(self, blobs: Sequence[np.ndarray]) -> List[np.ndarray]:
        """Invert ``filter_in`` (drops the size-info blob)."""
        if not blobs:
            return []
        size_info = np.asarray(blobs[-1], np.int64)
        payload = blobs[:-1]
        if size_info.size != len(payload):
            Log.fatal(
                f"size-info blob has {size_info.size} entries for "
                f"{len(payload)} payload blobs")
        out: List[np.ndarray] = []
        for blob, count in zip(payload, size_info):
            if count < 0:
                out.append(np.asarray(blob))
            else:
                out.append(self.decompress(np.asarray(blob), int(count)))
        return out

    def compressed_ratio(self, blobs: Sequence[np.ndarray],
                         filtered: Sequence[np.ndarray]) -> float:
        """Wire bytes after / before (diagnostic)."""
        before = sum(np.asarray(b).nbytes for b in blobs)
        after = sum(np.asarray(b).nbytes for b in filtered)
        return after / max(before, 1)


# -- int8 symmetric quantization ----------------------------------------------
#
# The byte-budget levers the serving stack shares (docs/SERVING.md
# "Quantized KV & params"): symmetric max-abs int8 with an fp32 scale.
# These are the HOST-side halves — param-snapshot pins
# (serving/snapshot.py) and the param-plane wire codec
# (serving/param_plane.py). The paged KV pools' traced
# quantize-on-write / dequantize-on-gather forms live next to the
# kernels in models/transformer.py (scales are jit operands there, never
# host values).

INT8_QMAX = 127.0


def quantize_int8(arr: np.ndarray, axis: Optional[int] = None):
    """Symmetric max-abs int8: ``(q int8, scale fp32)``.

    ``axis=None`` -> one per-tensor scale (shape ``(1,)`` — an ndarray,
    so it rides any wire/pytree path uniformly); an int ``axis`` ->
    per-slice scales with ``keepdims`` (the per-column form for
    Megatron-split matrices: the scale broadcasts over the quantized
    axis AND keeps the tensor's rank, so a sharding spec written for
    the weight applies to its scale unchanged). A zero slice gets
    scale 0 and dequantizes to exact zeros."""
    arr = np.asarray(arr)
    a = arr.astype(np.float32, copy=False)
    if axis is None:
        amax = np.max(np.abs(a), initial=0.0)
        scale = np.asarray([amax / INT8_QMAX], np.float32)
        safe = scale[0] if scale[0] > 0 else 1.0
        q = np.clip(np.rint(a / safe), -INT8_QMAX, INT8_QMAX)
        return q.astype(np.int8), scale
    amax = np.max(np.abs(a), axis=axis, keepdims=True)
    scale = (amax / INT8_QMAX).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.rint(a / safe), -INT8_QMAX, INT8_QMAX)
    return q.astype(np.int8), scale


def dequantize_int8(q: np.ndarray, scale: np.ndarray,
                    dtype=np.float32) -> np.ndarray:
    """Inverse of :func:`quantize_int8` (scale broadcasts; a ``(1,)``
    per-tensor scale multiplies through)."""
    q = np.asarray(q, np.float32)
    scale = np.asarray(scale, np.float32)
    if scale.size == 1:
        return (q * scale.reshape(())).astype(dtype)
    return (q * scale).astype(dtype)
