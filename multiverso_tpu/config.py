"""Typed flag/config registry.

TPU-native equivalent of the reference flag system
(``include/multiverso/util/configure.h:67-110``,
``src/util/configure.cpp:9-44`` in the Multiverso reference): a process-global
typed registry populated by ``define_*`` declarations, a command-line parser
consuming ``-key=value`` tokens (compacting argv in place), and programmatic
``set_flag`` (the reference's ``SetCMDFlag``).

Unlike the reference there is one registry keyed by name (not one singleton per
type); a flag's declared type is enforced on assignment with the same
string -> int -> bool -> float coercion ladder the reference applies when
parsing CLI text.
"""

from __future__ import annotations

import threading
from .analysis import lockwatch
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


class FlagError(KeyError):
    """Unknown flag or type mismatch."""


def _parse_bool(text: str) -> bool:
    t = text.strip().lower()
    if t in ("true", "1", "yes", "on"):
        return True
    if t in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"not a bool: {text!r}")


_COERCERS: Dict[type, Callable[[str], Any]] = {
    int: int,
    float: float,
    bool: _parse_bool,
    str: str,
}


@dataclass
class _Flag:
    name: str
    type: type
    value: Any
    description: str


class FlagRegister:
    """Process-global flag registry (one instance per process)."""

    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}
        self._lock = lockwatch.rlock("config.FlagRegister._lock")

    # -- declaration ------------------------------------------------------
    def define(self, name: str, type_: type, default: Any, description: str = "") -> None:
        if type_ not in _COERCERS:
            raise TypeError(f"unsupported flag type {type_!r}")
        with self._lock:
            if name in self._flags:
                # re-definition: keep the current value WITHOUT re-running
                # the coercer — the default may no longer coerce, and the
                # original contract never touched it on this path
                if self._flags[name].type is not type_:
                    raise FlagError(f"flag {name!r} redefined with different type")
                return
        # coerce OUTSIDE the registry lock: type_ is caller-supplied code
        # (locklint LK202 callback-under-lock), and a default whose
        # coercion raises must not do so while holding the lock
        value = type_(default)
        with self._lock:
            if name in self._flags:
                # Re-definition with identical type keeps the current value
                # (module reloads in tests); type conflict is an error.
                if self._flags[name].type is not type_:
                    raise FlagError(f"flag {name!r} redefined with different type")
                return
            self._flags[name] = _Flag(name, type_, value, description)

    # -- access -----------------------------------------------------------
    def get(self, name: str) -> Any:
        with self._lock:
            try:
                return self._flags[name].value
            except KeyError:
                raise FlagError(f"unknown flag {name!r}") from None

    def set(self, name: str, value: Any) -> None:
        """Programmatic set; accepts the declared type or coercible text."""
        with self._lock:
            try:
                flag = self._flags[name]
            except KeyError:
                raise FlagError(f"unknown flag {name!r}") from None
            if isinstance(value, str) and flag.type is not str:
                try:
                    value = _COERCERS[flag.type](value)
                except ValueError as exc:
                    raise FlagError(
                        f"flag {name!r}: cannot coerce {value!r} to {flag.type.__name__}"
                    ) from exc
            if flag.type is float and isinstance(value, int):
                value = float(value)
            if not isinstance(value, flag.type) or (
                flag.type is not bool and isinstance(value, bool)
            ):
                raise FlagError(
                    f"flag {name!r} expects {flag.type.__name__}, got {type(value).__name__}"
                )
            flag.value = value

    def known(self, name: str) -> bool:
        with self._lock:
            return name in self._flags

    def items(self) -> Dict[str, Any]:
        with self._lock:
            return {k: f.value for k, f in self._flags.items()}

    def describe(self) -> str:
        with self._lock:
            lines = [
                f"-{f.name}={f.value!r}  ({f.type.__name__}) {f.description}"
                for f in sorted(self._flags.values(), key=lambda f: f.name)
            ]
        return "\n".join(lines)

    # -- CLI --------------------------------------------------------------
    def parse_cmd_flags(self, argv: Optional[List[str]] = None) -> List[str]:
        """Consume ``-key=value`` / ``--key=value`` tokens from argv.

        Returns the remaining (unconsumed) argv, mirroring the reference's
        in-place argv compaction (``src/util/configure.cpp:9-44``). Unknown
        keys are left in argv untouched (apps layer their own config on top).
        """
        if argv is None:
            return []
        rest: List[str] = []
        for token in argv:
            body = None
            if token.startswith("--"):
                body = token[2:]
            elif token.startswith("-"):
                body = token[1:]
            if body and "=" in body:
                key, _, text = body.partition("=")
                if self.known(key):
                    flag_type = self._flags[key].type
                    try:
                        self.set(key, _COERCERS[flag_type](text) if flag_type is not str else text)
                        continue
                    except (ValueError, FlagError):
                        pass  # fall through: keep token for the app
            rest.append(token)
        return rest

    def reset(self) -> None:
        """Drop all flags (test helper)."""
        with self._lock:
            self._flags.clear()


_REGISTRY = FlagRegister()


# -- module-level API (mirrors MV_DEFINE_* / MV_GetCMDFlag / MV_SetCMDFlag) --

def define_int(name: str, default: int, description: str = "") -> None:
    _REGISTRY.define(name, int, default, description)


def define_float(name: str, default: float, description: str = "") -> None:
    _REGISTRY.define(name, float, default, description)


def define_bool(name: str, default: bool, description: str = "") -> None:
    _REGISTRY.define(name, bool, default, description)


def define_string(name: str, default: str, description: str = "") -> None:
    _REGISTRY.define(name, str, default, description)


def get_flag(name: str) -> Any:
    return _REGISTRY.get(name)


def set_flag(name: str, value: Any) -> None:
    _REGISTRY.set(name, value)


def parse_cmd_flags(argv: Optional[List[str]] = None) -> List[str]:
    return _REGISTRY.parse_cmd_flags(argv)


def registry() -> FlagRegister:
    return _REGISTRY


# -- core framework flags (reference: src/zoo.cpp:23-24, src/server.cpp:20-21,
# src/updater/updater.cpp:11-12, src/util/allocator.cpp:10,152) --------------

define_string("ps_role", "default", "process role: none|worker|server|default")
define_bool("ma", False, "model-averaging mode (no parameter tables; aggregate only)")
define_bool("sync", False, "synchronous (BSP) parameter-server semantics")
define_float("backup_worker_ratio", 0.0, "reserved: fraction of backup workers")
define_string("updater_type", "default", "server-side updater: default|sgd|adagrad|momentum_sgd")
define_int("omp_threads", 4, "host-side worker threads for async apply loops")
define_string("mesh_shape", "", "override logical mesh, e.g. '4,2' for (worker,server)")
define_int("sync_frequency", 1, "rounds between parameter synchronisations")
define_int("async_poll_ms", 20,
           "async PS: drain-thread poll interval (bounds peer-delta staleness)")
define_int("ssp_staleness", -1,
           "async PS: SSP round gap bound (-1 = unbounded/plain async)")
define_int("async_max_record_kb", 1024,
           "async PS: wire records larger than this split into parts "
           "(coordination-service gRPC message-size safety)")
define_int("async_max_inflight_mb", 64,
           "async PS: publisher backpressure watermark — publish blocks "
           "while un-acked published bytes exceed this")
define_bool("async_p2p", True,
            "async PS: payload bytes ride direct per-pair TCP sockets "
            "(the reference's p2p Isend/DEALER data plane); false = "
            "funnel payloads through the coordination-service KV")
define_float("failure_timeout_s", 0.0,
             "declare a peer dead after this many seconds of missed "
             "heartbeats and keep training without it (async bus "
             "survivor mode); 0 disables the watchdog")
define_int("prefill_token_budget", 32,
           "decode engine: per-iteration chunked-prefill token budget "
           "(Sarathi-style stall-free admission — inter-token latency is "
           "bounded by one budget-sized chunk regardless of arriving "
           "prompt length); 0 = monolithic whole-prompt admission")
define_int("kv_block_size", 16,
           "decode engine: paged KV cache block size in token positions "
           "(vLLM-style block pool — per-slot block tables ride the jitted "
           "step as traced data, so capacity, not slot geometry, bounds "
           "concurrency); 0 = contiguous per-slot strips")
define_int("kv_pool_blocks", 0,
           "decode engine: usable KV pool blocks (+1 scratch block is "
           "added); 0 = auto-size to the contiguous-equivalent capacity "
           "slots * ceil((max_prompt + max_new) / kv_block_size). "
           "serving.block_pool.blocks_for_bytes converts a device-bytes "
           "budget into this count")
define_int("decode_tp", 1,
           "decode engine: tensor-parallel width of the decode mesh — "
           "attention heads and the MLP hidden dim shard over a 'tp' axis "
           "spanning the first decode_tp devices, the paged K/V pools "
           "shard over the head slice of D, params reshard onto the mesh "
           "once per snapshot pin (serving.snapshot.shard_for_decode), and "
           "every per-token program compiles once against matched "
           "in/out_shardings (no spmd repartition in the hot loop). "
           "1 = single-device replicated decode (replicate_for_decode, "
           "the pre-PR 9 path). Needs kv_block_size > 0, "
           "decode_tp | n_heads and decode_tp | d_ff")
define_string("kv_quant", "none",
              "decode engine: paged KV cache storage precision — 'int8' "
              "stores both pools as int8 with a per-(layer, block) fp32 "
              "scale array riding the jitted programs as traced data "
              "(quantize-on-write, dequantize-on-gather; one compiled "
              "trace per engine config exactly as fp32), so the same "
              "pool-byte budget holds ~4x the blocks "
              "(block_pool.kv_bytes_per_block reports the real quantized "
              "+ scales footprint). 'none' = fp32 pools, bit-identical "
              "to the pre-quantization engine. Needs kv_block_size > 0; "
              "quality face: argmax-match rate vs the fp32 oracle "
              "(docs/SERVING.md 'Quantized KV & params')")
define_string("decode_param_quant", "none",
              "decode engine: pinned param snapshot precision — 'int8' "
              "quantizes each snapshot leaf symmetric per-tensor (per-"
              "column for matrices) ON THE HOST once per pinned version, "
              "shrinking the per-version pin copy (the one cross-mesh "
              "device_put) and per-device param bytes ~4x; dequant is "
              "folded into the pre-partitioned decode programs at "
              "compile time, so pin_copies memoization and "
              "decode_step_retraces == 0 survive. 'none' = fp32 pins")
define_bool("param_wire_compress", True,
            "param plane: route publish_delta/publish_keyed payloads "
            "through the reference SparseFilter (quantization.py) before "
            "the mvparam wire — sparse-ish deltas ship as (index, value) "
            "pairs, dense ones pass through untouched (lossless either "
            "way; subscribers decode transparently by payload shape). "
            "publish_bytes / wire_compressed_ratio land in publisher "
            "stats (docs/OBSERVABILITY.md)")
define_string("param_wire_quant", "none",
              "param plane: optional LOSSY int8 delta codec — 'int8' "
              "ships publish_delta/publish_keyed values as int8 with one "
              "fp32 per-record scale (~4x fewer wire bytes on top of "
              "-param_wire_compress; subscribers dequantize "
              "transparently). 'none' = exact values (default: the "
              "publish stream stays bit-exact)")
define_bool("prefix_cache", True,
            "decode engine: content-addressed KV block reuse over the "
            "paged pool — full blocks get a hash-chained identity, "
            "admission splices the longest cached prefix into the new "
            "sequence's block table (refcounted, copy-on-write) and "
            "prefills only the remainder; needs kv_block_size > 0 and "
            "prefill_token_budget > 0. false = every prompt prefills "
            "from token zero (the A/B baseline)")
define_bool("prefill_sp", False,
            "decode engine: sequence-parallel long-prompt prefill over "
            "the decode mesh — prompts at/above -prefill_sp_threshold "
            "prefill in prefill_token_budget * decode_tp token chunks "
            "with the chunk's rows sharded over the tp axis (one "
            "budget's worth of rows per device per iteration, so a long "
            "document admits in decode_tp x fewer iterations while the "
            "per-iteration ITL bound holds); shorter prompts keep the "
            "single-lane chunk program bit-for-bit. Needs kv_block_size "
            "> 0 and prefill_token_budget > 0; incompatible with "
            "kv_quant=int8 (docs/SERVING.md 'Long-context prefill')")
define_string("prefill_sp_backend", "ring",
              "decode engine: seqpar prefill collective schedule — "
              "'ring' rotates K/V shards with decode_tp - 1 ppermute "
              "steps (no head-count constraint; needs max_prompt + "
              "max_new divisible by decode_tp), 'ulysses' all_to_all-"
              "reshards the chunk rows onto the paged pool's native "
              "head shard (2 collectives total; needs n_heads "
              "divisible by decode_tp — already required by decode_tp "
              "itself)")
define_int("prefill_sp_threshold", 256,
           "decode engine: minimum prompt length (tokens) routed "
           "through the sequence-parallel prefill chunk program; "
           "shorter prompts take the single-lane prefill_chunk_paged "
           "path, whose outputs (and compiled trace) are exactly "
           "today's")
define_int("spec_k", 0,
           "decode engine: speculative decoding draft length — up to "
           "spec_k n-gram prompt-lookup drafts per live slot are scored "
           "by ONE fused verify step per iteration (fixed-K window "
           "[slots, spec_k + 1]; accepted length handled as traced data), "
           "emitting up to spec_k + 1 tokens per iteration with outputs "
           "token-identical to plain greedy decode. 0 = off (today's "
           "one-token path, bit-for-bit). Needs kv_block_size > 0")
define_bool("preempt", True,
            "decode engine: overload-graceful serving — OPTIMISTIC "
            "paged-KV admission (reserve prompt blocks only; the "
            "generation grows its reservation block-by-block at decode "
            "time) with preemption on pool exhaustion: the lowest-"
            "priority/youngest live sequence releases its blocks, "
            "re-enqueues at the front of its class, and on re-admission "
            "recomputes from prompt + emitted tokens — bit-identical "
            "output, host-side scheduling only (block tables stay "
            "traced data). Anti-livelock: -preempt_budget per request "
            "and a guaranteed-progress floor (the OLDEST live sequence "
            "is never preempted). Needs kv_block_size > 0 and "
            "prefill_token_budget > 0 (silently inert otherwise). "
            "false = the pre-PR worst-case prompt+max_new up-front "
            "reservation (the A/B baseline)")
define_int("preempt_budget", 3,
           "decode engine: max times one request may be preempted; a "
           "request whose budget is spent re-admits PESSIMISTICALLY "
           "(full worst-case reservation, so it can never need growth "
           "or be preempted again) — with the oldest-live floor this "
           "bounds recompute churn and makes preemption livelock-free")
define_int("sched_lookahead", 8,
           "decode engine: bounded admission lookahead past a "
           "block-starved queue head — up to this many younger "
           "requests of the head's class are scanned for one whose "
           "reservation fits right now (a huge request at the head "
           "must not starve small admissible ones). The bypass bound "
           "is GLOBAL: a starved head accumulates one skip per "
           "admission that jumps it (same-lane or other-lane), and at "
           "the bound ALL admission freezes until it fits — freed "
           "blocks then accumulate for it instead of being re-consumed "
           "by other lanes' optimistic admissions. 0 = no same-lane "
           "lookahead (strict FIFO within a class; the global freeze "
           "then engages after one bypass)")
define_bool("wal", False,
            "durable online learning: append every acknowledged LOCAL "
            "table apply to a per-rank write-ahead delta journal "
            "(io/wal.py) under -wal_dir; a restarted trainer replays "
            "records past the newest checkpoint's version watermark to "
            "recover the exact pre-crash table state "
            "(docs/DISTRIBUTED.md 'Durability')")
define_string("wal_dir", "",
              "write-ahead delta journal directory (required when "
              "-wal=true); segments rotate at -wal_segment_mb and are "
              "reaped once a completed checkpoint's watermark covers "
              "them")
define_bool("wal_fsync", False,
            "fsync the journal after every appended record: survives "
            "machine/power failure, not just process death (a killed "
            "process's written-but-unfsynced records already survive "
            "in the page cache); costs one fsync per acknowledged add")
define_int("wal_segment_mb", 64,
           "journal segment rotation size in MB — bounded replay reaps "
           "whole segments older than the newest complete checkpoint")
define_float("params_stale_after_s", 0.0,
             "staleness-aware serving: when the params publish stream "
             "has been silent (no source version move observed) for "
             "this long, replicas keep serving but flag STALE in "
             "health() and the SERVE_PARAMS_AGE gauge; recovery is "
             "automatic when a fenced trainer restart republishes. "
             "0 disables the verdict (the age is still reported)")
define_string("log_file", "", "optional log sink file")
define_string("log_level", "info", "debug|info|error|fatal")
define_bool("trace", False,
            "record host-side request spans (trace.py ring collector); "
            "export Chrome/Perfetto JSON via trace.export_chrome()")
define_int("trace_buffer", 65536,
           "span ring-buffer capacity while -trace is on (oldest spans "
           "are overwritten past it)")
define_string("metrics_jsonl", "",
              "append periodic Dashboard.snapshot() JSON lines (with "
              "interval deltas) to this file while the session runs")
define_float("metrics_interval_s", 10.0,
             "reporting period for -metrics_jsonl")
define_bool("trace_tail", False,
            "tail-based trace sampling: buffer spans per trace id and, at "
            "request completion, retain the full tree only for SLO-breaching "
            "(-trace_slo_ms), errored/shed, or 1-in-N (-trace_head_n) "
            "requests — cheap enough to leave -trace on under load")
define_float("trace_slo_ms", 250.0,
             "tail sampling: retain any trace whose root span exceeded this "
             "latency (the per-request SLO); 0 disables the latency trigger")
define_int("trace_head_n", 64,
           "tail sampling: additionally keep 1 in N completed traces as a "
           "healthy-baseline head sample (0 = keep anomalies only)")
define_bool("flight_recorder", True,
            "decode engine: always-on bounded ring of per-iteration records "
            "(iteration wall, slots, queue depth/age, token split, pool "
            "occupancy, snapshot version) — the black box the watchdog "
            "dumps and tools/engine_timeline.py renders")
define_int("flight_recorder_capacity", 4096,
           "flight-recorder ring capacity in iterations (oldest records "
           "are overwritten past it)")
define_bool("watchdog", True,
            "decode engine: self-diagnosis thread detecting engine stall, "
            "admission-queue age breach, and block-pool accounting drift; "
            "a trip increments WATCHDOG_TRIPS[engine] and dumps a "
            "diagnostic bundle to -debug_dump_dir")
define_float("watchdog_interval_s", 0.25,
             "watchdog poll period (trip latency is at most ~2 polls past "
             "the configured deadline)")
define_float("watchdog_stall_s", 10.0,
             "watchdog: trip when the engine makes no iteration progress "
             "for this long while sequences are live (sized well above "
             "any first-admission jit compile)")
define_float("watchdog_queue_age_s", 30.0,
             "watchdog: trip when the oldest queued request has waited "
             "this long without admission; 0 disables")
define_string("debug_dump_dir", "",
              "watchdog trip bundles (flight-recorder ring + engine stats "
              "+ dashboard snapshot + all-thread stacks) land in per-trip "
              "subdirectories here; empty = trip still counts and logs, "
              "no bundle")
define_float("slo_ttft_ms", 0.0,
             "serving SLO: p99 time-to-first-token target per decoder "
             "(rolling-window burn status in Dashboard.snapshot()); "
             "0 = no SLO registered")
define_float("slo_itl_ms", 0.0,
             "serving SLO: p99 inter-token-latency target per decoder; "
             "0 = no SLO registered")
define_float("slo_lat_ms", 0.0,
             "serving SLO: p99 enqueue-to-reply latency target per "
             "micro-batched model; 0 = no SLO registered")
define_bool("obs_plane", False,
            "fleet observability plane: run a per-node ObsAgent shipping "
            "bounded delta reports (changed Dashboard rows + interval "
            "deltas, log-bucketed histogram exports, per-engine "
            "stats/health/watchdog/flight summaries, tail-kept spans) "
            "over the p2p wire to the rank-0 ObsCollector, which sums "
            "counters exactly, merges histograms into fleet percentiles, "
            "computes fleet SLO burn, flags silent nodes DEGRADED, and "
            "assembles cross-process traces into one Perfetto doc "
            "(docs/OBSERVABILITY.md 'Fleet plane'). Single-process "
            "sessions run agent+collector in loopback")
define_int("obs_report_ms", 1000,
           "fleet plane: per-node report interval; a node silent for 2 "
           "report intervals is flagged DEGRADED by the collector")
define_string("obs_jsonl", "",
              "fleet plane: additionally append every shipped report as "
              "one JSON line here (multi-process sessions suffix .<rank>) "
              "— the offline archive tools/opscenter.py renders the "
              "fleet table / merged Prometheus / merged Perfetto from")
define_int("fleet_heartbeat_ms", 100,
           "serving fleet: replica heartbeat interval — each replica "
           "publishes its engine.health() over the mvserve wire at this "
           "period, and the router flags a replica DEAD after "
           "-fleet_dead_after_s (default 2 heartbeat intervals) of "
           "silence")
define_float("fleet_dead_after_s", 0.0,
             "serving fleet: heartbeat silence before the router marks a "
             "replica DEAD, drains its in-flight requests into the retry "
             "queue, and stops dispatching to it; 0 = 2 heartbeat "
             "intervals")
define_int("fleet_retry_max", 3,
           "serving fleet: per-request re-dispatch budget — a request "
           "whose replica died (or shed it) is replayed from the prompt "
           "on a survivor at most this many times before its future "
           "fails")
define_float("fleet_backoff_ms", 20.0,
             "serving fleet: base retry backoff — re-dispatch attempt n "
             "waits min(cap, base * 2^(n-1)) with jitter before "
             "re-queueing (docs/SERVING.md 'Serving fleet')")
define_float("fleet_backoff_cap_ms", 1000.0,
             "serving fleet: retry backoff cap")
define_int("fleet_shed_depth", 256,
           "serving fleet: aggregate router queue cap (pending + retry + "
           "in-flight) — past it submit sheds OverloadedError("
           "what='fleet') instead of queueing unboundedly")
define_float("fleet_deadline_s", 30.0,
             "serving fleet: default per-request deadline — a request "
             "not completed by then fails with DeadlineExceededError "
             "(per-submit override via deadline_s)")
define_string("chaos", "",
              "fault-injection plan for the serving fleet (serving/"
              "faultinject.py): comma-separated directives, e.g. "
              "'kill_at_request=5' / 'wedge_at_request=3:0.5' / "
              "'wire_delay=0.05:0.5' / 'wire_drop=0.1' / "
              "'slow_heartbeat=4'; empty = healthy")
define_int("chaos_seed", 0,
           "seed for the -chaos plan's probabilistic directives — a "
           "given (spec, seed) pair replays the identical fault "
           "schedule")
define_bool("lockwatch", False,
            "runtime lock-order witness: record per-thread acquisition "
            "order of every framework lock into a global DAG; a cycle "
            "(latent deadlock) increments LOCK_ORDER_VIOLATIONS and "
            "trips engine watchdogs with kind 'lock_order' "
            "(docs/ANALYSIS.md; always on in the test suite)")
define_bool("cost_ledger", False,
            "per-tenant cost attribution (serving/accounting.py): each "
            "decode request carries a host-only resource vector (queue "
            "wait, prefill/decode tokens, KV block-seconds, device step "
            "ms, transfer bytes, preemption recompute) finalized into "
            "per-tenant aggregates + lazy TENANT_*[engine.tenant] "
            "instruments the obs plane merges fleet-wide "
            "(docs/OBSERVABILITY.md 'Tenant accounting'); off = today's "
            "metrics surface byte-for-byte")
define_string("default_tenant", "default",
              "tenant id charged when a request carries none (back-"
              "compat: pre-tenant clients, archived wire payloads)")
define_int("tenant_max", 64,
           "per-engine tenant cardinality cap: past this many distinct "
           "tenant ids, new ones fold into the '~other' bucket — lazy "
           "keyed instruments stay bounded however hostile the ids")
define_float("cost_token", 1.0,
             "cost-weight: units per token computed (prefill + decode); "
             "the 1.0 default makes cost == tokens, deterministic and "
             "reconcilable to the engine counters")
define_float("cost_token_ms", 0.0,
             "cost-weight: units per device-step millisecond attributed "
             "by active-lane share; 0 = device time rides the vector "
             "but is not priced")
define_float("cost_block_byte_s", 0.0,
             "cost-weight: units per KV byte-second of residency "
             "(kv_block_s x the engine's per-block K/V bytes); 0 = "
             "residency rides the vector but is not priced")
define_float("cost_xfer_byte", 0.0,
             "cost-weight: units per raw KV transfer byte that crossed "
             "the engine boundary (fetched out or spliced in)")
