"""Benchmark: WordEmbedding training throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: word2vec skip-gram negative-sampling training pairs/sec at the
reference's NAMED configuration shape — text8: ~71k vocabulary, 200-dim
embeddings (BASELINE.json config 2; the corpus itself is synthesised with a
zipf unigram law because this environment has no network egress, but vocab
size, dimensionality, window, negatives and subsampling all match).
Negative draws are group-shared at G=64 (round 4: the 71k-vocab
real-scale probe — `tools/embedding_quality.py --realscale`, the frozen
bench config with planted clusters — holds full parity at every probed
G through 256 in aggregate AND in every zipf frequency band; the
default is capped at G=64 anyway because the <1% loss guard binds
first: final training loss drifts monotonically off the exact-draw
semantics (+0.8% at G=64, +1.4% at G=128 — the planted-cluster bar
saturates and stops discriminating, so a loss guard caps what the bar
cannot), while device-rate gains past G=64 are +2-3% per doubling
(xprof spans: 10.73M on-device at G=64, 11.06M at G=128) — not worth
double the loss drift. The r3 G=4 cap came from a
deliberately-harsh 332-word probe whose within-group negative
correlation is ~200x denser than text8's. Exact per-pair draws remain
one flag away, `-shared_negatives=0`.) Updates use the capped row-mean
stabiliser
(quality parity in the same doc) because raw summed updates DIVERGE at
64k batch on a zipf corpus — see the auto rule in apps/wordembedding.py.
Config provenance/freeze: BASELINE.md "bench.py config provenance".

``vs_baseline`` is the ratio against 1.0M pairs/sec, the ballpark of the
reference C++ implementation's per-host throughput on its published hardware
(the reference logs the metric but publishes no numbers — BASELINE.md).
The per-op roofline breakdown behind this number is in README.md
("Performance" section) and reproducible with tools/w2v_profile.py.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_BASELINE_PAIRS_PER_SEC = 1_000_000.0

# text8 shape (reference named config): 71,291-word vocab, 200 dims
_VOCAB = 71291
_DIM = 200


def make_corpus(path: str, n_words: int = 4_000_000, vocab: int = _VOCAB,
                seed: int = 5) -> None:
    rng = np.random.default_rng(seed)
    # zipf-ish unigram distribution over a closed vocab; one guaranteed
    # occurrence of every word so the dictionary reaches the full text8
    # vocabulary size
    ranks = np.arange(1, vocab + 1)
    probs = 1.0 / ranks
    probs /= probs.sum()
    words = rng.choice(vocab, size=n_words, p=probs)
    words[:vocab] = rng.permutation(vocab)
    with open(path, "w") as f:
        for i in range(0, n_words, 1000):
            f.write(" ".join(f"w{w}" for w in words[i:i + 1000]) + "\n")


def _probe_backend() -> str:
    """Fail fast when the TPU tunnel is down instead of hanging the
    driver: jax.devices() blocks forever if the axon relay died, so
    probe it in a subprocess with a timeout and fall back to a CLEARLY
    MARKED (non-comparable) CPU run."""
    import subprocess

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        return "cpu"
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=180)
        if probe.returncode == 0:
            return probe.stdout.strip() or "unknown"
    except subprocess.TimeoutExpired:
        pass
    return "unreachable"


def main() -> int:
    backend = _probe_backend()
    degraded = backend in ("unreachable", "cpu")
    if backend == "unreachable":
        print("bench: accelerator backend unreachable (axon tunnel down?); "
              "falling back to a marked CPU run", file=sys.stderr)
        import jax

        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import (Dictionary, encode_corpus,
                                                   subsample_probs)
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    # default = G=64 group-shared draws (parity-proven at the real-scale
    # probe in aggregate and per frequency band; capped at 64 by the
    # loss guard + measured throughput saturation —
    # docs/EMBEDDING_QUALITY.md real-scale section); `-shared_negatives=0`
    # restores exact per-pair reference semantics (parsed by the
    # framework's own flag registry, like every other option).
    mv.define_int("shared_negatives", 64,
                  "share each K-negative draw across G consecutive pairs")

    corpus = "/tmp/mv_bench_corpus_text8.txt"
    if not os.path.exists(corpus):
        make_corpus(corpus)

    rest = mv.init(["bench", "-log_level=error"] + sys.argv[1:])
    # bench has no app-layer flags beyond the registry: anything left over
    # is a typo or a bad value ('-oversample=2' once silently measured the
    # default config). Distinguish the two — a known key lands here when
    # its value failed coercion.
    leftover = [t for t in rest if t != "bench"]
    if leftover:
        from multiverso_tpu import config as _cfg

        for tok in leftover:
            key = tok.lstrip("-").partition("=")[0]
            kind = ("bad value for flag" if _cfg.registry().known(key)
                    else "unknown flag")
            print(f"bench: {kind}: {tok}", file=sys.stderr)
        mv.shutdown()
        return 2
    shared_neg = mv.get_flag("shared_negatives")
    dictionary = Dictionary.build(corpus, min_count=1)
    # TPU-native settings: bf16 embedding tables (f32 score/grad
    # accumulation in the step), 2.5x candidate oversampling so the
    # window/subsample rejection tests don't waste gather/scatter slots,
    # pre-drawn negative pool (contiguous-slice draws instead of random
    # gathers). row_mean (capped, cap=8) is ON: at 64k batch on a zipf
    # corpus the head words collect thousands of colliding pair grads per
    # step and raw summed updates diverge (NaN) — the reference's
    # sequential loop self-limits via sigmoid saturation; the cap plays
    # that role and measures quality parity (docs/EMBEDDING_QUALITY.md;
    # the static expected-count form scores identically and skips the
    # per-step counts scatter). Raw summed semantics remain available
    # (and stable) at small batch.
    cfg = Word2VecConfig(vocab_size=dictionary.vocab_size,
                         embedding_size=_DIM,
                         window=5, negative=5, init_lr=0.025,
                         batch_size=65536,
                         oversample=2.5, neg_pool_size=1 << 22,
                         row_mean_updates=True, row_mean_static=True,
                         shared_negatives=shared_neg)
    import jax.numpy as jnp
    w_in = mv.create_table("matrix", dictionary.vocab_size, _DIM,
                           init_value="random", dtype=jnp.bfloat16)
    w_out = mv.create_table("matrix", dictionary.vocab_size, _DIM,
                            dtype=jnp.bfloat16)
    model = Word2Vec(cfg, w_in, w_out,
                     counts=np.asarray(dictionary.counts, np.float64))
    model.total_words = 10 ** 9

    # device-resident corpus: upload once, sample+train on device
    ids, sent_ids = encode_corpus(corpus, dictionary)
    discard = subsample_probs(np.asarray(dictionary.counts, np.float64),
                              1e-3).astype(np.float32)
    model.load_corpus_chunk(ids, sent_ids, discard)

    steps_per_call = 25 if not degraded else 5
    loss, count = model.train_device_steps(steps_per_call)  # compile
    float(loss)

    # 20 x 25-step dispatches — FROZEN since r3 for cross-round
    # comparability. Do not lengthen the window: past ~32 in-flight
    # dispatches the tunnel's completion path adds ~1.5 s of host-side
    # overhead (40 iters wall-measures 7.7-8.6M while the xprof device
    # spans stay a constant 152.7 ms = 10.7M pairs/s on-device, zero
    # gaps — BASELINE.md "window-length effect").
    iters = 20 if not degraded else 2
    counts = []
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, count = model.train_device_steps(steps_per_call)
        counts.append(count)
    pairs = float(np.sum([float(c) for c in counts]))  # blocks on final
    elapsed = time.perf_counter() - t0
    mv.shutdown()

    value = pairs / elapsed
    # the negative-draw mode rides in the output line so every recorded
    # number is self-describing: G>1 group-shares draws (an algorithmic
    # relaxation over the reference's exact per-pair semantics — disclosed
    # in BASELINE.md, parity-gated in docs/EMBEDDING_QUALITY.md)
    record = {
        "metric": "word2vec_train_pairs_per_sec",
        "value": round(value, 1),
        "unit": "pairs/sec",
        "vs_baseline": round(value / _BASELINE_PAIRS_PER_SEC, 4),
        "negatives": ("exact" if shared_neg in (0, 1)
                      else f"group-shared G={shared_neg}"),
    }
    if degraded:
        record["backend"] = (f"{backend} DEGRADED — not comparable to "
                             "accelerator baselines")
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
