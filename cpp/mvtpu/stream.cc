#include "mvtpu/stream.h"

#include <cstring>

#include "mvtpu/log.h"

namespace mvtpu {

URI URI::Parse(const std::string& uri) {
  URI out;
  const size_t sep = uri.find("://");
  if (sep == std::string::npos) {
    out.path = uri;
    return out;
  }
  out.scheme = uri.substr(0, sep);
  const std::string rest = uri.substr(sep + 3);
  if (out.scheme == "file") {
    out.path = rest;
    return out;
  }
  const size_t slash = rest.find('/');
  if (slash == std::string::npos) {
    out.host = rest;
  } else {
    out.host = rest.substr(0, slash);
    out.path = rest.substr(slash);
  }
  return out;
}

LocalStream::LocalStream(const std::string& path, const char* mode) {
  std::string m(mode);
  if (m.find('b') == std::string::npos) m += 'b';
  file_ = std::fopen(path.c_str(), m.c_str());
  if (file_ == nullptr)
    Log::Error("LocalStream: cannot open %s (mode %s)", path.c_str(), mode);
}

LocalStream::~LocalStream() {
  if (file_ != nullptr) std::fclose(file_);
}

size_t LocalStream::Read(void* buf, size_t size) {
  if (file_ == nullptr) return 0;
  return std::fread(buf, 1, size, file_);
}

size_t LocalStream::Write(const void* buf, size_t size) {
  if (file_ == nullptr) return 0;
  return std::fwrite(buf, 1, size, file_);
}

void LocalStream::Flush() {
  if (file_ != nullptr) std::fflush(file_);
}

std::unique_ptr<Stream> CreateStream(const std::string& uri,
                                     const char* mode) {
  const URI parsed = URI::Parse(uri);
  if (parsed.scheme.empty() || parsed.scheme == "file") {
    auto stream = std::make_unique<LocalStream>(parsed.path, mode);
    if (!stream->Good()) return nullptr;
    return stream;
  }
  Log::Error("CreateStream: scheme '%s' not supported in the native layer "
           "(route through the Python IO layer)", parsed.scheme.c_str());
  return nullptr;
}

TextReader::TextReader(std::unique_ptr<Stream> stream, size_t buf_size)
    : stream_(std::move(stream)), buf_(buf_size) {}

bool TextReader::GetLine(std::string* line) {
  line->clear();
  for (;;) {
    if (pos_ == len_) {
      if (eof_) break;
      len_ = stream_ ? stream_->Read(buf_.data(), buf_.size()) : 0;
      pos_ = 0;
      if (len_ == 0) {
        eof_ = true;
        break;
      }
    }
    const char* start = buf_.data() + pos_;
    const char* nl = static_cast<const char*>(
        std::memchr(start, '\n', len_ - pos_));
    if (nl == nullptr) {
      line->append(start, len_ - pos_);
      pos_ = len_;
      continue;
    }
    line->append(start, static_cast<size_t>(nl - start));
    pos_ += static_cast<size_t>(nl - start) + 1;
    if (!line->empty() && line->back() == '\r') line->pop_back();
    return true;
  }
  if (!line->empty() && line->back() == '\r') line->pop_back();
  return !line->empty();
}

}  // namespace mvtpu
