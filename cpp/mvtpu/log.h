// Leveled, timestamped logger.
//
// Native form of the reference logger (Multiverso reference:
// include/multiverso/util/log.h:9-18,110-142): Debug/Info/Error/Fatal with
// "[LEVEL] [timestamp]" prefixes, optional file sink, CHECK macro.
#ifndef MVTPU_LOG_H_
#define MVTPU_LOG_H_

#include <cstdio>
#include <mutex>
#include <string>

namespace mvtpu {

enum class LogLevel { kDebug = 0, kInfo = 1, kError = 2, kFatal = 3 };

class Log {
 public:
  static void ResetLogLevel(LogLevel level);
  static void ResetLogFile(const std::string& path);  // "" detaches
  static void Write(LogLevel level, const char* format, ...);

  static void Debug(const char* format, ...);
  static void Info(const char* format, ...);
  static void Error(const char* format, ...);
  // Logs and aborts the process (the local store has no exception channel
  // across the C ABI).
  [[noreturn]] static void Fatal(const char* format, ...);
};

#define MVTPU_CHECK(cond)                                                  \
  do {                                                                     \
    if (!(cond)) {                                                         \
      ::mvtpu::Log::Fatal("CHECK failed at %s:%d: %s", __FILE__, __LINE__, \
                          #cond);                                          \
    }                                                                      \
  } while (0)

}  // namespace mvtpu

#endif  // MVTPU_LOG_H_
