#include "mvtpu/reader.h"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "mvtpu/stream.h"

namespace mvtpu {

namespace {

// Buffered line reader over stdio (TextReader analogue, io.h:114-130).
class LineReader {
 public:
  explicit LineReader(const std::string& path)
      : file_(std::fopen(path.c_str(), "rb")) {}
  ~LineReader() {
    if (file_ != nullptr) std::fclose(file_);
  }
  bool ok() const { return file_ != nullptr; }

  bool NextLine(std::string* line) {
    if (file_ == nullptr) return false;
    line->clear();
    char buf[1 << 16];
    while (std::fgets(buf, sizeof(buf), file_) != nullptr) {
      size_t len = std::strlen(buf);
      bool end = len > 0 && buf[len - 1] == '\n';
      if (end) buf[--len] = '\0';
      if (len > 0 && buf[len - 1] == '\r') buf[--len] = '\0';
      line->append(buf, len);
      if (end) return true;
      if (len + 1 < sizeof(buf)) return true;  // EOF without newline
    }
    return !line->empty();
  }

 private:
  std::FILE* file_;
};

inline bool IsSpace(char c) {
  return c == ' ' || c == '\t' || c == '\v' || c == '\f';
}

template <typename Fn>
void ForEachToken(const std::string& line, Fn fn) {
  const char* p = line.c_str();
  while (*p != '\0') {
    while (IsSpace(*p)) ++p;
    if (*p == '\0') break;
    const char* start = p;
    while (*p != '\0' && !IsSpace(*p)) ++p;
    fn(start, static_cast<size_t>(p - start));
  }
}

}  // namespace

bool Vocab::Build(const std::string& path, int min_count) {
  LineReader reader(path);
  if (!reader.ok()) return false;
  std::unordered_map<std::string, long long> counter;
  counter.reserve(1 << 20);
  std::string line, token;
  while (reader.NextLine(&line)) {
    ForEachToken(line, [&](const char* start, size_t len) {
      token.assign(start, len);
      ++counter[token];
    });
  }
  std::vector<std::pair<std::string, long long>> sorted;
  sorted.reserve(counter.size());
  for (auto& kv : counter) {
    if (kv.second >= min_count) sorted.emplace_back(kv.first, kv.second);
  }
  std::sort(sorted.begin(), sorted.end(), [](const auto& a, const auto& b) {
    return a.second != b.second ? a.second > b.second : a.first < b.first;
  });
  index_.clear();
  words_.clear();
  counts_.clear();
  train_words_ = 0;
  words_.reserve(sorted.size());
  counts_.reserve(sorted.size());
  for (auto& kv : sorted) {
    index_[kv.first] = static_cast<int>(words_.size());
    words_.push_back(kv.first);
    counts_.push_back(kv.second);
    train_words_ += kv.second;
  }
  return true;
}

bool Vocab::Encode(const std::string& path, std::vector<int32_t>* ids,
                   std::vector<int32_t>* sent_ids,
                   long long* words_read) const {
  LineReader reader(path);
  if (!reader.ok()) return false;
  ids->clear();
  sent_ids->clear();
  long long consumed = 0;
  std::string line, token;
  std::vector<int32_t> sentence;
  int32_t sent_counter = 0;
  while (reader.NextLine(&line)) {
    sentence.clear();
    ForEachToken(line, [&](const char* start, size_t len) {
      token.assign(start, len);
      auto it = index_.find(token);
      if (it != index_.end()) {
        sentence.push_back(it->second);
        ++consumed;
      }
    });
    if (sentence.size() < 2) continue;
    ids->insert(ids->end(), sentence.begin(), sentence.end());
    sent_ids->insert(sent_ids->end(), sentence.size(), sent_counter);
    ++sent_counter;
  }
  if (words_read != nullptr) *words_read = consumed;
  return true;
}

bool ParseLibsvm(const std::string& path, SvmData* out) {
  LineReader reader(path);
  if (!reader.ok()) return false;
  out->labels.clear();
  out->indptr.assign(1, 0);
  out->keys.clear();
  out->values.clear();
  std::string line;
  while (reader.NextLine(&line)) {
    bool first = true;
    bool any = false;
    ForEachToken(line, [&](const char* start, size_t len) {
      if (first) {
        out->labels.push_back(std::strtof(start, nullptr));
        first = false;
        any = true;
        return;
      }
      const char* colon =
          static_cast<const char*>(std::memchr(start, ':', len));
      if (colon == nullptr) {
        out->keys.push_back(
            static_cast<int32_t>(std::strtol(start, nullptr, 10)));
        out->values.push_back(1.0);
      } else {
        out->keys.push_back(
            static_cast<int32_t>(std::strtol(start, nullptr, 10)));
        out->values.push_back(std::strtod(colon + 1, nullptr));
      }
    });
    if (any) out->indptr.push_back(static_cast<int64_t>(out->keys.size()));
  }
  return true;
}

bool ParseBsparse(const std::string& path, SvmData* out) {
  // Record layout mirrors the Python writer (apps/lr_reader.write_bsparse)
  // and the reference BSparseSampleReader::ParseSample
  // (Applications/LogisticRegression/src/reader.cpp:382-444):
  //   <u64 nkeys><i32 label><f64 weight> then nkeys little-endian i64 keys;
  // the per-record scalar feature value is the weight.
  auto stream = CreateStream(path, "r");
  if (!stream) return false;
  out->labels.clear();
  out->indptr.assign(1, 0);
  out->keys.clear();
  out->values.clear();
  struct Head {
    uint64_t nkeys;
    int32_t label;
    double weight;
  } __attribute__((packed));
  Head head;
  std::vector<int64_t> key_buf;
  // Sanity bound on the per-record key count: a corrupt/misaligned file can
  // decode garbage as nkeys; without the cap, resize() on an exabyte-sized
  // request would throw across the C ABI (and nkeys * 8 could wrap size_t).
  constexpr uint64_t kMaxKeysPerRecord = 1ull << 32;
  for (;;) {
    size_t got = stream->Read(&head, sizeof(head));
    if (got == 0) break;                       // clean EOF at record boundary
    if (got != sizeof(head)) return false;     // truncated header
    if (head.nkeys > kMaxKeysPerRecord) return false;  // corrupt count
    key_buf.resize(head.nkeys);
    size_t want = head.nkeys * sizeof(int64_t);
    if (want > 0 && stream->Read(key_buf.data(), want) != want) {
      return false;                            // truncated keys
    }
    out->labels.push_back(static_cast<float>(head.label));
    for (int64_t k : key_buf) {
      if (k < INT32_MIN || k > INT32_MAX) {
        // SvmData keys are i32; refuse to truncate silently — the caller
        // falls back to the (i64-capable) Python reader.
        return false;
      }
      out->keys.push_back(static_cast<int32_t>(k));
      out->values.push_back(head.weight);
    }
    out->indptr.push_back(static_cast<int64_t>(out->keys.size()));
  }
  return true;
}

}  // namespace mvtpu
