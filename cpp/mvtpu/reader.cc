#include "mvtpu/reader.h"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace mvtpu {

namespace {

// Buffered line reader over stdio (TextReader analogue, io.h:114-130).
class LineReader {
 public:
  explicit LineReader(const std::string& path)
      : file_(std::fopen(path.c_str(), "rb")) {}
  ~LineReader() {
    if (file_ != nullptr) std::fclose(file_);
  }
  bool ok() const { return file_ != nullptr; }

  bool NextLine(std::string* line) {
    if (file_ == nullptr) return false;
    line->clear();
    char buf[1 << 16];
    while (std::fgets(buf, sizeof(buf), file_) != nullptr) {
      size_t len = std::strlen(buf);
      bool end = len > 0 && buf[len - 1] == '\n';
      if (end) buf[--len] = '\0';
      if (len > 0 && buf[len - 1] == '\r') buf[--len] = '\0';
      line->append(buf, len);
      if (end) return true;
      if (len + 1 < sizeof(buf)) return true;  // EOF without newline
    }
    return !line->empty();
  }

 private:
  std::FILE* file_;
};

inline bool IsSpace(char c) {
  return c == ' ' || c == '\t' || c == '\v' || c == '\f';
}

template <typename Fn>
void ForEachToken(const std::string& line, Fn fn) {
  const char* p = line.c_str();
  while (*p != '\0') {
    while (IsSpace(*p)) ++p;
    if (*p == '\0') break;
    const char* start = p;
    while (*p != '\0' && !IsSpace(*p)) ++p;
    fn(start, static_cast<size_t>(p - start));
  }
}

}  // namespace

bool Vocab::Build(const std::string& path, int min_count) {
  LineReader reader(path);
  if (!reader.ok()) return false;
  std::unordered_map<std::string, long long> counter;
  counter.reserve(1 << 20);
  std::string line, token;
  while (reader.NextLine(&line)) {
    ForEachToken(line, [&](const char* start, size_t len) {
      token.assign(start, len);
      ++counter[token];
    });
  }
  std::vector<std::pair<std::string, long long>> sorted;
  sorted.reserve(counter.size());
  for (auto& kv : counter) {
    if (kv.second >= min_count) sorted.emplace_back(kv.first, kv.second);
  }
  std::sort(sorted.begin(), sorted.end(), [](const auto& a, const auto& b) {
    return a.second != b.second ? a.second > b.second : a.first < b.first;
  });
  index_.clear();
  words_.clear();
  counts_.clear();
  train_words_ = 0;
  words_.reserve(sorted.size());
  counts_.reserve(sorted.size());
  for (auto& kv : sorted) {
    index_[kv.first] = static_cast<int>(words_.size());
    words_.push_back(kv.first);
    counts_.push_back(kv.second);
    train_words_ += kv.second;
  }
  return true;
}

bool Vocab::Encode(const std::string& path, std::vector<int32_t>* ids,
                   std::vector<int32_t>* sent_ids,
                   long long* words_read) const {
  LineReader reader(path);
  if (!reader.ok()) return false;
  ids->clear();
  sent_ids->clear();
  long long consumed = 0;
  std::string line, token;
  std::vector<int32_t> sentence;
  int32_t sent_counter = 0;
  while (reader.NextLine(&line)) {
    sentence.clear();
    ForEachToken(line, [&](const char* start, size_t len) {
      token.assign(start, len);
      auto it = index_.find(token);
      if (it != index_.end()) {
        sentence.push_back(it->second);
        ++consumed;
      }
    });
    if (sentence.size() < 2) continue;
    ids->insert(ids->end(), sentence.begin(), sentence.end());
    sent_ids->insert(sent_ids->end(), sentence.size(), sent_counter);
    ++sent_counter;
  }
  if (words_read != nullptr) *words_read = consumed;
  return true;
}

bool ParseLibsvm(const std::string& path, SvmData* out) {
  LineReader reader(path);
  if (!reader.ok()) return false;
  out->labels.clear();
  out->indptr.assign(1, 0);
  out->keys.clear();
  out->values.clear();
  std::string line;
  while (reader.NextLine(&line)) {
    bool first = true;
    bool any = false;
    ForEachToken(line, [&](const char* start, size_t len) {
      if (first) {
        out->labels.push_back(std::strtof(start, nullptr));
        first = false;
        any = true;
        return;
      }
      const char* colon =
          static_cast<const char*>(std::memchr(start, ':', len));
      if (colon == nullptr) {
        out->keys.push_back(
            static_cast<int32_t>(std::strtol(start, nullptr, 10)));
        out->values.push_back(1.0f);
      } else {
        out->keys.push_back(
            static_cast<int32_t>(std::strtol(start, nullptr, 10)));
        out->values.push_back(std::strtof(colon + 1, nullptr));
      }
    });
    if (any) out->indptr.push_back(static_cast<int64_t>(out->keys.size()));
  }
  return true;
}

}  // namespace mvtpu
