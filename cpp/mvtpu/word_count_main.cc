// Corpus word-count preprocessor.
//
// TPU-native rebuild of the reference WordEmbedding preprocessing tool
// (Applications/WordEmbedding/preprocess/word_count.cpp in the Multiverso
// reference): stream a whitespace-tokenised corpus, count occurrences, and
// write "word<space>count" lines sorted by descending count — the input the
// word2vec dictionary loader consumes. Uses the runtime's buffered stream
// layer instead of raw stdio.
//
// Usage: mv_word_count <corpus> <output> [min_count]

#include <algorithm>
#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

#include "mvtpu/log.h"
#include "mvtpu/stream.h"

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <corpus> <output> [min_count]\n", argv[0]);
    return 2;
  }
  const std::string corpus = argv[1];
  const std::string output = argv[2];
  const long long min_count = argc > 3 ? std::atoll(argv[3]) : 1;

  auto in = mvtpu::CreateStream(corpus, "r");
  if (!in) {
    mvtpu::Log::Error("cannot open corpus %s", corpus.c_str());
    return 1;
  }
  mvtpu::TextReader reader(std::move(in));
  std::unordered_map<std::string, long long> counts;
  long long total = 0;
  std::string line;
  while (reader.GetLine(&line)) {
    size_t pos = 0;
    while (pos < line.size()) {
      while (pos < line.size() &&
             std::isspace(static_cast<unsigned char>(line[pos])))
        ++pos;
      size_t end = pos;
      while (end < line.size() &&
             !std::isspace(static_cast<unsigned char>(line[end])))
        ++end;
      if (end > pos) {
        ++counts[line.substr(pos, end - pos)];
        ++total;
      }
      pos = end;
    }
  }

  std::vector<std::pair<std::string, long long>> sorted(counts.begin(),
                                                        counts.end());
  std::sort(sorted.begin(), sorted.end(), [](const auto& a, const auto& b) {
    return a.second != b.second ? a.second > b.second : a.first < b.first;
  });

  auto out = mvtpu::CreateStream(output, "w");
  if (!out) {
    mvtpu::Log::Error("cannot open output %s", output.c_str());
    return 1;
  }
  long long kept = 0;
  for (const auto& [word, count] : sorted) {
    if (count < min_count) break;  // sorted desc: everything after is below
    std::string rec = word + " " + std::to_string(count) + "\n";
    out->Write(rec.data(), rec.size());
    ++kept;
  }
  out->Flush();
  mvtpu::Log::Info("word_count: %lld tokens, %zu distinct, %lld kept -> %s",
                   total, sorted.size(), kept, output.c_str());
  return 0;
}
