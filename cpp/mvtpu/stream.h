// URI-dispatched binary streams + buffered text reader.
//
// Native form of the reference IO layer (Multiverso reference:
// include/multiverso/io/io.h:24-130 — URI parse, StreamFactory, TextReader;
// local file backend include/multiverso/io/local_stream.h:13). Schemes:
// "file://" (and bare paths) open local files; other schemes (hdfs://) are
// gated — CreateStream returns nullptr and logs, since the TPU deployment
// reads from local/NFS mounts and cloud storage goes through the Python
// layer. Checkpoint Store/Load and the native data readers sit on top.
#ifndef MVTPU_STREAM_H_
#define MVTPU_STREAM_H_

#include <cstddef>
#include <cstdio>
#include <memory>
#include <string>
#include <vector>

namespace mvtpu {

struct URI {
  std::string scheme;  // empty or "file", "hdfs", ...
  std::string host;
  std::string path;

  static URI Parse(const std::string& uri);
};

class Stream {
 public:
  virtual ~Stream() = default;
  virtual size_t Read(void* buf, size_t size) = 0;
  virtual size_t Write(const void* buf, size_t size) = 0;
  virtual bool Good() const = 0;
  virtual void Flush() = 0;
};

class LocalStream : public Stream {
 public:
  LocalStream(const std::string& path, const char* mode);
  ~LocalStream() override;
  size_t Read(void* buf, size_t size) override;
  size_t Write(const void* buf, size_t size) override;
  bool Good() const override { return file_ != nullptr; }
  void Flush() override;

 private:
  std::FILE* file_;
};

// mode: "r" | "w" | "a" (binary). Returns nullptr for unsupported schemes
// or open failure.
std::unique_ptr<Stream> CreateStream(const std::string& uri, const char* mode);

// Buffered line reader over a Stream (reference TextReader,
// include/multiverso/io/io.h:114).
class TextReader {
 public:
  explicit TextReader(std::unique_ptr<Stream> stream,
                      size_t buf_size = 1 << 16);
  // Returns false at EOF. Strips the trailing newline (and \r).
  bool GetLine(std::string* line);

 private:
  std::unique_ptr<Stream> stream_;
  std::vector<char> buf_;
  size_t pos_ = 0;
  size_t len_ = 0;
  bool eof_ = false;
};

}  // namespace mvtpu

#endif  // MVTPU_STREAM_H_
