#include "mvtpu/log.h"

#include <cstdarg>
#include <cstdlib>
#include <ctime>

namespace mvtpu {
namespace {

struct LogState {
  std::mutex mu;
  LogLevel level = LogLevel::kInfo;
  FILE* file = nullptr;
};

LogState& State() {
  static LogState state;
  return state;
}

const char* LevelName(LogLevel level) {
  switch (level) {
    case LogLevel::kDebug: return "DEBUG";
    case LogLevel::kInfo: return "INFO";
    case LogLevel::kError: return "ERROR";
    case LogLevel::kFatal: return "FATAL";
  }
  return "?";
}

void VWrite(LogLevel level, const char* format, va_list args) {
  LogState& state = State();
  std::lock_guard<std::mutex> lock(state.mu);
  if (level < state.level) return;
  char stamp[32];
  std::time_t now = std::time(nullptr);
  std::tm tm_buf;
  localtime_r(&now, &tm_buf);
  std::strftime(stamp, sizeof(stamp), "%Y-%m-%d %H:%M:%S", &tm_buf);
  char message[2048];
  std::vsnprintf(message, sizeof(message), format, args);
  std::fprintf(stdout, "[%s] [%s] %s\n", LevelName(level), stamp, message);
  std::fflush(stdout);
  if (state.file != nullptr) {
    std::fprintf(state.file, "[%s] [%s] %s\n", LevelName(level), stamp,
                 message);
    std::fflush(state.file);
  }
}

}  // namespace

void Log::ResetLogLevel(LogLevel level) { State().level = level; }

void Log::ResetLogFile(const std::string& path) {
  LogState& state = State();
  std::lock_guard<std::mutex> lock(state.mu);
  if (state.file != nullptr) {
    std::fclose(state.file);
    state.file = nullptr;
  }
  if (!path.empty()) state.file = std::fopen(path.c_str(), "a");
}

#define MVTPU_LOG_IMPL(name, level)           \
  void Log::name(const char* format, ...) {   \
    va_list args;                             \
    va_start(args, format);                   \
    VWrite(level, format, args);              \
    va_end(args);                             \
  }

MVTPU_LOG_IMPL(Debug, LogLevel::kDebug)
MVTPU_LOG_IMPL(Info, LogLevel::kInfo)
MVTPU_LOG_IMPL(Error, LogLevel::kError)

void Log::Write(LogLevel level, const char* format, ...) {
  va_list args;
  va_start(args, format);
  VWrite(level, format, args);
  va_end(args);
}

void Log::Fatal(const char* format, ...) {
  va_list args;
  va_start(args, format);
  VWrite(LogLevel::kFatal, format, args);
  va_end(args);
  std::abort();
}

}  // namespace mvtpu
