// Lua 5.1 syntax checker for the binding sources (VERDICT r2 item 7).
//
// No Lua interpreter ships in this environment, so binding/lua/*.lua could
// not be parsed by anything in CI — a syntax error would ship silently
// (the ABI replay covers the C-ABI call sequence
// but never reads the .lua files). This is a full lexer + recursive-descent
// parser for the Lua 5.1 grammar (reference manual §8); it accepts exactly
// the syntactically valid programs and reports the first error per file
// with line numbers. Run: lua_check FILE... (exit 1 on any error).
//
// Reference counterpart: the reference runs binding/lua/test.lua under
// torch/LuaJIT (binding/lua/README.md), which implies a parse.

#include "mvtpu/lua_lex.h"

#include <cctype>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace {

using namespace mvtpu_lua;  // Lexer, Token, LuaSyntaxError, TK_*
using SyntaxError = mvtpu_lua::LuaSyntaxError;

class Parser {
 public:
  Parser(const std::string& src, const std::string& file)
      : lex_(src, file) { advance(); }

  void parse_chunk_eof() {
    block();
    expect(TK_EOF, "<eof>");
  }

 private:
  void advance() { tok_ = lex_.next(); }

  bool check(TokKind k) const { return tok_.kind == k; }

  bool accept(TokKind k) {
    if (!check(k)) return false;
    advance();
    return true;
  }

  void expect(TokKind k, const char* what) {
    if (!check(k)) {
      std::ostringstream os;
      os << "expected " << what;
      lex_.err(tok_.line, os.str());
    }
    advance();
  }

  static bool block_follow(TokKind k) {
    return k == TK_EOF || k == TK_END || k == TK_ELSE || k == TK_ELSEIF ||
           k == TK_UNTIL;
  }

  // block ::= {stat [';']} [laststat [';']]
  void block() {
    for (;;) {
      if (check(TK_RETURN)) {
        advance();
        if (!block_follow(tok_.kind) && !check(TK_SEMI)) explist();
        accept(TK_SEMI);
        if (!block_follow(tok_.kind))
          lex_.err(tok_.line, "statement after return");
        return;
      }
      if (check(TK_BREAK)) {
        advance();
        accept(TK_SEMI);
        if (!block_follow(tok_.kind))
          lex_.err(tok_.line, "statement after break");
        return;
      }
      if (block_follow(tok_.kind)) return;
      statement();
      accept(TK_SEMI);
    }
  }

  void statement() {
    switch (tok_.kind) {
      case TK_DO:
        advance(); block(); expect(TK_END, "'end'"); return;
      case TK_WHILE:
        advance(); expr(); expect(TK_DO, "'do'"); block();
        expect(TK_END, "'end'"); return;
      case TK_REPEAT:
        advance(); block(); expect(TK_UNTIL, "'until'"); expr(); return;
      case TK_IF:
        advance(); expr(); expect(TK_THEN, "'then'"); block();
        while (accept(TK_ELSEIF)) { expr(); expect(TK_THEN, "'then'"); block(); }
        if (accept(TK_ELSE)) block();
        expect(TK_END, "'end'"); return;
      case TK_FOR: {
        advance();
        expect(TK_NAME, "name");
        if (accept(TK_ASSIGN)) {           // numeric for
          expr(); expect(TK_COMMA, "','"); expr();
          if (accept(TK_COMMA)) expr();
        } else {                           // generic for
          while (accept(TK_COMMA)) expect(TK_NAME, "name");
          expect(TK_IN, "'in' or '='");
          explist();
        }
        expect(TK_DO, "'do'"); block(); expect(TK_END, "'end'");
        return;
      }
      case TK_FUNCTION: {
        advance();
        expect(TK_NAME, "function name");
        while (accept(TK_DOT)) expect(TK_NAME, "name");
        if (accept(TK_COLON)) expect(TK_NAME, "method name");
        funcbody();
        return;
      }
      case TK_LOCAL:
        advance();
        if (accept(TK_FUNCTION)) {
          expect(TK_NAME, "function name");
          funcbody();
          return;
        }
        expect(TK_NAME, "name");
        while (accept(TK_COMMA)) expect(TK_NAME, "name");
        if (accept(TK_ASSIGN)) explist();
        return;
      default: {
        // exprstat: either a function call or an assignment to vars
        int line = tok_.line;
        bool is_call = suffixedexp();
        if (check(TK_ASSIGN) || check(TK_COMMA)) {
          if (is_call) lex_.err(line, "cannot assign to function call");
          while (accept(TK_COMMA)) {
            if (suffixedexp())
              lex_.err(tok_.line, "cannot assign to function call");
          }
          expect(TK_ASSIGN, "'='");
          explist();
        } else if (!is_call) {
          lex_.err(line, "syntax error (expression is not a statement)");
        }
        return;
      }
    }
  }

  void funcbody() {
    expect(TK_LPAREN, "'('");
    if (!check(TK_RPAREN)) {
      for (;;) {
        if (accept(TK_ELLIPSIS)) break;
        expect(TK_NAME, "parameter name");
        if (!accept(TK_COMMA)) break;
      }
    }
    expect(TK_RPAREN, "')'");
    block();
    expect(TK_END, "'end'");
  }

  void explist() {
    expr();
    while (accept(TK_COMMA)) expr();
  }

  // primaryexp ::= Name | '(' exp ')'
  void primaryexp() {
    if (accept(TK_NAME)) return;
    if (accept(TK_LPAREN)) {
      expr();
      expect(TK_RPAREN, "')'");
      return;
    }
    lex_.err(tok_.line, "unexpected symbol");
  }

  // suffixedexp ::= primaryexp { '.' Name | '[' exp ']' | ':' Name args | args }
  // returns true iff the whole expression is a function/method call
  bool suffixedexp() {
    primaryexp();
    bool is_call = false;
    for (;;) {
      switch (tok_.kind) {
        case TK_DOT:
          advance(); expect(TK_NAME, "field name"); is_call = false; break;
        case TK_LBRACKET:
          advance(); expr(); expect(TK_RBRACKET, "']'"); is_call = false; break;
        case TK_COLON:
          advance(); expect(TK_NAME, "method name"); args(); is_call = true;
          break;
        case TK_LPAREN: case TK_LBRACE: case TK_STRING:
          args(); is_call = true; break;
        default:
          return is_call;
      }
    }
  }

  void args() {
    if (accept(TK_STRING)) return;
    if (check(TK_LBRACE)) { tablector(); return; }
    expect(TK_LPAREN, "function arguments");
    if (!check(TK_RPAREN)) explist();
    expect(TK_RPAREN, "')'");
  }

  void tablector() {
    expect(TK_LBRACE, "'{'");
    while (!check(TK_RBRACE)) {
      if (check(TK_LBRACKET)) {
        advance(); expr(); expect(TK_RBRACKET, "']'");
        expect(TK_ASSIGN, "'='"); expr();
      } else if (check(TK_NAME)) {
        // Name '=' exp, or an expression starting with a Name — need the
        // one-token lookahead on '=' vs anything else
        Token save = tok_;
        advance();
        if (accept(TK_ASSIGN)) {
          expr();
        } else {
          // re-parse as expression continuing from the consumed Name:
          // run the suffix/operator tail with the Name as primary
          expr_after_name();
          (void)save;
        }
      } else {
        expr();
      }
      if (!accept(TK_COMMA) && !accept(TK_SEMI)) break;
    }
    expect(TK_RBRACE, "'}'");
  }

  // operator precedence (Lua 5.1 manual §2.5.6)
  struct OpPrio { int left, right; };
  static bool binop_prio(TokKind k, OpPrio* p) {
    switch (k) {
      case TK_OR: *p = {1, 1}; return true;
      case TK_AND: *p = {2, 2}; return true;
      case TK_LT: case TK_GT: case TK_LE: case TK_GE:
      case TK_NE: case TK_EQ: *p = {3, 3}; return true;
      case TK_CONCAT: *p = {5, 4}; return true;     // right assoc
      case TK_PLUS: case TK_MINUS: *p = {6, 6}; return true;
      case TK_STAR: case TK_SLASH: case TK_PERCENT: *p = {7, 7}; return true;
      case TK_CARET: *p = {10, 9}; return true;     // right assoc
      default: return false;
    }
  }
  static constexpr int kUnaryPrio = 8;

  void expr(int limit = 0) {
    simpleexp(limit);
    OpPrio p;
    while (binop_prio(tok_.kind, &p) && p.left > limit) {
      advance();
      expr(p.right);
    }
  }

  // like expr(), but the leading Name was already consumed (tablector)
  void expr_after_name() {
    suffix_tail();
    OpPrio p;
    while (binop_prio(tok_.kind, &p)) {
      advance();
      expr(p.right);
    }
  }

  void suffix_tail() {
    for (;;) {
      switch (tok_.kind) {
        case TK_DOT: advance(); expect(TK_NAME, "field name"); break;
        case TK_LBRACKET: advance(); expr(); expect(TK_RBRACKET, "']'"); break;
        case TK_COLON: advance(); expect(TK_NAME, "method name"); args(); break;
        case TK_LPAREN: case TK_LBRACE: case TK_STRING: args(); break;
        default: return;
      }
    }
  }

  void simpleexp(int limit) {
    (void)limit;
    switch (tok_.kind) {
      case TK_NIL: case TK_TRUE: case TK_FALSE: case TK_NUMBER:
      case TK_STRING: case TK_ELLIPSIS:
        advance(); return;
      case TK_FUNCTION:
        advance(); funcbody(); return;
      case TK_LBRACE:
        tablector(); return;
      case TK_NOT: case TK_HASH: case TK_MINUS:
        advance(); expr(kUnaryPrio); return;
      default:
        suffixedexp(); return;
    }
  }

  Lexer lex_;
  Token tok_;
};

}  // namespace

int main(int argc, char* argv[]) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s FILE.lua...\n", argv[0]);
    return 2;
  }
  int failures = 0;
  for (int i = 1; i < argc; ++i) {
    std::ifstream in(argv[i], std::ios::binary);
    if (!in) {
      std::fprintf(stderr, "%s: cannot open\n", argv[i]);
      ++failures;
      continue;
    }
    std::ostringstream buf;
    buf << in.rdbuf();
    std::string src = buf.str();
    // skip a shebang line, like the Lua loader does
    if (src.size() >= 1 && src[0] == '#') {
      size_t nl = src.find('\n');
      src = nl == std::string::npos ? std::string() : src.substr(nl);
    }
    try {
      Parser p(src, argv[i]);
      p.parse_chunk_eof();
      std::printf("%s: syntax OK\n", argv[i]);
    } catch (const SyntaxError& e) {
      std::fprintf(stderr, "%s\n", e.what());
      ++failures;
    }
  }
  if (failures == 0) std::printf("lua syntax check: OK\n");
  return failures ? 1 : 0;
}
