// Lua 5.1 syntax checker for the binding sources (VERDICT r2 item 7).
//
// No Lua interpreter ships in this environment, so binding/lua/*.lua could
// not be parsed by anything in CI — a syntax error would ship silently
// (the ABI replay, cpp/mvtpu/lua_abi_replay.cc, covers the C-ABI semantics
// but never reads the .lua files). This is a full lexer + recursive-descent
// parser for the Lua 5.1 grammar (reference manual §8); it accepts exactly
// the syntactically valid programs and reports the first error per file
// with line numbers. Run: lua_check FILE... (exit 1 on any error).
//
// Reference counterpart: the reference runs binding/lua/test.lua under
// torch/LuaJIT (binding/lua/README.md), which implies a parse.

#include <cctype>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace {

enum TokKind {
  TK_EOF, TK_NAME, TK_NUMBER, TK_STRING,
  // keywords
  TK_AND, TK_BREAK, TK_DO, TK_ELSE, TK_ELSEIF, TK_END, TK_FALSE, TK_FOR,
  TK_FUNCTION, TK_IF, TK_IN, TK_LOCAL, TK_NIL, TK_NOT, TK_OR, TK_REPEAT,
  TK_RETURN, TK_THEN, TK_TRUE, TK_UNTIL, TK_WHILE,
  // symbols
  TK_PLUS, TK_MINUS, TK_STAR, TK_SLASH, TK_PERCENT, TK_CARET, TK_HASH,
  TK_EQ, TK_NE, TK_LE, TK_GE, TK_LT, TK_GT, TK_ASSIGN, TK_LPAREN, TK_RPAREN,
  TK_LBRACE, TK_RBRACE, TK_LBRACKET, TK_RBRACKET, TK_SEMI, TK_COLON,
  TK_COMMA, TK_DOT, TK_CONCAT, TK_ELLIPSIS,
};

struct Token {
  TokKind kind;
  std::string text;
  int line;
};

struct SyntaxError : std::runtime_error {
  explicit SyntaxError(const std::string& m) : std::runtime_error(m) {}
};

class Lexer {
 public:
  Lexer(const std::string& src, const std::string& file)
      : s_(src), file_(file) {}

  Token next() {
    skip_space_and_comments();
    Token t;
    t.line = line_;
    if (pos_ >= s_.size()) { t.kind = TK_EOF; return t; }
    char c = s_[pos_];
    if (std::isalpha(static_cast<unsigned char>(c)) || c == '_')
      return name_or_keyword();
    if (std::isdigit(static_cast<unsigned char>(c)) ||
        (c == '.' && pos_ + 1 < s_.size() &&
         std::isdigit(static_cast<unsigned char>(s_[pos_ + 1]))))
      return number();
    if (c == '"' || c == '\'') return short_string();
    if (c == '[') {
      size_t lvl;
      if (long_bracket_level(&lvl)) return long_string(lvl);
      ++pos_; t.kind = TK_LBRACKET; return t;
    }
    return symbol();
  }

  [[noreturn]] void err(int line, const std::string& msg) const {
    std::ostringstream os;
    os << file_ << ":" << line << ": " << msg;
    throw SyntaxError(os.str());
  }

 private:
  void skip_space_and_comments() {
    for (;;) {
      while (pos_ < s_.size() &&
             std::isspace(static_cast<unsigned char>(s_[pos_]))) {
        if (s_[pos_] == '\n') ++line_;
        ++pos_;
      }
      if (pos_ + 1 < s_.size() && s_[pos_] == '-' && s_[pos_ + 1] == '-') {
        pos_ += 2;
        size_t lvl;
        if (pos_ < s_.size() && s_[pos_] == '[' && long_bracket_level(&lvl)) {
          long_string(lvl);   // long comment body
        } else {
          while (pos_ < s_.size() && s_[pos_] != '\n') ++pos_;
        }
        continue;
      }
      return;
    }
  }

  // at '[': true iff an opening long bracket '[' '='* '[' starts here
  bool long_bracket_level(size_t* lvl) const {
    size_t p = pos_ + 1, eq = 0;
    while (p < s_.size() && s_[p] == '=') { ++eq; ++p; }
    if (p < s_.size() && s_[p] == '[') { *lvl = eq; return true; }
    return false;
  }

  Token long_string(size_t lvl) {
    Token t; t.kind = TK_STRING; t.line = line_;
    pos_ += 2 + lvl;                       // consume '[' '='* '['
    if (pos_ < s_.size() && s_[pos_] == '\n') { ++line_; ++pos_; }
    std::string close = "]" + std::string(lvl, '=') + "]";
    for (;;) {
      if (pos_ >= s_.size()) err(t.line, "unterminated long string/comment");
      if (s_[pos_] == ']' && s_.compare(pos_, close.size(), close) == 0) {
        pos_ += close.size();
        return t;
      }
      if (s_[pos_] == '\n') ++line_;
      ++pos_;
    }
  }

  Token short_string() {
    Token t; t.kind = TK_STRING; t.line = line_;
    char quote = s_[pos_++];
    for (;;) {
      if (pos_ >= s_.size() || s_[pos_] == '\n')
        err(t.line, "unterminated string");
      char c = s_[pos_++];
      if (c == quote) return t;
      if (c == '\\') {
        if (pos_ >= s_.size()) err(t.line, "unterminated string escape");
        if (s_[pos_] == '\n') ++line_;
        ++pos_;                            // any escaped char (incl. \n)
      }
    }
  }

  Token number() {
    Token t; t.kind = TK_NUMBER; t.line = line_;
    size_t start = pos_;
    if (s_[pos_] == '0' && pos_ + 1 < s_.size() &&
        (s_[pos_ + 1] == 'x' || s_[pos_ + 1] == 'X')) {
      pos_ += 2;
      while (pos_ < s_.size() &&
             std::isxdigit(static_cast<unsigned char>(s_[pos_]))) ++pos_;
      if (pos_ == start + 2) err(t.line, "malformed hex number");
      return t;
    }
    bool seen_dot = false, seen_exp = false;
    while (pos_ < s_.size()) {
      char c = s_[pos_];
      if (std::isdigit(static_cast<unsigned char>(c))) { ++pos_; continue; }
      if (c == '.' && !seen_dot && !seen_exp) { seen_dot = true; ++pos_; continue; }
      if ((c == 'e' || c == 'E') && !seen_exp) {
        seen_exp = true; ++pos_;
        if (pos_ < s_.size() && (s_[pos_] == '+' || s_[pos_] == '-')) ++pos_;
        if (pos_ >= s_.size() ||
            !std::isdigit(static_cast<unsigned char>(s_[pos_])))
          err(t.line, "malformed number exponent");
        continue;
      }
      break;
    }
    if (pos_ < s_.size() &&
        (std::isalpha(static_cast<unsigned char>(s_[pos_])) || s_[pos_] == '_'))
      err(t.line, "malformed number");
    return t;
  }

  Token name_or_keyword() {
    Token t; t.line = line_;
    size_t start = pos_;
    while (pos_ < s_.size() &&
           (std::isalnum(static_cast<unsigned char>(s_[pos_])) ||
            s_[pos_] == '_'))
      ++pos_;
    t.text = s_.substr(start, pos_ - start);
    static const struct { const char* w; TokKind k; } kw[] = {
        {"and", TK_AND}, {"break", TK_BREAK}, {"do", TK_DO},
        {"else", TK_ELSE}, {"elseif", TK_ELSEIF}, {"end", TK_END},
        {"false", TK_FALSE}, {"for", TK_FOR}, {"function", TK_FUNCTION},
        {"if", TK_IF}, {"in", TK_IN}, {"local", TK_LOCAL}, {"nil", TK_NIL},
        {"not", TK_NOT}, {"or", TK_OR}, {"repeat", TK_REPEAT},
        {"return", TK_RETURN}, {"then", TK_THEN}, {"true", TK_TRUE},
        {"until", TK_UNTIL}, {"while", TK_WHILE},
    };
    t.kind = TK_NAME;
    for (const auto& e : kw)
      if (t.text == e.w) { t.kind = e.k; break; }
    return t;
  }

  Token symbol() {
    Token t; t.line = line_;
    char c = s_[pos_++];
    char n = pos_ < s_.size() ? s_[pos_] : '\0';
    switch (c) {
      case '+': t.kind = TK_PLUS; return t;
      case '-': t.kind = TK_MINUS; return t;
      case '*': t.kind = TK_STAR; return t;
      case '/': t.kind = TK_SLASH; return t;
      case '%': t.kind = TK_PERCENT; return t;
      case '^': t.kind = TK_CARET; return t;
      case '#': t.kind = TK_HASH; return t;
      case '(': t.kind = TK_LPAREN; return t;
      case ')': t.kind = TK_RPAREN; return t;
      case '{': t.kind = TK_LBRACE; return t;
      case '}': t.kind = TK_RBRACE; return t;
      case ']': t.kind = TK_RBRACKET; return t;
      case ';': t.kind = TK_SEMI; return t;
      case ':': t.kind = TK_COLON; return t;
      case ',': t.kind = TK_COMMA; return t;
      case '=':
        if (n == '=') { ++pos_; t.kind = TK_EQ; } else t.kind = TK_ASSIGN;
        return t;
      case '~':
        if (n == '=') { ++pos_; t.kind = TK_NE; return t; }
        err(line_, "unexpected '~'");
      case '<':
        if (n == '=') { ++pos_; t.kind = TK_LE; } else t.kind = TK_LT;
        return t;
      case '>':
        if (n == '=') { ++pos_; t.kind = TK_GE; } else t.kind = TK_GT;
        return t;
      case '.':
        if (n == '.') {
          ++pos_;
          if (pos_ < s_.size() && s_[pos_] == '.') { ++pos_; t.kind = TK_ELLIPSIS; }
          else t.kind = TK_CONCAT;
        } else {
          t.kind = TK_DOT;
        }
        return t;
      default: {
        std::ostringstream os;
        os << "unexpected character '" << c << "'";
        err(line_, os.str());
      }
    }
  }

  const std::string& s_;
  std::string file_;
  size_t pos_ = 0;
  int line_ = 1;
};

class Parser {
 public:
  Parser(const std::string& src, const std::string& file)
      : lex_(src, file) { advance(); }

  void parse_chunk_eof() {
    block();
    expect(TK_EOF, "<eof>");
  }

 private:
  void advance() { tok_ = lex_.next(); }

  bool check(TokKind k) const { return tok_.kind == k; }

  bool accept(TokKind k) {
    if (!check(k)) return false;
    advance();
    return true;
  }

  void expect(TokKind k, const char* what) {
    if (!check(k)) {
      std::ostringstream os;
      os << "expected " << what;
      lex_.err(tok_.line, os.str());
    }
    advance();
  }

  static bool block_follow(TokKind k) {
    return k == TK_EOF || k == TK_END || k == TK_ELSE || k == TK_ELSEIF ||
           k == TK_UNTIL;
  }

  // block ::= {stat [';']} [laststat [';']]
  void block() {
    for (;;) {
      if (check(TK_RETURN)) {
        advance();
        if (!block_follow(tok_.kind) && !check(TK_SEMI)) explist();
        accept(TK_SEMI);
        if (!block_follow(tok_.kind))
          lex_.err(tok_.line, "statement after return");
        return;
      }
      if (check(TK_BREAK)) {
        advance();
        accept(TK_SEMI);
        if (!block_follow(tok_.kind))
          lex_.err(tok_.line, "statement after break");
        return;
      }
      if (block_follow(tok_.kind)) return;
      statement();
      accept(TK_SEMI);
    }
  }

  void statement() {
    switch (tok_.kind) {
      case TK_DO:
        advance(); block(); expect(TK_END, "'end'"); return;
      case TK_WHILE:
        advance(); expr(); expect(TK_DO, "'do'"); block();
        expect(TK_END, "'end'"); return;
      case TK_REPEAT:
        advance(); block(); expect(TK_UNTIL, "'until'"); expr(); return;
      case TK_IF:
        advance(); expr(); expect(TK_THEN, "'then'"); block();
        while (accept(TK_ELSEIF)) { expr(); expect(TK_THEN, "'then'"); block(); }
        if (accept(TK_ELSE)) block();
        expect(TK_END, "'end'"); return;
      case TK_FOR: {
        advance();
        expect(TK_NAME, "name");
        if (accept(TK_ASSIGN)) {           // numeric for
          expr(); expect(TK_COMMA, "','"); expr();
          if (accept(TK_COMMA)) expr();
        } else {                           // generic for
          while (accept(TK_COMMA)) expect(TK_NAME, "name");
          expect(TK_IN, "'in' or '='");
          explist();
        }
        expect(TK_DO, "'do'"); block(); expect(TK_END, "'end'");
        return;
      }
      case TK_FUNCTION: {
        advance();
        expect(TK_NAME, "function name");
        while (accept(TK_DOT)) expect(TK_NAME, "name");
        if (accept(TK_COLON)) expect(TK_NAME, "method name");
        funcbody();
        return;
      }
      case TK_LOCAL:
        advance();
        if (accept(TK_FUNCTION)) {
          expect(TK_NAME, "function name");
          funcbody();
          return;
        }
        expect(TK_NAME, "name");
        while (accept(TK_COMMA)) expect(TK_NAME, "name");
        if (accept(TK_ASSIGN)) explist();
        return;
      default: {
        // exprstat: either a function call or an assignment to vars
        int line = tok_.line;
        bool is_call = suffixedexp();
        if (check(TK_ASSIGN) || check(TK_COMMA)) {
          if (is_call) lex_.err(line, "cannot assign to function call");
          while (accept(TK_COMMA)) {
            if (suffixedexp())
              lex_.err(tok_.line, "cannot assign to function call");
          }
          expect(TK_ASSIGN, "'='");
          explist();
        } else if (!is_call) {
          lex_.err(line, "syntax error (expression is not a statement)");
        }
        return;
      }
    }
  }

  void funcbody() {
    expect(TK_LPAREN, "'('");
    if (!check(TK_RPAREN)) {
      for (;;) {
        if (accept(TK_ELLIPSIS)) break;
        expect(TK_NAME, "parameter name");
        if (!accept(TK_COMMA)) break;
      }
    }
    expect(TK_RPAREN, "')'");
    block();
    expect(TK_END, "'end'");
  }

  void explist() {
    expr();
    while (accept(TK_COMMA)) expr();
  }

  // primaryexp ::= Name | '(' exp ')'
  void primaryexp() {
    if (accept(TK_NAME)) return;
    if (accept(TK_LPAREN)) {
      expr();
      expect(TK_RPAREN, "')'");
      return;
    }
    lex_.err(tok_.line, "unexpected symbol");
  }

  // suffixedexp ::= primaryexp { '.' Name | '[' exp ']' | ':' Name args | args }
  // returns true iff the whole expression is a function/method call
  bool suffixedexp() {
    primaryexp();
    bool is_call = false;
    for (;;) {
      switch (tok_.kind) {
        case TK_DOT:
          advance(); expect(TK_NAME, "field name"); is_call = false; break;
        case TK_LBRACKET:
          advance(); expr(); expect(TK_RBRACKET, "']'"); is_call = false; break;
        case TK_COLON:
          advance(); expect(TK_NAME, "method name"); args(); is_call = true;
          break;
        case TK_LPAREN: case TK_LBRACE: case TK_STRING:
          args(); is_call = true; break;
        default:
          return is_call;
      }
    }
  }

  void args() {
    if (accept(TK_STRING)) return;
    if (check(TK_LBRACE)) { tablector(); return; }
    expect(TK_LPAREN, "function arguments");
    if (!check(TK_RPAREN)) explist();
    expect(TK_RPAREN, "')'");
  }

  void tablector() {
    expect(TK_LBRACE, "'{'");
    while (!check(TK_RBRACE)) {
      if (check(TK_LBRACKET)) {
        advance(); expr(); expect(TK_RBRACKET, "']'");
        expect(TK_ASSIGN, "'='"); expr();
      } else if (check(TK_NAME)) {
        // Name '=' exp, or an expression starting with a Name — need the
        // one-token lookahead on '=' vs anything else
        Token save = tok_;
        advance();
        if (accept(TK_ASSIGN)) {
          expr();
        } else {
          // re-parse as expression continuing from the consumed Name:
          // run the suffix/operator tail with the Name as primary
          expr_after_name();
          (void)save;
        }
      } else {
        expr();
      }
      if (!accept(TK_COMMA) && !accept(TK_SEMI)) break;
    }
    expect(TK_RBRACE, "'}'");
  }

  // operator precedence (Lua 5.1 manual §2.5.6)
  struct OpPrio { int left, right; };
  static bool binop_prio(TokKind k, OpPrio* p) {
    switch (k) {
      case TK_OR: *p = {1, 1}; return true;
      case TK_AND: *p = {2, 2}; return true;
      case TK_LT: case TK_GT: case TK_LE: case TK_GE:
      case TK_NE: case TK_EQ: *p = {3, 3}; return true;
      case TK_CONCAT: *p = {5, 4}; return true;     // right assoc
      case TK_PLUS: case TK_MINUS: *p = {6, 6}; return true;
      case TK_STAR: case TK_SLASH: case TK_PERCENT: *p = {7, 7}; return true;
      case TK_CARET: *p = {10, 9}; return true;     // right assoc
      default: return false;
    }
  }
  static constexpr int kUnaryPrio = 8;

  void expr(int limit = 0) {
    simpleexp(limit);
    OpPrio p;
    while (binop_prio(tok_.kind, &p) && p.left > limit) {
      advance();
      expr(p.right);
    }
  }

  // like expr(), but the leading Name was already consumed (tablector)
  void expr_after_name() {
    suffix_tail();
    OpPrio p;
    while (binop_prio(tok_.kind, &p)) {
      advance();
      expr(p.right);
    }
  }

  void suffix_tail() {
    for (;;) {
      switch (tok_.kind) {
        case TK_DOT: advance(); expect(TK_NAME, "field name"); break;
        case TK_LBRACKET: advance(); expr(); expect(TK_RBRACKET, "']'"); break;
        case TK_COLON: advance(); expect(TK_NAME, "method name"); args(); break;
        case TK_LPAREN: case TK_LBRACE: case TK_STRING: args(); break;
        default: return;
      }
    }
  }

  void simpleexp(int limit) {
    (void)limit;
    switch (tok_.kind) {
      case TK_NIL: case TK_TRUE: case TK_FALSE: case TK_NUMBER:
      case TK_STRING: case TK_ELLIPSIS:
        advance(); return;
      case TK_FUNCTION:
        advance(); funcbody(); return;
      case TK_LBRACE:
        tablector(); return;
      case TK_NOT: case TK_HASH: case TK_MINUS:
        advance(); expr(kUnaryPrio); return;
      default:
        suffixedexp(); return;
    }
  }

  Lexer lex_;
  Token tok_;
};

}  // namespace

int main(int argc, char* argv[]) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s FILE.lua...\n", argv[0]);
    return 2;
  }
  int failures = 0;
  for (int i = 1; i < argc; ++i) {
    std::ifstream in(argv[i], std::ios::binary);
    if (!in) {
      std::fprintf(stderr, "%s: cannot open\n", argv[i]);
      ++failures;
      continue;
    }
    std::ostringstream buf;
    buf << in.rdbuf();
    std::string src = buf.str();
    // skip a shebang line, like the Lua loader does
    if (src.size() >= 1 && src[0] == '#') {
      size_t nl = src.find('\n');
      src = nl == std::string::npos ? std::string() : src.substr(nl);
    }
    try {
      Parser p(src, argv[i]);
      p.parse_chunk_eof();
      std::printf("%s: syntax OK\n", argv[i]);
    } catch (const SyntaxError& e) {
      std::fprintf(stderr, "%s\n", e.what());
      ++failures;
    }
  }
  if (failures == 0) std::printf("lua syntax check: OK\n");
  return failures ? 1 : 0;
}
