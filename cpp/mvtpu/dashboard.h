// Named timing monitors (count / total ms / average).
//
// Native form of the reference Dashboard/Monitor (Multiverso reference:
// include/multiverso/dashboard.h:16-73, src/dashboard.cpp:14-45).
#ifndef MVTPU_DASHBOARD_H_
#define MVTPU_DASHBOARD_H_

#include <chrono>
#include <map>
#include <mutex>
#include <string>

namespace mvtpu {

class Monitor {
 public:
  void Begin() { start_ = std::chrono::steady_clock::now(); }
  void End() {
    double ms = std::chrono::duration<double, std::milli>(
                    std::chrono::steady_clock::now() - start_)
                    .count();
    std::lock_guard<std::mutex> lock(mu_);
    ++count_;
    total_ms_ += ms;
  }
  long long count() const { return count_; }
  double total_ms() const { return total_ms_; }
  double average_ms() const { return count_ ? total_ms_ / count_ : 0.0; }

 private:
  std::mutex mu_;
  long long count_ = 0;
  double total_ms_ = 0.0;
  std::chrono::steady_clock::time_point start_;
};

class Dashboard {
 public:
  static Monitor* GetOrCreate(const std::string& name);
  // Renders "[name] count = N total = X ms avg = Y ms" lines.
  static std::string Display();

 private:
  static std::mutex mu_;
  static std::map<std::string, Monitor*> monitors_;
};

}  // namespace mvtpu

#endif  // MVTPU_DASHBOARD_H_
