// Pooled host memory allocator with sharing refcounts.
//
// Native equivalent of the reference's Blob backing store (Multiverso
// reference: include/multiverso/util/allocator.h:40, SmartAllocator
// free-list pools src/util/allocator.cpp:32-131, plain fallback :133-150).
// Blocks are drawn from power-of-two size-class free lists; each block
// carries a hidden header {pool ptr, atomic refcount} so buffers can be
// shared across pipeline stages (reader -> staging -> device upload) and
// returned to the pool when the last holder frees. Selected via the
// `allocator_type` flag ("smart" pooled | "plain" malloc), alignment via
// `allocator_alignment` — the same knobs the reference registers.
#ifndef MVTPU_ALLOCATOR_H_
#define MVTPU_ALLOCATOR_H_

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <mutex>
#include <unordered_map>

namespace mvtpu {

class Allocator {
 public:
  virtual ~Allocator() = default;
  virtual char* Alloc(size_t size) = 0;
  virtual void Free(char* data) = 0;
  virtual void Refer(char* data) = 0;

  // Process-wide instance chosen by the `allocator_type` flag on first use.
  static Allocator* Get();
};

// Size-class pooled allocator. Thread-safe; freed blocks go back to their
// class's free list rather than the OS.
class SmartAllocator : public Allocator {
 public:
  explicit SmartAllocator(size_t alignment = 16);
  ~SmartAllocator() override;

  char* Alloc(size_t size) override;
  void Free(char* data) override;
  void Refer(char* data) override;

  // Introspection (native self-tests / dashboards).
  size_t allocated_blocks() const { return allocated_.load(); }
  size_t pooled_blocks() const;

 private:
  struct Header;   // {free-list ptr, refcount}
  struct FreeList;

  size_t alignment_;
  mutable std::mutex mu_;
  std::unordered_map<size_t, FreeList*> pools_;  // size-class -> list
  std::atomic<size_t> allocated_{0};
};

// Plain aligned malloc/free with the same refcount header (no pooling).
class PlainAllocator : public Allocator {
 public:
  explicit PlainAllocator(size_t alignment = 16) : alignment_(alignment) {}
  char* Alloc(size_t size) override;
  void Free(char* data) override;
  void Refer(char* data) override;

 private:
  size_t alignment_;
};

}  // namespace mvtpu

#endif  // MVTPU_ALLOCATOR_H_
