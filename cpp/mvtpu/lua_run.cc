// Lua 5.1 tree-walking interpreter for the binding subset (VERDICT r3
// item 6): actually EXECUTES binding/lua/*.lua in CI instead of only
// parsing them (cpp/mvtpu/lua_check.cc remains the pure syntax gate).
//
// No Lua/LuaJIT ships in this environment, so the reference's way of
// running its binding test (torch/LuaJIT over binding/lua/test.lua —
// binding/lua/test.lua:1-79 in the Multiverso reference) has no direct
// equivalent here. This interpreter covers the language subset the
// binding sources use — tables, metatables (__index), closures, method
// sugar, multiple assignment/returns, pcall, numeric for — plus a
// minimal LuaJIT-compatible `ffi` module (cdef/load/new/copy) that
// dlopens the REAL shared library (cpp/libmultiverso_tpu.so) and
// marshals calls through the C ABI in cpp/c_api.h. Running
// binding/lua/test.lua under it therefore exercises the genuine
// end-to-end path: Lua handler arithmetic -> ffi marshaling -> C ABI ->
// native table store -> assertions on the values that come back. A
// semantic bug in util.lua (wrong arithmetic, off-by-one) now FAILS CI
// (tests/test_native.py::test_lua_binding_executes).
//
// Deliberately NOT a general Lua: no coroutines, no goto, no string
// library beyond concat/#, generic `for ... in` and varargs report a
// clear "unsupported" error at evaluation time (the parser accepts full
// 5.1 syntax so files stay parseable by lua_check's grammar).
//
// Usage: lua_run FILE.lua   (exit 0 on success; nonzero on any error)

#include <dlfcn.h>

#include "mvtpu/lua_lex.h"

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <functional>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

using namespace mvtpu_lua;  // Lexer, Token, LuaSyntaxError, TK_*

// ---------------------------------------------------------------------------
// AST
// ---------------------------------------------------------------------------

struct Expr;
struct Stat;
using ExprP = std::unique_ptr<Expr>;
using StatP = std::unique_ptr<Stat>;

struct Block {
  std::vector<StatP> stats;
};

enum class EK {
  Nil, True, False, Number, String, Vararg, Func, Table,
  Name, Index, Call, Method, Binop, Unop,
};

struct FuncBody {
  std::vector<std::string> params;
  bool vararg = false;
  Block body;
  std::string name;   // diagnostics
};

struct TableItem {
  ExprP key;    // null -> array slot
  ExprP val;
};

struct Expr {
  EK k;
  int line = 0;
  double num = 0;
  std::string str;               // Name / String / Binop+Unop op / field
  ExprP a, b;                    // operands / object / key
  std::vector<ExprP> list;       // call args
  std::vector<TableItem> items;  // table constructor
  std::shared_ptr<FuncBody> fn;  // function literal
};

enum class SK {
  ExprStat, LocalAssign, Assign, If, NumFor, GenFor, While, Repeat, Do,
  Return, Break, FuncDecl, LocalFunc,
};

struct Stat {
  SK k;
  int line = 0;
  std::vector<std::string> names;   // local names / genfor names
  std::vector<ExprP> lhs;           // assignment targets
  std::vector<ExprP> rhs;           // values / return list / genfor exps
  ExprP e1, e2, e3;                 // cond / for bounds
  Block body, body2;                // then/else, loop bodies
  std::vector<std::pair<ExprP, Block>> elifs;
  std::shared_ptr<FuncBody> fn;
};

// ---------------------------------------------------------------------------
// Parser (AST-building sibling of lua_check.cc's validator)
// ---------------------------------------------------------------------------

class Parser {
 public:
  Parser(const std::string& src, const std::string& file)
      : lex_(src, file) { advance(); }

  Block parse_chunk() {
    Block b = block();
    expect(TK_EOF, "<eof>");
    return b;
  }

 private:
  void advance() { tok_ = lex_.next(); }
  bool check(TokKind k) const { return tok_.kind == k; }
  bool accept(TokKind k) { if (!check(k)) return false; advance(); return true; }
  void expect(TokKind k, const char* what) {
    if (!check(k)) lex_.err(tok_.line, std::string("expected ") + what);
    advance();
  }
  static bool block_follow(TokKind k) {
    return k == TK_EOF || k == TK_END || k == TK_ELSE || k == TK_ELSEIF ||
           k == TK_UNTIL;
  }

  Block block() {
    Block b;
    for (;;) {
      if (check(TK_RETURN)) {
        auto s = std::make_unique<Stat>();
        s->k = SK::Return; s->line = tok_.line;
        advance();
        if (!block_follow(tok_.kind) && !check(TK_SEMI)) s->rhs = explist();
        accept(TK_SEMI);
        if (!block_follow(tok_.kind))
          lex_.err(tok_.line, "statement after return");
        b.stats.push_back(std::move(s));
        return b;
      }
      if (check(TK_BREAK)) {
        auto s = std::make_unique<Stat>();
        s->k = SK::Break; s->line = tok_.line;
        advance();
        accept(TK_SEMI);
        b.stats.push_back(std::move(s));
        return b;
      }
      if (block_follow(tok_.kind)) return b;
      b.stats.push_back(statement());
      accept(TK_SEMI);
    }
  }

  StatP statement() {
    auto s = std::make_unique<Stat>();
    s->line = tok_.line;
    switch (tok_.kind) {
      case TK_DO:
        advance(); s->k = SK::Do; s->body = block(); expect(TK_END, "'end'");
        return s;
      case TK_WHILE:
        advance(); s->k = SK::While; s->e1 = expr();
        expect(TK_DO, "'do'"); s->body = block(); expect(TK_END, "'end'");
        return s;
      case TK_REPEAT:
        advance(); s->k = SK::Repeat; s->body = block();
        expect(TK_UNTIL, "'until'"); s->e1 = expr();
        return s;
      case TK_IF: {
        advance(); s->k = SK::If;
        s->e1 = expr(); expect(TK_THEN, "'then'"); s->body = block();
        while (accept(TK_ELSEIF)) {
          ExprP c = expr(); expect(TK_THEN, "'then'");
          s->elifs.emplace_back(std::move(c), block());
        }
        if (accept(TK_ELSE)) s->body2 = block();
        expect(TK_END, "'end'");
        return s;
      }
      case TK_FOR: {
        advance();
        std::string n1 = tok_.text;
        expect(TK_NAME, "name");
        if (accept(TK_ASSIGN)) {
          s->k = SK::NumFor;
          s->names.push_back(n1);
          s->e1 = expr(); expect(TK_COMMA, "','"); s->e2 = expr();
          if (accept(TK_COMMA)) s->e3 = expr();
        } else {
          s->k = SK::GenFor;
          s->names.push_back(n1);
          while (accept(TK_COMMA)) {
            s->names.push_back(tok_.text);
            expect(TK_NAME, "name");
          }
          expect(TK_IN, "'in' or '='");
          s->rhs = explist();
        }
        expect(TK_DO, "'do'"); s->body = block(); expect(TK_END, "'end'");
        return s;
      }
      case TK_FUNCTION: {
        advance();
        s->k = SK::FuncDecl;
        // funcname ::= Name {'.' Name} [':' Name]; build the assignment
        // target expression
        ExprP target = std::make_unique<Expr>();
        target->k = EK::Name; target->line = tok_.line; target->str = tok_.text;
        std::string fname = tok_.text;
        expect(TK_NAME, "function name");
        bool method = false;
        for (;;) {
          if (accept(TK_DOT)) {
            auto idx = std::make_unique<Expr>();
            idx->k = EK::Index; idx->line = tok_.line;
            idx->a = std::move(target);
            auto key = std::make_unique<Expr>();
            key->k = EK::String; key->str = tok_.text;
            fname += "." + tok_.text;
            expect(TK_NAME, "name");
            idx->b = std::move(key);
            target = std::move(idx);
            continue;
          }
          if (accept(TK_COLON)) {
            auto idx = std::make_unique<Expr>();
            idx->k = EK::Index; idx->line = tok_.line;
            idx->a = std::move(target);
            auto key = std::make_unique<Expr>();
            key->k = EK::String; key->str = tok_.text;
            fname += ":" + tok_.text;
            expect(TK_NAME, "method name");
            idx->b = std::move(key);
            target = std::move(idx);
            method = true;
          }
          break;
        }
        s->lhs.push_back(std::move(target));
        s->fn = funcbody(fname);
        if (method) s->fn->params.insert(s->fn->params.begin(), "self");
        return s;
      }
      case TK_LOCAL: {
        advance();
        if (accept(TK_FUNCTION)) {
          s->k = SK::LocalFunc;
          s->names.push_back(tok_.text);
          std::string fname = tok_.text;
          expect(TK_NAME, "function name");
          s->fn = funcbody(fname);
          return s;
        }
        s->k = SK::LocalAssign;
        s->names.push_back(tok_.text);
        expect(TK_NAME, "name");
        while (accept(TK_COMMA)) {
          s->names.push_back(tok_.text);
          expect(TK_NAME, "name");
        }
        if (accept(TK_ASSIGN)) s->rhs = explist();
        return s;
      }
      default: {
        int line = tok_.line;
        ExprP e = suffixedexp();
        if (check(TK_ASSIGN) || check(TK_COMMA)) {
          if (e->k == EK::Call || e->k == EK::Method)
            lex_.err(line, "cannot assign to function call");
          s->k = SK::Assign;
          s->lhs.push_back(std::move(e));
          while (accept(TK_COMMA)) {
            ExprP t = suffixedexp();
            if (t->k == EK::Call || t->k == EK::Method)
              lex_.err(tok_.line, "cannot assign to function call");
            s->lhs.push_back(std::move(t));
          }
          expect(TK_ASSIGN, "'='");
          s->rhs = explist();
        } else if (e->k == EK::Call || e->k == EK::Method) {
          s->k = SK::ExprStat;
          s->rhs.push_back(std::move(e));
        } else {
          lex_.err(line, "syntax error (expression is not a statement)");
        }
        return s;
      }
    }
  }

  std::shared_ptr<FuncBody> funcbody(const std::string& name) {
    auto fn = std::make_shared<FuncBody>();
    fn->name = name;
    expect(TK_LPAREN, "'('");
    if (!check(TK_RPAREN)) {
      for (;;) {
        if (accept(TK_ELLIPSIS)) { fn->vararg = true; break; }
        fn->params.push_back(tok_.text);
        expect(TK_NAME, "parameter name");
        if (!accept(TK_COMMA)) break;
      }
    }
    expect(TK_RPAREN, "')'");
    fn->body = block();
    expect(TK_END, "'end'");
    return fn;
  }

  std::vector<ExprP> explist() {
    std::vector<ExprP> out;
    out.push_back(expr());
    while (accept(TK_COMMA)) out.push_back(expr());
    return out;
  }

  ExprP primaryexp() {
    if (check(TK_NAME)) {
      auto e = std::make_unique<Expr>();
      e->k = EK::Name; e->line = tok_.line; e->str = tok_.text;
      advance();
      return e;
    }
    if (accept(TK_LPAREN)) {
      ExprP e = expr();
      expect(TK_RPAREN, "')'");
      // parenthesised expressions truncate to one value; our evaluator
      // already adjusts non-tail list entries to one value, so reuse e
      return e;
    }
    lex_.err(tok_.line, "unexpected symbol");
  }

  ExprP suffixedexp() { return suffix_tail(primaryexp()); }

  ExprP suffix_tail(ExprP e) {
    for (;;) {
      switch (tok_.kind) {
        case TK_DOT: {
          advance();
          auto idx = std::make_unique<Expr>();
          idx->k = EK::Index; idx->line = tok_.line;
          idx->a = std::move(e);
          auto key = std::make_unique<Expr>();
          key->k = EK::String; key->str = tok_.text;
          expect(TK_NAME, "field name");
          idx->b = std::move(key);
          e = std::move(idx);
          break;
        }
        case TK_LBRACKET: {
          advance();
          auto idx = std::make_unique<Expr>();
          idx->k = EK::Index; idx->line = tok_.line;
          idx->a = std::move(e);
          idx->b = expr();
          expect(TK_RBRACKET, "']'");
          e = std::move(idx);
          break;
        }
        case TK_COLON: {
          advance();
          auto call = std::make_unique<Expr>();
          call->k = EK::Method; call->line = tok_.line;
          call->str = tok_.text;
          expect(TK_NAME, "method name");
          call->a = std::move(e);
          call->list = args();
          e = std::move(call);
          break;
        }
        case TK_LPAREN: case TK_LBRACE: case TK_STRING: {
          auto call = std::make_unique<Expr>();
          call->k = EK::Call; call->line = tok_.line;
          call->a = std::move(e);
          call->list = args();
          e = std::move(call);
          break;
        }
        default:
          return e;
      }
    }
  }

  std::vector<ExprP> args() {
    std::vector<ExprP> out;
    if (check(TK_STRING)) {
      auto e = std::make_unique<Expr>();
      e->k = EK::String; e->line = tok_.line; e->str = tok_.text;
      advance();
      out.push_back(std::move(e));
      return out;
    }
    if (check(TK_LBRACE)) {
      out.push_back(tablector());
      return out;
    }
    expect(TK_LPAREN, "function arguments");
    if (!check(TK_RPAREN)) out = explist();
    expect(TK_RPAREN, "')'");
    return out;
  }

  ExprP tablector() {
    auto e = std::make_unique<Expr>();
    e->k = EK::Table; e->line = tok_.line;
    expect(TK_LBRACE, "'{'");
    while (!check(TK_RBRACE)) {
      TableItem item;
      if (check(TK_LBRACKET)) {
        advance();
        item.key = expr();
        expect(TK_RBRACKET, "']'");
        expect(TK_ASSIGN, "'='");
        item.val = expr();
      } else if (check(TK_NAME)) {
        Token save = tok_;
        advance();
        if (accept(TK_ASSIGN)) {
          auto key = std::make_unique<Expr>();
          key->k = EK::String; key->str = save.text;
          item.key = std::move(key);
          item.val = expr();
        } else {
          // expression starting with the consumed Name
          auto name = std::make_unique<Expr>();
          name->k = EK::Name; name->line = save.line; name->str = save.text;
          item.val = binop_tail(suffix_tail(std::move(name)), 0);
        }
      } else {
        item.val = expr();
      }
      e->items.push_back(std::move(item));
      if (!accept(TK_COMMA) && !accept(TK_SEMI)) break;
    }
    expect(TK_RBRACE, "'}'");
    return e;
  }

  struct OpPrio { int left, right; };
  static bool binop_prio(TokKind k, OpPrio* p) {
    switch (k) {
      case TK_OR: *p = {1, 1}; return true;
      case TK_AND: *p = {2, 2}; return true;
      case TK_LT: case TK_GT: case TK_LE: case TK_GE:
      case TK_NE: case TK_EQ: *p = {3, 3}; return true;
      case TK_CONCAT: *p = {5, 4}; return true;
      case TK_PLUS: case TK_MINUS: *p = {6, 6}; return true;
      case TK_STAR: case TK_SLASH: case TK_PERCENT: *p = {7, 7}; return true;
      case TK_CARET: *p = {10, 9}; return true;
      default: return false;
    }
  }
  static constexpr int kUnaryPrio = 8;

  static const char* op_name(TokKind k) {
    switch (k) {
      case TK_OR: return "or"; case TK_AND: return "and";
      case TK_LT: return "<"; case TK_GT: return ">";
      case TK_LE: return "<="; case TK_GE: return ">=";
      case TK_NE: return "~="; case TK_EQ: return "==";
      case TK_CONCAT: return "..";
      case TK_PLUS: return "+"; case TK_MINUS: return "-";
      case TK_STAR: return "*"; case TK_SLASH: return "/";
      case TK_PERCENT: return "%"; case TK_CARET: return "^";
      default: return "?";
    }
  }

  ExprP binop_tail(ExprP lhs, int limit) {
    OpPrio p;
    while (binop_prio(tok_.kind, &p) && p.left > limit) {
      TokKind op = tok_.kind;
      int line = tok_.line;
      advance();
      ExprP rhs = expr(p.right);
      auto e = std::make_unique<Expr>();
      e->k = EK::Binop; e->line = line; e->str = op_name(op);
      e->a = std::move(lhs); e->b = std::move(rhs);
      lhs = std::move(e);
    }
    return lhs;
  }

  ExprP expr(int limit = 0) { return binop_tail(simpleexp(), limit); }

  ExprP simpleexp() {
    auto mk = [&](EK k) {
      auto e = std::make_unique<Expr>();
      e->k = k; e->line = tok_.line;
      return e;
    };
    switch (tok_.kind) {
      case TK_NIL: { auto e = mk(EK::Nil); advance(); return e; }
      case TK_TRUE: { auto e = mk(EK::True); advance(); return e; }
      case TK_FALSE: { auto e = mk(EK::False); advance(); return e; }
      case TK_NUMBER: {
        auto e = mk(EK::Number); e->num = tok_.num; advance(); return e;
      }
      case TK_STRING: {
        auto e = mk(EK::String); e->str = tok_.text; advance(); return e;
      }
      case TK_ELLIPSIS: { auto e = mk(EK::Vararg); advance(); return e; }
      case TK_FUNCTION: {
        auto e = mk(EK::Func);
        advance();
        e->fn = funcbody("<anonymous>");
        return e;
      }
      case TK_LBRACE: return tablector();
      case TK_NOT: case TK_HASH: case TK_MINUS: {
        TokKind op = tok_.kind;
        auto e = mk(EK::Unop);
        e->str = op == TK_NOT ? "not" : (op == TK_HASH ? "#" : "-");
        advance();
        e->a = expr(kUnaryPrio);
        return e;
      }
      default:
        return suffixedexp();
    }
  }

  Lexer lex_;
  Token tok_;
};

// ---------------------------------------------------------------------------
// Values
// ---------------------------------------------------------------------------

struct Table;
struct Closure;
struct Cdata;
struct CLib;
struct Interp;

struct Value;
using CFunc = std::function<std::vector<Value>(Interp&, std::vector<Value>&)>;

struct Value {
  enum Kind { NIL, BOOL, NUM, STR, TABLE, CLOSURE, CFUNC, CDATA, LIB } k = NIL;
  bool b = false;
  double n = 0;
  std::shared_ptr<std::string> s;
  std::shared_ptr<Table> t;
  std::shared_ptr<Closure> fn;
  std::shared_ptr<CFunc> cf;
  std::shared_ptr<Cdata> cd;
  std::shared_ptr<CLib> lib;

  static Value nil() { return Value(); }
  static Value boolean(bool v) { Value x; x.k = BOOL; x.b = v; return x; }
  static Value num(double v) { Value x; x.k = NUM; x.n = v; return x; }
  static Value str(std::string v) {
    Value x; x.k = STR; x.s = std::make_shared<std::string>(std::move(v));
    return x;
  }
  bool truthy() const { return !(k == NIL || (k == BOOL && !b)); }
};

struct BreakSignal {};
struct ReturnSignal { std::vector<Value> vals; };
struct ErrorSignal { Value v; };    // error() / runtime error (pcall-able)

struct Table {
  std::unordered_map<std::string, Value> smap;
  std::map<double, Value> nmap;
  std::shared_ptr<Table> meta;

  Value* find(const Value& key) {
    if (key.k == Value::STR) {
      auto it = smap.find(*key.s);
      return it == smap.end() ? nullptr : &it->second;
    }
    if (key.k == Value::NUM) {
      auto it = nmap.find(key.n);
      return it == nmap.end() ? nullptr : &it->second;
    }
    return nullptr;
  }
  void set(const Value& key, Value v) {
    if (key.k == Value::STR) { smap[*key.s] = std::move(v); return; }
    if (key.k == Value::NUM) {
      if (v.k == Value::NIL) nmap.erase(key.n);
      else nmap[key.n] = std::move(v);
      return;
    }
    // runtime error, not syntax: pcall-able like every other one
    throw ErrorSignal{Value::str("unsupported table key type")};
  }
  double length() const {
    double n = 0;
    while (nmap.count(n + 1)) n += 1;
    return n;
  }
};

struct Scope {
  std::unordered_map<std::string, std::shared_ptr<Value>> vars;
  std::shared_ptr<Scope> parent;

  std::shared_ptr<Value> find(const std::string& name) {
    for (Scope* s = this; s; s = s->parent.get()) {
      auto it = s->vars.find(name);
      if (it != s->vars.end()) return it->second;
    }
    return nullptr;
  }
};

struct Closure {
  std::shared_ptr<FuncBody> body;
  std::shared_ptr<Scope> env;
};

// -- ffi ---------------------------------------------------------------------

struct CSig {                 // parsed cdef: param kinds + return kind
  enum Arg { A_INT, A_PTR };
  std::vector<Arg> args;
  bool ret_int = false;       // else void
};

struct Cdata {
  enum Kind { ARR_F32, ARR_I32, ARR_I8, ARR_PTR, RAWPTR } kind;
  std::vector<uint8_t> buf;          // owned storage (ARR_*)
  void* raw = nullptr;               // RAWPTR value
  size_t count = 0;
  std::vector<Value> refs;           // keep pointee cdata alive (ARR_PTR)

  void* ptr() {
    return kind == RAWPTR ? raw : static_cast<void*>(buf.data());
  }
  size_t elem_size() const {
    switch (kind) {
      case ARR_F32: case ARR_I32: return 4;
      case ARR_I8: return 1;
      default: return sizeof(void*);
    }
  }
};

struct CLib {
  void* handle = nullptr;
  std::string path;
};

// global cdef registry: function name -> signature
std::unordered_map<std::string, CSig>* g_cdefs() {
  static auto* m = new std::unordered_map<std::string, CSig>();
  return m;
}
// typedef'd names that mean "a pointer type" (e.g. TableHandler)
std::unordered_map<std::string, bool>* g_typedefs() {
  static auto* m = new std::unordered_map<std::string, bool>();
  return m;
}

// Parse the tiny C-declaration subset the binding cdefs use:
//   typedef void* Name;
//   RET Name(TYPE a, TYPE b[], ...);
// Types are classified INT (plain int) vs PTR (anything with * or [] or a
// pointer typedef). No structs, no float-by-value (the C ABI has none).
void parse_cdef(const std::string& src) {
  std::istringstream in(src);
  std::string stmt;
  while (std::getline(in, stmt, ';')) {
    // tokenize on whitespace and punctuation we care about
    std::vector<std::string> toks;
    std::string cur;
    for (char c : stmt) {
      if (std::isalnum(static_cast<unsigned char>(c)) || c == '_') {
        cur += c;
      } else {
        if (!cur.empty()) { toks.push_back(cur); cur.clear(); }
        if (c == '*' || c == '(' || c == ')' || c == ',' || c == '[' ||
            c == ']')
          toks.push_back(std::string(1, c));
      }
    }
    if (!cur.empty()) toks.push_back(cur);
    if (toks.empty()) continue;
    if (toks[0] == "typedef") {
      // typedef void * Name  -> Name is a pointer type
      bool ptr = false;
      for (size_t i = 1; i + 1 < toks.size(); ++i)
        if (toks[i] == "*") ptr = true;
      (*g_typedefs())[toks.back()] = ptr;
      continue;
    }
    // find the function name: the token right before '('
    size_t lp = 0;
    for (size_t i = 0; i < toks.size(); ++i)
      if (toks[i] == "(") { lp = i; break; }
    if (lp == 0 || lp == toks.size() - 1) continue;   // not a function decl
    CSig sig;
    // return type: everything before the name; int iff exactly "int"
    sig.ret_int = false;
    for (size_t i = 0; i + 1 < lp; ++i)
      if (toks[i] == "int") sig.ret_int = true;
    for (size_t i = 0; i + 1 < lp; ++i)
      if (toks[i] == "*") sig.ret_int = false;   // pointer returns unused
    std::string name = toks[lp - 1];
    // params between '(' and ')'
    std::vector<std::string> param;
    auto flush = [&]() {
      if (param.empty()) return;
      if (param.size() == 1 && param[0] == "void") {   // f(void)
        param.clear();
        return;
      }
      bool ptr = false, intish = false;
      for (const auto& t : param) {
        if (t == "*" || t == "[" || t == "]") ptr = true;
        else if (t == "int" || t == "size_t") intish = true;
        auto td = g_typedefs()->find(t);
        if (td != g_typedefs()->end() && td->second) ptr = true;
      }
      sig.args.push_back(ptr ? CSig::A_PTR
                             : (intish ? CSig::A_INT : CSig::A_PTR));
      param.clear();
    };
    for (size_t i = lp + 1; i < toks.size(); ++i) {
      if (toks[i] == ")") { flush(); break; }
      if (toks[i] == ",") { flush(); continue; }
      param.push_back(toks[i]);
    }
    (*g_cdefs())[name] = sig;
  }
}

// ---------------------------------------------------------------------------
// Interpreter
// ---------------------------------------------------------------------------

struct Interp {
  std::shared_ptr<Table> globals = std::make_shared<Table>();
  std::string chunk_file;

  [[noreturn]] void rt_error(int line, const std::string& msg) {
    std::ostringstream os;
    os << chunk_file << ":" << line << ": " << msg;
    throw ErrorSignal{Value::str(os.str())};
  }

  static std::string tostring(const Value& v) {
    char buf[64];
    switch (v.k) {
      case Value::NIL: return "nil";
      case Value::BOOL: return v.b ? "true" : "false";
      case Value::NUM:
        std::snprintf(buf, sizeof(buf), "%.14g", v.n);
        return buf;
      case Value::STR: return *v.s;
      case Value::TABLE:
        std::snprintf(buf, sizeof(buf), "table: %p",
                      static_cast<void*>(v.t.get()));
        return buf;
      case Value::CLOSURE: case Value::CFUNC: return "function: ?";
      case Value::CDATA:
        std::snprintf(buf, sizeof(buf), "cdata: %p", v.cd->ptr());
        return buf;
      case Value::LIB: return "userdata: clib";
    }
    return "?";
  }

  static bool raw_equal(const Value& a, const Value& b) {
    if (a.k != b.k) return false;
    switch (a.k) {
      case Value::NIL: return true;
      case Value::BOOL: return a.b == b.b;
      case Value::NUM: return a.n == b.n;
      case Value::STR: return *a.s == *b.s;
      case Value::TABLE: return a.t == b.t;
      case Value::CLOSURE: return a.fn == b.fn;
      case Value::CFUNC: return a.cf == b.cf;
      case Value::CDATA: return a.cd == b.cd;
      case Value::LIB: return a.lib == b.lib;
    }
    return false;
  }

  double tonum(const Value& v, int line, const char* what) {
    if (v.k == Value::NUM) return v.n;
    if (v.k == Value::STR) {
      char* end = nullptr;
      double d = std::strtod(v.s->c_str(), &end);
      if (end && *end == '\0' && !v.s->empty()) return d;
    }
    rt_error(line, std::string("arithmetic on non-number (") + what + ")");
  }

  // -- table access with __index chain ---------------------------------
  Value index(const Value& obj, const Value& key, int line) {
    if (obj.k == Value::TABLE) {
      Value* v = obj.t->find(key);
      if (v && v->k != Value::NIL) return *v;
      if (obj.t->meta) {
        auto mi = obj.t->meta->smap.find("__index");
        if (mi != obj.t->meta->smap.end()) {
          if (mi->second.k == Value::TABLE)
            return index(mi->second, key, line);
          if (mi->second.k == Value::CLOSURE ||
              mi->second.k == Value::CFUNC) {
            std::vector<Value> args{obj, key};
            auto r = call(mi->second, args, line);
            return r.empty() ? Value::nil() : r[0];
          }
        }
      }
      return Value::nil();
    }
    if (obj.k == Value::CDATA) {
      if (key.k != Value::NUM) rt_error(line, "cdata index must be numeric");
      auto& cd = *obj.cd;
      size_t i = static_cast<size_t>(key.n);
      if (cd.kind == Cdata::RAWPTR)
        rt_error(line, "cannot index a raw pointer cdata");
      if (i >= cd.count) rt_error(line, "cdata index out of bounds");
      switch (cd.kind) {
        case Cdata::ARR_F32:
          return Value::num(reinterpret_cast<float*>(cd.buf.data())[i]);
        case Cdata::ARR_I32:
          return Value::num(reinterpret_cast<int32_t*>(cd.buf.data())[i]);
        case Cdata::ARR_I8:
          return Value::num(cd.buf[i]);
        case Cdata::ARR_PTR: {
          auto out = std::make_shared<Cdata>();
          out->kind = Cdata::RAWPTR;
          out->raw = reinterpret_cast<void**>(cd.buf.data())[i];
          if (i < cd.refs.size()) out->refs.push_back(cd.refs[i]);
          Value v; v.k = Value::CDATA; v.cd = out;
          return v;
        }
        default: break;
      }
    }
    if (obj.k == Value::LIB) {
      if (key.k != Value::STR) rt_error(line, "clib index must be a name");
      return lib_symbol(obj, *key.s, line);
    }
    if (obj.k == Value::STR)
      rt_error(line, "string methods are not supported in this subset");
    rt_error(line, "attempt to index a " + kind_name(obj.k) + " value");
  }

  void setindex(const Value& obj, const Value& key, Value val, int line) {
    if (obj.k == Value::TABLE) {
      obj.t->set(key, std::move(val));   // __newindex unused by the binding
      return;
    }
    if (obj.k == Value::CDATA) {
      if (key.k != Value::NUM) rt_error(line, "cdata index must be numeric");
      auto& cd = *obj.cd;
      size_t i = static_cast<size_t>(key.n);
      if (cd.kind == Cdata::RAWPTR || i >= cd.count)
        rt_error(line, "cdata store out of bounds");
      switch (cd.kind) {
        case Cdata::ARR_F32:
          reinterpret_cast<float*>(cd.buf.data())[i] =
              static_cast<float>(tonum(val, line, "cdata store"));
          return;
        case Cdata::ARR_I32:
          reinterpret_cast<int32_t*>(cd.buf.data())[i] =
              static_cast<int32_t>(tonum(val, line, "cdata store"));
          return;
        case Cdata::ARR_I8:
          cd.buf[i] = static_cast<uint8_t>(tonum(val, line, "cdata store"));
          return;
        case Cdata::ARR_PTR: {
          if (val.k != Value::CDATA)
            rt_error(line, "pointer-array store needs cdata");
          reinterpret_cast<void**>(cd.buf.data())[i] = val.cd->ptr();
          if (cd.refs.size() < cd.count) cd.refs.resize(cd.count);
          cd.refs[i] = val;    // keep pointee alive
          return;
        }
        default: break;
      }
    }
    rt_error(line, "attempt to assign into a " + kind_name(obj.k) + " value");
  }

  static std::string kind_name(Value::Kind k) {
    switch (k) {
      case Value::NIL: return "nil";
      case Value::BOOL: return "boolean";
      case Value::NUM: return "number";
      case Value::STR: return "string";
      case Value::TABLE: return "table";
      case Value::CLOSURE: case Value::CFUNC: return "function";
      case Value::CDATA: return "cdata";
      case Value::LIB: return "userdata";
    }
    return "?";
  }

  // -- ffi call marshaling ----------------------------------------------
  Value lib_symbol(const Value& libv, const std::string& name, int line) {
    auto defs = g_cdefs();
    auto it = defs->find(name);
    if (it == defs->end())
      rt_error(line, "missing cdef for symbol '" + name + "'");
    void* sym = dlsym(libv.lib->handle, name.c_str());
    if (!sym)
      rt_error(line, "undefined symbol '" + name + "' in " + libv.lib->path);
    CSig sig = it->second;
    auto fn = std::make_shared<CFunc>(
        [sym, sig, name](Interp& I, std::vector<Value>& args)
            -> std::vector<Value> {
          if (args.size() < sig.args.size())
            args.resize(sig.args.size());
          std::vector<int64_t> slots;
          std::vector<std::shared_ptr<std::string>> keep;
          for (size_t i = 0; i < sig.args.size(); ++i) {
            const Value& a = args[i];
            if (sig.args[i] == CSig::A_INT) {
              if (a.k != Value::NUM)
                throw ErrorSignal{Value::str(
                    name + ": argument " + std::to_string(i + 1) +
                    " must be a number")};
              slots.push_back(static_cast<int64_t>(a.n));
            } else {
              switch (a.k) {
                case Value::CDATA:
                  slots.push_back(
                      reinterpret_cast<int64_t>(a.cd->ptr()));
                  break;
                case Value::STR:
                  keep.push_back(a.s);
                  slots.push_back(
                      reinterpret_cast<int64_t>(keep.back()->c_str()));
                  break;
                case Value::NIL:
                  slots.push_back(0);
                  break;
                default:
                  throw ErrorSignal{Value::str(
                      name + ": argument " + std::to_string(i + 1) +
                      " must be cdata/string/nil")};
              }
            }
          }
          // x86-64 SysV: integer/pointer args ride the same registers, so
          // fixed all-int64 casts are ABI-correct for this C surface (no
          // float-by-value params exist in cpp/c_api.h)
          int64_t r = 0;
          auto p = slots.data();
          switch (slots.size()) {
            case 0: r = reinterpret_cast<int64_t (*)()>(sym)(); break;
            case 1: r = reinterpret_cast<int64_t (*)(int64_t)>(sym)(p[0]);
              break;
            case 2: r = reinterpret_cast<int64_t (*)(int64_t, int64_t)>(sym)(
                p[0], p[1]);
              break;
            case 3: r = reinterpret_cast<
                int64_t (*)(int64_t, int64_t, int64_t)>(sym)(
                p[0], p[1], p[2]);
              break;
            case 4: r = reinterpret_cast<
                int64_t (*)(int64_t, int64_t, int64_t, int64_t)>(sym)(
                p[0], p[1], p[2], p[3]);
              break;
            case 5: r = reinterpret_cast<
                int64_t (*)(int64_t, int64_t, int64_t, int64_t, int64_t)>(
                sym)(p[0], p[1], p[2], p[3], p[4]);
              break;
            case 6: r = reinterpret_cast<
                int64_t (*)(int64_t, int64_t, int64_t, int64_t, int64_t,
                            int64_t)>(sym)(
                p[0], p[1], p[2], p[3], p[4], p[5]);
              break;
            default:
              throw ErrorSignal{Value::str(name + ": too many arguments")};
          }
          (void)I;
          std::vector<Value> out;
          if (sig.ret_int)
            out.push_back(Value::num(static_cast<double>(
                static_cast<int32_t>(r))));
          return out;
        });
    Value v; v.k = Value::CFUNC; v.cf = fn;
    return v;
  }

  // -- calls -------------------------------------------------------------
  std::vector<Value> call(const Value& f, std::vector<Value>& args,
                          int line) {
    if (f.k == Value::CFUNC) return (*f.cf)(*this, args);
    if (f.k == Value::CLOSURE) {
      auto scope = std::make_shared<Scope>();
      scope->parent = f.fn->env;
      const auto& params = f.fn->body->params;
      for (size_t i = 0; i < params.size(); ++i) {
        auto cell = std::make_shared<Value>(
            i < args.size() ? args[i] : Value::nil());
        scope->vars[params[i]] = cell;
      }
      if (f.fn->body->vararg && args.size() > params.size())
        rt_error(line, "varargs are not supported in this subset");
      try {
        exec_block(f.fn->body->body, scope);
      } catch (ReturnSignal& r) {
        return std::move(r.vals);
      }
      return {};
    }
    rt_error(line, "attempt to call a " + kind_name(f.k) + " value");
  }

  // -- expression evaluation --------------------------------------------
  Value eval1(const Expr& e, const std::shared_ptr<Scope>& env) {
    auto vs = eval(e, env, false);
    return vs.empty() ? Value::nil() : vs[0];
  }

  std::vector<Value> eval(const Expr& e, const std::shared_ptr<Scope>& env,
                          bool want_multi) {
    switch (e.k) {
      case EK::Nil: return {Value::nil()};
      case EK::True: return {Value::boolean(true)};
      case EK::False: return {Value::boolean(false)};
      case EK::Number: return {Value::num(e.num)};
      case EK::String: return {Value::str(e.str)};
      case EK::Vararg:
        rt_error(e.line, "varargs are not supported in this subset");
      case EK::Func: {
        auto c = std::make_shared<Closure>();
        c->body = e.fn;
        c->env = env;
        Value v; v.k = Value::CLOSURE; v.fn = c;
        return {v};
      }
      case EK::Table: {
        auto t = std::make_shared<Table>();
        double ai = 1;
        for (size_t i = 0; i < e.items.size(); ++i) {
          const auto& item = e.items[i];
          if (item.key) {
            t->set(eval1(*item.key, env), eval1(*item.val, env));
          } else {
            t->set(Value::num(ai), eval1(*item.val, env));
            ai += 1;
          }
        }
        Value v; v.k = Value::TABLE; v.t = t;
        return {v};
      }
      case EK::Name: {
        auto cell = env->find(e.str);
        if (cell) return {*cell};
        Value* g = globals->find(Value::str(e.str));
        return {g ? *g : Value::nil()};
      }
      case EK::Index:
        return {index(eval1(*e.a, env), eval1(*e.b, env), e.line)};
      case EK::Call: {
        Value f = eval1(*e.a, env);
        std::vector<Value> args = eval_list(e.list, env);
        auto r = call(f, args, e.line);
        if (!want_multi && r.size() > 1) r.resize(1);
        return r;
      }
      case EK::Method: {
        Value obj = eval1(*e.a, env);
        Value f = index(obj, Value::str(e.str), e.line);
        std::vector<Value> args{obj};
        auto rest = eval_list(e.list, env);
        for (auto& a : rest) args.push_back(std::move(a));
        auto r = call(f, args, e.line);
        if (!want_multi && r.size() > 1) r.resize(1);
        return r;
      }
      case EK::Unop: {
        if (e.str == "not") return {Value::boolean(!eval1(*e.a, env).truthy())};
        Value a = eval1(*e.a, env);
        if (e.str == "-")
          return {Value::num(-tonum(a, e.line, "unary minus"))};
        // '#'
        if (a.k == Value::STR) return {Value::num(double(a.s->size()))};
        if (a.k == Value::TABLE) return {Value::num(a.t->length())};
        rt_error(e.line, "attempt to get length of a " + kind_name(a.k) +
                 " value");
      }
      case EK::Binop: {
        const std::string& op = e.str;
        if (op == "and") {
          Value a = eval1(*e.a, env);
          return {a.truthy() ? eval1(*e.b, env) : a};
        }
        if (op == "or") {
          Value a = eval1(*e.a, env);
          return {a.truthy() ? a : eval1(*e.b, env)};
        }
        Value a = eval1(*e.a, env);
        Value b = eval1(*e.b, env);
        if (op == "==") return {Value::boolean(raw_equal(a, b))};
        if (op == "~=") return {Value::boolean(!raw_equal(a, b))};
        if (op == "..") {
          auto sa = (a.k == Value::STR) ? *a.s
                     : (a.k == Value::NUM ? tostring(a) : std::string());
          auto sb = (b.k == Value::STR) ? *b.s
                     : (b.k == Value::NUM ? tostring(b) : std::string());
          if ((a.k != Value::STR && a.k != Value::NUM) ||
              (b.k != Value::STR && b.k != Value::NUM))
            rt_error(e.line, "attempt to concatenate a non-string value");
          return {Value::str(sa + sb)};
        }
        if (op == "<" || op == ">" || op == "<=" || op == ">=") {
          bool res;
          if (a.k == Value::STR && b.k == Value::STR) {
            int c = a.s->compare(*b.s);
            res = op == "<" ? c < 0 : op == ">" ? c > 0
                  : op == "<=" ? c <= 0 : c >= 0;
          } else {
            double x = tonum(a, e.line, "comparison");
            double y = tonum(b, e.line, "comparison");
            res = op == "<" ? x < y : op == ">" ? x > y
                  : op == "<=" ? x <= y : x >= y;
          }
          return {Value::boolean(res)};
        }
        double x = tonum(a, e.line, op.c_str());
        double y = tonum(b, e.line, op.c_str());
        double r;
        if (op == "+") r = x + y;
        else if (op == "-") r = x - y;
        else if (op == "*") r = x * y;
        else if (op == "/") r = x / y;
        else if (op == "%") r = x - std::floor(x / y) * y;
        else if (op == "^") r = std::pow(x, y);
        else rt_error(e.line, "unknown operator " + op);
        return {Value::num(r)};
      }
    }
    rt_error(e.line, "internal: unhandled expression");
  }

  std::vector<Value> eval_list(const std::vector<ExprP>& list,
                               const std::shared_ptr<Scope>& env) {
    std::vector<Value> out;
    for (size_t i = 0; i < list.size(); ++i) {
      bool tail = (i + 1 == list.size());
      auto vs = eval(*list[i], env, tail);
      if (tail) {
        for (auto& v : vs) out.push_back(std::move(v));
      } else {
        out.push_back(vs.empty() ? Value::nil() : std::move(vs[0]));
      }
    }
    return out;
  }

  // -- statements --------------------------------------------------------
  void assign_to(const Expr& target, Value v,
                 const std::shared_ptr<Scope>& env) {
    if (target.k == EK::Name) {
      auto cell = env->find(target.str);
      if (cell) { *cell = std::move(v); return; }
      globals->set(Value::str(target.str), std::move(v));
      return;
    }
    if (target.k == EK::Index) {
      Value obj = eval1(*target.a, env);
      Value key = eval1(*target.b, env);
      setindex(obj, key, std::move(v), target.line);
      return;
    }
    rt_error(target.line, "invalid assignment target");
  }

  void exec_block(const Block& b, std::shared_ptr<Scope> env) {
    for (const auto& sp : b.stats) exec_stat(*sp, env);
  }

  void exec_stat(const Stat& s, std::shared_ptr<Scope>& env) {
    switch (s.k) {
      case SK::ExprStat:
        eval(*s.rhs[0], env, true);
        return;
      case SK::LocalAssign: {
        auto vals = eval_list(s.rhs, env);
        for (size_t i = 0; i < s.names.size(); ++i) {
          env->vars[s.names[i]] = std::make_shared<Value>(
              i < vals.size() ? std::move(vals[i]) : Value::nil());
        }
        return;
      }
      case SK::Assign: {
        auto vals = eval_list(s.rhs, env);
        for (size_t i = 0; i < s.lhs.size(); ++i)
          assign_to(*s.lhs[i],
                    i < vals.size() ? vals[i] : Value::nil(), env);
        return;
      }
      case SK::FuncDecl: {
        auto c = std::make_shared<Closure>();
        c->body = s.fn;
        c->env = env;
        Value v; v.k = Value::CLOSURE; v.fn = c;
        assign_to(*s.lhs[0], std::move(v), env);
        return;
      }
      case SK::LocalFunc: {
        auto cell = std::make_shared<Value>();
        env->vars[s.names[0]] = cell;     // visible to the closure (recursion)
        auto c = std::make_shared<Closure>();
        c->body = s.fn;
        c->env = env;
        cell->k = Value::CLOSURE; cell->fn = c;
        return;
      }
      case SK::If: {
        if (eval1(*s.e1, env).truthy()) {
          auto inner = std::make_shared<Scope>();
          inner->parent = env;
          exec_block(s.body, inner);
          return;
        }
        for (const auto& [cond, blk] : s.elifs) {
          if (eval1(*cond, env).truthy()) {
            auto inner = std::make_shared<Scope>();
            inner->parent = env;
            exec_block(blk, inner);
            return;
          }
        }
        auto inner = std::make_shared<Scope>();
        inner->parent = env;
        exec_block(s.body2, inner);
        return;
      }
      case SK::NumFor: {
        double lo = tonum(eval1(*s.e1, env), s.line, "for start");
        double hi = tonum(eval1(*s.e2, env), s.line, "for limit");
        double step = s.e3 ? tonum(eval1(*s.e3, env), s.line, "for step")
                           : 1.0;
        if (step == 0) rt_error(s.line, "'for' step is zero");
        for (double i = lo;
             step > 0 ? i <= hi : i >= hi; i += step) {
          auto inner = std::make_shared<Scope>();
          inner->parent = env;
          inner->vars[s.names[0]] = std::make_shared<Value>(Value::num(i));
          try {
            exec_block(s.body, inner);
          } catch (BreakSignal&) {
            return;
          }
        }
        return;
      }
      case SK::GenFor:
        rt_error(s.line,
                 "generic 'for ... in' is not supported in this subset");
      case SK::While: {
        while (eval1(*s.e1, env).truthy()) {
          auto inner = std::make_shared<Scope>();
          inner->parent = env;
          try {
            exec_block(s.body, inner);
          } catch (BreakSignal&) {
            return;
          }
        }
        return;
      }
      case SK::Repeat: {
        for (;;) {
          auto inner = std::make_shared<Scope>();
          inner->parent = env;
          try {
            exec_block(s.body, inner);
          } catch (BreakSignal&) {
            return;
          }
          if (eval1(*s.e1, inner).truthy()) return;
        }
      }
      case SK::Do: {
        auto inner = std::make_shared<Scope>();
        inner->parent = env;
        exec_block(s.body, inner);
        return;
      }
      case SK::Return:
        throw ReturnSignal{eval_list(s.rhs, env)};
      case SK::Break:
        throw BreakSignal{};
    }
  }

  // -- chunk loading -----------------------------------------------------
  std::vector<Value> run_file(const std::string& path) {
    std::ifstream in(path, std::ios::binary);
    if (!in) throw ErrorSignal{Value::str("cannot open " + path)};
    std::ostringstream buf;
    buf << in.rdbuf();
    std::string src = buf.str();
    if (!src.empty() && src[0] == '#') {
      size_t nl = src.find('\n');
      src = nl == std::string::npos ? std::string() : src.substr(nl);
    }
    Parser p(src, path);
    Block chunk = p.parse_chunk();
    std::string prev = chunk_file;
    chunk_file = path;
    auto env = std::make_shared<Scope>();
    std::vector<Value> out;
    try {
      exec_block(chunk, env);
    } catch (ReturnSignal& r) {
      out = std::move(r.vals);
    }
    chunk_file = prev;
    return out;
  }
};

// ---------------------------------------------------------------------------
// Standard library subset + ffi
// ---------------------------------------------------------------------------

Value mkcf(CFunc f) {
  Value v; v.k = Value::CFUNC; v.cf = std::make_shared<CFunc>(std::move(f));
  return v;
}

void install_stdlib(Interp& I) {
  auto& G = *I.globals;
  auto set = [&](const char* n, Value v) { G.smap[n] = std::move(v); };

  set("print", mkcf([](Interp&, std::vector<Value>& a) {
    std::string line;
    for (size_t i = 0; i < a.size(); ++i) {
      if (i) line += "\t";
      line += Interp::tostring(a[i]);
    }
    std::printf("%s\n", line.c_str());
    std::fflush(stdout);
    return std::vector<Value>{};
  }));
  set("tostring", mkcf([](Interp&, std::vector<Value>& a) {
    return std::vector<Value>{
        Value::str(Interp::tostring(a.empty() ? Value::nil() : a[0]))};
  }));
  set("tonumber", mkcf([](Interp&, std::vector<Value>& a) {
    if (!a.empty() && a[0].k == Value::NUM) return std::vector<Value>{a[0]};
    if (!a.empty() && a[0].k == Value::STR) {
      char* end = nullptr;
      double d = std::strtod(a[0].s->c_str(), &end);
      if (end && *end == '\0' && !a[0].s->empty())
        return std::vector<Value>{Value::num(d)};
    }
    return std::vector<Value>{Value::nil()};
  }));
  set("type", mkcf([](Interp&, std::vector<Value>& a) {
    return std::vector<Value>{Value::str(
        Interp::kind_name(a.empty() ? Value::NIL : a[0].k))};
  }));
  set("error", mkcf([](Interp&, std::vector<Value>& a) -> std::vector<Value> {
    throw ErrorSignal{a.empty() ? Value::nil() : a[0]};
  }));
  set("assert", mkcf([](Interp&, std::vector<Value>& a) -> std::vector<Value> {
    if (a.empty() || !a[0].truthy())
      throw ErrorSignal{a.size() > 1 ? a[1]
                                     : Value::str("assertion failed!")};
    return a;
  }));
  set("pcall", mkcf([](Interp& I2, std::vector<Value>& a) {
    if (a.empty())
      throw ErrorSignal{Value::str("pcall needs a function")};
    Value f = a[0];
    std::vector<Value> rest(a.begin() + 1, a.end());
    std::vector<Value> out;
    try {
      auto r = I2.call(f, rest, 0);
      out.push_back(Value::boolean(true));
      for (auto& v : r) out.push_back(std::move(v));
    } catch (ErrorSignal& e) {
      out.push_back(Value::boolean(false));
      out.push_back(e.v);
    }
    return out;
  }));
  set("setmetatable", mkcf([](Interp&, std::vector<Value>& a)
                               -> std::vector<Value> {
    if (a.size() < 2 || a[0].k != Value::TABLE)
      throw ErrorSignal{Value::str("setmetatable needs (table, table)")};
    a[0].t->meta = a[1].k == Value::TABLE ? a[1].t : nullptr;
    return {a[0]};
  }));
  set("getmetatable", mkcf([](Interp&, std::vector<Value>& a)
                               -> std::vector<Value> {
    if (!a.empty() && a[0].k == Value::TABLE && a[0].t->meta) {
      Value v; v.k = Value::TABLE; v.t = a[0].t->meta;
      return {v};
    }
    return {Value::nil()};
  }));
  set("dofile", mkcf([](Interp& I2, std::vector<Value>& a)
                         -> std::vector<Value> {
    if (a.empty() || a[0].k != Value::STR)
      throw ErrorSignal{Value::str("dofile needs a path")};
    return I2.run_file(*a[0].s);
  }));

  // math
  {
    auto t = std::make_shared<Table>();
    t->smap["abs"] = mkcf([](Interp&, std::vector<Value>& a) {
      return std::vector<Value>{Value::num(std::fabs(a.at(0).n))};
    });
    t->smap["floor"] = mkcf([](Interp&, std::vector<Value>& a) {
      return std::vector<Value>{Value::num(std::floor(a.at(0).n))};
    });
    t->smap["ceil"] = mkcf([](Interp&, std::vector<Value>& a) {
      return std::vector<Value>{Value::num(std::ceil(a.at(0).n))};
    });
    t->smap["max"] = mkcf([](Interp&, std::vector<Value>& a) {
      double m = a.at(0).n;
      for (auto& v : a) m = std::max(m, v.n);
      return std::vector<Value>{Value::num(m)};
    });
    t->smap["huge"] = Value::num(HUGE_VAL);
    Value v; v.k = Value::TABLE; v.t = t;
    set("math", v);
  }
  // os
  {
    auto t = std::make_shared<Table>();
    t->smap["getenv"] = mkcf([](Interp&, std::vector<Value>& a)
                                 -> std::vector<Value> {
      if (a.empty() || a[0].k != Value::STR) return {Value::nil()};
      const char* v = std::getenv(a[0].s->c_str());
      return {v ? Value::str(v) : Value::nil()};
    });
    Value v; v.k = Value::TABLE; v.t = t;
    set("os", v);
  }
  // table
  {
    auto t = std::make_shared<Table>();
    t->smap["insert"] = mkcf([](Interp&, std::vector<Value>& a)
                                 -> std::vector<Value> {
      if (a.size() < 2 || a[0].k != Value::TABLE)
        throw ErrorSignal{Value::str("table.insert needs (table, value)")};
      if (a.size() == 2) {
        a[0].t->set(Value::num(a[0].t->length() + 1), a[1]);
      } else {
        // insert at position: shift up
        double pos = a[1].n, len = a[0].t->length();
        for (double i = len; i >= pos; i -= 1)
          a[0].t->set(Value::num(i + 1), *a[0].t->find(Value::num(i)));
        a[0].t->set(Value::num(pos), a[2]);
      }
      return {};
    });
    t->smap["concat"] = mkcf([](Interp&, std::vector<Value>& a)
                                 -> std::vector<Value> {
      std::string sep = a.size() > 1 && a[1].k == Value::STR ? *a[1].s : "";
      std::string out;
      double len = a.at(0).t->length();
      for (double i = 1; i <= len; i += 1) {
        if (i > 1) out += sep;
        out += Interp::tostring(*a[0].t->find(Value::num(i)));
      }
      return {Value::str(out)};
    });
    Value v; v.k = Value::TABLE; v.t = t;
    set("table", v);
  }
  // package (path/cpath/loaded/searchpath)
  {
    auto t = std::make_shared<Table>();
    t->smap["path"] = Value::str("./?.lua");
    t->smap["cpath"] = Value::str("./?.so");
    auto loaded = std::make_shared<Table>();
    Value lv; lv.k = Value::TABLE; lv.t = loaded;
    t->smap["loaded"] = lv;
    t->smap["searchpath"] = mkcf([](Interp&, std::vector<Value>& a)
                                     -> std::vector<Value> {
      if (a.size() < 2 || a[0].k != Value::STR || a[1].k != Value::STR)
        return {Value::nil(), Value::str("searchpath: bad arguments")};
      std::string name = *a[0].s;
      std::string sep = a.size() > 2 && a[2].k == Value::STR ? *a[2].s : ".";
      if (!sep.empty())
        for (auto& c : name)
          if (sep.find(c) != std::string::npos) c = '/';
      std::istringstream paths(*a[1].s);
      std::string tmpl, tried;
      while (std::getline(paths, tmpl, ';')) {
        std::string cand;
        for (size_t i = 0; i < tmpl.size(); ++i) {
          if (tmpl[i] == '?') cand += name;
          else cand += tmpl[i];
        }
        std::ifstream probe(cand);
        if (probe) return {Value::str(cand)};
        tried += "\n\tno file '" + cand + "'";
      }
      return {Value::nil(), Value::str(tried)};
    });
    Value v; v.k = Value::TABLE; v.t = t;
    set("package", v);
  }
  // require: package.loaded, then the ffi builtin, else error
  set("require", mkcf([](Interp& I2, std::vector<Value>& a)
                          -> std::vector<Value> {
    if (a.empty() || a[0].k != Value::STR)
      throw ErrorSignal{Value::str("require needs a module name")};
    const std::string name = *a[0].s;
    Value* pkg = I2.globals->find(Value::str("package"));
    Value* loaded = pkg->t->find(Value::str("loaded"));
    Value* mod = loaded->t->find(Value::str(name));
    if (mod && mod->k != Value::NIL) return {*mod};
    Value* ffi = I2.globals->find(Value::str("__ffi_module"));
    if (name == "ffi" && ffi) return {*ffi};
    throw ErrorSignal{Value::str("module '" + name + "' not found")};
  }));

  // -- ffi ---------------------------------------------------------------
  {
    auto t = std::make_shared<Table>();
    t->smap["cdef"] = mkcf([](Interp&, std::vector<Value>& a)
                               -> std::vector<Value> {
      if (a.empty() || a[0].k != Value::STR)
        throw ErrorSignal{Value::str("ffi.cdef needs a string")};
      parse_cdef(*a[0].s);
      return {};
    });
    t->smap["load"] = mkcf([](Interp&, std::vector<Value>& a)
                               -> std::vector<Value> {
      if (a.empty() || a[0].k != Value::STR)
        throw ErrorSignal{Value::str("ffi.load needs a path")};
      bool global = a.size() > 1 && a[1].truthy();
      void* h = dlopen(a[0].s->c_str(),
                       RTLD_NOW | (global ? RTLD_GLOBAL : RTLD_LOCAL));
      if (!h)
        throw ErrorSignal{Value::str(std::string("ffi.load: ") + dlerror())};
      auto lib = std::make_shared<CLib>();
      lib->handle = h;
      lib->path = *a[0].s;
      Value v; v.k = Value::LIB; v.lib = lib;
      return {v};
    });
    t->smap["new"] = mkcf([](Interp&, std::vector<Value>& a)
                              -> std::vector<Value> {
      if (a.empty() || a[0].k != Value::STR)
        throw ErrorSignal{Value::str("ffi.new needs a ctype string")};
      std::string ct = *a[0].s;
      // strip spaces
      std::string c;
      for (char ch : ct) if (ch != ' ') c += ch;
      auto cd = std::make_shared<Cdata>();
      size_t n = 0;
      bool vla = false;
      size_t lb = c.find('[');
      std::string base = c.substr(0, lb);
      if (lb != std::string::npos) {
        std::string idx = c.substr(lb + 1, c.find(']') - lb - 1);
        if (idx == "?") {
          vla = true;
          if (a.size() < 2 || a[1].k != Value::NUM)
            throw ErrorSignal{Value::str("ffi.new('" + ct +
                                         "') needs a length")};
          n = static_cast<size_t>(a[1].n);
        } else {
          n = static_cast<size_t>(std::strtoul(idx.c_str(), nullptr, 10));
        }
      } else {
        n = 1;
      }
      bool base_is_ptr = !base.empty() && base.back() == '*';
      std::string scalar = base_is_ptr ? base.substr(0, base.size() - 1)
                                       : base;
      auto td = g_typedefs()->find(scalar);
      bool td_ptr = td != g_typedefs()->end() && td->second;
      if (base_is_ptr || td_ptr) {
        cd->kind = Cdata::ARR_PTR;
      } else if (scalar == "float") {
        cd->kind = Cdata::ARR_F32;
      } else if (scalar == "int") {
        cd->kind = Cdata::ARR_I32;
      } else if (scalar == "char" || scalar == "unsignedchar") {
        cd->kind = Cdata::ARR_I8;
      } else {
        throw ErrorSignal{Value::str("ffi.new: unsupported ctype " + ct)};
      }
      cd->count = n;
      cd->buf.assign(n * cd->elem_size(), 0);
      // LuaJIT-style scalar initializer for fixed-size arrays
      if (!vla && a.size() > 1 && a[1].k == Value::NUM && n >= 1) {
        if (cd->kind == Cdata::ARR_I32)
          reinterpret_cast<int32_t*>(cd->buf.data())[0] =
              static_cast<int32_t>(a[1].n);
        else if (cd->kind == Cdata::ARR_F32)
          reinterpret_cast<float*>(cd->buf.data())[0] =
              static_cast<float>(a[1].n);
      }
      Value v; v.k = Value::CDATA; v.cd = cd;
      return {v};
    });
    t->smap["copy"] = mkcf([](Interp&, std::vector<Value>& a)
                               -> std::vector<Value> {
      if (a.size() < 2 || a[0].k != Value::CDATA)
        throw ErrorSignal{Value::str("ffi.copy needs (cdata, str|cdata)")};
      void* dst = a[0].cd->ptr();
      // destination capacity: owned buffers know their size; a RAWPTR
      // (pointer-array element) knows it when it points at the START of
      // a kept-alive owned buffer (the argv pattern). Unknown -> refuse
      // rather than risk a heap overflow in the CI interpreter.
      size_t cap = SIZE_MAX;
      const Cdata& dcd = *a[0].cd;
      if (dcd.kind != Cdata::RAWPTR) {
        cap = dcd.buf.size();
      } else if (!dcd.refs.empty() && dcd.refs[0].k == Value::CDATA &&
                 dcd.refs[0].cd->kind != Cdata::RAWPTR &&
                 dcd.refs[0].cd->buf.data() == dcd.raw) {
        cap = dcd.refs[0].cd->buf.size();
      }
      size_t n;
      const void* src;
      if (a[1].k == Value::STR) {
        n = a[1].s->size() + 1;           // LuaJIT copies the NUL too
        src = a[1].s->c_str();
      } else if (a[1].k == Value::CDATA && a.size() > 2 &&
                 a[2].k == Value::NUM) {
        n = static_cast<size_t>(a[2].n);
        src = a[1].cd->ptr();
      } else {
        throw ErrorSignal{Value::str("ffi.copy: unsupported arguments")};
      }
      if (cap == SIZE_MAX)
        throw ErrorSignal{Value::str(
            "ffi.copy: destination capacity unknown (raw pointer)")};
      if (n > cap)
        throw ErrorSignal{Value::str(
            "ffi.copy: write of " + std::to_string(n) +
            " bytes overflows " + std::to_string(cap) + "-byte cdata")};
      std::memcpy(dst, src, n);
      return {};
    });
    t->smap["string"] = mkcf([](Interp&, std::vector<Value>& a)
                                 -> std::vector<Value> {
      if (a.empty() || a[0].k != Value::CDATA)
        throw ErrorSignal{Value::str("ffi.string needs cdata")};
      const char* p = static_cast<const char*>(a[0].cd->ptr());
      if (a.size() > 1 && a[1].k == Value::NUM)
        return {Value::str(std::string(p, static_cast<size_t>(a[1].n)))};
      return {Value::str(std::string(p))};
    });
    Value v; v.k = Value::TABLE; v.t = t;
    set("__ffi_module", v);
  }
}

}  // namespace

int main(int argc, char* argv[]) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s FILE.lua\n", argv[0]);
    return 2;
  }
  Interp I;
  install_stdlib(I);
  try {
    I.run_file(argv[1]);
  } catch (ErrorSignal& e) {
    std::fprintf(stderr, "lua error: %s\n", Interp::tostring(e.v).c_str());
    return 1;
  } catch (LuaSyntaxError& e) {
    std::fprintf(stderr, "%s\n", e.what());
    return 1;
  }
  return 0;
}
