#include "mvtpu/allocator.h"

#include <cstdlib>
#include <cstring>
#include <memory>
#include <new>

#include "mvtpu/flags.h"
#include "mvtpu/log.h"

namespace mvtpu {

namespace {

char* AlignedAlloc(size_t bytes, size_t alignment) {
  void* raw = nullptr;
  if (alignment < sizeof(void*)) alignment = sizeof(void*);
  if (posix_memalign(&raw, alignment, bytes) != 0) throw std::bad_alloc();
  return static_cast<char*>(raw);
}

size_t SizeClass(size_t size) {
  size_t cls = 32;
  while (cls < size) cls <<= 1;
  return cls;
}

}  // namespace

struct SmartAllocator::Header {
  FreeList* list;          // owning size-class list (for Free routing)
  std::atomic<int> refs;
};

struct SmartAllocator::FreeList {
  size_t size_class;
  std::mutex mu;
  // Singly-linked free blocks; the Header area of a pooled block stores the
  // `next` pointer while it sits on the list.
  char* head = nullptr;
  size_t count = 0;
};

SmartAllocator::SmartAllocator(size_t alignment) : alignment_(alignment) {}

SmartAllocator::~SmartAllocator() {
  std::lock_guard<std::mutex> lock(mu_);
  for (auto& kv : pools_) {
    FreeList* list = kv.second;
    char* block = list->head;
    while (block != nullptr) {
      char* next;
      std::memcpy(&next, block, sizeof(char*));
      std::free(block);
      block = next;
    }
    delete list;
  }
}

char* SmartAllocator::Alloc(size_t size) {
  const size_t header = (sizeof(Header) + alignment_ - 1) / alignment_ *
                        alignment_;
  const size_t cls = SizeClass(size + header);
  FreeList* list;
  {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = pools_.find(cls);
    if (it == pools_.end()) {
      list = new FreeList();
      list->size_class = cls;
      pools_[cls] = list;
    } else {
      list = it->second;
    }
  }
  char* block = nullptr;
  {
    std::lock_guard<std::mutex> lock(list->mu);
    if (list->head != nullptr) {
      block = list->head;
      std::memcpy(&list->head, block, sizeof(char*));
      --list->count;
    }
  }
  if (block == nullptr) block = AlignedAlloc(cls, alignment_);
  auto* h = new (block) Header();
  h->list = list;
  h->refs.store(1, std::memory_order_relaxed);
  allocated_.fetch_add(1, std::memory_order_relaxed);
  return block + header;
}

void SmartAllocator::Refer(char* data) {
  const size_t header = (sizeof(Header) + alignment_ - 1) / alignment_ *
                        alignment_;
  auto* h = reinterpret_cast<Header*>(data - header);
  h->refs.fetch_add(1, std::memory_order_relaxed);
}

void SmartAllocator::Free(char* data) {
  const size_t header = (sizeof(Header) + alignment_ - 1) / alignment_ *
                        alignment_;
  char* block = data - header;
  auto* h = reinterpret_cast<Header*>(block);
  if (h->refs.fetch_sub(1, std::memory_order_acq_rel) != 1) return;
  FreeList* list = h->list;
  h->~Header();
  std::lock_guard<std::mutex> lock(list->mu);
  std::memcpy(block, &list->head, sizeof(char*));
  list->head = block;
  ++list->count;
  allocated_.fetch_sub(1, std::memory_order_relaxed);
}

size_t SmartAllocator::pooled_blocks() const {
  std::lock_guard<std::mutex> lock(mu_);
  size_t total = 0;
  for (auto& kv : pools_) {
    std::lock_guard<std::mutex> l2(kv.second->mu);
    total += kv.second->count;
  }
  return total;
}

char* PlainAllocator::Alloc(size_t size) {
  const size_t header = (sizeof(std::atomic<int>) + alignment_ - 1) /
                        alignment_ * alignment_;
  char* block = AlignedAlloc(size + header, alignment_);
  new (block) std::atomic<int>(1);
  return block + header;
}

void PlainAllocator::Refer(char* data) {
  const size_t header = (sizeof(std::atomic<int>) + alignment_ - 1) /
                        alignment_ * alignment_;
  reinterpret_cast<std::atomic<int>*>(data - header)
      ->fetch_add(1, std::memory_order_relaxed);
}

void PlainAllocator::Free(char* data) {
  const size_t header = (sizeof(std::atomic<int>) + alignment_ - 1) /
                        alignment_ * alignment_;
  char* block = data - header;
  auto* refs = reinterpret_cast<std::atomic<int>*>(block);
  if (refs->fetch_sub(1, std::memory_order_acq_rel) == 1) std::free(block);
}

Allocator* Allocator::Get() {
  static Allocator* instance = [] {
    Flags& flags = Flags::Get();
    flags.DefineString("allocator_type", "smart");
    flags.DefineInt("allocator_alignment", 16);
    const size_t align =
        static_cast<size_t>(flags.GetInt("allocator_alignment"));
    if (flags.GetString("allocator_type") == "smart")
      return static_cast<Allocator*>(new SmartAllocator(align));
    return static_cast<Allocator*>(new PlainAllocator(align));
  }();
  return instance;
}

}  // namespace mvtpu
