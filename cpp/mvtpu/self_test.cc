// In-library self-tests for the host-runtime primitives, reachable from the
// C ABI (MV_RunNativeTests) so the Python test suite can exercise the
// native allocator / queue / prefetcher / stream layers through ctypes —
// the same single-process testing stance as the rest of the framework
// (SURVEY.md §4).
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "mvtpu/allocator.h"
#include "mvtpu/async_buffer.h"
#include "mvtpu/common.h"
#include "mvtpu/log.h"
#include "mvtpu/stream.h"

namespace mvtpu {
namespace {

int failures = 0;

#define ST_CHECK(cond)                                              \
  do {                                                              \
    if (!(cond)) {                                                  \
      Log::Error("self_test failed at %s:%d: %s", __FILE__,         \
                 __LINE__, #cond);                                  \
      ++failures;                                                   \
    }                                                               \
  } while (0)

void TestAllocator() {
  SmartAllocator alloc(16);
  char* a = alloc.Alloc(100);
  std::memset(a, 7, 100);
  ST_CHECK(reinterpret_cast<uintptr_t>(a) % 16 == 0);
  ST_CHECK(alloc.allocated_blocks() == 1);
  alloc.Refer(a);
  alloc.Free(a);  // still shared
  ST_CHECK(alloc.allocated_blocks() == 1);
  alloc.Free(a);  // back to pool
  ST_CHECK(alloc.allocated_blocks() == 0);
  ST_CHECK(alloc.pooled_blocks() == 1);
  char* b = alloc.Alloc(90);  // same size class -> reuses pooled block
  ST_CHECK(b == a);
  ST_CHECK(alloc.pooled_blocks() == 0);
  char* c = alloc.Alloc(5000);  // different class
  ST_CHECK(c != nullptr);
  alloc.Free(b);
  alloc.Free(c);

  // concurrent alloc/free hammering
  std::atomic<bool> ok{true};
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&alloc, &ok] {
      for (int i = 0; i < 1000; ++i) {
        char* p = alloc.Alloc(64 + (i % 5) * 64);
        if (p == nullptr) { ok = false; continue; }
        p[0] = 1;
        alloc.Free(p);
      }
    });
  }
  for (auto& th : threads) th.join();
  ST_CHECK(ok.load());
  ST_CHECK(alloc.allocated_blocks() == 0);

  PlainAllocator plain(32);
  char* p = plain.Alloc(10);
  ST_CHECK(reinterpret_cast<uintptr_t>(p) % 32 == 0);
  plain.Refer(p);
  plain.Free(p);
  plain.Free(p);
}

void TestQueueWaiter() {
  MtQueue<int> q;
  std::vector<int> got;
  std::thread consumer([&q, &got] {
    int v;
    while (q.Pop(&v)) got.push_back(v);
  });
  for (int i = 0; i < 100; ++i) q.Push(i);
  while (q.Size() > 0) std::this_thread::yield();
  q.Exit();
  consumer.join();
  ST_CHECK(got.size() == 100);

  Waiter w;
  w.Reset(2);
  std::thread t1([&w] { w.Notify(); });
  std::thread t2([&w] { w.Notify(); });
  w.Wait();
  t1.join();
  t2.join();
}

void TestAsyncBuffer() {
  std::vector<int> buf_a(4), buf_b(4);
  std::atomic<int> fills{0};
  {
    ASyncBuffer<std::vector<int>> prefetcher(
        &buf_a, &buf_b, [&fills](std::vector<int>* buf) {
          const int n = fills.fetch_add(1);
          for (auto& v : *buf) v = n;
        });
    std::vector<int>* first = prefetcher.Get();
    ST_CHECK((*first)[0] == 0);           // first prefetch
    std::vector<int>* second = prefetcher.Get();
    ST_CHECK((*second)[0] == 1);          // refilled while we "worked"
    ST_CHECK(first != second);            // double buffering alternates
    std::vector<int>* third = prefetcher.Get();
    ST_CHECK(third == first);
    ST_CHECK((*third)[0] == 2);
  }
}

void TestStream() {
  // per-process path: concurrent test runners must not share the file
  const std::string path_s = "/tmp/mvtpu_selftest_stream." +
                             std::to_string(::getpid()) + ".bin";
  const char* path = path_s.c_str();
  {
    auto out = CreateStream(std::string("file://") + path, "w");
    ST_CHECK(out != nullptr);
    const char payload[] = "line one\nline two\r\nlast";
    out->Write(payload, sizeof(payload) - 1);
  }
  {
    auto in = CreateStream(path, "r");  // bare path = file scheme
    ST_CHECK(in != nullptr);
    TextReader reader(std::move(in), 8);  // tiny buffer: cross-refill lines
    std::string line;
    ST_CHECK(reader.GetLine(&line) && line == "line one");
    ST_CHECK(reader.GetLine(&line) && line == "line two");
    ST_CHECK(reader.GetLine(&line) && line == "last");
    ST_CHECK(!reader.GetLine(&line));
  }
  std::remove(path);
  ST_CHECK(CreateStream("hdfs://nn/path", "r") == nullptr);

  const URI u = URI::Parse("hdfs://namenode:9000/a/b");
  ST_CHECK(u.scheme == "hdfs" && u.host == "namenode:9000" &&
           u.path == "/a/b");
}

}  // namespace

int RunNativeTests() {
  failures = 0;
  TestAllocator();
  TestQueueWaiter();
  TestAsyncBuffer();
  TestStream();
  return failures;
}

}  // namespace mvtpu
