// Replay of binding/lua/test.lua's exact C-ABI call sequence.
//
// No Lua interpreter ships in this environment, so the Lua binding cannot
// execute its own test file; this driver performs the IDENTICAL sequence of
// shared-library calls the LuaJIT FFI handlers would make
// (binding/lua/{init,ArrayTableHandler,MatrixTableHandler}.lua), asserting
// the same invariants test.lua asserts. If this passes, every ABI symbol,
// signature and semantic the Lua binding depends on is verified — the only
// thing left untested is LuaJIT's own FFI marshalling.
//
// Reference counterpart: binding/lua/test.lua (torch.Tester invariants
// scaling with num_workers).

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "../c_api.h"

static int failures = 0;

static void expect_near(float a, float b, const char* what) {
  if (std::fabs(a - b) >= 1e-4f) {
    std::fprintf(stderr, "FAIL %s: %f vs %f\n", what, a, b);
    ++failures;
  }
}

int main(int argc, char* argv[]) {
  // mv.init() -> MV_Init(argc, argv) (init.lua:43-52)
  MV_Init(&argc, argv);
  int workers = MV_NumWorkers();

  // -- array invariants (test.lua:22-35) ---------------------------------
  {
    const int size = 16;
    TableHandler at = nullptr;
    MV_NewArrayTable(size, &at);             // ArrayTableHandler:new
    MV_Barrier();
    std::vector<float> delta(size);
    for (int iter = 0; iter < 3; ++iter) {
      for (int i = 0; i < size; ++i) delta[i] = float(i + 1);
      MV_AddAsyncArrayTable(at, delta.data(), size);  // at:add (async form)
    }
    MV_Barrier();
    std::vector<float> got(size);
    MV_GetArrayTable(at, got.data(), size);  // at:get
    for (int i = 0; i < size; ++i) {
      expect_near(got[i], 3.0f * float(i + 1) * float(workers),
                  "array accumulation");
    }
  }

  // -- matrix invariants, whole + rows (test.lua:37-51) ------------------
  {
    const int num_row = 4, num_col = 3, size = num_row * num_col;
    TableHandler mt = nullptr;
    MV_NewMatrixTable(num_row, num_col, &mt);  // MatrixTableHandler:new
    MV_Barrier();
    std::vector<float> delta(size, 1.0f);
    MV_AddAsyncMatrixTableAll(mt, delta.data(), size);  // mt:add(whole)
    MV_Barrier();
    float row_delta[num_col] = {10.0f, 10.0f, 10.0f};
    int row_ids[1] = {1};
    MV_AddAsyncMatrixTableByRows(mt, row_delta, num_col, row_ids, 1);
    MV_Barrier();
    std::vector<float> all(size);
    MV_GetMatrixTableAll(mt, all.data(), size);
    expect_near(all[0], 1.0f * workers, "matrix row 0");
    expect_near(all[num_col], (1.0f + 10.0f) * workers, "matrix row 1");
    float rows[num_col];
    MV_GetMatrixTableByRows(mt, rows, num_col, row_ids, 1);
    expect_near(rows[0], (1.0f + 10.0f) * workers, "matrix get by row");
  }

  // init_value averaging trick (ArrayTableHandler.lua:25-34): each worker
  // adds init/num_workers; the sum reconstructs the value
  {
    const int size = 8;
    TableHandler at = nullptr;
    MV_NewArrayTable(size, &at);
    std::vector<float> init(size);
    for (int i = 0; i < size; ++i) init[i] = float(10 + i) / float(workers);
    MV_AddArrayTable(at, init.data(), size);   // sync add, like :new
    MV_Barrier();
    std::vector<float> got(size);
    MV_GetArrayTable(at, got.data(), size);
    for (int i = 0; i < size; ++i) {
      expect_near(got[i], float(10 + i), "init_value averaging");
    }
  }

  MV_ShutDown();
  if (failures == 0) {
    std::printf("lua ABI replay: OK (workers=%d)\n", workers);
    return 0;
  }
  std::fprintf(stderr, "lua ABI replay: %d failure(s)\n", failures);
  return 1;
}
