// Standalone driver for the native self-tests, used by the sanitizer
// builds (`make tsan` / `make asan`) — race/memory detection for the C++
// runtime, which the reference never had (SURVEY §5.2: "no TSan/ASan build
// configs").
namespace mvtpu {
int RunNativeTests();
}

int main() { return mvtpu::RunNativeTests(); }
