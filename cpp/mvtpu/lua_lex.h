// Shared Lua 5.1 lexer for the binding toolchain: the syntax gate
// (lua_check.cc) and the interpreter (lua_run.cc) tokenise identically
// by construction. Errors throw LuaSyntaxError with file:line context.

#ifndef MVTPU_LUA_LEX_H_
#define MVTPU_LUA_LEX_H_

#include <cctype>
#include <cstdlib>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>

namespace mvtpu_lua {

struct LuaSyntaxError : std::runtime_error {
  explicit LuaSyntaxError(const std::string& m) : std::runtime_error(m) {}
};

enum TokKind {
  TK_EOF, TK_NAME, TK_NUMBER, TK_STRING,
  TK_AND, TK_BREAK, TK_DO, TK_ELSE, TK_ELSEIF, TK_END, TK_FALSE, TK_FOR,
  TK_FUNCTION, TK_IF, TK_IN, TK_LOCAL, TK_NIL, TK_NOT, TK_OR, TK_REPEAT,
  TK_RETURN, TK_THEN, TK_TRUE, TK_UNTIL, TK_WHILE,
  TK_PLUS, TK_MINUS, TK_STAR, TK_SLASH, TK_PERCENT, TK_CARET, TK_HASH,
  TK_EQ, TK_NE, TK_LE, TK_GE, TK_LT, TK_GT, TK_ASSIGN, TK_LPAREN, TK_RPAREN,
  TK_LBRACE, TK_RBRACE, TK_LBRACKET, TK_RBRACKET, TK_SEMI, TK_COLON,
  TK_COMMA, TK_DOT, TK_CONCAT, TK_ELLIPSIS,
};

struct Token {
  TokKind kind = TK_EOF;
  std::string text;   // NAME/STRING payload
  double num = 0;     // NUMBER payload
  int line = 1;
};

class Lexer {
 public:
  Lexer(const std::string& src, std::string file)
      : s_(src), file_(std::move(file)) {}

  Token next() {
    skip_space_and_comments();
    Token t;
    t.line = line_;
    if (pos_ >= s_.size()) { t.kind = TK_EOF; return t; }
    char c = s_[pos_];
    if (std::isalpha(static_cast<unsigned char>(c)) || c == '_')
      return name_or_keyword();
    if (std::isdigit(static_cast<unsigned char>(c)) ||
        (c == '.' && pos_ + 1 < s_.size() &&
         std::isdigit(static_cast<unsigned char>(s_[pos_ + 1]))))
      return number();
    if (c == '"' || c == '\'') return short_string();
    if (c == '[') {
      size_t lvl;
      if (long_bracket_level(&lvl)) return long_string(lvl);
      ++pos_; t.kind = TK_LBRACKET; return t;
    }
    return symbol();
  }

  [[noreturn]] void err(int line, const std::string& msg) const {
    std::ostringstream os;
    os << file_ << ":" << line << ": " << msg;
    throw LuaSyntaxError(os.str());
  }

  const std::string& file() const { return file_; }

 private:
  void skip_space_and_comments() {
    for (;;) {
      while (pos_ < s_.size() &&
             std::isspace(static_cast<unsigned char>(s_[pos_]))) {
        if (s_[pos_] == '\n') ++line_;
        ++pos_;
      }
      if (pos_ + 1 < s_.size() && s_[pos_] == '-' && s_[pos_ + 1] == '-') {
        pos_ += 2;
        size_t lvl;
        if (pos_ < s_.size() && s_[pos_] == '[' && long_bracket_level(&lvl)) {
          long_string(lvl);
        } else {
          while (pos_ < s_.size() && s_[pos_] != '\n') ++pos_;
        }
        continue;
      }
      return;
    }
  }

  bool long_bracket_level(size_t* lvl) const {
    size_t p = pos_ + 1, eq = 0;
    while (p < s_.size() && s_[p] == '=') { ++eq; ++p; }
    if (p < s_.size() && s_[p] == '[') { *lvl = eq; return true; }
    return false;
  }

  Token long_string(size_t lvl) {
    Token t; t.kind = TK_STRING; t.line = line_;
    pos_ += 2 + lvl;
    if (pos_ < s_.size() && s_[pos_] == '\n') { ++line_; ++pos_; }
    std::string close = "]" + std::string(lvl, '=') + "]";
    size_t start = pos_;
    for (;;) {
      if (pos_ >= s_.size()) err(t.line, "unterminated long string/comment");
      if (s_[pos_] == ']' && s_.compare(pos_, close.size(), close) == 0) {
        t.text = s_.substr(start, pos_ - start);
        pos_ += close.size();
        return t;
      }
      if (s_[pos_] == '\n') ++line_;
      ++pos_;
    }
  }

  Token short_string() {
    Token t; t.kind = TK_STRING; t.line = line_;
    char quote = s_[pos_++];
    std::string out;
    for (;;) {
      if (pos_ >= s_.size() || s_[pos_] == '\n')
        err(t.line, "unterminated string");
      char c = s_[pos_++];
      if (c == quote) { t.text = out; return t; }
      if (c == '\\') {
        if (pos_ >= s_.size()) err(t.line, "unterminated string escape");
        char e = s_[pos_++];
        switch (e) {
          case 'n': out += '\n'; break;
          case 't': out += '\t'; break;
          case 'r': out += '\r'; break;
          case 'a': out += '\a'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'v': out += '\v'; break;
          case '\n': out += '\n'; ++line_; break;
          case '\\': case '"': case '\'': out += e; break;
          default:
            if (std::isdigit(static_cast<unsigned char>(e))) {
              int v = e - '0';
              for (int k = 0; k < 2 && pos_ < s_.size() &&
                   std::isdigit(static_cast<unsigned char>(s_[pos_])); ++k)
                v = v * 10 + (s_[pos_++] - '0');
              out += static_cast<char>(v);
            } else {
              out += e;
            }
        }
        continue;
      }
      out += c;
    }
  }

  Token number() {
    Token t; t.kind = TK_NUMBER; t.line = line_;
    size_t start = pos_;
    if (s_[pos_] == '0' && pos_ + 1 < s_.size() &&
        (s_[pos_ + 1] == 'x' || s_[pos_ + 1] == 'X')) {
      pos_ += 2;
      while (pos_ < s_.size() &&
             std::isxdigit(static_cast<unsigned char>(s_[pos_]))) ++pos_;
      if (pos_ == start + 2) err(t.line, "malformed hex number");
      t.num = static_cast<double>(
          std::strtoull(s_.substr(start + 2, pos_ - start - 2).c_str(),
                        nullptr, 16));
      return t;
    }
    bool seen_dot = false, seen_exp = false;
    while (pos_ < s_.size()) {
      char c = s_[pos_];
      if (std::isdigit(static_cast<unsigned char>(c))) { ++pos_; continue; }
      if (c == '.' && !seen_dot && !seen_exp) { seen_dot = true; ++pos_; continue; }
      if ((c == 'e' || c == 'E') && !seen_exp) {
        seen_exp = true; ++pos_;
        if (pos_ < s_.size() && (s_[pos_] == '+' || s_[pos_] == '-')) ++pos_;
        if (pos_ >= s_.size() ||
            !std::isdigit(static_cast<unsigned char>(s_[pos_])))
          err(t.line, "malformed number exponent");
        continue;
      }
      break;
    }
    if (pos_ < s_.size() &&
        (std::isalpha(static_cast<unsigned char>(s_[pos_])) || s_[pos_] == '_'))
      err(t.line, "malformed number");
    t.num = std::strtod(s_.substr(start, pos_ - start).c_str(), nullptr);
    return t;
  }

  Token name_or_keyword() {
    Token t; t.line = line_;
    size_t start = pos_;
    while (pos_ < s_.size() &&
           (std::isalnum(static_cast<unsigned char>(s_[pos_])) ||
            s_[pos_] == '_'))
      ++pos_;
    t.text = s_.substr(start, pos_ - start);
    static const struct { const char* w; TokKind k; } kw[] = {
        {"and", TK_AND}, {"break", TK_BREAK}, {"do", TK_DO},
        {"else", TK_ELSE}, {"elseif", TK_ELSEIF}, {"end", TK_END},
        {"false", TK_FALSE}, {"for", TK_FOR}, {"function", TK_FUNCTION},
        {"if", TK_IF}, {"in", TK_IN}, {"local", TK_LOCAL}, {"nil", TK_NIL},
        {"not", TK_NOT}, {"or", TK_OR}, {"repeat", TK_REPEAT},
        {"return", TK_RETURN}, {"then", TK_THEN}, {"true", TK_TRUE},
        {"until", TK_UNTIL}, {"while", TK_WHILE},
    };
    t.kind = TK_NAME;
    for (const auto& e : kw)
      if (t.text == e.w) { t.kind = e.k; break; }
    return t;
  }

  Token symbol() {
    Token t; t.line = line_;
    char c = s_[pos_++];
    char n = pos_ < s_.size() ? s_[pos_] : '\0';
    switch (c) {
      case '+': t.kind = TK_PLUS; return t;
      case '-': t.kind = TK_MINUS; return t;
      case '*': t.kind = TK_STAR; return t;
      case '/': t.kind = TK_SLASH; return t;
      case '%': t.kind = TK_PERCENT; return t;
      case '^': t.kind = TK_CARET; return t;
      case '#': t.kind = TK_HASH; return t;
      case '(': t.kind = TK_LPAREN; return t;
      case ')': t.kind = TK_RPAREN; return t;
      case '{': t.kind = TK_LBRACE; return t;
      case '}': t.kind = TK_RBRACE; return t;
      case ']': t.kind = TK_RBRACKET; return t;
      case ';': t.kind = TK_SEMI; return t;
      case ':': t.kind = TK_COLON; return t;
      case ',': t.kind = TK_COMMA; return t;
      case '=':
        if (n == '=') { ++pos_; t.kind = TK_EQ; } else t.kind = TK_ASSIGN;
        return t;
      case '~':
        if (n == '=') { ++pos_; t.kind = TK_NE; return t; }
        err(line_, "unexpected '~'");
      case '<':
        if (n == '=') { ++pos_; t.kind = TK_LE; } else t.kind = TK_LT;
        return t;
      case '>':
        if (n == '=') { ++pos_; t.kind = TK_GE; } else t.kind = TK_GT;
        return t;
      case '.':
        if (n == '.') {
          ++pos_;
          if (pos_ < s_.size() && s_[pos_] == '.') { ++pos_; t.kind = TK_ELLIPSIS; }
          else t.kind = TK_CONCAT;
        } else {
          t.kind = TK_DOT;
        }
        return t;
      default: {
        std::ostringstream os;
        os << "unexpected character '" << c << "'";
        err(line_, os.str());
      }
    }
  }

  const std::string& s_;
  std::string file_;
  size_t pos_ = 0;
  int line_ = 1;
};


}  // namespace mvtpu_lua

#endif  // MVTPU_LUA_LEX_H_
