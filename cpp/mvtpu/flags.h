// Typed flag registry + "-key=value" CLI parsing.
//
// Native form of the reference config system (Multiverso reference:
// include/multiverso/util/configure.h:67-110, src/util/configure.cpp:9-44),
// sharing behavior with the Python registry in multiverso_tpu/config.py:
// one registry keyed by name, argv compaction on parse, programmatic set.
#ifndef MVTPU_FLAGS_H_
#define MVTPU_FLAGS_H_

#include <map>
#include <mutex>
#include <string>

namespace mvtpu {

class Flags {
 public:
  static Flags& Get();

  void DefineInt(const std::string& name, long long value);
  void DefineDouble(const std::string& name, double value);
  void DefineBool(const std::string& name, bool value);
  void DefineString(const std::string& name, const std::string& value);

  // Returns false if the flag is unknown or the text does not coerce.
  bool Set(const std::string& name, const std::string& text);
  bool Known(const std::string& name) const;

  long long GetInt(const std::string& name, long long fallback = 0) const;
  double GetDouble(const std::string& name, double fallback = 0.0) const;
  bool GetBool(const std::string& name, bool fallback = false) const;
  std::string GetString(const std::string& name,
                        const std::string& fallback = "") const;

  // Consumes known "-key=value" tokens, compacting argv in place; returns
  // the new argc.
  int ParseCmdFlags(int argc, char** argv);

 private:
  enum class Type { kInt, kDouble, kBool, kString };
  struct Entry {
    Type type;
    long long i = 0;
    double d = 0.0;
    bool b = false;
    std::string s;
  };
  mutable std::mutex mu_;
  std::map<std::string, Entry> entries_;
};

}  // namespace mvtpu

#endif  // MVTPU_FLAGS_H_
