// Native data loaders: word2vec corpus vocab/encoder + libsvm parser.
//
// Native re-build of the reference app readers — WordEmbedding's
// Dictionary/Reader (Multiverso reference:
// Applications/WordEmbedding/src/dictionary.cpp, reader.cpp) and
// LogisticRegression's SampleReader parse path
// (Applications/LogisticRegression/src/reader.cpp:169). These are the
// host-side hot loops of the data pipeline; the Python apps call them via
// ctypes (multiverso_tpu/native.py) to feed the device-resident training
// paths without Python tokenisation overhead.
#ifndef MVTPU_READER_H_
#define MVTPU_READER_H_

#include <cstdint>
#include <string>
#include <unordered_map>
#include <vector>

namespace mvtpu {

class Vocab {
 public:
  // Streams the corpus, counts whitespace tokens, keeps count >= min_count,
  // orders by descending count (reference Dictionary semantics).
  bool Build(const std::string& path, int min_count);

  int size() const { return static_cast<int>(words_.size()); }
  long long train_words() const { return train_words_; }
  const std::vector<long long>& counts() const { return counts_; }
  const std::string& word(int id) const { return words_[id]; }
  int id(const std::string& word) const {
    auto it = index_.find(word);
    return it == index_.end() ? -1 : it->second;
  }

  // Encodes the corpus into (word ids, sentence ids); one input line = one
  // sentence; out-of-vocab tokens are dropped; sentences with < 2 surviving
  // tokens are skipped. Returns the consumed word count (pre-drop) in
  // *words_read for lr-decay bookkeeping.
  bool Encode(const std::string& path, std::vector<int32_t>* ids,
              std::vector<int32_t>* sent_ids, long long* words_read) const;

 private:
  std::unordered_map<std::string, int> index_;
  std::vector<std::string> words_;
  std::vector<long long> counts_;
  long long train_words_ = 0;
};

// Parsed libsvm/dense samples in CSR-like layout.
struct SvmData {
  std::vector<float> labels;
  std::vector<int64_t> indptr;  // size labels.size() + 1
  std::vector<int32_t> keys;
  std::vector<double> values;
};

// "label k:v k:v ..." per line (value defaults to 1 when omitted).
bool ParseLibsvm(const std::string& path, SvmData* out);

// Packed binary sparse records (LogReg bsparse format,
// LR/src/reader.cpp:382-444): <u64 nkeys><i32 label><f64 weight> + keys.
// Returns false on open failure or a truncated record.
bool ParseBsparse(const std::string& path, SvmData* out);

}  // namespace mvtpu

#endif  // MVTPU_READER_H_
