#include "mvtpu/dashboard.h"

#include <sstream>

namespace mvtpu {

std::mutex Dashboard::mu_;
std::map<std::string, Monitor*> Dashboard::monitors_;

Monitor* Dashboard::GetOrCreate(const std::string& name) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = monitors_.find(name);
  if (it != monitors_.end()) return it->second;
  Monitor* mon = new Monitor();
  monitors_[name] = mon;
  return mon;
}

std::string Dashboard::Display() {
  std::lock_guard<std::mutex> lock(mu_);
  std::ostringstream out;
  out << "--------------Dashboard--------------\n";
  for (const auto& kv : monitors_) {
    out << "[" << kv.first << "] count = " << kv.second->count()
        << " total = " << kv.second->total_ms()
        << " ms avg = " << kv.second->average_ms() << " ms\n";
  }
  return out.str();
}

}  // namespace mvtpu
