// Host-side concurrency primitives.
//
// Native equivalents of the reference utilities (Multiverso reference:
// include/multiverso/util/mt_queue.h:19-147, util/waiter.h:9-35,
// util/async_buffer.h:11-116). These back the local table store's async
// apply thread and the native data loaders.
#ifndef MVTPU_COMMON_H_
#define MVTPU_COMMON_H_

#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <utility>

namespace mvtpu {

// Blocking MPMC queue with an Exit/Alive shutdown protocol.
template <typename T>
class MtQueue {
 public:
  MtQueue() : alive_(true) {}

  void Push(T item) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      queue_.push_back(std::move(item));
    }
    cv_.notify_one();
  }

  // Blocks until an item arrives or Exit(); returns false on shutdown.
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lock(mu_);
    cv_.wait(lock, [this] { return !queue_.empty() || !alive_; });
    if (queue_.empty()) return false;
    *out = std::move(queue_.front());
    queue_.pop_front();
    return true;
  }

  bool TryPop(T* out) {
    std::lock_guard<std::mutex> lock(mu_);
    if (queue_.empty()) return false;
    *out = std::move(queue_.front());
    queue_.pop_front();
    return true;
  }

  size_t Size() const {
    std::lock_guard<std::mutex> lock(mu_);
    return queue_.size();
  }

  bool Empty() const {
    std::lock_guard<std::mutex> lock(mu_);
    return queue_.empty();
  }

  void Exit() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      alive_ = false;
    }
    cv_.notify_all();
  }

  bool Alive() const {
    std::lock_guard<std::mutex> lock(mu_);
    return alive_;
  }

 private:
  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::deque<T> queue_;
  bool alive_;
};

// Counted latch: Wait blocks until the count reaches zero.
class Waiter {
 public:
  explicit Waiter(int count = 0) : count_(count) {}

  void Reset(int count) {
    std::lock_guard<std::mutex> lock(mu_);
    count_ = count;
  }

  void Notify() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      --count_;
    }
    cv_.notify_all();
  }

  void Wait() {
    std::unique_lock<std::mutex> lock(mu_);
    cv_.wait(lock, [this] { return count_ <= 0; });
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  int count_;
};

}  // namespace mvtpu

#endif  // MVTPU_COMMON_H_
