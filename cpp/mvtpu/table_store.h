// In-process float table store: the C ABI's standalone backend.
//
// Native re-implementation of the reference's single-process PS semantics
// (Multiverso reference: role=ALL where worker and server live in one
// process — src/zoo.cpp:23,31 — backed by ArrayTable/MatrixTable storage,
// src/table/array_table.cpp:98-152, src/table/matrix_table.cpp:348-465,
// with server-side updaters, src/updater/). Bindings that load the shared
// library without a host runtime (the Lua FFI binding, C programs) get the
// full Get/Add/updater/checkpoint behavior locally; when the Python runtime
// installs the bridge (bridge.h), these tables are bypassed and state lives
// in TPU HBM instead.
//
// Async adds run on a per-store apply thread draining an MtQueue — the
// worker-actor pattern (src/worker.cpp) reduced to one process.
#ifndef MVTPU_TABLE_STORE_H_
#define MVTPU_TABLE_STORE_H_

#include <atomic>
#include <cstdio>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "mvtpu/common.h"

namespace mvtpu {

struct AddOptionC {
  int worker_id = 0;
  float learning_rate = 0.01f;
  float momentum = 0.0f;
  float rho = 0.1f;
  float lambda = 0.1f;
};

// Server-side updater over a contiguous float shard (default/sgd/adagrad/
// momentum_sgd, matching src/updater formulas; OpenMP-parallel like
// src/updater/updater.cpp:15-22).
class Updater {
 public:
  virtual ~Updater() = default;
  virtual void Update(std::vector<float>& data, const float* delta,
                      size_t offset, size_t size, const AddOptionC& option) = 0;
  static std::unique_ptr<Updater> Create(const std::string& type,
                                         size_t table_size, int num_workers);
};

class Table {
 public:
  Table(long long num_row, long long num_col, const std::string& updater_type,
        int num_workers);

  long long num_row() const { return num_row_; }
  long long num_col() const { return num_col_; }
  long long size() const { return num_row_ * num_col_; }

  void Get(float* out, long long size) const;
  void GetRows(const int* row_ids, int n, float* out) const;
  void Add(const float* delta, long long size, const AddOptionC& option);
  void AddRows(const int* row_ids, int n, const float* delta,
               const AddOptionC& option);

  bool Store(std::FILE* f) const;
  bool Load(std::FILE* f);

 private:
  friend class TableStore;
  long long num_row_;
  long long num_col_;
  mutable std::mutex mu_;
  std::vector<float> data_;
  std::unique_ptr<Updater> updater_;
};

// Owns tables + the async apply thread.
class TableStore {
 public:
  static TableStore& Get();

  int CreateTable(long long num_row, long long num_col);
  Table* table(int id);

  // Enqueue an async whole-table or row add (copies the delta).
  void AddAsync(int table_id, std::vector<float> delta,
                std::vector<int> row_ids, AddOptionC option);
  // Drain pending async adds (MV_Barrier semantics in-process).
  void Flush();
  void Shutdown();

 private:
  TableStore();
  ~TableStore();
  void ApplyLoop();

  struct PendingAdd {
    int table_id;
    std::vector<float> delta;
    std::vector<int> row_ids;  // empty = whole table
    AddOptionC option;
  };

  std::mutex mu_;
  std::vector<std::unique_ptr<Table>> tables_;
  MtQueue<PendingAdd> queue_;
  std::atomic<long long> enqueued_{0};
  std::atomic<long long> applied_{0};
  std::thread apply_thread_;
  std::atomic<bool> running_{false};
};

}  // namespace mvtpu

#endif  // MVTPU_TABLE_STORE_H_
