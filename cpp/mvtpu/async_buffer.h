// Double-buffered background prefetcher.
//
// Native equivalent of the reference's ASyncBuffer (Multiverso reference:
// include/multiverso/util/async_buffer.h:11-116): a background thread runs
// the user fill action into the non-ready buffer; Get() waits for the
// prefetch, swaps buffers, and immediately triggers the next fill. Used to
// overlap host-side data preparation with device steps (the same
// compute/IO overlap the reference uses between parameter pulls and
// training, LR/src/model/ps_model.cpp:236).
#ifndef MVTPU_ASYNC_BUFFER_H_
#define MVTPU_ASYNC_BUFFER_H_

#include <functional>
#include <thread>
#include <utility>

#include "mvtpu/common.h"

namespace mvtpu {

template <typename BufferT>
class ASyncBuffer {
 public:
  using Fill = std::function<void(BufferT* buffer)>;

  // Both buffers are owned by the caller and must outlive this object.
  ASyncBuffer(BufferT* buffer_a, BufferT* buffer_b, Fill fill)
      : buffers_{buffer_a, buffer_b}, fill_(std::move(fill)) {
    ready_.Reset(1);
    worker_ = std::thread(&ASyncBuffer::Loop, this);
    Trigger(0);
  }

  ~ASyncBuffer() { Join(); }

  // Waits for the in-flight prefetch, returns its buffer, and starts
  // prefetching into the other one.
  BufferT* Get() {
    ready_.Wait();
    BufferT* out = buffers_[current_];
    current_ ^= 1;
    ready_.Reset(1);
    Trigger(current_);
    return out;
  }

  // Stops the background thread (idempotent). Restartable is not needed —
  // construct a new instance, matching the reference's Join semantics.
  void Join() {
    if (worker_.joinable()) {
      jobs_.Exit();
      worker_.join();
    }
  }

 private:
  void Trigger(int slot) { jobs_.Push(slot); }

  void Loop() {
    int slot;
    while (jobs_.Pop(&slot)) {
      fill_(buffers_[slot]);
      ready_.Notify();
    }
  }

  BufferT* buffers_[2];
  Fill fill_;
  int current_ = 0;
  Waiter ready_;
  MtQueue<int> jobs_;
  std::thread worker_;
};

}  // namespace mvtpu

#endif  // MVTPU_ASYNC_BUFFER_H_
