#include "mvtpu/flags.h"

#include <algorithm>
#include <cstdlib>
#include <cstring>

namespace mvtpu {

Flags& Flags::Get() {
  static Flags instance;
  return instance;
}

void Flags::DefineInt(const std::string& name, long long value) {
  std::lock_guard<std::mutex> lock(mu_);
  if (entries_.count(name)) return;
  Entry e;
  e.type = Type::kInt;
  e.i = value;
  entries_[name] = e;
}

void Flags::DefineDouble(const std::string& name, double value) {
  std::lock_guard<std::mutex> lock(mu_);
  if (entries_.count(name)) return;
  Entry e;
  e.type = Type::kDouble;
  e.d = value;
  entries_[name] = e;
}

void Flags::DefineBool(const std::string& name, bool value) {
  std::lock_guard<std::mutex> lock(mu_);
  if (entries_.count(name)) return;
  Entry e;
  e.type = Type::kBool;
  e.b = value;
  entries_[name] = e;
}

void Flags::DefineString(const std::string& name, const std::string& value) {
  std::lock_guard<std::mutex> lock(mu_);
  if (entries_.count(name)) return;
  Entry e;
  e.type = Type::kString;
  e.s = value;
  entries_[name] = e;
}

static bool ParseBool(const std::string& text, bool* out) {
  std::string t;
  t.reserve(text.size());
  for (char c : text) t.push_back(static_cast<char>(std::tolower(c)));
  if (t == "true" || t == "1" || t == "yes" || t == "on") {
    *out = true;
    return true;
  }
  if (t == "false" || t == "0" || t == "no" || t == "off") {
    *out = false;
    return true;
  }
  return false;
}

bool Flags::Set(const std::string& name, const std::string& text) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(name);
  if (it == entries_.end()) return false;
  Entry& e = it->second;
  char* end = nullptr;
  switch (e.type) {
    case Type::kInt: {
      long long v = std::strtoll(text.c_str(), &end, 10);
      if (end == text.c_str() || *end != '\0') return false;
      e.i = v;
      return true;
    }
    case Type::kDouble: {
      double v = std::strtod(text.c_str(), &end);
      if (end == text.c_str() || *end != '\0') return false;
      e.d = v;
      return true;
    }
    case Type::kBool:
      return ParseBool(text, &e.b);
    case Type::kString:
      e.s = text;
      return true;
  }
  return false;
}

bool Flags::Known(const std::string& name) const {
  std::lock_guard<std::mutex> lock(mu_);
  return entries_.count(name) != 0;
}

long long Flags::GetInt(const std::string& name, long long fallback) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(name);
  return it != entries_.end() && it->second.type == Type::kInt ? it->second.i
                                                               : fallback;
}

double Flags::GetDouble(const std::string& name, double fallback) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(name);
  return it != entries_.end() && it->second.type == Type::kDouble
             ? it->second.d
             : fallback;
}

bool Flags::GetBool(const std::string& name, bool fallback) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(name);
  return it != entries_.end() && it->second.type == Type::kBool ? it->second.b
                                                                : fallback;
}

std::string Flags::GetString(const std::string& name,
                             const std::string& fallback) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(name);
  return it != entries_.end() && it->second.type == Type::kString
             ? it->second.s
             : fallback;
}

int Flags::ParseCmdFlags(int argc, char** argv) {
  int kept = 0;
  for (int i = 0; i < argc; ++i) {
    const char* token = argv[i];
    const char* body = nullptr;
    if (std::strncmp(token, "--", 2) == 0) {
      body = token + 2;
    } else if (token[0] == '-') {
      body = token + 1;
    }
    bool consumed = false;
    if (body != nullptr) {
      const char* eq = std::strchr(body, '=');
      if (eq != nullptr) {
        std::string key(body, eq - body);
        if (Known(key) && Set(key, std::string(eq + 1))) consumed = true;
      }
    }
    if (!consumed) argv[kept++] = argv[i];
  }
  return kept;
}

}  // namespace mvtpu
