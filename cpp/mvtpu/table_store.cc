#include "mvtpu/table_store.h"

#include <cmath>
#include <cstring>

#include "mvtpu/flags.h"
#include "mvtpu/log.h"

namespace mvtpu {

namespace {

constexpr float kAdaGradEps = 1e-6f;

class DefaultUpdater : public Updater {
 public:
  void Update(std::vector<float>& data, const float* delta, size_t offset,
              size_t size, const AddOptionC&) override {
#pragma omp parallel for
    for (long long i = 0; i < static_cast<long long>(size); ++i) {
      data[offset + i] += delta[i];
    }
  }
};

class SgdUpdater : public Updater {
 public:
  void Update(std::vector<float>& data, const float* delta, size_t offset,
              size_t size, const AddOptionC&) override {
#pragma omp parallel for
    for (long long i = 0; i < static_cast<long long>(size); ++i) {
      data[offset + i] -= delta[i];
    }
  }
};

class MomentumUpdater : public Updater {
 public:
  explicit MomentumUpdater(size_t table_size) : state_(table_size, 0.0f) {}

  void Update(std::vector<float>& data, const float* delta, size_t offset,
              size_t size, const AddOptionC& option) override {
    const float m = option.momentum;
#pragma omp parallel for
    for (long long i = 0; i < static_cast<long long>(size); ++i) {
      float s = m * state_[offset + i] + (1.0f - m) * delta[i];
      state_[offset + i] = s;
      data[offset + i] -= s;
    }
  }

 private:
  std::vector<float> state_;
};

class AdaGradUpdater : public Updater {
 public:
  AdaGradUpdater(size_t table_size, int num_workers)
      : size_(table_size),
        g_sqr_(static_cast<size_t>(num_workers) * table_size, 0.0f) {}

  void Update(std::vector<float>& data, const float* delta, size_t offset,
              size_t size, const AddOptionC& option) override {
    float* g = g_sqr_.data() + static_cast<size_t>(option.worker_id) * size_;
    const float rho = option.rho;
    const float lr = option.learning_rate;
#pragma omp parallel for
    for (long long i = 0; i < static_cast<long long>(size); ++i) {
      float d = delta[i];
      float acc = g[offset + i] + d * d;
      g[offset + i] = acc;
      data[offset + i] -= rho / std::sqrt(acc + kAdaGradEps) * d / lr;
    }
  }

 private:
  size_t size_;
  std::vector<float> g_sqr_;
};

}  // namespace

std::unique_ptr<Updater> Updater::Create(const std::string& type,
                                         size_t table_size, int num_workers) {
  if (type == "sgd") return std::unique_ptr<Updater>(new SgdUpdater());
  if (type == "momentum_sgd")
    return std::unique_ptr<Updater>(new MomentumUpdater(table_size));
  if (type == "adagrad")
    return std::unique_ptr<Updater>(
        new AdaGradUpdater(table_size, num_workers < 1 ? 1 : num_workers));
  return std::unique_ptr<Updater>(new DefaultUpdater());
}

Table::Table(long long num_row, long long num_col,
             const std::string& updater_type, int num_workers)
    : num_row_(num_row),
      num_col_(num_col),
      data_(static_cast<size_t>(num_row * num_col), 0.0f),
      updater_(Updater::Create(updater_type, static_cast<size_t>(num_row * num_col),
                               num_workers)) {}

void Table::Get(float* out, long long size) const {
  std::lock_guard<std::mutex> lock(mu_);
  MVTPU_CHECK(size <= this->size());
  std::memcpy(out, data_.data(), static_cast<size_t>(size) * sizeof(float));
}

void Table::GetRows(const int* row_ids, int n, float* out) const {
  std::lock_guard<std::mutex> lock(mu_);
  for (int i = 0; i < n; ++i) {
    MVTPU_CHECK(row_ids[i] >= 0 && row_ids[i] < num_row_);
    std::memcpy(out + static_cast<size_t>(i) * num_col_,
                data_.data() + static_cast<size_t>(row_ids[i]) * num_col_,
                static_cast<size_t>(num_col_) * sizeof(float));
  }
}

void Table::Add(const float* delta, long long size, const AddOptionC& option) {
  std::lock_guard<std::mutex> lock(mu_);
  MVTPU_CHECK(size <= this->size());
  updater_->Update(data_, delta, 0, static_cast<size_t>(size), option);
}

void Table::AddRows(const int* row_ids, int n, const float* delta,
                    const AddOptionC& option) {
  std::lock_guard<std::mutex> lock(mu_);
  for (int i = 0; i < n; ++i) {
    MVTPU_CHECK(row_ids[i] >= 0 && row_ids[i] < num_row_);
    updater_->Update(data_, delta + static_cast<size_t>(i) * num_col_,
                     static_cast<size_t>(row_ids[i]) * num_col_,
                     static_cast<size_t>(num_col_), option);
  }
}

bool Table::Store(std::FILE* f) const {
  std::lock_guard<std::mutex> lock(mu_);
  long long dims[2] = {num_row_, num_col_};
  if (std::fwrite(dims, sizeof(dims), 1, f) != 1) return false;
  return std::fwrite(data_.data(), sizeof(float), data_.size(), f) ==
         data_.size();
}

bool Table::Load(std::FILE* f) {
  std::lock_guard<std::mutex> lock(mu_);
  long long dims[2];
  if (std::fread(dims, sizeof(dims), 1, f) != 1) return false;
  if (dims[0] != num_row_ || dims[1] != num_col_) return false;
  return std::fread(data_.data(), sizeof(float), data_.size(), f) ==
         data_.size();
}

TableStore& TableStore::Get() {
  static TableStore instance;
  return instance;
}

TableStore::TableStore() {
  running_ = true;
  apply_thread_ = std::thread(&TableStore::ApplyLoop, this);
}

TableStore::~TableStore() { Shutdown(); }

void TableStore::Shutdown() {
  bool expected = true;
  if (!running_.compare_exchange_strong(expected, false)) return;
  queue_.Exit();
  if (apply_thread_.joinable()) apply_thread_.join();
}

int TableStore::CreateTable(long long num_row, long long num_col) {
  std::lock_guard<std::mutex> lock(mu_);
  std::string updater_type =
      Flags::Get().GetString("updater_type", "default");
  int workers = static_cast<int>(Flags::Get().GetInt("num_workers", 1));
  tables_.emplace_back(new Table(num_row, num_col, updater_type, workers));
  return static_cast<int>(tables_.size()) - 1;
}

Table* TableStore::table(int id) {
  std::lock_guard<std::mutex> lock(mu_);
  if (id < 0 || id >= static_cast<int>(tables_.size())) return nullptr;
  return tables_[id].get();
}

void TableStore::AddAsync(int table_id, std::vector<float> delta,
                          std::vector<int> row_ids, AddOptionC option) {
  ++enqueued_;
  queue_.Push(PendingAdd{table_id, std::move(delta), std::move(row_ids),
                         option});
}

void TableStore::ApplyLoop() {
  PendingAdd add;
  while (queue_.Pop(&add)) {
    Table* t = table(add.table_id);
    if (t != nullptr) {
      if (add.row_ids.empty()) {
        t->Add(add.delta.data(), static_cast<long long>(add.delta.size()),
               add.option);
      } else {
        t->AddRows(add.row_ids.data(), static_cast<int>(add.row_ids.size()),
                   add.delta.data(), add.option);
      }
    }
    ++applied_;
  }
}

void TableStore::Flush() {
  // Spin-wait until the apply thread catches up (barrier semantics; the
  // queue is typically short). Matches Actor::Stop's drain in the reference.
  while (applied_.load() < enqueued_.load() && running_.load()) {
    std::this_thread::yield();
  }
}

}  // namespace mvtpu
