// C ABI implementation: local native store by default, host bridge when
// installed (see c_api.h). Reference surface: src/c_api.cpp:10-92 in the
// Multiverso reference.
#include "c_api.h"

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "mvtpu/flags.h"
#include "mvtpu/log.h"
#include "mvtpu/reader.h"
#include "mvtpu/table_store.h"

namespace mvtpu {
int RunNativeTests();  // self_test.cc
}

namespace {

using mvtpu::AddOptionC;
using mvtpu::Flags;
using mvtpu::TableStore;

MV_Bridge g_bridge;
bool g_bridge_installed = false;
std::mutex g_mu;

// Handlers encode the table id + kind; 1-based so NULL stays invalid.
constexpr intptr_t kArrayTag = 1 << 28;

intptr_t MakeHandler(int id, bool is_array) {
  return (is_array ? kArrayTag : 0) | (id + 1);
}
int HandlerId(TableHandler h) {
  return static_cast<int>((reinterpret_cast<intptr_t>(h) & (kArrayTag - 1)) -
                          1);
}

bool BridgeHas(void* fn) { return g_bridge_installed && fn != nullptr; }

void RegisterCoreFlags() {
  Flags& flags = Flags::Get();
  flags.DefineString("ps_role", "default");
  flags.DefineBool("ma", false);
  flags.DefineBool("sync", false);
  flags.DefineString("updater_type", "default");
  flags.DefineInt("num_workers", 1);
  flags.DefineInt("omp_threads", 4);
  flags.DefineString("log_level", "info");
  // registered before ParseCmdFlags so -allocator_* CLI values are consumed
  // (Allocator::Get() re-Defines them as a no-op fallback for lib-only use)
  flags.DefineString("allocator_type", "smart");
  flags.DefineInt("allocator_alignment", 16);
}

}  // namespace

extern "C" {

void MV_InstallBridge(const MV_Bridge* bridge) {
  std::lock_guard<std::mutex> lock(g_mu);
  std::memcpy(&g_bridge, bridge, sizeof(MV_Bridge));
  g_bridge_installed = true;
}

void MV_ClearBridge() {
  std::lock_guard<std::mutex> lock(g_mu);
  g_bridge_installed = false;
  std::memset(&g_bridge, 0, sizeof(MV_Bridge));
}

void MV_Init(int* argc, char* argv[]) {
  RegisterCoreFlags();
  if (argc != nullptr && argv != nullptr) {
    *argc = Flags::Get().ParseCmdFlags(*argc, argv);
  }
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.init))) {
    g_bridge.init(argc, argv);
  }
}

void MV_ShutDown() {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.shutdown))) {
    g_bridge.shutdown();
    return;
  }
  TableStore::Get().Flush();
}

void MV_Barrier() {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.barrier))) {
    g_bridge.barrier();
    return;
  }
  TableStore::Get().Flush();  // in-process: drain pending async adds
}

int MV_NumWorkers() {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.num_workers)))
    return g_bridge.num_workers();
  return static_cast<int>(Flags::Get().GetInt("num_workers", 1));
}

int MV_WorkerId() {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.worker_id)))
    return g_bridge.worker_id();
  return 0;
}

int MV_ServerId() {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.server_id)))
    return g_bridge.server_id();
  return 0;
}

int MV_Rank() {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.rank))) return g_bridge.rank();
  return 0;
}

int MV_Size() {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.size))) return g_bridge.size();
  return 1;
}

int MV_NumServers() {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.num_servers)))
    return g_bridge.num_servers();
  return 1;
}

int MV_SetFlag(const char* name, const char* value) {
  RegisterCoreFlags();
  return Flags::Get().Set(name, value) ? 0 : -1;
}

/* ---- array tables ---- */

void MV_NewArrayTable(int size, TableHandler* out) {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.new_array))) {
    *out = reinterpret_cast<TableHandler>(
        MakeHandler(g_bridge.new_array(size), true));
    return;
  }
  int id = TableStore::Get().CreateTable(size, 1);
  *out = reinterpret_cast<TableHandler>(MakeHandler(id, true));
}

void MV_GetArrayTable(TableHandler handler, float* data, int size) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.get_array))) {
    g_bridge.get_array(id, data, size);
    return;
  }
  TableStore::Get().Flush();
  mvtpu::Table* t = TableStore::Get().table(id);
  MVTPU_CHECK(t != nullptr);
  t->Get(data, size);
}

void MV_AddArrayTable(TableHandler handler, float* data, int size) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.add_array))) {
    g_bridge.add_array(id, data, size, 0);
    return;
  }
  mvtpu::Table* t = TableStore::Get().table(id);
  MVTPU_CHECK(t != nullptr);
  t->Add(data, size, AddOptionC{});
}

void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.add_array))) {
    g_bridge.add_array(id, data, size, 1);
    return;
  }
  TableStore::Get().AddAsync(id, std::vector<float>(data, data + size), {},
                             AddOptionC{});
}

/* ---- matrix tables ---- */

void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out) {
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.new_matrix))) {
    *out = reinterpret_cast<TableHandler>(
        MakeHandler(g_bridge.new_matrix(num_row, num_col), false));
    return;
  }
  int id = TableStore::Get().CreateTable(num_row, num_col);
  *out = reinterpret_cast<TableHandler>(MakeHandler(id, false));
}

void MV_GetMatrixTableAll(TableHandler handler, float* data, int size) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.get_matrix))) {
    g_bridge.get_matrix(id, data, size);
    return;
  }
  TableStore::Get().Flush();
  mvtpu::Table* t = TableStore::Get().table(id);
  MVTPU_CHECK(t != nullptr);
  t->Get(data, size);
}

void MV_AddMatrixTableAll(TableHandler handler, float* data, int size) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.add_matrix))) {
    g_bridge.add_matrix(id, data, size, 0);
    return;
  }
  mvtpu::Table* t = TableStore::Get().table(id);
  MVTPU_CHECK(t != nullptr);
  t->Add(data, size, AddOptionC{});
}

void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data, int size) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.add_matrix))) {
    g_bridge.add_matrix(id, data, size, 1);
    return;
  }
  TableStore::Get().AddAsync(id, std::vector<float>(data, data + size), {},
                             AddOptionC{});
}

void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size,
                             int row_ids[], int row_ids_n) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.get_rows))) {
    g_bridge.get_rows(id, data, size, row_ids, row_ids_n);
    return;
  }
  TableStore::Get().Flush();
  mvtpu::Table* t = TableStore::Get().table(id);
  MVTPU_CHECK(t != nullptr);
  t->GetRows(row_ids, row_ids_n, data);
}

void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size,
                             int row_ids[], int row_ids_n) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.add_rows))) {
    g_bridge.add_rows(id, data, size, row_ids, row_ids_n, 0);
    return;
  }
  mvtpu::Table* t = TableStore::Get().table(id);
  MVTPU_CHECK(t != nullptr);
  t->AddRows(row_ids, row_ids_n, data, AddOptionC{});
}

void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data, int size,
                                  int row_ids[], int row_ids_n) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.add_rows))) {
    g_bridge.add_rows(id, data, size, row_ids, row_ids_n, 1);
    return;
  }
  TableStore::Get().AddAsync(id, std::vector<float>(data, data + size),
                             std::vector<int>(row_ids, row_ids + row_ids_n),
                             AddOptionC{});
}

int MV_StoreTable(TableHandler handler, const char* path) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.store_table)))
    return g_bridge.store_table(id, path);
  TableStore::Get().Flush();
  mvtpu::Table* t = TableStore::Get().table(id);
  if (t == nullptr) return -1;
  std::FILE* f = std::fopen(path, "wb");
  if (f == nullptr) return -1;
  bool ok = t->Store(f);
  std::fclose(f);
  return ok ? 0 : -1;
}

int MV_LoadTable(TableHandler handler, const char* path) {
  int id = HandlerId(handler);
  if (BridgeHas(reinterpret_cast<void*>(g_bridge.load_table)))
    return g_bridge.load_table(id, path);
  TableStore::Get().Flush();
  mvtpu::Table* t = TableStore::Get().table(id);
  if (t == nullptr) return -1;
  std::FILE* f = std::fopen(path, "rb");
  if (f == nullptr) return -1;
  bool ok = t->Load(f);
  std::fclose(f);
  return ok ? 0 : -1;
}

/* ---- native data loaders ---- */

VocabHandler MV_VocabBuild(const char* path, int min_count) {
  auto* vocab = new mvtpu::Vocab();
  if (!vocab->Build(path, min_count)) {
    delete vocab;
    return nullptr;
  }
  return vocab;
}

int MV_VocabSize(VocabHandler vocab) {
  return static_cast<mvtpu::Vocab*>(vocab)->size();
}

long long MV_VocabTrainWords(VocabHandler vocab) {
  return static_cast<mvtpu::Vocab*>(vocab)->train_words();
}

void MV_VocabCounts(VocabHandler vocab, long long* out) {
  const auto& counts = static_cast<mvtpu::Vocab*>(vocab)->counts();
  std::memcpy(out, counts.data(), counts.size() * sizeof(long long));
}

const char* MV_VocabWord(VocabHandler vocab, int id) {
  return static_cast<mvtpu::Vocab*>(vocab)->word(id).c_str();
}

void MV_VocabFree(VocabHandler vocab) {
  delete static_cast<mvtpu::Vocab*>(vocab);
}

long long MV_CorpusEncode(VocabHandler vocab, const char* path,
                          int32_t** ids_out, int32_t** sents_out,
                          long long* n_out) {
  auto* v = static_cast<mvtpu::Vocab*>(vocab);
  std::vector<int32_t> ids, sents;
  long long words_read = 0;
  if (!v->Encode(path, &ids, &sents, &words_read)) return -1;
  auto* ids_buf = new int32_t[ids.size()];
  auto* sents_buf = new int32_t[sents.size()];
  std::memcpy(ids_buf, ids.data(), ids.size() * sizeof(int32_t));
  std::memcpy(sents_buf, sents.data(), sents.size() * sizeof(int32_t));
  *ids_out = ids_buf;
  *sents_out = sents_buf;
  *n_out = static_cast<long long>(ids.size());
  return words_read;
}

void MV_BufferFree(void* ptr) { delete[] static_cast<int32_t*>(ptr); }

SvmHandler MV_SvmParse(const char* path) {
  auto* data = new mvtpu::SvmData();
  if (!mvtpu::ParseLibsvm(path, data)) {
    delete data;
    return nullptr;
  }
  return data;
}

SvmHandler MV_BsparseParse(const char* path) {
  auto* data = new mvtpu::SvmData();
  bool ok = false;
  try {
    ok = mvtpu::ParseBsparse(path, data);
  } catch (...) {   // never let an exception cross the C ABI into ctypes
    ok = false;
  }
  if (!ok) {
    delete data;
    return nullptr;
  }
  return data;
}

long long MV_SvmNumSamples(SvmHandler svm) {
  return static_cast<long long>(
      static_cast<mvtpu::SvmData*>(svm)->labels.size());
}

long long MV_SvmNumEntries(SvmHandler svm) {
  return static_cast<long long>(static_cast<mvtpu::SvmData*>(svm)->keys.size());
}

void MV_SvmCopy(SvmHandler svm, float* labels, int64_t* indptr, int32_t* keys,
                double* values) {
  auto* data = static_cast<mvtpu::SvmData*>(svm);
  std::memcpy(labels, data->labels.data(),
              data->labels.size() * sizeof(float));
  std::memcpy(indptr, data->indptr.data(),
              data->indptr.size() * sizeof(int64_t));
  std::memcpy(keys, data->keys.data(), data->keys.size() * sizeof(int32_t));
  std::memcpy(values, data->values.data(),
              data->values.size() * sizeof(double));
}

void MV_SvmFree(SvmHandler svm) { delete static_cast<mvtpu::SvmData*>(svm); }

int MV_ExtAbiVersion(void) { return MV_EXT_ABI_VERSION; }

int MV_RunNativeTests(void) { return mvtpu::RunNativeTests(); }

}  // extern "C"
