/* C ABI of multiverso-tpu.
 *
 * Source-compatible with the reference surface
 * (include/multiverso/c_api.h:14-54 in the Multiverso reference): the same
 * MV_Init/ShutDown/Barrier, worker queries, and float Array/Matrix table
 * calls, so the reference's Python/Lua callers port unchanged.
 *
 * Backends: by default the library serves tables from an in-process native
 * store (single-process PS — the reference's role=ALL mode). A host runtime
 * (the Python/JAX framework) can install a bridge (MV_InstallBridge) that
 * reroutes every call to TPU-resident sharded tables.
 *
 * Extensions beyond the reference surface are marked "ext".
 */
#ifndef MVTPU_C_API_H_
#define MVTPU_C_API_H_

#include <stdint.h>

#define DllExport

#ifdef __cplusplus
extern "C" {
#endif

typedef void* TableHandler;

/* ext: ABI revision of the non-reference extensions (Svm readers, bridge,
 * vocab). Bumped whenever an exported signature changes so a stale .so and
 * a newer Python loader can never exchange mis-sized buffers. Rev 2: f64
 * SvmData values. */
#define MV_EXT_ABI_VERSION 2
DllExport int MV_ExtAbiVersion();

DllExport void MV_Init(int* argc, char* argv[]);
DllExport void MV_ShutDown();
DllExport void MV_Barrier();
DllExport int MV_NumWorkers();
DllExport int MV_WorkerId();
DllExport int MV_ServerId();

/* ext: more process queries + flags */
DllExport int MV_Rank();
DllExport int MV_Size();
DllExport int MV_NumServers();
DllExport int MV_SetFlag(const char* name, const char* value);

/* Array Table (float) */
DllExport void MV_NewArrayTable(int size, TableHandler* out);
DllExport void MV_GetArrayTable(TableHandler handler, float* data, int size);
DllExport void MV_AddArrayTable(TableHandler handler, float* data, int size);
DllExport void MV_AddAsyncArrayTable(TableHandler handler, float* data,
                                     int size);

/* Matrix Table (float) */
DllExport void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out);
DllExport void MV_GetMatrixTableAll(TableHandler handler, float* data,
                                    int size);
DllExport void MV_AddMatrixTableAll(TableHandler handler, float* data,
                                    int size);
DllExport void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data,
                                         int size);
DllExport void MV_GetMatrixTableByRows(TableHandler handler, float* data,
                                       int size, int row_ids[], int row_ids_n);
DllExport void MV_AddMatrixTableByRows(TableHandler handler, float* data,
                                       int size, int row_ids[], int row_ids_n);
DllExport void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data,
                                            int size, int row_ids[],
                                            int row_ids_n);

/* ext: table checkpoint (reference Serializable Store/Load) */
DllExport int MV_StoreTable(TableHandler handler, const char* path);
DllExport int MV_LoadTable(TableHandler handler, const char* path);

/* ext: host-runtime bridge. All pointers may be NULL (falls back to the
 * local store for that operation). */
typedef struct MV_Bridge {
  void (*init)(int* argc, char** argv);
  void (*shutdown)(void);
  void (*barrier)(void);
  int (*num_workers)(void);
  int (*worker_id)(void);
  int (*server_id)(void);
  int (*rank)(void);
  int (*size)(void);
  int (*num_servers)(void);
  /* tables: ids are small ints chosen by the bridge owner */
  int (*new_array)(int size);
  void (*get_array)(int table, float* data, int size);
  void (*add_array)(int table, const float* data, int size, int async_hint);
  int (*new_matrix)(int num_row, int num_col);
  void (*get_matrix)(int table, float* data, int size);
  void (*add_matrix)(int table, const float* data, int size, int async_hint);
  void (*get_rows)(int table, float* data, int size, const int* row_ids,
                   int row_ids_n);
  void (*add_rows)(int table, const float* data, int size, const int* row_ids,
                   int row_ids_n, int async_hint);
  int (*store_table)(int table, const char* path);
  int (*load_table)(int table, const char* path);
} MV_Bridge;

DllExport void MV_InstallBridge(const MV_Bridge* bridge);
DllExport void MV_ClearBridge();

/* ext: native data loaders (word2vec corpus + libsvm) */
typedef void* VocabHandler;
DllExport VocabHandler MV_VocabBuild(const char* path, int min_count);
DllExport int MV_VocabSize(VocabHandler vocab);
DllExport long long MV_VocabTrainWords(VocabHandler vocab);
DllExport void MV_VocabCounts(VocabHandler vocab, long long* out);
DllExport const char* MV_VocabWord(VocabHandler vocab, int id);
DllExport void MV_VocabFree(VocabHandler vocab);
/* Encodes the corpus; returns word/sentence-id buffers owned by the library
 * (free with MV_BufferFree). *n_out = token count; returns words consumed. */
DllExport long long MV_CorpusEncode(VocabHandler vocab, const char* path,
                                    int32_t** ids_out, int32_t** sents_out,
                                    long long* n_out);
DllExport void MV_BufferFree(void* ptr);

typedef void* SvmHandler;
DllExport SvmHandler MV_SvmParse(const char* path);
/* Packed binary sparse records (LogReg bsparse format); same handle ABI. */
DllExport SvmHandler MV_BsparseParse(const char* path);
DllExport long long MV_SvmNumSamples(SvmHandler svm);
DllExport long long MV_SvmNumEntries(SvmHandler svm);
/* values are double so text/binary sample values round-trip exactly
 * (parity with the Python readers, which yield f64). */
DllExport void MV_SvmCopy(SvmHandler svm, float* labels, int64_t* indptr,
                          int32_t* keys, double* values);
DllExport void MV_SvmFree(SvmHandler svm);

/* ext: in-library self-tests of the native primitives (allocator, queues,
 * async prefetcher, stream IO). Returns the number of failed checks. */
DllExport int MV_RunNativeTests(void);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* MVTPU_C_API_H_ */
