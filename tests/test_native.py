"""Native runtime tests: C ABI local store, readers, Python bridge.

Mirrors the reference's C-API-through-bindings coverage (python/lua binding
tests) against our cpp/ library, in one process (reference role=ALL mode).
"""

import ctypes
import os
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "cpp", "libmultiverso_tpu.so")


@pytest.fixture(scope="session")
def native_lib():
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    subprocess.run(["make", "-C", os.path.join(REPO, "cpp")], check=True,
                   capture_output=True)
    from multiverso_tpu import native

    lib = native.load()
    assert lib is not None
    return lib


def _handler():
    return ctypes.c_void_p()


def test_native_primitives_self_test(native_lib):
    """Allocator / MtQueue / Waiter / ASyncBuffer / Stream self-tests
    (cpp/mvtpu/self_test.cc) run inside the library; 0 = all passed."""
    assert native_lib.MV_RunNativeTests() == 0


def test_c_api_array_local_store(native_lib):
    lib = native_lib
    lib.MV_ClearBridge()
    h = _handler()
    lib.MV_NewArrayTable(64, ctypes.byref(h))
    delta = np.full(64, 1.5, np.float32)
    ptr = delta.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    lib.MV_AddArrayTable(h, ptr, 64)
    lib.MV_AddAsyncArrayTable(h, ptr, 64)
    lib.MV_Barrier()  # drains async
    out = np.zeros(64, np.float32)
    lib.MV_GetArrayTable(h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                         64)
    np.testing.assert_allclose(out, 3.0)


def test_c_api_matrix_rows_and_checkpoint(native_lib, tmp_path):
    lib = native_lib
    lib.MV_ClearBridge()
    h = _handler()
    lib.MV_NewMatrixTable(8, 4, ctypes.byref(h))
    whole = np.ones((8, 4), np.float32)
    fp = ctypes.POINTER(ctypes.c_float)
    lib.MV_AddMatrixTableAll(h, whole.ctypes.data_as(fp), 32)
    rows = np.full((2, 4), 2.0, np.float32)
    ids = (ctypes.c_int * 2)(1, 5)
    lib.MV_AddMatrixTableByRows(h, rows.ctypes.data_as(fp), 8, ids, 2)
    got = np.zeros((2, 4), np.float32)
    lib.MV_GetMatrixTableByRows(h, got.ctypes.data_as(fp), 8, ids, 2)
    np.testing.assert_allclose(got, 3.0)

    path = str(tmp_path / "table.bin").encode()
    assert lib.MV_StoreTable(h, path) == 0
    more = np.ones((8, 4), np.float32)
    lib.MV_AddMatrixTableAll(h, more.ctypes.data_as(fp), 32)
    assert lib.MV_LoadTable(h, path) == 0
    out = np.zeros((8, 4), np.float32)
    lib.MV_GetMatrixTableAll(h, out.ctypes.data_as(fp), 32)
    expect = np.ones((8, 4), np.float32)
    expect[[1, 5]] = 3.0
    np.testing.assert_allclose(out, expect)


def test_c_api_updater_flag(native_lib):
    lib = native_lib
    lib.MV_ClearBridge()
    assert lib.MV_SetFlag(b"updater_type", b"sgd") == 0
    h = _handler()
    lib.MV_NewArrayTable(8, ctypes.byref(h))
    delta = np.full(8, 0.5, np.float32)
    fp = ctypes.POINTER(ctypes.c_float)
    lib.MV_AddArrayTable(h, delta.ctypes.data_as(fp), 8)
    out = np.zeros(8, np.float32)
    lib.MV_GetArrayTable(h, out.ctypes.data_as(fp), 8)
    np.testing.assert_allclose(out, -0.5)  # sgd: data -= delta
    assert lib.MV_SetFlag(b"updater_type", b"default") == 0
    assert lib.MV_SetFlag(b"no_such_flag", b"1") == -1


def test_native_vocab_and_encode(native_lib, tmp_path):
    from multiverso_tpu import native

    corpus = tmp_path / "c.txt"
    corpus.write_text("the cat sat\nthe dog sat\nthe the rare\n")
    vocab = native.build_vocab(str(corpus), min_count=2)
    assert vocab.size == 2  # the(4), sat(2); cat/dog/rare dropped
    words = vocab.words()
    assert words[0] == "the"
    counts = vocab.counts()
    assert counts[0] == 4
    assert vocab.train_words == sum(counts)
    ids, sents, words_read = vocab.encode(str(corpus))
    # per line in-vocab tokens: 2 + 2 + 2 (line 3 keeps 'the the')
    assert words_read == 6
    assert len(ids) == len(sents)
    assert sents.max() >= 1
    vocab.free()


def test_native_libsvm_parse(native_lib, tmp_path):
    from multiverso_tpu import native

    path = tmp_path / "d.svm"
    path.write_text("1 3:0.5 7:2\n0 1:1.5\n1 2 5\n")
    labels, indptr, keys, values = native.parse_libsvm(str(path))
    np.testing.assert_allclose(labels, [1, 0, 1])
    np.testing.assert_array_equal(indptr, [0, 2, 3, 5])
    np.testing.assert_array_equal(keys, [3, 7, 1, 2, 5])
    np.testing.assert_allclose(values, [0.5, 2.0, 1.5, 1.0, 1.0])


def test_bridge_routes_to_jax_tables(native_lib, mv_session):
    """C ABI calls land on the JAX session's sharded tables via the bridge."""
    from multiverso_tpu import native

    assert native.install_bridge()
    try:
        lib = native_lib
        h = _handler()
        lib.MV_NewArrayTable(32, ctypes.byref(h))
        delta = np.full(32, 2.0, np.float32)
        fp = ctypes.POINTER(ctypes.c_float)
        lib.MV_AddArrayTable(h, delta.ctypes.data_as(fp), 32)
        out = np.zeros(32, np.float32)
        lib.MV_GetArrayTable(h, out.ctypes.data_as(fp), 32)
        np.testing.assert_allclose(out, 2.0)
        # the state is visible from the python side (same table object)
        sess_table = mv_session.session().tables[-1]
        np.testing.assert_allclose(sess_table.get(), 2.0)
        # matrix by rows through the bridge
        hm = _handler()
        lib.MV_NewMatrixTable(4, 4, ctypes.byref(hm))
        rows = np.full((1, 4), 3.0, np.float32)
        ids = (ctypes.c_int * 1)(2)
        lib.MV_AddMatrixTableByRows(hm, rows.ctypes.data_as(fp), 4, ids, 1)
        got = np.zeros((1, 4), np.float32)
        lib.MV_GetMatrixTableByRows(hm, got.ctypes.data_as(fp), 4, ids, 1)
        np.testing.assert_allclose(got, 3.0)
    finally:
        native.clear_bridge()


def test_python_binding_compat(mv_session):
    """Reference binding surface (api.py/tables.py) works end to end."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "binding", "python"))
    try:
        import multiverso as ref_mv

        assert ref_mv.workers_num() >= 1
        assert ref_mv.is_master_worker()
        at = ref_mv.ArrayTableHandler(16, init_value=np.arange(16))
        ref_mv.barrier()
        np.testing.assert_allclose(at.get(), np.arange(16))
        at.add(np.ones(16), sync=True)
        np.testing.assert_allclose(at.get(), np.arange(16) + 1)

        mt = ref_mv.MatrixTableHandler(4, 4)
        mt.add(np.ones((4, 4)), sync=True)
        mt.add(np.full((1, 4), 5.0), row_ids=[2], sync=True)
        got = mt.get()
        assert got[2, 0] == 6.0 and got[0, 0] == 1.0
        np.testing.assert_allclose(mt.get(row_ids=[2])[0], 6.0)
    finally:
        sys.path.remove(os.path.join(REPO, "binding", "python"))


def test_jax_ext_param_manager(mv_session):
    import sys

    sys.path.insert(0, os.path.join(REPO, "binding", "python"))
    try:
        import jax.numpy as jnp
        from multiverso.jax_ext import MVNetParamManager, MVSharedArray

        params = {"w": jnp.ones((3, 2)), "b": jnp.zeros(2)}
        manager = MVNetParamManager(params)
        new = {"w": manager.params["w"] + 1.0, "b": manager.params["b"] + 0.5}
        manager.set_params(new)
        synced = manager.sync_all_param()
        np.testing.assert_allclose(np.asarray(synced["w"]), 2.0)
        np.testing.assert_allclose(np.asarray(synced["b"]), 0.5)

        shared = MVSharedArray(np.zeros((2, 2)))
        shared.set_value(np.full((2, 2), 3.0))
        out = shared.mv_sync()
        np.testing.assert_allclose(out, 3.0)
    finally:
        sys.path.remove(os.path.join(REPO, "binding", "python"))


def test_torch_ext_param_manager(mv_session):
    import sys

    sys.path.insert(0, os.path.join(REPO, "binding", "python"))
    try:
        torch = pytest.importorskip("torch")
        from multiverso.torch_ext import MVTorchParamManager

        model = torch.nn.Linear(4, 2)
        manager = MVTorchParamManager(model)
        before = manager._flatten().copy()
        with torch.no_grad():
            for p in model.parameters():
                p.add_(1.0)
        manager.sync_all_param()
        after = manager._flatten()
        np.testing.assert_allclose(after, before + 1.0, rtol=1e-5)
    finally:
        sys.path.remove(os.path.join(REPO, "binding", "python"))


def test_jax_ext_shared_registry(mv_session):
    import sys

    sys.path.insert(0, os.path.join(REPO, "binding", "python"))
    try:
        from multiverso.jax_ext import mv_shared, sync_all_mv_shared_vars
        from multiverso.jax_ext import param_manager as pm

        pm._all_mv_shared.clear()
        a = mv_shared(np.zeros(4))
        b = mv_shared(np.ones(2))
        a.set_value(np.full(4, 2.0))
        b.set_value(np.full(2, 5.0))
        sync_all_mv_shared_vars()
        np.testing.assert_allclose(a.get_value(), 2.0)
        np.testing.assert_allclose(b.get_value(), 5.0)
        pm._all_mv_shared.clear()
    finally:
        sys.path.remove(os.path.join(REPO, "binding", "python"))


def test_native_bsparse_matches_python(native_lib, tmp_path):
    """C++ bsparse parser agrees with the Python reader record-for-record."""
    from multiverso_tpu import native
    from multiverso_tpu.apps.lr_reader import iter_bsparse, write_bsparse

    samples = [
        (1.0, np.asarray([3, 7, 100], np.int64), np.full(3, 2.5)),
        (0.0, np.asarray([5], np.int64), np.full(1, 1.0)),
        (1.0, np.asarray([], np.int64), np.asarray([], np.float64)),
    ]
    path = str(tmp_path / "x.bsparse")
    write_bsparse(path, samples)

    labels, indptr, keys, values = native.parse_bsparse(path)
    py = list(iter_bsparse(path))
    assert labels.shape[0] == len(py) == 3
    for i, (lab, k, v) in enumerate(py):
        lo, hi = int(indptr[i]), int(indptr[i + 1])
        assert float(labels[i]) == lab
        np.testing.assert_array_equal(keys[lo:hi], k)
        np.testing.assert_allclose(values[lo:hi], v)

    # truncated record -> error, not silent EOF
    data = open(path, "rb").read()
    bad = str(tmp_path / "bad.bsparse")
    open(bad, "wb").write(data[:-4])
    with pytest.raises(IOError):
        native.parse_bsparse(bad)


def test_lua_abi_replay():
    """The Lua binding's full ABI call sequence (binding/lua/test.lua)
    replayed by a C driver against the shared library — the executable
    stand-in for the binding until a Lua interpreter exists here."""
    import subprocess

    binary = os.path.join(REPO, "cpp", "lua_abi_replay")
    if not os.path.exists(binary):
        build = subprocess.run(["make", "-s", "lua_abi_replay"],
                               cwd=os.path.join(REPO, "cpp"),
                               capture_output=True, text=True)
        assert build.returncode == 0, build.stderr[-2000:]
    result = subprocess.run([binary], capture_output=True, text=True,
                            timeout=120)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "lua ABI replay: OK" in result.stdout


def test_lua_syntax_check(tmp_path):
    """VERDICT r2 item 7: the shipped .lua files are actually PARSED in CI
    (full Lua 5.1 lexer+parser, cpp/mvtpu/lua_check.cc), and a deliberately
    broken handler file fails the check."""
    import glob
    import subprocess

    binary = os.path.join(REPO, "cpp", "lua_check")
    if not os.path.exists(binary):
        build = subprocess.run(["make", "-s", "lua_check"],
                               cwd=os.path.join(REPO, "cpp"),
                               capture_output=True, text=True)
        assert build.returncode == 0, build.stderr[-2000:]

    lua_files = sorted(glob.glob(os.path.join(REPO, "binding", "lua",
                                              "**", "*.lua"), recursive=True))
    assert len(lua_files) >= 5, lua_files   # handlers + init + util + test
    result = subprocess.run([binary] + lua_files, capture_output=True,
                            text=True, timeout=60)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "lua syntax check: OK" in result.stdout

    broken = tmp_path / "broken.lua"
    broken.write_text("local t = { function oops( end\n")
    result = subprocess.run([binary, str(broken)], capture_output=True,
                            text=True, timeout=60)
    assert result.returncode == 1
    assert "broken.lua" in result.stderr


def test_lua_binding_executes(tmp_path):
    """VERDICT r3 item 6: binding/lua/test.lua is EXECUTED in CI, not just
    parsed — cpp/mvtpu/lua_run.cc (a tree-walking Lua 5.1 interpreter for
    the binding subset with a LuaJIT-style ffi) runs the whole test
    through the real shared library's C ABI, and a deliberately wrong
    util.lua arithmetic change FAILS."""
    import shutil
    import subprocess

    binary = os.path.join(REPO, "cpp", "lua_run")
    lib = os.path.join(REPO, "cpp", "libmultiverso_tpu.so")
    if not (os.path.exists(binary) and os.path.exists(lib)):
        build = subprocess.run(["make", "-s", "lua_run", "libmultiverso_tpu.so"],
                               cwd=os.path.join(REPO, "cpp"),
                               capture_output=True, text=True)
        assert build.returncode == 0, build.stderr[-2000:]
    env = dict(os.environ, MV_NATIVE_LIB=lib)

    # the real binding test: handler arithmetic -> ffi -> C ABI -> asserts
    result = subprocess.run([binary, "binding/lua/test.lua"], cwd=REPO,
                            env=env, capture_output=True, text=True,
                            timeout=120)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "lua binding test: OK" in result.stdout

    # mutation gate: semantic (not syntactic) breakage must fail — double
    # the util.lua conversion arithmetic and the accumulation assert trips
    mut = tmp_path / "mut"
    shutil.copytree(os.path.join(REPO, "binding"), mut / "binding")
    util = mut / "binding" / "lua" / "util.lua"
    src = util.read_text()
    assert "buf[i - 1] = data[i] or 0" in src
    util.write_text(src.replace("buf[i - 1] = data[i] or 0",
                                "buf[i - 1] = (data[i] or 0) * 2"))
    result = subprocess.run([binary, "binding/lua/test.lua"], cwd=mut,
                            env=env, capture_output=True, text=True,
                            timeout=120)
    assert result.returncode == 1, (result.stdout, result.stderr)
    assert "array accumulation" in result.stderr
