"""Flag-system tests (reference: src/util/configure.cpp behaviors)."""

import pytest

from multiverso_tpu import config


def test_define_and_get_defaults():
    config.define_int("t_int", 7, "test int")
    config.define_string("t_str", "hello", "test str")
    config.define_bool("t_bool", True, "test bool")
    config.define_float("t_float", 2.5, "test float")
    assert config.get_flag("t_int") == 7
    assert config.get_flag("t_str") == "hello"
    assert config.get_flag("t_bool") is True
    assert config.get_flag("t_float") == 2.5


def test_parse_cmd_flags_consumes_known_tokens():
    config.define_int("t_parse_a", 1)
    config.define_bool("t_parse_b", False)
    config.define_string("t_parse_c", "x")
    rest = config.parse_cmd_flags(
        ["prog", "-t_parse_a=42", "-t_parse_b=true", "--t_parse_c=abc",
         "-unknown=1", "positional"]
    )
    assert config.get_flag("t_parse_a") == 42
    assert config.get_flag("t_parse_b") is True
    assert config.get_flag("t_parse_c") == "abc"
    # argv compaction: unknown/positional tokens survive
    assert rest == ["prog", "-unknown=1", "positional"]


def test_set_flag_coercion_and_type_safety():
    config.define_int("t_set_i", 0)
    config.set_flag("t_set_i", "13")
    assert config.get_flag("t_set_i") == 13
    with pytest.raises(config.FlagError):
        config.set_flag("t_set_i", "not-an-int")
    with pytest.raises(config.FlagError):
        config.set_flag("no_such_flag", 1)
    with pytest.raises(config.FlagError):
        config.get_flag("no_such_flag")


def test_bool_parse_ladder():
    config.define_bool("t_bool2", False)
    for text, expect in [("true", True), ("1", True), ("on", True),
                         ("false", False), ("0", False), ("off", False)]:
        config.set_flag("t_bool2", text)
        assert config.get_flag("t_bool2") is expect


def test_redefine_same_type_keeps_value():
    config.define_int("t_redef", 5)
    config.set_flag("t_redef", 9)
    config.define_int("t_redef", 5)  # module reload: no clobber
    assert config.get_flag("t_redef") == 9
    with pytest.raises(config.FlagError):
        config.define_string("t_redef", "x")


def test_core_flags_registered():
    for name in ["ps_role", "ma", "sync", "updater_type", "omp_threads",
                 "backup_worker_ratio", "mesh_shape", "sync_frequency"]:
        assert config.registry().known(name)


def test_define_coerces_default_outside_registry_lock():
    """Regression (locklint LK202, found by this PR's lint pass): the
    declared type is caller-supplied code; define() used to call it
    while holding the registry lock, so a coercion that blocks (or
    raises) wedged every concurrent flag read behind it."""
    import threading

    entered, release = threading.Event(), threading.Event()

    class _Slow:
        def __init__(self, default):
            entered.set()
            release.wait(10)

    reg = config.FlagRegister()
    config._COERCERS[_Slow] = _Slow
    try:
        t = threading.Thread(target=lambda: reg.define("t_slow", _Slow, 0))
        t.start()
        assert entered.wait(5), "define never reached the default coercion"
        got = reg._lock.acquire(timeout=2)
        assert got, "define held the registry lock across default coercion"
        reg._lock.release()
        release.set()
        t.join(10)
        assert not t.is_alive()
        assert reg.known("t_slow")
    finally:
        del config._COERCERS[_Slow]
    # a raising coercion must leave the registry untouched and usable
    with pytest.raises(ValueError):
        reg.define("t_bad", int, "not-an-int")
    assert not reg.known("t_bad")
    reg.define("t_ok", int, 4)
    assert reg.get("t_ok") == 4


def test_redefinition_never_reruns_the_coercer():
    """Companion to the outside-the-lock coercion move: a re-definition
    with identical type keeps the current value WITHOUT touching the
    (possibly no-longer-coercible) default — the original early-return
    contract. A module re-executed with a stale default must not raise."""
    reg = config.FlagRegister()
    reg.define("t_re", int, 7)
    reg.set("t_re", 9)
    reg.define("t_re", int, "not-an-int")   # must NOT coerce, NOT raise
    assert reg.get("t_re") == 9
    with pytest.raises(config.FlagError):
        reg.define("t_re", float, 1.0)      # type mismatch still surfaces
