"""tools/perf_tables.py regression: all three modes run end-to-end.

The device mode exercises table internals (`_apply_fn`/`_row_apply`/
`_row_gather` staging); this test pins the harness so a table refactor
cannot silently break it while the suite stays green.
"""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(mode):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys, runpy; sys.argv = ['perf_tables', %r, '-rows=256', "
        "'-cols=8', '-rounds=2', '-percent=5']; "
        "runpy.run_path('tools/perf_tables.py', run_name='__main__')"
        % mode
    )
    return subprocess.run([sys.executable, "-c", code], cwd=_REPO, env=env,
                          capture_output=True, text=True, timeout=300)


def test_all_modes_run():
    for mode in ("dense", "sparse", "device"):
        result = _run(mode)
        assert result.returncode == 0, (mode, result.stderr[-2000:])
        assert "ms/round" in result.stdout, (mode, result.stdout)


def test_lightlda_mode_runs():
    """LightLDA-style sparse workload (BASELINE config 4 shape, shrunk):
    dirty-row filtered pulls + per-worker pushes with count conservation."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys, runpy; sys.argv = ['perf_tables', 'lightlda', "
        "'-rows=512', '-cols=8', '-rounds=2', '-workers=2', "
        "'-doc_words=64']; "
        "runpy.run_path('tools/perf_tables.py', run_name='__main__')"
    )
    result = subprocess.run([sys.executable, "-c", code], cwd=_REPO, env=env,
                            capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stderr[-2000:]
    assert "filtered pull:" in result.stdout
    assert "probe: +0.0" in result.stdout, result.stdout
