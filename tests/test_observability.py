"""Observability integration: instruments, export surface, traced serving.

The acceptance contract of the tracing/metrics PR (docs/OBSERVABILITY.md):

* **explain one request** — a traced serving request exports a Chrome
  trace in which ITS root span contains queue-wait, admission/prefill
  and per-iteration decode children, all under one trace id (the e2e
  smoke below validates structure: monotonic ts, matched B/E pairs, one
  root per request);
* **off = free** — tracing is disabled by default and the decode hot
  loop must not allocate a single trace object per iteration while off;
* **one snapshot, many sinks** — ``Dashboard.snapshot()`` round-trips
  through the JSON-lines reporter and the Prometheus text renderer with
  identical values;
* **instruments are trustworthy under concurrency** — Histogram record
  vs percentiles races (ring wrap-around included) never tear.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from multiverso_tpu import trace
from multiverso_tpu.dashboard import (Counter, Dashboard, Gauge, Histogram,
                                      MetricsExporter, parse_prometheus,
                                      render_prometheus)


@pytest.fixture()
def traced():
    trace.enable(65536)
    trace.collector().clear()
    yield trace.collector()
    trace.disable()
    trace.collector().clear()


def _wait(pred, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while not pred():
        assert time.monotonic() < deadline, "condition never held"
        time.sleep(0.005)


# -- instruments -------------------------------------------------------------

def test_watch_resolves_every_instrument_kind():
    """Regression: watch() only looked at Monitors — a live Histogram or
    Gauge reported "not monitored"."""
    Dashboard.reset()
    hist = Dashboard.get_or_create_histogram("SERVE_TTFT[lm]")
    hist.record(12.5)
    gauge = Dashboard.get_or_create_gauge("SLOT_OCC[lm]")
    gauge.set(0.75)
    counter = Dashboard.get_or_create_counter("SERVE_SHED[lm]")
    counter.inc(3)
    Dashboard.get_or_create("TABLE_ADD[t]").record(1.0)

    assert "p99" in Dashboard.watch("SERVE_TTFT[lm]")
    assert "0.750" in Dashboard.watch("SLOT_OCC[lm]")
    assert "total = 3" in Dashboard.watch("SERVE_SHED[lm]")
    assert "count = 1" in Dashboard.watch("TABLE_ADD[t]")
    assert Dashboard.watch("nope") == "[nope] not monitored"


def test_histogram_summary_mean_max():
    h = Histogram("t_mm", window=16, register=False)
    for v in (1.0, 2.0, 3.0, 94.0):
        h.record(v)
    s = h.summary()
    assert s["mean_ms"] == pytest.approx(25.0)
    assert s["max_ms"] == 94.0
    assert "mean = 25.000 ms" in h.info_string()
    assert "max = 94.000 ms" in h.info_string()
    # aging out: max follows the WINDOW, not lifetime
    for _ in range(16):
        h.record(5.0)
    s = h.summary()
    assert s["max_ms"] == 5.0 and s["mean_ms"] == 5.0
    assert s["count"] == 20                       # lifetime count survives


def test_histogram_concurrent_record_vs_percentiles():
    """Ring wrap-around under contention: percentiles taken WHILE other
    threads hammer record() must always come from real recorded values
    (window smaller than the write volume forces constant wrapping)."""
    h = Histogram("t_conc", window=64, register=False)
    stop = threading.Event()
    errors = []

    def writer(ix: int) -> None:
        # every recorded value lives in [1, 2] — any torn read would
        # surface as a percentile outside the band (e.g. the 0.0 of an
        # unwritten slot miscounted as live)
        i = 0
        while not stop.is_set():
            h.record(1.0 + ((ix + i) % 100) / 100.0)
            i += 1

    def reader() -> None:
        while not stop.is_set():
            try:
                qs = h.percentiles((0, 50, 99, 100))
                s = h.summary()
            except Exception as exc:      # pragma: no cover
                errors.append(exc)
                return
            if h.count:                    # after the first record landed
                for v in list(qs.values()) + [s["mean_ms"], s["max_ms"]]:
                    if not 1.0 <= v <= 2.0:
                        errors.append(AssertionError(f"torn value {v}"))
                        return

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors[0]
    assert h.count > 64                    # the ring wrapped many times
    assert len(h.percentiles((50,))) == 1  # still functional after


def test_counter_monotonic():
    c = Counter("t_ctr", register=False)
    c.inc()
    c.inc(9)
    assert c.get() == 10
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.get() == 10


# -- export surface ----------------------------------------------------------

def _populate_dashboard():
    Dashboard.reset()
    h = Dashboard.get_or_create_histogram("SERVE_TTFT[lm]")
    for v in (1.5, 2.5, 300.0):
        h.record(v)
    Dashboard.get_or_create_gauge("DECODE_TPS[lm]").set(123.5)
    Dashboard.get_or_create_counter("SERVE_SHED[lm]").inc(7)
    m = Dashboard.get_or_create("TABLE_ADD[t]")
    m.record(4.25)
    m.record(1.75)


def test_snapshot_covers_every_instrument():
    _populate_dashboard()
    snap = Dashboard.snapshot()
    assert snap["SERVE_TTFT[lm]"]["type"] == "histogram"
    assert snap["SERVE_TTFT[lm]"]["count"] == 3
    assert snap["SERVE_TTFT[lm]"]["max_ms"] == 300.0
    assert snap["DECODE_TPS[lm]"] == {"type": "gauge", "value": 123.5}
    assert snap["SERVE_SHED[lm]"] == {"type": "counter", "value": 7}
    assert snap["TABLE_ADD[t]"]["count"] == 2
    assert snap["TABLE_ADD[t]"]["avg_ms"] == pytest.approx(3.0)
    assert json.loads(json.dumps(snap)) == snap       # plain data only


def test_snapshot_roundtrips_jsonl_and_prometheus():
    """The acceptance-criteria identity: one snapshot, three sinks, same
    values."""
    _populate_dashboard()
    sink = io.StringIO()
    exporter = MetricsExporter(interval_s=60.0, sink=sink)
    record = exporter.report_once()
    snap = record["snapshot"]

    # JSON-lines: the archived line deserializes to the identical snapshot
    line = sink.getvalue().strip().splitlines()[0]
    assert json.loads(line)["snapshot"] == snap

    # Prometheus text: every (instrument, stat) sample carries EXACTLY
    # the snapshot's value (repr round-trip, not approx). The expected
    # sample names follow the renderer's naming rule.
    text = exporter.prometheus()
    assert text == render_prometheus(snap)
    parsed = parse_prometheus(text)
    import re as _re
    for name, row in snap.items():
        base = _re.sub(r"[^a-zA-Z0-9_]", "_",
                       name.partition("[")[0].lower()).strip("_")
        expected = {}
        for field, value in row.items():
            if field == "type":
                continue
            full = f"mv_{base}" if field == "value" else f"mv_{base}_{field}"
            expected[full] = float(value)
        assert parsed[name] == expected


def test_exporter_interval_deltas():
    _populate_dashboard()
    exporter = MetricsExporter(interval_s=60.0)
    exporter.report_once()
    Dashboard.get_or_create_counter("SERVE_SHED[lm]").inc(5)
    Dashboard.get_or_create_histogram("SERVE_TTFT[lm]").record(9.0)
    time.sleep(0.02)
    rec = exporter.report_once()
    assert rec["interval_s"] > 0
    d = rec["deltas"]
    assert d["SERVE_SHED[lm]"]["value"] == 5
    assert d["SERVE_SHED[lm]"]["value_per_s"] > 0
    assert d["SERVE_TTFT[lm]"]["count"] == 1
    # gauges have no monotone fields -> never in deltas
    assert "DECODE_TPS[lm]" not in d
    # a reset instrument reports no (negative) delta
    Dashboard.get_or_create_histogram("SERVE_TTFT[lm]").reset()
    rec = exporter.report_once()
    assert "SERVE_TTFT[lm]" not in rec["deltas"]


def test_exporter_thread_writes_lines(tmp_path):
    _populate_dashboard()
    path = str(tmp_path / "metrics.jsonl")
    exporter = MetricsExporter(interval_s=0.05, sink=path).start()
    _wait(lambda: exporter.reports >= 2)
    exporter.stop(final_report=True)
    lines = open(path).read().strip().splitlines()
    assert len(lines) >= 3
    for line in lines:
        rec = json.loads(line)
        assert "SERVE_TTFT[lm]" in rec["snapshot"]


def test_exporter_snapshots_outside_its_own_lock():
    """Regression (locklint LK204, found by this PR's lint pass):
    report_once used to call Dashboard.snapshot() — the registry lock
    plus every instrument's — while holding the exporter's private lock,
    serializing concurrent prometheus() scrapes and stop() behind the
    whole sweep. The runtime witness proves the fix structurally: after
    reports, no (exporter-lock -> registry-lock) order edge may exist."""
    from multiverso_tpu.analysis import lockwatch

    _populate_dashboard()
    exporter = MetricsExporter(interval_s=60.0)
    exporter.report_once()
    exporter.report_once()
    assert ("dashboard.MetricsExporter._lock",
            "dashboard.Dashboard._lock") not in lockwatch.edges()


def test_exporter_reports_commit_in_snapshot_order(monkeypatch):
    """Regression for the LK204 fix's new race: with the snapshot taken
    outside the exporter's state lock, two concurrent report_once calls
    (the reporter loop racing stop()'s final report) could commit out of
    snapshot order — the older snapshot landing as newest double-counts
    the interval its deltas re-span. _report_lock serializes the
    snapshot+commit pair WITHOUT re-serializing prometheus() scrapes
    behind the registry sweep; intervals run on the monotonic clock so
    a wall-clock step (NTP) can't skew the rates either."""
    import time as _time

    _populate_dashboard()
    exporter = MetricsExporter(interval_s=60.0)
    exporter.report_once()
    # a backwards WALL clock step must not produce a negative interval
    real_time = _time.time
    monkeypatch.setattr(time, "time", lambda: real_time() - 30.0)
    rec = exporter.report_once()
    monkeypatch.undo()
    assert rec["interval_s"] >= 0
    # wedge one report mid-sweep: a concurrent report must WAIT (commit
    # order == snapshot order), while a scrape must NOT
    entered, release = threading.Event(), threading.Event()
    real_snapshot = Dashboard.snapshot

    def slow_snapshot():
        snap = real_snapshot()
        entered.set()
        release.wait(10)
        return snap

    monkeypatch.setattr(Dashboard, "snapshot", staticmethod(slow_snapshot))
    t = threading.Thread(target=exporter.report_once)
    t.start()
    second_done = threading.Event()
    t2 = threading.Thread(
        target=lambda: (exporter.report_once(), second_done.set()))
    try:
        assert entered.wait(5)
        t2.start()
        assert not second_done.wait(0.3), \
            "concurrent report_once overtook a mid-snapshot one"
        exporter.prometheus()           # scrape stays unblocked
        release.set()
        assert second_done.wait(5)
    finally:
        release.set()
        t.join(10)
        t2.join(10)
    assert exporter.reports == 4
    rec = exporter.report_once()
    assert rec["interval_s"] is not None and rec["interval_s"] >= 0


def test_dashboard_reset_detaches_running_exporter(tmp_path):
    """The test-isolation contract: Dashboard.reset() must stop any
    still-running reporter thread — a leaked exporter would keep
    snapshotting (and writing its sink) across every later test."""
    Dashboard.reset()
    exporter = MetricsExporter(interval_s=0.05,
                               sink=str(tmp_path / "m.jsonl")).start()
    _wait(lambda: exporter.reports >= 1)
    thread = exporter._thread
    assert thread is not None and thread.is_alive()
    Dashboard.reset()
    assert exporter._thread is None
    assert not thread.is_alive()
    assert Dashboard._reporters == []
    exporter.stop()                               # idempotent


def test_slo_windowed_burn_status():
    """Rolling-window SLO: value vs target, breach fraction, and burn
    (breach over error budget) — all riding snapshot() as plain data."""
    Dashboard.reset()
    hist = Dashboard.get_or_create_histogram("SERVE_TTFT[lm]")
    slo = Dashboard.set_slo("SERVE_TTFT[lm]", 100.0, percentile=90.0)
    for _ in range(10):
        hist.record(10.0)
    s = slo.summary()
    assert s["ok"] == 1 and s["breach_frac"] == 0.0 and s["burn"] == 0.0
    for _ in range(10):
        hist.record(500.0)
    s = slo.summary()
    assert s["ok"] == 0 and s["value_ms"] == 500.0
    assert s["breach_frac"] == pytest.approx(0.5)
    assert s["burn"] == pytest.approx(5.0)        # 50% breach / 10% budget
    snap = Dashboard.snapshot()
    row = snap["SLO_P90[SERVE_TTFT[lm]]"]
    assert row["type"] == "slo" and row["ok"] == 0
    assert json.loads(json.dumps(snap)) == snap   # still plain data
    assert "BURNING" in Dashboard.watch("SLO_P90[SERVE_TTFT[lm]]")
    # set_slo on the same (source, percentile) re-targets in place
    assert Dashboard.set_slo("SERVE_TTFT[lm]", 1000.0,
                             percentile=90.0) is slo
    assert slo.summary()["ok"] == 1
    # rolling: the breaching samples age out of the window
    for _ in range(Histogram.WINDOW):
        hist.record(1.0)
    assert slo.summary()["breach_frac"] == 0.0


# -- traced serving ----------------------------------------------------------

def test_batcher_handoff_keeps_trace_ids(mv_session, traced):
    """Trace-context propagation across the batcher worker-thread
    boundary: each request's queue-wait/exec spans carry ITS trace id
    (no cross-request leakage), even co-batched in one flush."""
    from multiverso_tpu.serving import InferenceServer

    class Echo:
        source = (lambda: (None, 0), lambda: 0)

        def run(self, payloads, bucket, snap):
            return [p for p in payloads]

    srv = InferenceServer("t")
    srv.register("echo", Echo(), max_batch=8, deadline_ms=5.0)
    futs = [srv.submit("echo", i) for i in range(4)]
    for f in futs:
        f.result(timeout=10)
    _wait(lambda: sum(s.name == "serve.request"
                      for s in traced.spans()) == 4)
    spans = traced.spans()
    roots = [s for s in spans if s.name == "serve.request"]
    assert len({r.trace_id for r in roots}) == 4    # one trace per request
    for root in roots:
        children = [s for s in spans if s.trace_id == root.trace_id
                    and s is not root]
        names = {s.name for s in children}
        assert {"queue.wait", "batch.exec"} <= names
        for s in children:
            assert s.parent_id == root.span_id      # no leaked parents
    # flush-thread spans carry the bucket decision
    execs = [s for s in spans if s.name == "batch.exec"]
    assert all(s.attrs["bucket"] == 4 for s in execs)
    assert all(s.attrs["batch_n"] == 4 for s in execs)


def test_traced_decode_request_end_to_end(mv_session, traced, tmp_path):
    """CI smoke (the ISSUE acceptance walk): a tiny traced serving
    request through the continuous-batching engine -> Chrome trace JSON
    -> structural validation (monotonic ts, matched B/E, ONE root per
    request) -> the root's trace contains queue wait, admission/prefill
    and >=1 decode iteration under the same trace id."""
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import InferenceServer

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq=48)
    lm = TransformerLM(cfg)
    srv = InferenceServer("t")
    srv.register_decoder("lm", lm, slots=4, max_prompt=8, max_new=6)

    prompts = [np.arange(1, 5, dtype=np.int32),
               np.arange(2, 8, dtype=np.int32)]
    futs = [srv.submit("lm", {"prompt": p, "max_new": 4}) for p in prompts]
    replies = [f.result(timeout=60) for f in futs]
    assert all(len(r["result"]) == 4 for r in replies)
    _wait(lambda: sum(s.name == "serve.request"
                      for s in traced.spans()) == 2)

    path = str(tmp_path / "serve_trace.json")
    doc = trace.export_chrome(path)
    events = json.load(open(path))["traceEvents"]
    assert events == doc["traceEvents"]
    stats = trace.validate_chrome_events(events, root_name="serve.request")
    assert stats["roots"] >= 2

    spans = traced.spans()
    roots = [s for s in spans if s.name == "serve.request"]
    assert len(roots) == 2
    assert len({r.trace_id for r in roots}) == 2
    for root in roots:
        tree = [s for s in spans if s.trace_id == root.trace_id]
        names = [s.name for s in tree]
        assert "queue.wait" in names
        admits = [s for s in tree if s.name == "decode.admit"]
        assert len(admits) == 1
        # admission explains itself: slot, its schedule (chunk count +
        # budget for the default chunked admission), the paged-KV
        # reservation (blocks held + pool free at admit) and the pinned
        # snapshot version — which must match the reply's
        a = admits[0].attrs
        assert {"slot", "chunks", "budget", "blocks", "pool_free",
                "snapshot_version", "prompt_len"} <= set(a)
        assert a["blocks"] >= 1
        # every chunk of the admission is its own span under the same
        # trace, and their count is what the admit span claims
        chunks = [s for s in tree if s.name == "decode.prefill_chunk"]
        assert len(chunks) == a["chunks"] >= 1
        assert all(s.parent_id == root.span_id for s in chunks)
        assert all(s.attrs["budget"] == a["budget"] for s in chunks)
        iters = [s for s in tree if s.name == "decode.iter"]
        assert len(iters) >= 1                    # max_new=4 -> 3 iters
        assert all(s.parent_id == root.span_id for s in iters)
        # children lie inside the root's interval (the nesting the
        # Chrome B/E validation relies on)
        for s in tree:
            assert s.t0 >= root.t0 - 1e-6
            assert s.t1 <= root.t1 + 1e-6
    reply_versions = {r["snapshot_version"] for r in replies}
    admit_versions = {s.attrs["snapshot_version"] for s in spans
                      if s.name == "decode.admit"}
    assert admit_versions == reply_versions


def test_tracing_disabled_no_decode_hot_loop_overhead(mv_session,
                                                      monkeypatch):
    """The overhead guard: with the collector OFF (the default), a full
    generation through the engine must not construct one Span, record
    one event, or touch the collector — the hot loop's only tracing
    cost is the ``enabled()`` attribute read."""
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import InferenceServer

    assert not trace.enabled()
    calls = {"span": 0, "record": 0}
    real_span_init = trace.Span.__init__

    def counting_init(self, *a, **kw):
        calls["span"] += 1
        return real_span_init(self, *a, **kw)

    real_record = trace.TraceCollector.record

    def counting_record(self, sp):
        calls["record"] += 1
        return real_record(self, sp)

    monkeypatch.setattr(trace.Span, "__init__", counting_init)
    monkeypatch.setattr(trace.TraceCollector, "record", counting_record)

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq=48)
    srv = InferenceServer("t")
    engine = srv.register_decoder("lm", TransformerLM(cfg), slots=2,
                                  max_prompt=8, max_new=8)
    out = srv.submit("lm", np.arange(1, 6, dtype=np.int32)).result(
        timeout=60)
    assert len(out["result"]) == 8               # 7 decode iterations ran
    assert calls == {"span": 0, "record": 0}
    assert trace.collector().spans() == []
    # the ALWAYS-ON flight recorder was live the whole time — proving
    # the zero-Span guarantee holds with black-box recording running —
    # and it added no compiled trace to the fused step
    assert engine.recorder is not None and engine.recorder.total > 0
    assert engine.step_cache_size() == 1


def test_tail_sampled_decode_keeps_only_sampled_trees(mv_session):
    """Serving-path tail sampling: with an unreachable SLO and no head
    sample, a healthy engine's requests leave NOTHING in the ring (the
    leave-it-on posture); with head_n=1 every tree survives intact."""
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import InferenceServer

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq=48)
    srv = InferenceServer("t")
    srv.register_decoder("lm", TransformerLM(cfg), slots=2, max_prompt=8,
                         max_new=4)
    try:
        trace.enable(4096, tail=trace.TailConfig(slo_ms=1e9, head_n=0))
        for _ in range(2):
            srv.submit("lm", np.arange(1, 5, dtype=np.int32)).result(
                timeout=60)
        # snapshot.pin spans are roots of their own traces, so completed
        # counts >= the two requests — but NOTHING may survive the
        # sampler (no breach, no error, no head sample)
        _wait(lambda: trace.collector().tail_completed >= 2)
        col = trace.collector()
        assert col.spans() == []                 # every tree discarded
        assert col.tail_kept == 0
        assert col.tail_discarded == col.tail_completed >= 2

        trace.enable(4096, tail=trace.TailConfig(slo_ms=1e9, head_n=1))
        srv.submit("lm", np.arange(1, 5, dtype=np.int32)).result(
            timeout=60)
        _wait(lambda: any(s.name == "serve.request"
                          for s in trace.collector().spans()))
        spans = trace.collector().spans()
        req_ids = {s.trace_id for s in spans if s.name == "serve.request"}
        assert len(req_ids) == 1
        tree = [s for s in spans if s.trace_id in req_ids]
        names = {s.name for s in tree}
        # the whole tree survived the sampler, parentage intact
        assert {"serve.request", "queue.wait", "decode.admit",
                "decode.iter"} <= names
        root = [s for s in tree if s.name == "serve.request"][0]
        assert root.attrs["tail_keep"] == "head"
        assert all(s.parent_id == root.span_id for s in tree
                   if s is not root)
    finally:
        trace.disable()
        trace.collector().clear()


def test_table_add_span_tagged(mv_session, traced):
    """TABLE_ADD's trace twin carries the table name and the version the
    apply produced — the join key between a serving trace's
    snapshot_version and the training write that created it."""
    table = mv_session.create_table("array", 8, name="obs_t")
    table.add(np.ones(8, np.float32))
    table.add(np.ones(8, np.float32))
    adds = [s for s in traced.spans() if s.name == "table.add"]
    assert len(adds) == 2
    assert [s.attrs["version"] for s in adds] == [1, 2]
    assert all(s.attrs["table"] == "obs_t" for s in adds)
