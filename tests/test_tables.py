"""Table tests mirroring the reference integration invariants
(Test/main.cpp: TestArray/TestMatrix/TestKV — value == sum of workers' adds).
"""

import io

import numpy as np
import pytest


def test_array_accumulation_invariant(mv_session):
    mv = mv_session
    table = mv.create_table("array", 64)
    iters, workers = 5, 3  # simulate 3 workers adding in turn (1-process BSP)
    delta = np.ones(64, np.float32)
    for _ in range(iters):
        for w in range(workers):
            table.add(delta)
    np.testing.assert_allclose(table.get(), np.full(64, iters * workers, np.float32))


def test_array_async_then_wait(mv_session):
    table = mv_session.create_table("array", 16)
    handles = [table.add_async(np.ones(16, np.float32)) for _ in range(4)]
    for h in handles:
        h.wait()
    np.testing.assert_allclose(table.get(), np.full(16, 4.0))


def test_array_sharded_over_server_axis(mv_session):
    table = mv_session.create_table("array", 64)
    servers = mv_session.num_servers()
    spec = table.array.sharding.spec
    if servers > 1:
        assert spec[0] == "server"


def test_array_init_value_and_get_into(mv_session):
    init = np.arange(10, dtype=np.float32)
    table = mv_session.create_table("array", 10, init_value=init)
    out = np.zeros(10, np.float32)
    table.get_into(out)
    np.testing.assert_array_equal(out, init)


def test_matrix_whole_and_row_ops(mv_session):
    mv = mv_session
    num_row, num_col = 16, 8
    table = mv.create_table("matrix", num_row, num_col)
    table.add(np.ones((num_row, num_col), np.float32))
    rows = [0, 3, 9]
    table.add_rows(rows, np.full((3, num_col), 2.0, np.float32))
    got = table.get()
    expect = np.ones((num_row, num_col), np.float32)
    for r in rows:
        expect[r] += 2.0
    np.testing.assert_allclose(got, expect)
    np.testing.assert_allclose(table.get_rows(rows), expect[rows])
    np.testing.assert_allclose(table.get_row(3), expect[3])


def test_matrix_duplicate_row_adds_accumulate(mv_session):
    table = mv_session.create_table("matrix", 4, 4)
    table.add_rows([2, 2], np.ones((2, 4), np.float32))
    np.testing.assert_allclose(table.get_row(2), np.full(4, 2.0))


def test_matrix_row_bucketing_many_sizes(mv_session):
    # exercise several pad buckets (1, 8, 9->16, 100->128)
    table = mv_session.create_table("matrix", 128, 4)
    for count in [1, 8, 9, 100]:
        ids = np.arange(count) % 128
        table.add_rows(ids, np.ones((count, 4), np.float32))
    total = table.get().sum()
    np.testing.assert_allclose(total, (1 + 8 + 9 + 100) * 4)


def test_matrix_random_init_distribution(mv_session):
    table = mv_session.create_table("matrix", 100, 50, init_value="random", seed=1)
    got = table.get()
    # (U[0,1)-0.5)/num_col: bounded by 0.5/50
    assert np.all(np.abs(got) <= 0.5 / 50 + 1e-7)
    assert np.std(got) > 0


def test_sparse_matrix_dirty_rows(mv_session):
    mv = mv_session
    table = mv.create_table("matrix", 8, 4, is_sparse=True, num_sim_workers=2)
    from multiverso_tpu.updaters import AddOption

    # worker 0 adds rows 1,5 -> dirty for worker 1 only
    table.add_rows([1, 5], np.ones((2, 4), np.float32), AddOption(worker_id=0))
    ids0, _ = table.get_dirty_rows(0)
    assert ids0.size == 0  # own writes aren't dirty for self
    ids1, rows1 = table.get_dirty_rows(1)
    np.testing.assert_array_equal(ids1, [1, 5])
    np.testing.assert_allclose(rows1, np.ones((2, 4)))
    # second get: bitmap cleared
    ids1b, _ = table.get_dirty_rows(1)
    assert ids1b.size == 0


def test_kv_table_add_get_raw(mv_session):
    table = mv_session.create_table("kv")
    table.add([1, 5, 9], [1.0, 2.0, 3.0])
    table.add([5], [2.0])
    assert table.get([1, 5, 9, 42]) == [1.0, 4.0, 3.0, 0]
    raw = table.raw()
    assert raw[5] == 4.0
    assert len(table) == 3
    table.sync()  # single-process: no-op, must not hang


def test_sparse_table_keyed_ops(mv_session):
    table = mv_session.create_table("sparse", 1000)
    keys = [3, 500, 999]
    table.add_keys(keys, [1.0, 2.0, 3.0])
    table.add_keys([500], [0.5])
    np.testing.assert_allclose(table.get_keys(keys), [1.0, 2.5, 3.0])
    # untouched keys stay zero
    np.testing.assert_allclose(table.get_keys([0, 42]), [0.0, 0.0])


def test_ftrl_table_zn_accumulation(mv_session):
    table = mv_session.create_table("ftrl", 100)
    keys = [7, 42]
    table.add_keys(keys, delta_z=[0.1, 0.2], delta_n=[1.0, 4.0])
    table.add_keys([7], delta_z=[0.3], delta_n=[1.0])
    z, n = table.get_keys(keys)
    np.testing.assert_allclose(z, [0.4, 0.2], rtol=1e-6)
    np.testing.assert_allclose(n, [2.0, 4.0], rtol=1e-6)


def test_table_updater_selection(mv_session):
    mv = mv_session
    # sgd: data -= delta
    table = mv.create_table("array", 8, updater="sgd")
    table.add(np.full(8, 0.5, np.float32))
    np.testing.assert_allclose(table.get(), np.full(8, -0.5))
    # momentum on a matrix via dense fallback
    mt = mv.create_table("matrix", 4, 4, updater="momentum_sgd")
    from multiverso_tpu.updaters import AddOption

    mt.add_rows([1], np.ones((1, 4), np.float32), AddOption(momentum=0.0))
    expect = np.zeros((4, 4), np.float32)
    expect[1] = -1.0
    np.testing.assert_allclose(mt.get(), expect)


def test_store_load_roundtrip(mv_session):
    mv = mv_session
    table = mv.create_table("matrix", 8, 4)
    table.add(np.random.default_rng(0).random((8, 4)).astype(np.float32))
    buf = io.BytesIO()
    table.store(buf)
    snapshot = table.get()
    table.add(np.ones((8, 4), np.float32))  # mutate
    buf.seek(0)
    table.load(buf)
    np.testing.assert_allclose(table.get(), snapshot)

    kv = mv.create_table("kv")
    kv.add([1, 2], [5.0, 6.0])
    buf2 = io.BytesIO()
    kv.store(buf2)
    kv.add([1], [1.0])
    buf2.seek(0)
    kv.load(buf2)
    assert kv.get([1, 2]) == [5.0, 6.0]


def test_integer_table_forced_default_updater(mv_session):
    import jax.numpy as jnp

    table = mv_session.create_table("array", 8, dtype=jnp.int32, updater="sgd")
    table.add(np.full(8, 3, np.int32))
    np.testing.assert_array_equal(table.get(), np.full(8, 3, np.int32))


def test_create_table_unknown_kind(mv_session):
    from multiverso_tpu.log import FatalError

    with pytest.raises(FatalError):
        mv_session.create_table("nope")


def test_uneven_leading_dim_still_sharded(mv_session):
    """VERDICT r1: uneven dims must PAD to a server-axis multiple and stay
    sharded (reference handles the remainder range explicitly,
    src/table/array_table.cpp:11-22), never fall back to replication."""
    mv = mv_session
    servers = mv.num_servers()
    # the text8 vocabulary (71,291 rows) — indivisible by any server count > 1
    table = mv.create_table("matrix", 71291, 4)
    assert table.array.sharding.spec[0] == "server"
    assert table.array.shape[0] % servers == 0
    assert table.array.shape[0] - 71291 < servers
    assert table.shape == (71291, 4)


def test_uneven_dim_exact_semantics_at_ragged_tail(mv_session):
    mv = mv_session
    servers = mv.num_servers()
    rows = 8 * servers + 3 if servers > 1 else 11   # force a ragged tail
    table = mv.create_table("matrix", rows, 4)
    if servers > 1:
        assert table.array.sharding.spec[0] == "server"
    # whole-table add covers the tail rows exactly
    table.add(np.ones((rows, 4), np.float32))
    got = table.get()
    assert got.shape == (rows, 4)
    np.testing.assert_allclose(got, 1.0)
    # keyed add on the last (ragged) row
    table.add_rows([rows - 1], np.full((1, 4), 2.0, np.float32))
    np.testing.assert_allclose(table.get_row(rows - 1), 3.0)
    np.testing.assert_allclose(table.get_rows([0, rows - 1]),
                               [[1.0] * 4, [3.0] * 4])
    # store/load round-trips the LOGICAL array
    import io as _io

    buf = _io.BytesIO()
    table.store(buf)
    buf.seek(0)
    table2 = mv.create_table("matrix", rows, 4)
    table2.load(buf)
    np.testing.assert_allclose(table2.get(), table.get())


def test_uneven_array_with_stateful_updater(mv_session):
    mv = mv_session
    servers = mv.num_servers()
    n = 8 * servers + 1 if servers > 1 else 9
    table = mv.create_table("array", n, updater="adagrad")
    if servers > 1:
        assert table.array.sharding.spec[0] == "server"
    delta = np.ones(n, np.float32)
    table.add(delta)
    got = table.get()
    assert got.shape == (n,)
    # adagrad moves every logical element identically (uniform delta)
    assert np.allclose(got, got[0]) and got[0] < 0


def test_apply_remote_keyed_feeds_remote_accum(mv_session):
    """Keyed bus applies must feed the remote-delta accumulator exactly like
    dense ones (r3 review: a keyed peer delta missing from _remote_accum is
    counted as own movement by the async pusher and republished — echo
    amplification)."""
    import numpy as np

    import multiverso_tpu as mv
    from multiverso_tpu.updaters import AddOption

    t = mv.create_table("matrix", 8, 4)
    t._remote_accum = np.zeros((8, 4), np.float32)
    ids = np.array([1, 6, 1], np.int32)           # repeated id accumulates
    vals = np.full((3, 4), 0.5, np.float32)
    t._apply_remote_keyed(ids, vals, AddOption())
    got = t.get()
    assert np.allclose(got[1], 1.0) and np.allclose(got[6], 0.5)
    assert np.allclose(t._remote_accum[1], 1.0)
    assert np.allclose(t._remote_accum[6], 0.5)
    assert np.allclose(t._remote_accum[0], 0.0)
    # own-movement computation nets out the peer delta exactly
    own = np.asarray(t.get(), np.float32) - 0.0 - t._remote_accum
    assert np.allclose(own, 0.0)
    t._remote_accum = None
