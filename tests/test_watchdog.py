"""Watchdog (serving/watchdog.py): triggers, bundles, and the e2e stall.

The trigger matrix runs against a duck-typed fake engine (fast, exact);
the end-to-end test wedges a REAL engine's fused step and requires the
live watchdog thread to trip within its deadline, dump the full bundle
(flight ring + stats + dashboard + thread stacks), and count the trip.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.dashboard import Dashboard
from multiverso_tpu.serving.watchdog import (EngineWatchdog, WatchdogConfig,
                                             thread_stacks)


class _FakeEngine:
    """The watchdog's whole contract: health() / pool_drift() / stats()
    / name / recorder."""

    name = "fake"

    def __init__(self):
        self.h = {"iters_total": 7, "last_iter_age_s": 0.0, "live_seqs": 0,
                  "active_slots": 0, "queue_depth": 0, "queue_age_s": 0.0,
                  "stopped": False}
        self.drift = None
        self.recorder = None

    def health(self):
        return dict(self.h)

    def pool_drift(self):
        return self.drift

    def stats(self):
        return {"marker": 123, **self.h}


@pytest.fixture()
def fake_wd(tmp_path):
    Dashboard.reset()
    engine = _FakeEngine()
    wd = EngineWatchdog(engine, WatchdogConfig(
        stall_s=0.5, queue_age_s=2.0, dump_dir=str(tmp_path)), start=False)
    yield engine, wd
    Dashboard.reset()


def test_stall_requires_live_work_and_rearms(fake_wd):
    engine, wd = fake_wd
    assert wd.check_once() == []                  # healthy
    engine.h["last_iter_age_s"] = 5.0
    assert wd.check_once() == []                  # idle != stalled
    engine.h["live_seqs"] = 2
    fired = wd.check_once()
    assert len(fired) == 1 and "stall" in fired[0]
    assert wd.check_once() == []                  # edge-triggered
    engine.h["last_iter_age_s"] = 0.0             # progress resumed
    assert wd.check_once() == []
    engine.h["last_iter_age_s"] = 5.0             # stalls AGAIN: re-armed
    assert len(wd.check_once()) == 1
    assert wd.trip_count == 2
    assert Dashboard.get_or_create_counter(
        "WATCHDOG_TRIPS[fake]").get() == 2


def test_queue_age_breach_trips(fake_wd):
    engine, wd = fake_wd
    engine.h["queue_age_s"] = 1.0
    assert wd.check_once() == []                  # under the limit
    engine.h["queue_age_s"] = 3.0
    fired = wd.check_once()
    assert len(fired) == 1 and "queue-age breach" in fired[0]
    assert wd.trips[0][0] == "queue_age"


def test_pool_drift_needs_two_consecutive_verdicts(fake_wd):
    engine, wd = fake_wd
    engine.drift = "leak: 2 free + 1 live != capacity 4"
    assert wd.check_once() == []                  # first sighting arms
    fired = wd.check_once()                       # verdict persisted
    assert len(fired) == 1 and "block-pool drift" in fired[0]
    # a transient that CLEARS between polls never trips
    wd2 = EngineWatchdog(engine, wd.config, start=False)
    engine.drift = "leak: transient"
    assert wd2.check_once() == []
    engine.drift = None
    assert wd2.check_once() == []
    assert wd2.trip_count == 0
    # the VERDICT must persist, not the exact message: a real leak's
    # free/live counts fluctuate under live traffic poll to poll
    wd3 = EngineWatchdog(engine, wd.config, start=False)
    engine.drift = "leak: 2 free + 1 live != capacity 4"
    assert wd3.check_once() == []
    engine.drift = "leak: 1 free + 2 live != capacity 4"
    fired = wd3.check_once()
    assert len(fired) == 1 and "block-pool drift" in fired[0]


def test_stopped_engine_never_trips(fake_wd):
    engine, wd = fake_wd
    engine.h.update(stopped=True, live_seqs=3, last_iter_age_s=99.0,
                    queue_age_s=99.0)
    engine.drift = "leak"
    assert wd.check_once() == []
    assert wd.check_once() == []
    assert wd.trip_count == 0


def test_bundle_layout_and_no_dump_dir(fake_wd, tmp_path):
    engine, wd = fake_wd
    engine.h.update(live_seqs=1, last_iter_age_s=5.0)
    wd.check_once()
    kind, reason, bundle = wd.trips[0]
    assert kind == "stall" and bundle is not None
    files = set(os.listdir(bundle))
    assert {"stats.json", "dashboard.json", "stacks.txt"} <= files
    meta = json.load(open(os.path.join(bundle, "stats.json")))
    assert meta["kind"] == "stall" and meta["engine"] == "fake"
    assert meta["stats"]["marker"] == 123
    json.load(open(os.path.join(bundle, "dashboard.json")))   # valid JSON
    assert "MainThread" in open(os.path.join(bundle, "stacks.txt")).read()
    # without a dump dir the trip still counts, with no bundle
    engine2 = _FakeEngine()
    engine2.h.update(live_seqs=1, last_iter_age_s=5.0)
    seen = []
    wd2 = EngineWatchdog(engine2, WatchdogConfig(
        stall_s=0.5, on_trip=lambda r, b: seen.append((r, b))),
        start=False)
    wd2.check_once()
    assert wd2.trips[0][2] is None
    assert seen and seen[0][1] is None and "stall" in seen[0][0]


def test_flapping_condition_bounded_memory_and_bundles(fake_wd):
    """A condition oscillating around its threshold re-trips every
    clear/re-breach cycle; trips must stay counted but bounded in memory
    and STOP writing bundles at max_bundles (each bundle is a full
    ring + snapshot + stacks — unbounded dumps fill the degraded
    replica's own disk)."""
    engine, wd = fake_wd
    for _ in range(70):
        engine.h["queue_age_s"] = 3.0             # breach
        assert len(wd.check_once()) == 1
        engine.h["queue_age_s"] = 0.0             # clear -> re-arm
        assert wd.check_once() == []
    assert wd.trip_count == 70
    assert Dashboard.get_or_create_counter(
        "WATCHDOG_TRIPS[fake]").get() == 70
    assert len(wd.trips) == 64                    # bounded, newest kept
    assert wd.bundles == wd.config.max_bundles == 16
    # bundles stopped at trip 16: everything after is count-and-log only
    assert all(t[2] is None for t in list(wd.trips)[-54:])
    assert sum(os.path.isdir(os.path.join(wd.config.dump_dir, d))
               for d in os.listdir(wd.config.dump_dir)) == 16


def test_thread_stacks_cover_live_threads():
    text = thread_stacks()
    assert "MainThread" in text
    assert "test_thread_stacks_cover_live_threads" in text


# -- real engine --------------------------------------------------------------

def test_injected_stall_trips_within_deadline_e2e(mv_session, tmp_path):
    """The acceptance walk: a wedged fused step on a live engine trips
    the RUNNING watchdog thread within stall_s + ~2 polls, the bundle
    holds the iteration ring and the wedged thread's stack, and
    WATCHDOG_TRIPS increments — then the engine recovers and finishes
    the generation once unblocked."""
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import InferenceServer

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq=48)
    srv = InferenceServer("t")
    engine = srv.register_decoder("lm", TransformerLM(cfg), slots=2,
                                  max_prompt=8, max_new=8, watchdog=False)
    # a healthy generation first, so the flight ring holds real records
    out = srv.submit("lm", np.arange(1, 5, dtype=np.int32)).result(
        timeout=60)
    assert len(out["result"]) == 8

    tripped = threading.Event()
    engine.watchdog = EngineWatchdog(engine, WatchdogConfig(
        interval_s=0.05, stall_s=0.4, queue_age_s=0.0,
        dump_dir=str(tmp_path),
        on_trip=lambda reason, bundle: tripped.set()))

    release = threading.Event()
    orig_step = engine._step_fn

    def wedged_step(*args, **kwargs):
        release.wait(30)
        return orig_step(*args, **kwargs)

    engine._step_fn = wedged_step
    t0 = time.monotonic()
    fut = srv.submit("lm", np.arange(1, 6, dtype=np.int32))
    try:
        assert tripped.wait(5.0), "watchdog missed its deadline"
        trip_latency = time.monotonic() - t0
        assert trip_latency < 5.0
        wd = engine.watchdog
        assert wd.trip_count == 1
        kind, reason, bundle = wd.trips[0]
        assert kind == "stall" and "live sequence" in reason
        files = set(os.listdir(bundle))
        assert {"stats.json", "dashboard.json", "stacks.txt",
                "ring.jsonl"} <= files
        # the ring dump: meta line + the healthy generation's iterations
        lines = open(os.path.join(bundle, "ring.jsonl")).read().splitlines()
        assert json.loads(lines[0])["flight_recorder"]["name"] == "lm"
        assert len(lines) - 1 >= 5                # >= max_new-1 iterations
        # the stack dump shows WHERE the engine thread is wedged
        stacks = open(os.path.join(bundle, "stacks.txt")).read()
        assert "serve-decode-lm" in stacks and "wedged_step" in stacks
        snap = Dashboard.snapshot()
        assert snap["WATCHDOG_TRIPS[lm]"]["value"] == 1
        assert engine.stats()["watchdog_trips"] == 1
    finally:
        release.set()
    # unwedged: the generation completes and the stall re-arms
    assert len(fut.result(timeout=60)["result"]) == 8


def test_pool_drift_detector_on_real_engine(mv_session):
    """A hand-corrupted block pool (blocks allocated behind the engine's
    back) fires the drift detector after the two-poll persistence; a
    healthy engine never does."""
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
    from multiverso_tpu.serving import InferenceServer

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq=48)
    srv = InferenceServer("t")
    engine = srv.register_decoder("lm", TransformerLM(cfg), slots=2,
                                  max_prompt=8, max_new=4, watchdog=False)
    wd = EngineWatchdog(engine, WatchdogConfig(stall_s=30.0), start=False)
    out = srv.submit("lm", np.arange(1, 5, dtype=np.int32)).result(
        timeout=60)
    assert len(out["result"]) == 4
    for _ in range(4):                            # healthy: forever silent
        assert wd.check_once() == []
    assert engine.pool_drift() is None
    # corrupt: a reservation nothing owns (the leak signature)
    engine._pool.alloc(1)
    # ... but the same pool state mid-monolithic-admission is NOT a
    # leak: _admit holds reservations across its (possibly seconds-long)
    # cold-bucket compile before any slot goes active
    engine._admitting = True
    assert engine.pool_drift() is None
    # ... and that same in-flight admission IS live work to the stall
    # check: its requests are off the queue with no slot active yet, so
    # a wedged fused prefill would otherwise be invisible
    assert engine.health()["live_seqs"] == 1
    engine._admitting = False
    assert engine.health()["live_seqs"] == 0
    assert wd.check_once() == []                  # first sighting
    fired = wd.check_once()                       # persisted -> trip
    assert len(fired) == 1
    assert "live block" in fired[0] and "zero live sequences" in fired[0]
    assert wd.trips[0][0] == "pool_drift"
