"""Word2vec model + app tests (reference: WordEmbedding training invariants)."""

import os

import numpy as np
import pytest


def _toy_corpus(tmp_path, repeats=200):
    """Two word 'clusters' that co-occur: (a b c) and (x y z)."""
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(repeats):
        lines.append(" ".join(rng.permutation(["a", "b", "c"]).tolist()))
        lines.append(" ".join(rng.permutation(["x", "y", "z"]).tolist()))
    path = tmp_path / "corpus.txt"
    path.write_text("\n".join(lines))
    return str(path)


def test_unigram_alias_distribution():
    from multiverso_tpu.models.word2vec import build_unigram_alias

    counts = np.array([100, 10, 1], np.float64)
    thresh, alias = build_unigram_alias(counts)
    assert thresh.shape == (3,) and alias.shape == (3,)
    # sampling matches p ~ counts^0.75 within tolerance
    import jax

    from multiverso_tpu.models.word2vec import pack_alias_table, sample_negatives
    import jax.numpy as jnp

    samples = np.asarray(sample_negatives(
        jax.random.PRNGKey(0),
        pack_alias_table(jnp.asarray(thresh), jnp.asarray(alias)),
        (20000,)))
    freq = np.bincount(samples, minlength=3) / samples.size
    expect = counts ** 0.75
    expect /= expect.sum()
    np.testing.assert_allclose(freq, expect, atol=0.02)


def test_huffman_codes_valid():
    from multiverso_tpu.models.word2vec import build_huffman

    counts = np.array([50, 30, 10, 5, 5], np.float64)
    h = build_huffman(counts)
    # frequent words get shorter codes
    lengths = h.mask.sum(axis=1)
    assert lengths[0] <= lengths[-1]
    # all inner-node ids within [0, vocab-1)
    used = h.paths[h.mask > 0]
    assert used.min() >= 0 and used.max() < counts.shape[0] - 1


def test_dictionary_and_pairs(mv_session, tmp_path):
    from multiverso_tpu.apps.wordembedding import Dictionary, iter_pair_batches

    corpus = _toy_corpus(tmp_path)
    d = Dictionary.build(corpus, min_count=1)
    assert d.vocab_size == 6
    assert d.train_words == 1200
    batches = list(iter_pair_batches(corpus, d, window=2, batch_size=128,
                                     sample=0))
    assert all(c.shape == (128,) for c, _, _ in batches)
    # pairs only within cluster lines: center and context in same triple
    clusters = {d.word2id[w]: 0 for w in "abc"} | {d.word2id[w]: 1 for w in "xyz"}
    for centers, contexts, mask in batches:
        valid = mask > 0
        for c, t in zip(centers[valid], contexts[valid]):
            assert clusters[int(c)] == clusters[int(t)]


def test_pair_batches_sharding_partitions_lines(mv_session, tmp_path):
    """Multi-worker data partition (ADVICE r2): shards are disjoint by raw
    line and their union covers the whole corpus."""
    from multiverso_tpu.apps.wordembedding import Dictionary, iter_pair_batches

    # distinct word per line so every pair identifies its source line
    words = [f"w{i}" for i in range(8)]
    corpus = tmp_path / "shard.txt"
    corpus.write_text("".join(f"{w} {w} {w} {w}\n" for w in words) * 40)
    d = Dictionary.build(str(corpus), min_count=1)

    def centers_seen(shard):
        seen = set()
        for c, _, m in iter_pair_batches(str(corpus), d, window=1,
                                         batch_size=32, sample=0,
                                         shard=shard):
            seen.update(int(x) for x in np.asarray(c)[np.asarray(m) > 0])
        return seen

    s0, s1 = centers_seen((0, 2)), centers_seen((1, 2))
    lines0 = {d.words[i] for i in s0}
    lines1 = {d.words[i] for i in s1}
    assert lines0 == {f"w{i}" for i in range(0, 8, 2)}
    assert lines1 == {f"w{i}" for i in range(1, 8, 2)}
    assert centers_seen((0, 1)) == s0 | s1


@pytest.mark.parametrize("mode", ["neg", "hs", "adagrad", "cbow", "hs+neg"])
def test_word2vec_learns_cooccurrence(mv_session, tmp_path, mode):
    """After training, in-cluster similarity should beat cross-cluster."""
    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import Dictionary, train
    from multiverso_tpu.models.word2vec import Word2VecConfig

    corpus = _toy_corpus(tmp_path)
    cfg = Word2VecConfig(
        embedding_size=16, window=2,
        negative=0 if mode == "hs" else 3,
        hs=(mode in ("hs", "hs+neg")), use_adagrad=(mode == "adagrad"),
        cbow=(mode == "cbow"),
        init_lr=0.03, batch_size=128, seed=3)
    out = str(tmp_path / f"vec_{mode}.txt")
    result = train(corpus, out, cfg, epochs=3, min_count=1, sample=0,
                   log_every=0)
    assert result.words_trained > 0
    assert os.path.exists(out)

    # parse embeddings back and check cluster structure
    with open(out) as f:
        header = f.readline().split()
        assert header == ["6", "16"]
        vecs = {}
        for line in f:
            parts = line.split()
            vecs[parts[0]] = np.asarray([float(v) for v in parts[1:]])

    def sim(a, b):
        va, vb = vecs[a], vecs[b]
        return va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-9)

    in_cluster = np.mean([sim("a", "b"), sim("b", "c"), sim("x", "y"),
                          sim("y", "z")])
    cross = np.mean([sim("a", "x"), sim("b", "y"), sim("c", "z")])
    assert in_cluster > cross, (mode, in_cluster, cross)


def test_word2vec_lr_decay_in_word_units(mv_session, tmp_path):
    """LR must decay over corpus words, not collapse to the floor early."""
    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import train
    from multiverso_tpu.models.word2vec import Word2VecConfig

    corpus = _toy_corpus(tmp_path, repeats=100)
    cfg = Word2VecConfig(embedding_size=8, window=2, negative=2,
                         init_lr=0.1, batch_size=64)
    # capture lr trajectory via a wrapper table... simpler: train then check
    # the model's internal counters stayed in word range
    from multiverso_tpu.apps.wordembedding import Dictionary

    d = Dictionary.build(corpus, min_count=1)
    result = train(corpus, None, cfg, epochs=1, min_count=1, sample=0,
                   dictionary=d, log_every=0)
    # 1 epoch over 600 words: pairs >> words, but decay tracked words
    assert result.pairs_trained > d.train_words  # pairs really exceed words


def test_word2vec_requires_an_objective(mv_session):
    import multiverso_tpu as mv
    from multiverso_tpu.log import FatalError
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    w_in = mv.create_table("matrix", 8, 4)
    w_out = mv.create_table("matrix", 8, 4)
    with pytest.raises(FatalError):
        Word2Vec(Word2VecConfig(vocab_size=8, negative=0, hs=False),
                 w_in, w_out)


def test_cbow_device_resident(mv_session, tmp_path):
    """CBOW on the device-resident path learns cluster structure."""
    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import Dictionary, encode_corpus
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    corpus = _toy_corpus(tmp_path)
    d = Dictionary.build(corpus, min_count=1)
    cfg = Word2VecConfig(vocab_size=d.vocab_size, embedding_size=16,
                         window=2, negative=3, cbow=True, init_lr=0.003,
                         batch_size=256, seed=9)
    w_in = mv.create_table("matrix", d.vocab_size, 16, init_value="random",
                           seed=9)
    w_out = mv.create_table("matrix", d.vocab_size, 16)
    model = Word2Vec(cfg, w_in, w_out, counts=np.asarray(d.counts, np.float64))
    model.total_words = 10 ** 9
    ids, sents = encode_corpus(corpus, d)
    model.load_corpus_chunk(ids, sents)
    for _ in range(10):
        loss, count = model.train_device_steps(20)
    assert np.isfinite(float(loss)) and float(count) > 0

    vecs = w_in.get()

    def sim(a, b):
        va, vb = vecs[d.word2id[a]], vecs[d.word2id[b]]
        return va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-9)

    assert np.mean([sim("a", "b"), sim("x", "y")]) > \
        np.mean([sim("a", "x"), sim("b", "y")])


def test_word2vec_device_resident_path(mv_session, tmp_path):
    """load_corpus_chunk + train_device_steps learns the same structure."""
    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import Dictionary, encode_corpus
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    corpus = _toy_corpus(tmp_path)
    d = Dictionary.build(corpus, min_count=1)
    cfg = Word2VecConfig(vocab_size=d.vocab_size, embedding_size=16,
                         window=2, negative=3, init_lr=0.01, batch_size=256,
                         seed=5)
    w_in = mv.create_table("matrix", d.vocab_size, 16, init_value="random",
                           seed=5)
    w_out = mv.create_table("matrix", d.vocab_size, 16)
    model = Word2Vec(cfg, w_in, w_out, counts=np.asarray(d.counts, np.float64))
    model.total_words = 10 ** 9
    ids, sents = encode_corpus(corpus, d)
    model.load_corpus_chunk(ids, sents)
    first_loss = None
    for i in range(10):
        loss, count = model.train_device_steps(20)
        if i == 0:
            first_loss = float(loss)
    last_loss = float(loss)
    assert float(count) > 0
    assert last_loss < first_loss  # learning

    vecs = w_in.get()

    def sim(a, b):
        va, vb = vecs[d.word2id[a]], vecs[d.word2id[b]]
        return va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-9)

    in_cluster = np.mean([sim("a", "b"), sim("x", "y")])
    cross = np.mean([sim("a", "x"), sim("b", "y")])
    assert in_cluster > cross


def test_word2vec_sharded_tables(mv_session, tmp_path):
    """Embedding tables stay sharded over the server axis during training."""
    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import Dictionary, train
    from multiverso_tpu.models.word2vec import Word2VecConfig

    mv.shutdown()
    mv.set_flag("mesh_shape", "2,4")
    mv.init()
    try:
        corpus = _toy_corpus(tmp_path, repeats=20)
        # vocab 6 doesn't divide 4 -> table falls back to unsharded; use a
        # padded vocab table instead by checking the training still works.
        cfg = Word2VecConfig(embedding_size=8, window=2, negative=2,
                             init_lr=0.05, batch_size=64)
        result = train(corpus, None, cfg, epochs=1, min_count=1, sample=0,
                       log_every=0)
        assert result.words_trained > 0
    finally:
        mv.set_flag("mesh_shape", "")


def test_negative_pool_distribution_and_slicing():
    """Pool draws follow unigram^0.75 and slices differ across keys."""
    import jax
    import jax.numpy as jnp

    from multiverso_tpu.models.word2vec import (build_negative_pool,
                                                build_unigram_alias,
                                                pool_negatives)

    counts = np.array([100, 10, 1], np.float64)
    thresh, alias = build_unigram_alias(counts)
    pool = build_negative_pool(thresh, alias, 50000, seed=3)
    freq = np.bincount(pool, minlength=3) / pool.size
    expect = counts ** 0.75
    expect /= expect.sum()
    np.testing.assert_allclose(freq, expect, atol=0.02)

    dev_pool = jnp.asarray(pool)
    a = np.asarray(pool_negatives(jax.random.PRNGKey(0), dev_pool, (64, 5)))
    b = np.asarray(pool_negatives(jax.random.PRNGKey(1), dev_pool, (64, 5)))
    assert a.shape == (64, 5)
    assert not np.array_equal(a, b)          # different offsets
    assert set(np.unique(a)) <= {0, 1, 2}


def test_train_device_steps_with_pool(tmp_path, mv_session):
    """Fused corpus training with the pre-drawn pool stays finite and
    counts pairs (the bench configuration's sampler path)."""
    import jax.numpy as jnp

    import multiverso_tpu as mv
    from multiverso_tpu.apps.wordembedding import (Dictionary, encode_corpus,
                                                   subsample_probs)
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    rng = np.random.default_rng(0)
    lines = [" ".join(f"w{rng.integers(0, 20)}" for _ in range(30))
             for _ in range(50)]
    corpus = tmp_path / "c.txt"
    corpus.write_text("\n".join(lines))
    dictionary = Dictionary.build(str(corpus), min_count=1)
    cfg = Word2VecConfig(vocab_size=dictionary.vocab_size, embedding_size=16,
                         window=3, negative=3, batch_size=64,
                         neg_pool_size=4096)
    w_in = mv.create_table("matrix", dictionary.vocab_size, 16,
                           init_value="random")
    w_out = mv.create_table("matrix", dictionary.vocab_size, 16)
    model = Word2Vec(cfg, w_in, w_out,
                     counts=np.asarray(dictionary.counts, np.float64))
    model.total_words = 10 ** 6
    ids, sent_ids = encode_corpus(str(corpus), dictionary)
    discard = subsample_probs(np.asarray(dictionary.counts, np.float64),
                              1e-3).astype(np.float32)
    model.load_corpus_chunk(ids, sent_ids, discard)
    loss, count = model.train_device_steps(4)
    assert np.isfinite(float(loss))
    assert float(count) > 0


def test_dictionary_save_load_roundtrip(tmp_path):
    from multiverso_tpu.apps.wordembedding import Dictionary

    corpus = tmp_path / "c.txt"
    corpus.write_text("a a a b b c\n" * 10)
    d = Dictionary.build(str(corpus), min_count=1)
    vocab_file = tmp_path / "vocab.txt"
    d.save(str(vocab_file))
    loaded = Dictionary.load(str(vocab_file), min_count=1)
    assert loaded.words == d.words
    assert loaded.counts == d.counts
    assert loaded.word2id == d.word2id
    # min_count filter applies at load (a=30, b=20, c=10)
    filtered = Dictionary.load(str(vocab_file), min_count=25)
    assert filtered.words == ["a"]


def test_row_mean_updates_stabilize_large_batch(mv_session):
    """Summed scatter diverges when batch >> vocab; row-mean must not.

    (The batched-sum failure mode: hot rows receive thousands of summed
    pair grads at full lr — the reference never hits it because it applies
    pairs sequentially.)
    """
    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    rng = np.random.default_rng(0)
    vocab, dim, B = 16, 8, 2048   # batch 128x vocab: heavy collisions

    def run(row_mean):
        cfg = Word2VecConfig(vocab_size=vocab, embedding_size=dim,
                             negative=3, batch_size=B,
                             row_mean_updates=row_mean, seed=1)
        w_in = mv.create_table("matrix", vocab, dim, init_value="random")
        w_out = mv.create_table("matrix", vocab, dim)
        model = Word2Vec(cfg, w_in, w_out, counts=np.ones(vocab))
        loss = None
        for _ in range(15):
            loss = model.train_batch(
                rng.integers(0, vocab, B).astype(np.int32),
                rng.integers(0, vocab, B).astype(np.int32))
        return float(loss)

    stable = run(row_mean=True)
    assert np.isfinite(stable) and stable < 10.0
    unstable = run(row_mean=False)
    assert not np.isfinite(unstable) or unstable > stable


def test_shared_negatives_converges(mv_session):
    """Group-shared negatives trains the same structure as exact draws."""
    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    rng = np.random.default_rng(2)
    vocab, dim, B = 32, 16, 256
    cfg = Word2VecConfig(vocab_size=vocab, embedding_size=dim, negative=4,
                         batch_size=B, shared_negatives=8,
                         row_mean_updates=True, init_lr=0.1)
    w_in = mv.create_table("matrix", vocab, dim, init_value="random")
    w_out = mv.create_table("matrix", vocab, dim)
    model = Word2Vec(cfg, w_in, w_out, counts=np.ones(vocab))
    # pairs always (i, i+1 mod half): structure the model can learn
    centers = (np.arange(B) % (vocab // 2)).astype(np.int32)
    contexts = ((centers + 1) % (vocab // 2)).astype(np.int32)
    first = float(model.train_batch(centers, contexts))
    for _ in range(60):
        last = float(model.train_batch(centers, contexts))
    assert np.isfinite(last)
    assert last < first * 0.8, (first, last)


def test_shared_negatives_batch_divisibility(mv_session):
    import multiverso_tpu as mv
    from multiverso_tpu.log import FatalError
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    cfg = Word2VecConfig(vocab_size=8, embedding_size=4, negative=2,
                         batch_size=10, shared_negatives=4)
    w_in = mv.create_table("matrix", 8, 4)
    w_out = mv.create_table("matrix", 8, 4)
    with pytest.raises(FatalError):
        Word2Vec(cfg, w_in, w_out, counts=np.ones(8))


def test_dictionary_extras(tmp_path):
    """Reference dictionary extras (dictionary.h:42-62): whitelist,
    infrequent-word merging, tri-letter loading."""
    from multiverso_tpu.apps.wordembedding import (_INFREQUENT_BUCKET,
                                                   Dictionary)

    d = Dictionary(min_count=1)
    for word, count in [("the", 100), ("cat", 3), ("sat", 2), ("rare", 1),
                        ("keepme", 1)]:
        d.insert(word, count)
    d.set_whitelist(["keepme"])
    d.merge_infrequent_words(3)
    # 'the' and 'cat' survive; 'sat'+'rare' merge into the bucket;
    # whitelisted 'keepme' survives despite low freq
    assert d.word2id["the"] != d.word2id["cat"]
    assert d.word2id["sat"] == d.word2id["rare"] == d.word2id[
        _INFREQUENT_BUCKET]
    assert d.counts[d.word2id[_INFREQUENT_BUCKET]] == 3
    assert "keepme" in d.word2id
    assert d.encode(["the", "sat", "rare"])[1] == d.encode(["rare"])[0]

    d2 = Dictionary(min_count=1)
    vocab_file = tmp_path / "wc.txt"
    vocab_file.write_text("cat 5\nrare 1\n")
    d2.load_tri_letter(str(vocab_file), min_count=2, letter_count=3)
    # '#cat#' -> trigrams #ca, cat, at#; 'rare' filtered by min_count
    assert set(d2.words) == {"#ca", "cat", "at#"}
    assert all(c == 5 for c in d2.counts)

    d3 = Dictionary(min_count=1)
    d3.load_tri_letter(str(vocab_file), min_count=1, letter_count=3,
                       combine=True)
    assert "rare" in d3.word2id and "#ra" in d3.word2id

    d4 = Dictionary(min_count=1)
    for word, count in [("a", 5), ("b", 1)]:
        d4.insert(word, count)
    d4.remove_words_less_than(2)
    assert d4.words == ["a"]


def test_device_corpus_chunk_rotation(mv_session, tmp_path, monkeypatch):
    """Corpora over the HBM budget rotate through equal-length device
    chunks (north-star 1B-token scale); equal lengths keep ONE compiled
    fused program; training stays finite and counts words correctly."""
    import numpy as np

    from multiverso_tpu.apps import wordembedding as we

    rng = np.random.default_rng(0)
    corpus = tmp_path / "big.txt"
    with open(corpus, "w") as f:
        f.write(" ".join(f"w{i}" for i in range(20)) + "\n")
        for _ in range(400):
            f.write(" ".join(f"w{i}" for i in rng.integers(0, 20, 16)) + "\n")

    # shrink the budget so this corpus (~6.8k tokens) needs 3 chunks
    monkeypatch.setattr(we, "_DEVICE_CORPUS_MAX_TOKENS", 2500)
    cfg = we.Word2VecConfig(embedding_size=8, negative=2, batch_size=256,
                            steps_per_call=2)
    res = we.train(str(corpus), None, cfg, epochs=2, min_count=1,
                   log_every=0, device_corpus=True, steps_per_call=2)
    assert np.isfinite(res.final_loss)
    assert res.pairs_trained > 0


def test_row_mean_static_matches_realized(mv_session):
    """Static expected-count scaling trains like realized-count scaling
    (hot rows: expectation ~= realization) and stays finite."""
    import numpy as np

    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig

    mv = mv_session
    rng = np.random.default_rng(0)
    vocab, dim, B = 500, 16, 8192
    probs = 1.0 / np.arange(1, vocab + 1)
    probs /= probs.sum()
    counts = np.maximum(probs * 1e6, 5)
    ids = rng.choice(vocab, size=100_000, p=probs).astype(np.int32)
    sents = (np.arange(ids.size) // 200).astype(np.int32)

    def run(static):
        cfg = Word2VecConfig(vocab_size=vocab, embedding_size=dim,
                             negative=3, batch_size=B, seed=2,
                             oversample=2.0,
                             row_mean_updates=True, row_mean_static=static)
        w_in = mv.create_table("matrix", vocab, dim, init_value="random",
                               seed=5)
        w_out = mv.create_table("matrix", vocab, dim)
        m = Word2Vec(cfg, w_in, w_out, counts=counts)
        m.load_corpus_chunk(ids, sents, np.zeros(vocab, np.float32))
        losses = []
        for _ in range(6):
            loss, _ = m.train_device_steps(2)
            losses.append(float(loss))
        return losses

    real = run(static=False)
    stat = run(static=True)
    assert np.isfinite(stat).all() and np.isfinite(real).all()
    assert stat[-1] < stat[0]                  # both descend
    assert abs(stat[-1] - real[-1]) < 0.3, (stat[-1], real[-1])


def test_dp_dispatch_exchange_exact_vs_sequential_oracle(tmp_path):
    """dp_sync="dispatch" contract: the multi-batch dispatch on a dp-worker
    mesh equals w0 + sum over workers of that worker's SEQUENTIAL local
    deltas (each worker sees its own updates immediately, peers' at the
    dispatch boundary). HS mode keeps the step RNG-free, so the per-worker
    oracle is bit-reproducible; the only tolerance is psum summation order.
    """
    import jax.numpy as jnp
    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import (HuffmanCodes, Word2Vec,
                                                Word2VecConfig, build_huffman)
    from multiverso_tpu.runtime import Session

    vocab, dim, dp, S, B = 32, 8, 4, 3, 16
    counts = np.arange(1, vocab + 1, dtype=np.float64)
    huff = build_huffman(counts)
    rng = np.random.default_rng(11)
    centers = rng.integers(0, vocab, (S, B)).astype(np.int32)
    contexts = rng.integers(0, vocab, (S, B)).astype(np.int32)
    mask = np.ones((S, B), np.float32)

    def train(mesh_shape, dp_sync, c, t, m):
        Session._instance = None
        mv.set_flag("mesh_shape", mesh_shape)
        mv.init(["dpx", "-log_level=error"])
        try:
            cfg = Word2VecConfig(vocab_size=vocab, embedding_size=dim,
                                 negative=0, hs=True, batch_size=c.shape[1],
                                 init_lr=0.1, seed=5, dp_sync=dp_sync)
            w_in = mv.create_table("matrix", vocab, dim)
            w_out = mv.create_table("matrix", vocab, dim)
            w_in.add_rows(np.arange(vocab, dtype=np.int32),
                          rng0.standard_normal((vocab, dim)).astype(np.float32))
            model = Word2Vec(cfg, w_in, w_out, counts=counts, huffman=huff)
            model.train_batches(c, t, m)
            return np.asarray(w_in.get()), np.asarray(w_out.get())
        finally:
            mv.shutdown()
            mv.set_flag("mesh_shape", "")
            Session._instance = None

    # deterministic shared init for every run
    rng0 = np.random.default_rng(99)
    got_in, got_out = train(f"{dp},1", "dispatch", centers, contexts, mask)

    # oracle: each worker trains its batch COLUMNS shard sequentially on a
    # 1-worker mesh; deltas sum onto the shared init
    rng0 = np.random.default_rng(99)
    w0_in = w0_out = None
    tot_in = tot_out = 0.0
    Bl = B // dp
    for w in range(dp):
        rng0 = np.random.default_rng(99)
        sl = slice(w * Bl, (w + 1) * Bl)
        fin, fout = train("1,1", "dispatch",
                          centers[:, sl], contexts[:, sl], mask[:, sl])
        if w0_in is None:
            rng0 = np.random.default_rng(99)
            w0_in = rng0.standard_normal((vocab, dim)).astype(np.float32)
            w0_out = np.zeros((vocab, dim), np.float32)
        tot_in = tot_in + (fin - w0_in)
        tot_out = tot_out + (fout - w0_out)

    np.testing.assert_allclose(got_in, w0_in + tot_in, rtol=0, atol=2e-5)
    np.testing.assert_allclose(got_out, w0_out + tot_out, rtol=0, atol=2e-5)


def test_dp_dispatch_keyed_exchange_matches_dense(tmp_path):
    """dp_exchange="keyed" contract: the dirty-row-union exchange equals
    the dense exchange exactly — both when the union fits the cap (keyed
    wire path) and when it overflows (in-dispatch dense fallback via the
    replicated-predicate cond). HS mode keeps the step RNG-free."""
    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import (Word2Vec, Word2VecConfig,
                                                build_huffman)
    from multiverso_tpu.runtime import Session

    vocab, dim, dp, S, B = 32, 8, 4, 3, 16
    counts = np.arange(1, vocab + 1, dtype=np.float64)
    huff = build_huffman(counts)
    rng = np.random.default_rng(11)
    centers = rng.integers(0, vocab, (S, B)).astype(np.int32)
    contexts = rng.integers(0, vocab, (S, B)).astype(np.int32)
    mask = np.ones((S, B), np.float32)

    def train(dp_exchange, cap):
        global rng0
        Session._instance = None
        mv.set_flag("mesh_shape", f"{dp},1")
        mv.init(["dpk", "-log_level=error"])
        try:
            cfg = Word2VecConfig(vocab_size=vocab, embedding_size=dim,
                                 negative=0, hs=True, batch_size=B,
                                 init_lr=0.1, seed=5, dp_sync="dispatch",
                                 dp_exchange=dp_exchange, dp_keyed_cap=cap)
            w_in = mv.create_table("matrix", vocab, dim)
            w_out = mv.create_table("matrix", vocab, dim)
            rng0 = np.random.default_rng(99)
            w_in.add_rows(np.arange(vocab, dtype=np.int32),
                          rng0.standard_normal((vocab, dim)
                                               ).astype(np.float32))
            model = Word2Vec(cfg, w_in, w_out, counts=counts, huffman=huff)
            model.train_batches(centers, contexts, mask)
            return np.asarray(w_in.get()), np.asarray(w_out.get())
        finally:
            mv.shutdown()
            mv.set_flag("mesh_shape", "")
            Session._instance = None

    dense_in, dense_out = train("dense", 0)
    # cap >= vocab: the union always fits -> pure keyed wire path
    keyed_in, keyed_out = train("keyed", vocab)
    np.testing.assert_allclose(keyed_in, dense_in, rtol=0, atol=1e-6)
    np.testing.assert_allclose(keyed_out, dense_out, rtol=0, atol=1e-6)
    # cap=8 rows << touched union -> every dispatch takes the overflow
    # fallback; still exact
    over_in, over_out = train("keyed", 8)
    np.testing.assert_allclose(over_in, dense_in, rtol=0, atol=1e-6)
    np.testing.assert_allclose(over_out, dense_out, rtol=0, atol=1e-6)


def test_dp_corpus_stream_advances_per_worker_arc(tmp_path):
    """The stream cursor is a PER-WORKER arc position under
    dp_sync="dispatch": one dispatch consumes n_steps * (M // dp)
    positions of each worker's arc, not n_steps * M — advancing by the
    global M would skip/alias corpus coverage (r4 review finding)."""
    import multiverso_tpu as mv
    from multiverso_tpu.models.word2vec import Word2Vec, Word2VecConfig
    from multiverso_tpu.runtime import Session

    Session._instance = None
    mv.set_flag("mesh_shape", "2,4")
    mv.init(["dparc", "-log_level=error"])
    try:
        vocab, dim = 64, 8
        cfg = Word2VecConfig(vocab_size=vocab, embedding_size=dim,
                             negative=2, batch_size=32, window=2,
                             oversample=2.0, seed=5)
        w_in = mv.create_table("matrix", vocab, dim, init_value="random")
        w_out = mv.create_table("matrix", vocab, dim)
        counts = np.ones(vocab, np.float64)
        model = Word2Vec(cfg, w_in, w_out, counts=counts)
        assert model._dp_local() == 2
        n = 4096
        rng = np.random.default_rng(3)
        ids = rng.integers(0, vocab, n).astype(np.int32)
        model.load_corpus_chunk(ids, np.zeros(n, np.int32))
        M = model._candidate_batch(n)
        assert M % 2 == 0
        loss, count = model.train_device_steps(3)
        assert np.isfinite(float(loss))
        assert model._stream_pos == 3 * (M // 2)
    finally:
        mv.shutdown()
        mv.set_flag("mesh_shape", "")
        Session._instance = None
