"""tools/bench_compare.py: the standing serving-perf regression gate.

Pure-host unit tests (no jax, no session): direction rules, relative
thresholds, per-metric overrides, the min-ms latency-noise floor, and
the CLI's exit-code contract over real files.
"""

import json

from tools.bench_compare import (compare, flatten_workloads, main,
                                 metric_direction)


def _line(**workloads):
    return {"bench": "serving", "workloads": workloads,
            "dashboard": {"SERVE_LAT[x]": {"p50_ms": 1.0}}}


BASE = _line(
    w2v={"qps": 1000.0, "p50_ms": 4.0, "p99_ms": 20.0, "shed_rate": 0.01,
         "completed": 500, "speedup_batched": 5.0},
    lm_chunked_prefill={"itl_p99_speedup": 3.0, "tokens_per_s_ratio": 1.0,
                        "chunked": {"itl_p99_ms": 10.0,
                                    "tokens_per_s": 400.0}},
)


def test_metric_direction_rules():
    assert metric_direction("qps") == 1
    assert metric_direction("tokens_per_s") == 1
    assert metric_direction("speedup_engine") == 1
    assert metric_direction("itl_p99_speedup") == 1
    assert metric_direction("tokens_per_s_ratio") == 1
    assert metric_direction("p99_ms") == -1
    assert metric_direction("shed_rate") == -1
    # paged-KV capacity metrics: sequences held at a fixed KV-bytes
    # budget regress DOWN, bytes per held sequence regress UP
    assert metric_direction("capacity_seqs") == 1
    assert metric_direction("kv_bytes_per_seq") == -1
    # sharded-decode metrics: per-device KV bytes regress UP (tensor
    # parallelism exists to shrink them); step retraces ride the
    # zero-baseline rule — one compiled fused step per engine config
    assert metric_direction("kv_bytes_per_device") == -1
    assert metric_direction("decode_step_retraces") == -1
    # speculative decoding (lm_spec_decode A/B): amortization gates,
    # trace-dependent acceptance archives _info
    assert metric_direction("accepted_per_step") == 1
    assert metric_direction("speedup_spec") == 1
    assert metric_direction("acceptance_rate_info") == 0
    # fleet plane (obs_plane A/B): dropped reports ride the
    # zero-baseline rule — the plane's reports are bounded by design,
    # so a drop on an idle loopback collector is a bug; its tok/s
    # columns are noise-floor _info
    assert metric_direction("obs_dropped_reports") == -1
    assert metric_direction("tokens_per_s_obs_on_info") == 0
    assert metric_direction("obs_reports_info") == 0
    # the _info suffix overrides every pattern rule: measured-but-noisy
    # columns ride the archive without flapping the gate
    assert metric_direction("tokens_per_s_info") == 0
    assert metric_direction("itl_p99_ms_info") == 0
    assert metric_direction("shed_rate_info") == 0
    assert metric_direction("tokens_per_s_speedup_info") == 0
    # serving-fleet recovery invariants (lm_fleet_chaos A/B)
    assert metric_direction("requests_lost") == -1
    assert metric_direction("output_mismatches") == -1
    assert metric_direction("recovery_time_s") == -1
    # durable online learning (lm_trainer_chaos A/B): acknowledged
    # updates lost and unexpected fence rejections are zero-baseline
    # hard gates; the restart wall clock regresses UP; WAL replay
    # volume and the staleness peak archive as _info
    assert metric_direction("updates_lost") == -1
    assert metric_direction("epoch_fence_rejections_unexpected") == -1
    assert metric_direction("trainer_recovery_time_s") == -1
    assert metric_direction("wal_replay_records_info") == 0
    assert metric_direction("staleness_peak_s_info") == 0
    # overload-graceful serving (lm_overload A/B): bit-identical
    # preempted outputs and zero starvation are zero-baseline hard
    # gates, deadline drops regress UP; preemption churn and the
    # per-class latencies archive as _info
    assert metric_direction("preempt_output_mismatches") == -1
    assert metric_direction("starved_requests") == -1
    assert metric_direction("deadline_drops") == -1
    assert metric_direction("preemptions_info") == 0
    assert metric_direction("lat_p99_class0_ms_info") == 0
    # disaggregated prefill/decode (lm_disagg A/B): raw K/V bytes over
    # the wire regress UP (the transfer plane exists to move less of
    # them), the dedup fraction regresses DOWN, the repeat phase is a
    # zero-baseline gate via the kv_bytes_moved suffix, the decode-ITL
    # ratio rides the higher-better ratio rule; TTFT/tok-per-leg _info
    assert metric_direction("kv_bytes_moved") == -1
    assert metric_direction("dedup_repeat_kv_bytes_moved") == -1
    assert metric_direction("xfer_dedup_hit_rate") == 1
    assert metric_direction("itl_p99_ratio") == 1
    assert metric_direction("ttft_p99_ms_disagg_info") == 0
    assert metric_direction("xfer_blocks_info") == 0
    # tenant accounting (accounting A/B): the conservation residual is
    # a zero-baseline hard gate — any nonzero drift means tokens were
    # consumed without attribution; the per-tenant cost columns and the
    # ledger overhead ride as _info
    # long-context serving (lm_long_context A/B): document TTFT and the
    # short interactive requests' tail ITL both regress UP on the
    # seqpar leg; the off leg's twins and the cross-leg ratios are
    # noise-floor _info
    assert metric_direction("ttft_long_p50") == -1
    assert metric_direction("itl_short_p99") == -1
    assert metric_direction("ttft_long_p50_info") == 0
    assert metric_direction("itl_short_p99_info") == 0
    assert metric_direction("ttft_long_speedup_info") == 0
    assert metric_direction("itl_short_p99_ratio_info") == 0
    assert metric_direction("seqpar_chunks_info") == 0
    assert metric_direction("seqpar_traces") == 0   # informational count
    assert metric_direction("accounting_drift") == -1
    assert metric_direction("cost_acme_info") == 0
    assert metric_direction("ledger_overhead_frac_info") == 0
    assert metric_direction("tenants_live_info") == 0
    assert metric_direction("completed") == 0       # informational
    assert metric_direction("jit_traces") == 0
    assert metric_direction("step_traces") == 0
    assert metric_direction("kv_pool_blocks") == 0
    assert metric_direction("block_allocs") == 0


def test_updates_lost_zero_baseline_gate():
    """updates_lost 0 -> 1 must regress even though the baseline is 0
    (the zero-baseline rule): an acknowledged update lost to a trainer
    kill is an invariant break, not noise."""
    base = _line(lm_trainer_chaos={"updates_lost": 0.0,
                                   "epoch_fence_rejections_unexpected":
                                       0.0})
    good = _line(lm_trainer_chaos={"updates_lost": 0.0,
                                   "epoch_fence_rejections_unexpected":
                                       0.0})
    bad = _line(lm_trainer_chaos={"updates_lost": 1.0,
                                  "epoch_fence_rejections_unexpected":
                                      2.0})
    regs, _ = compare(base, good)
    assert regs == []
    regs, _ = compare(base, bad)
    assert {r["metric"] for r in regs} == {
        "lm_trainer_chaos.updates_lost",
        "lm_trainer_chaos.epoch_fence_rejections_unexpected"}


def test_accounting_drift_zero_baseline_gate():
    """accounting_drift 0 -> nonzero must regress even though the
    baseline is 0 (the zero-baseline rule): a token consumed without a
    tenant attribution breaks the conservation identity — an invariant
    break, not noise — while the per-tenant cost columns archive _info."""
    clean = {"accounting_drift": 0.0, "requests": 48.0,
             "cost_acme_info": 120.0, "tenants_live_info": 3.0}
    base = _line(accounting=clean)
    good = _line(accounting=json.loads(json.dumps(clean)))
    regs, _ = compare(base, good)
    assert regs == []
    bad = _line(accounting={"accounting_drift": 7.0, "requests": 48.0,
                            "cost_acme_info": 9000.0,
                            "tenants_live_info": 3.0})
    regs, _ = compare(base, bad)
    assert {r["metric"] for r in regs} == {"accounting.accounting_drift"}


def test_preempt_invariants_zero_baseline_gate():
    """preempt_output_mismatches / starved_requests / deadline_drops
    0 -> nonzero must regress though the baseline is 0 (the zero-
    baseline rule): a preempted generation diverging from its oracle,
    a starved request, or a blown deadline on the met-by-design trace
    is an invariant break, not noise — while the churn counters and
    per-class p99s ride as _info."""
    clean = {"preempt_output_mismatches": 0.0,
             "preempt": {"starved_requests": 0.0, "deadline_drops": 0.0,
                         "capacity_seqs": 11.0, "preemptions_info": 9.0,
                         "lat_p99_class2_ms_info": 40.0}}
    base = _line(lm_overload=clean)
    good = _line(lm_overload=json.loads(json.dumps(clean)))
    regs, _ = compare(base, good)
    assert regs == []
    bad = _line(lm_overload={
        "preempt_output_mismatches": 1.0,
        "preempt": {"starved_requests": 2.0, "deadline_drops": 3.0,
                    "capacity_seqs": 11.0, "preemptions_info": 900.0,
                    "lat_p99_class2_ms_info": 4000.0}})
    regs, _ = compare(base, bad)
    assert {r["metric"] for r in regs} == {
        "lm_overload.preempt_output_mismatches",
        "lm_overload.preempt.starved_requests",
        "lm_overload.preempt.deadline_drops"}


def test_watchdog_trips_hard_gate():
    """Any watchdog trip on a clean-baseline bench regresses: the
    zero-baseline rule makes the trip count itself the worseness, so
    a single trip (1.0) blows every sane tolerance — while the
    observability A/B's _info tok/s columns never gate at all."""
    assert metric_direction("watchdog_trips") == -1
    base = _line(observability={"watchdog_trips": 0.0,
                                "tokens_per_s_traced_info": 100.0,
                                "trace_overhead_frac_info": 0.01})
    bad = _line(observability={"watchdog_trips": 1.0,
                               "tokens_per_s_traced_info": 50.0,
                               "trace_overhead_frac_info": 0.4})
    regressions, _ = compare(base, bad)
    assert [r["metric"] for r in regressions] == [
        "observability.watchdog_trips"]
    assert compare(base, base)[0] == []           # clean stays clean


def test_sharded_decode_metrics_gate():
    """The lm_sharded_decode surface: a retrace of the fused step on a
    zero-retrace baseline regresses hard (the PR 2 partitioner drag
    must stay out of the hot loop), and per-device KV bytes growing
    past tolerance regresses like any capacity metric."""
    base = _line(lm_sharded_decode={"sharded": {
        "decode_step_retraces": 0.0, "kv_bytes_per_device": 25600.0,
        "tokens_per_s_info": 900.0}})
    bad = _line(lm_sharded_decode={"sharded": {
        "decode_step_retraces": 3.0, "kv_bytes_per_device": 51200.0,
        "tokens_per_s_info": 400.0}})
    names = {r["metric"] for r in compare(base, bad)[0]}
    assert names == {"lm_sharded_decode.sharded.decode_step_retraces",
                     "lm_sharded_decode.sharded.kv_bytes_per_device"}
    assert compare(base, base)[0] == []


def test_capacity_metrics_gate_both_directions():
    """The lm_paged_kv capacity surface rides the standing gate: fewer
    concurrent sequences (or more KV bytes per sequence) at the same
    budget is a regression, improvements never flag."""
    base = _line(lm_paged_kv={"paged": {"capacity_seqs": 12.0,
                                        "kv_bytes_per_seq": 40000.0}})
    worse = _line(lm_paged_kv={"paged": {"capacity_seqs": 6.0,
                                         "kv_bytes_per_seq": 80000.0}})
    names = {r["metric"] for r in compare(base, worse)[0]}
    assert names == {"lm_paged_kv.paged.capacity_seqs",
                     "lm_paged_kv.paged.kv_bytes_per_seq"}
    better = _line(lm_paged_kv={"paged": {"capacity_seqs": 24.0,
                                          "kv_bytes_per_seq": 20000.0}})
    assert compare(base, better)[0] == []


def test_flatten_skips_dashboard_archive():
    flat = flatten_workloads(BASE)
    assert "w2v.qps" in flat
    assert "lm_chunked_prefill.chunked.itl_p99_ms" in flat
    assert not any(k.startswith("SERVE_LAT") for k in flat)


def test_no_regression_within_tolerance():
    new = json.loads(json.dumps(BASE))
    new["workloads"]["w2v"]["qps"] = 900.0           # -10% < 25% tol
    new["workloads"]["w2v"]["p99_ms"] = 23.0         # +15% < 25% tol
    regressions, rows = compare(BASE, new)
    assert regressions == []
    assert any(r["metric"] == "w2v.qps" for r in rows)


def test_detects_throughput_and_latency_regressions():
    new = json.loads(json.dumps(BASE))
    new["workloads"]["w2v"]["qps"] = 500.0                      # -50%
    new["workloads"]["lm_chunked_prefill"]["chunked"]["itl_p99_ms"] = 40.0
    regressions, _ = compare(BASE, new)
    names = {r["metric"] for r in regressions}
    assert names == {"w2v.qps", "lm_chunked_prefill.chunked.itl_p99_ms"}
    # worst first
    assert regressions[0]["worse_frac"] >= regressions[-1]["worse_frac"]


def test_per_metric_override_and_min_ms_floor():
    new = json.loads(json.dumps(BASE))
    new["workloads"]["w2v"]["p50_ms"] = 5.2          # +30%
    # default tolerance flags it, a 50% override clears it
    assert any(r["metric"] == "w2v.p50_ms" for r in compare(BASE, new)[0])
    assert compare(BASE, new, overrides={"p50_ms": 0.5})[0] == []
    # most-specific override wins: a tight full-path gate beats a loose
    # leaf gate for that metric (and only that metric)
    tight = compare(BASE, new,
                    overrides={"p50_ms": 0.5, "w2v.p50_ms": 0.1})[0]
    assert [r["metric"] for r in tight] == ["w2v.p50_ms"]
    # sub-min-ms latencies never gate (scheduler noise)
    tiny_base = _line(w2v={"p50_ms": 0.2})
    tiny_new = _line(w2v={"p50_ms": 0.9})            # +350% but < 1 ms
    assert compare(tiny_base, tiny_new)[0] == []


def test_zero_baseline_lower_is_better_still_gates():
    """A healthy baseline sheds nothing (shed_rate 0.0); a candidate that
    starts shedding must NOT slip through the relative-threshold math —
    the new value stands in for the worseness when the base is zero."""
    base = _line(w2v={"shed_rate": 0.0, "qps": 0.0})
    bad = _line(w2v={"shed_rate": 0.4, "qps": 100.0})
    regressions, _ = compare(base, bad)
    assert [r["metric"] for r in regressions] == ["w2v.shed_rate"]
    # a sub-tolerance shed rate still passes; a zero->zero is clean; and
    # the zero-qps baseline (broken base run) never gates
    ok = _line(w2v={"shed_rate": 0.1, "qps": 100.0})
    assert compare(base, ok)[0] == []
    assert compare(base, base)[0] == []


def test_cli_exit_codes(tmp_path):
    base_f = tmp_path / "base.json"
    new_f = tmp_path / "new.json"
    base_f.write_text(json.dumps(BASE) + "\n")

    new = json.loads(json.dumps(BASE))
    new["workloads"]["w2v"]["qps"] = 500.0
    new_f.write_text("some log line\n" + json.dumps(new) + "\n")

    assert main([str(base_f), str(base_f)]) == 0             # self-diff
    assert main([str(base_f), str(new_f)]) == 1              # regression
    assert main([str(base_f), str(new_f),
                 "--metric", "qps=0.6"]) == 0                # overridden
    bad = tmp_path / "bad.json"
    bad.write_text("not json at all\n")
    assert main([str(base_f), str(bad)]) == 2                # malformed


def test_dropped_gated_metrics_surfaced():
    """A gated metric present in the baseline but absent from the
    candidate (e.g. the sharded A/B skipping on a 1-device run) is
    reported as lost coverage — the intersection-only compare must not
    make a disappearing gate invisible."""
    from tools.bench_compare import dropped_gated_metrics

    base = _line(lm_sharded_decode={"sharded": {
        "decode_step_retraces": 0.0, "kv_bytes_per_device": 25600.0,
        "pin_copies_info": 1.0}})
    new = _line(lm_sharded_decode={"skipped": "needs >= 2 devices"})
    dropped = dropped_gated_metrics(base, new)
    assert dropped == ["lm_sharded_decode.sharded.decode_step_retraces",
                       "lm_sharded_decode.sharded.kv_bytes_per_device"]
    assert dropped_gated_metrics(base, base) == []


def test_fleet_chaos_metrics_gate():
    """The serving-fleet recovery rows (lm_fleet_chaos A/B):
    requests_lost and fleet_redispatch_output_mismatches ride the
    zero-baseline hard gate (a healthy fleet loses nothing and replays
    bit-identically — ANY loss/mismatch on the candidate is a bug),
    recovery_time_s regresses UP, and the fault-free aggregate
    fleet_tokens_per_s regresses DOWN."""
    assert metric_direction("requests_lost") == -1
    assert metric_direction("fleet_redispatch_output_mismatches") == -1
    assert metric_direction("recovery_time_s") == -1
    assert metric_direction("fleet_tokens_per_s") == 1
    assert metric_direction("fleet_tokens_per_s_chaos_info") == 0
    assert metric_direction("redispatched_info") == 0
    base = _line(lm_fleet_chaos={
        "requests_lost": 0, "fleet_redispatch_output_mismatches": 0,
        "recovery_time_s": 0.3, "fleet_tokens_per_s": 1200.0})
    lossy = _line(lm_fleet_chaos={
        "requests_lost": 2, "fleet_redispatch_output_mismatches": 0,
        "recovery_time_s": 0.3, "fleet_tokens_per_s": 1200.0})
    regressions, _ = compare(base, lossy)
    assert [r["metric"] for r in regressions] == [
        "lm_fleet_chaos.requests_lost"]
    mismatched = _line(lm_fleet_chaos={
        "requests_lost": 0, "fleet_redispatch_output_mismatches": 1,
        "recovery_time_s": 0.3, "fleet_tokens_per_s": 1200.0})
    regressions, _ = compare(base, mismatched)
    assert [r["metric"] for r in regressions] == [
        "lm_fleet_chaos.fleet_redispatch_output_mismatches"]
    slow_recovery = _line(lm_fleet_chaos={
        "requests_lost": 0, "fleet_redispatch_output_mismatches": 0,
        "recovery_time_s": 0.9, "fleet_tokens_per_s": 1200.0})
    regressions, _ = compare(base, slow_recovery)
    assert [r["metric"] for r in regressions] == [
        "lm_fleet_chaos.recovery_time_s"]
    slower_fleet = _line(lm_fleet_chaos={
        "requests_lost": 0, "fleet_redispatch_output_mismatches": 0,
        "recovery_time_s": 0.3, "fleet_tokens_per_s": 500.0})
    regressions, _ = compare(base, slower_fleet)
    assert [r["metric"] for r in regressions] == [
        "lm_fleet_chaos.fleet_tokens_per_s"]
    assert compare(base, base)[0] == []
