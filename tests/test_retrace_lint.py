"""retrace_lint: every rule fires on its seeded fixture, none on the
sanctioned-idiom file, plus targeted regressions for linter bugs fixed
while triaging the real tree (compound-statement double-visit, handle
rebinding, `x is None` dispatch).
"""

import os
import textwrap

from multiverso_tpu.analysis import retrace_lint
from multiverso_tpu.analysis.common import parse_module

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_fixture(name):
    mod = parse_module(os.path.join(FIXTURES, name), root=REPO_ROOT)
    assert mod is not None, f"fixture {name} failed to parse"
    return retrace_lint.lint_module(mod)


def _lint_snippet(src):
    import ast

    from multiverso_tpu.analysis.common import Module

    tree = ast.parse(textwrap.dedent(src))
    mod = Module(path="snippet.py", name="snippet", tree=tree,
                 source=src)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            mod.classes[node.name] = node
        elif isinstance(node, ast.FunctionDef):
            mod.functions[node.name] = node
    return retrace_lint.lint_module(mod)


# -- true positives: the seeded corpus ----------------------------------------

EXPECTED_TP = {
    ("RT101", "rt101_jit_in_loop"),
    ("RT101", "rt101_jit_in_comprehension"),
    ("RT102", "rt102_int_coerce"),
    ("RT102", "rt102_item"),
    ("RT102", "rt102_numpy"),
    ("RT103", "rt103_if"),
    ("RT103", "rt103_while"),
    ("RT103", "rt103_assert"),
    ("RT103", "rt103_for"),
    ("RT103", "rt103_taint_propagates.helper"),   # intra-module taint
    ("RT104", "rt104_mutable_capture"),
    ("RT104", "rt104_unhashable_static"),
    ("RT105", "rt105_donated_reuse"),
    ("RT106", "Rt106Engine._iterate"),
    ("RT106", "Rt106ShardedEngine._iterate"),    # builder on the hot path
    ("RT106", "Rt106SpecEngine._iterate"),       # verify-step builder
    ("RT106", "Rt106XferEngine._iterate"),       # kv-transfer fetch builder
    ("RT106", "Rt106QuantEngine._iterate"),      # quant-step builder
    ("RT106", "Rt106CostEngine._iterate"),       # cost-reducer builder
    ("RT106", "Rt106SeqparEngine._iterate"),     # seqpar-chunk builder
}


def test_every_seeded_hazard_detected():
    found = {(f.rule, f.qualname) for f in _lint_fixture("retrace_tp.py")}
    missing = EXPECTED_TP - found
    assert not missing, f"seeded hazards not detected: {sorted(missing)}"


def test_no_rule_without_true_positive_coverage():
    """A rule with zero corpus coverage is a rule that can silently stop
    working — the acceptance criterion, enforced."""
    rules = {f.rule for f in _lint_fixture("retrace_tp.py")}
    assert rules >= {"RT101", "RT102", "RT103", "RT104", "RT105", "RT106"}


def test_no_unexpected_findings_in_tp_fixture():
    """The TP corpus is exact: anything beyond the seeded set is a
    false positive hiding inside the fixture file."""
    found = {(f.rule, f.qualname) for f in _lint_fixture("retrace_tp.py")}
    assert found == EXPECTED_TP, (
        f"unexpected extras: {sorted(found - EXPECTED_TP)}")


# -- false positives: the sanctioned idioms must stay clean -------------------

def test_sanctioned_idioms_lint_clean():
    findings = _lint_fixture("retrace_fp.py")
    assert not findings, "false positives on sanctioned idioms:\n" + \
        "\n".join(f.render() for f in findings)


# -- regressions for linter bugs fixed against the real tree ------------------

def test_donation_inside_with_block_not_double_visited():
    """The compound-statement double-visit bug: a donate call nested in
    a `with` block was scanned twice (once via the With, once via the
    Assign), flagging the donation itself as a read."""
    findings = _lint_snippet("""
        import jax
        _step = jax.jit(lambda x: x, donate_argnums=(0,))

        def train(x, lock):
            with lock:
                x = _step(x)
            return x
    """)
    assert not [f for f in findings if f.rule == "RT105"]


def test_rebound_handle_calls_do_not_donate():
    """A handle name rebound to a non-donating jit (the w2v probe's
    branch-selected kernels) must stop counting as a donation site."""
    findings = _lint_snippet("""
        import jax
        fn = jax.jit(lambda x: x, donate_argnums=(0,))

        def probe(x, fast):
            global fn
            if fast:
                fn = jax.jit(lambda x: x * 2)
            y = fn(x)
            return x + y
    """)
    assert not [f for f in findings if f.rule == "RT105"]


def test_is_none_dispatch_not_a_traced_branch():
    findings = _lint_snippet("""
        import jax

        @jax.jit
        def f(x, mask=None):
            if mask is None:
                return x
            return x * mask
    """)
    assert not [f for f in findings if f.rule == "RT103"]


def test_shape_branching_not_flagged():
    findings = _lint_snippet("""
        import jax

        @jax.jit
        def f(x):
            if x.shape[0] > 4:
                return x[:4]
            return x
    """)
    assert not [f for f in findings if f.rule == "RT103"]


def test_static_argnums_param_exempt_from_taint():
    findings = _lint_snippet("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(0,))
        def f(n, x):
            if n > 4:          # n is static: a host int, branch is fine
                return x * n
            return x
    """)
    assert not [f for f in findings if f.rule == "RT103"]


def test_donated_reuse_across_statements_still_caught():
    """The ordered-statement scan still sees a read in a LATER nested
    block (the hazard the double-visit fix must not lose)."""
    findings = _lint_snippet("""
        import jax
        _step = jax.jit(lambda x: x, donate_argnums=(0,))

        def train(x, flag):
            y = _step(x)
            if flag:
                z = x + 1      # read-after-donate inside a nested block
            return y
    """)
    assert [f for f in findings if f.rule == "RT105"]


def test_rt106_builder_call_on_iteration_path_fires():
    """A module-level function that (transitively) constructs a pjit is
    a program BUILDER: calling it from a method reachable from _loop is
    the per-iteration recompile RT106 exists to catch, even though no
    jax.jit literal appears in the method."""
    findings = _lint_snippet("""
        import jax

        def _make_step(fn, specs):
            return jax.jit(fn, in_shardings=specs, out_shardings=specs)

        def _make_programs(fn, specs):
            return _make_step(fn, specs), _make_step(fn, specs)

        class Engine:
            def _loop(self):
                while True:
                    self._iterate()

            def _iterate(self):
                step, _ = _make_programs(self._fn, self._specs)
                return step(1.0)
    """)
    hits = [f for f in findings if f.rule == "RT106"]
    assert hits and hits[0].qualname == "Engine._iterate", findings


def test_rt106_builder_in_init_and_warmup_is_construction_time():
    """The decode-mesh contract: __init__/warmup building sharded
    programs through a builder (and the iteration path only DISPATCHING
    the handles) is clean — construction-time sites, not hazards."""
    findings = _lint_snippet("""
        import jax

        def _make_step(fn, specs):
            return jax.jit(fn, in_shardings=specs, out_shardings=specs)

        class Engine:
            def __init__(self, fn, specs):
                self._specs = specs
                self._step = _make_step(fn, specs)

            def warmup(self):
                self._step = _make_step(lambda x: x, self._specs)
                return self._step(0.0)

            def _loop(self):
                while True:
                    self._iterate()

            def _iterate(self):
                return self._step(1.0)
    """)
    assert not [f for f in findings if f.rule == "RT106"], findings


def _snippet_module(name, src):
    import ast

    from multiverso_tpu.analysis.common import Module

    tree = ast.parse(textwrap.dedent(src))
    mod = Module(path=name.replace(".", "/") + ".py", name=name,
                 tree=tree, source=src)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            mod.classes[node.name] = node
        elif isinstance(node, ast.FunctionDef):
            mod.functions[node.name] = node
    return mod


def test_rt106_cross_module_builder_links_in_whole_tree_runs():
    """lint_modules links builders ACROSS modules: an engine importing
    make_sharded_decode_programs-style builders from another module —
    even via a function-level relative import, the engine idiom — and
    calling one from the iteration path fires RT106; the same import
    used only in __init__ stays clean."""
    builders = _snippet_module("pkg.models.transformer", """
        import jax

        def make_sharded_decode_programs(fn, specs):
            return jax.jit(fn, in_shardings=specs, out_shardings=specs)
    """)
    hot = _snippet_module("pkg.serving.engine", """
        class Engine:
            def _loop(self):
                while True:
                    self._iterate()

            def _iterate(self):
                from ..models.transformer import make_sharded_decode_programs

                step = make_sharded_decode_programs(self._fn, self._specs)
                return step(1.0)
    """)
    findings = retrace_lint.lint_modules([builders, hot])
    hits = [f for f in findings if f.rule == "RT106"]
    assert hits and hits[0].qualname == "Engine._iterate", findings

    clean = _snippet_module("pkg.serving.engine2", """
        class Engine:
            def __init__(self, fn, specs):
                from ..models.transformer import make_sharded_decode_programs

                self._step = make_sharded_decode_programs(fn, specs)

            def _loop(self):
                while True:
                    self._iterate()

            def _iterate(self):
                return self._step(1.0)
    """)
    findings = retrace_lint.lint_modules([builders, clean])
    assert not [f for f in findings if f.rule == "RT106"], findings


def test_rt106_decorated_jit_handle_dispatch_is_not_a_builder():
    """A @partial(jax.jit, ...)-decorated module function is a PRE-BUILT
    cached handle — calling it from the iteration path is sanctioned
    dispatch, not per-call construction (the decorator must not make
    the function read as a builder)."""
    findings = _lint_snippet("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(0,))
        def scaled(n, x):
            return x * n

        class Engine:
            def _loop(self):
                while True:
                    self._iterate()

            def _iterate(self):
                return scaled(2, self._x)
    """)
    assert not [f for f in findings if f.rule == "RT106"], findings


def test_rt106_jit_factory_decorated_function_is_dispatch():
    """A function decorated by a custom jit-wrapping decorator FACTORY
    (the `@my_jit(...)` shape) is a pre-built handle too: the decorator
    call must not leak into the builder closure map and flag its
    dispatch from the iteration path."""
    findings = _lint_snippet("""
        import jax

        def _make_step(n):
            def deco(fn):
                return jax.jit(fn, static_argnums=(n,))
            return deco

        @_make_step(0)
        def scaled(n, x):
            return x * n

        class Engine:
            def _loop(self):
                while True:
                    self._iterate()

            def _iterate(self):
                return scaled(2, self._x)
    """)
    assert not [f for f in findings if f.rule == "RT106"], findings
