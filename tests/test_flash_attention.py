"""Pallas flash attention vs the O(s^2) oracle (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multiverso_tpu.ops import (flash_attention, flash_attention_partial,
                                merge_partials, reference_attention,
                                ring_attention)
from multiverso_tpu.topology import SEQ_AXIS, make_mesh


def _qkv(seq, heads=2, dim=32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((seq, heads, dim)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [128, 96])  # aligned and ragged
def test_flash_matches_reference(causal, seq):
    q, k, v = _qkv(seq)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=128)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_cross_attention_lengths():
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((40, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((72, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((72, 2, 16)), jnp.float32)
    out = flash_attention(q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    q, k, v = _qkv(64, heads=2, dim=16, seed=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4)


def test_flash_grads_cross_lengths_and_ragged():
    """Backward kernels over unequal, non-power-of-two q/k lengths (pads
    both grid axes; padded rows/keys must contribute zero grad)."""
    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((40, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((72, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((72, 2, 16)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_flash = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_multi_k_block_fwd_bwd(causal):
    """seq > block_k: the GENERAL multi-k-block online-softmax kernels.

    Every other test here uses seq <= 128 with block_k >= 128, which the
    nk==1 single-block specializations answer — leaving the general
    forward (running max/sum rescale across k blocks) and the two-pass
    backward with zero off-hardware coverage (ADVICE r5). seq=256 with
    block_q=64 / block_k=128 forces nk=2, fwd and bwd, causal and not.
    """
    q, k, v = _qkv(256, heads=2, dim=16, seed=6)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=128)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, causal=causal, block_q=64, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4)


def test_best_attention_crossover_dispatch():
    """attention="flash" must never be slower than XLA: below the measured
    crossover it routes to reference_attention, above to the kernel; both
    produce the same numbers."""
    from multiverso_tpu.ops.flash_attention import best_attention

    q, k, v = _qkv(64, heads=2, dim=16, seed=4)
    ref = reference_attention(q, k, v, causal=True)
    # 64 < default threshold -> XLA path (identical)
    np.testing.assert_array_equal(
        np.asarray(best_attention(q, k, v, causal=True)), np.asarray(ref))
    # forced low threshold + explicit interpret -> kernel path (off-TPU
    # the dispatch otherwise always answers XLA; interpret=True is the
    # test override)
    np.testing.assert_allclose(
        np.asarray(best_attention(q, k, v, causal=True, min_flash_seq=1,
                                  interpret=True)),
        np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_partial_merge_equals_full():
    q, k, v = _qkv(64, heads=2, dim=16, seed=2)
    half = 32
    acc_a, m_a, l_a = flash_attention_partial(q, k[:half], v[:half], 0, 0,
                                              causal=True)
    acc_b, m_b, l_b = flash_attention_partial(q, k[half:], v[half:], 0, half,
                                              causal=True)
    m, l, acc = merge_partials(m_a, l_a, acc_a, m_b, l_b, acc_b)
    out = acc / jnp.maximum(l, 1e-20).transpose(1, 0)[:, :, None]
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_pallas_impl(causal):
    n = jax.device_count()
    mesh = make_mesh((n,), axis_names=(SEQ_AXIS,))
    q, k, v = _qkv(16 * n, heads=2, dim=16, seed=4)
    out = ring_attention(q, k, v, mesh, causal=causal, impl="pallas")
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_pallas_grads(causal):
    """The Pallas ring's custom VJP (backward ring rotating (k,v,dk,dv)
    with the partial backward kernels) must match grads of the unsharded
    oracle — long-context SP training at kernel speed."""
    n = jax.device_count()
    mesh = make_mesh((n,), axis_names=(SEQ_AXIS,))
    q, k, v = _qkv(16 * n, heads=2, dim=16, seed=6)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh, causal=causal,
                           impl="pallas") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-3, atol=1e-3)


def test_ring_attention_pallas_grads_under_jit():
    n = jax.device_count()
    mesh = make_mesh((n,), axis_names=(SEQ_AXIS,))
    q, k, v = _qkv(16 * n, heads=2, dim=16, seed=7)
    step = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring_attention(
        q, k, v, mesh, causal=True, impl="pallas") ** 2),
        argnums=(0, 1, 2)))
    gq, gk, gv = step(q, k, v)
    assert all(np.isfinite(np.asarray(g)).all() for g in (gq, gk, gv))
