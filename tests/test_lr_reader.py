"""LogReg reader-family tests (reference: LR/src/reader.{h,cpp} variants)."""

import numpy as np
import pytest


def test_parse_weighted():
    from multiverso_tpu.apps.lr_reader import parse_weighted

    label, keys, vals = parse_weighted("1:2.5 3:0.5 7:2.0", True, 10)
    assert label == 1.0
    np.testing.assert_array_equal(keys, [3, 7])
    np.testing.assert_allclose(vals, [1.25, 5.0])  # scaled by weight

    label, keys, vals = parse_weighted("0:0.5 0.2 0.4", False, 3)
    assert label == 0.0
    np.testing.assert_allclose(vals, [0.1, 0.2, 0.0])

    # weightless lines behave like the default reader
    label, _, vals = parse_weighted("1 3:0.5", True, 10)
    np.testing.assert_allclose(vals, [0.5])


def test_bsparse_round_trip(tmp_path):
    from multiverso_tpu.apps.lr_reader import iter_bsparse, write_bsparse

    path = str(tmp_path / "data.bsparse")
    rng = np.random.default_rng(0)
    samples = []
    for _ in range(100):
        nkeys = int(rng.integers(1, 12))
        keys = np.sort(rng.choice(1000, nkeys, replace=False)).astype(np.int64)
        weight = float(rng.standard_normal())
        samples.append((float(rng.integers(0, 2)), keys,
                        np.full(nkeys, weight, np.float64)))
    assert write_bsparse(path, samples) == 100

    out = list(iter_bsparse(path, chunk_size=64))  # tiny chunks: refill path
    assert len(out) == 100
    for (l0, k0, v0), (l1, k1, v1) in zip(samples, out):
        assert l0 == l1
        np.testing.assert_array_equal(k0, k1)
        np.testing.assert_allclose(v0, v1)


def test_bsparse_truncated_raises(tmp_path):
    from multiverso_tpu.apps.lr_reader import iter_bsparse, write_bsparse

    path = str(tmp_path / "data.bsparse")
    write_bsparse(path, [(1.0, np.arange(8, dtype=np.int64),
                          np.ones(8))])
    blob = open(path, "rb").read()
    trunc = str(tmp_path / "trunc.bsparse")
    with open(trunc, "wb") as f:
        f.write(blob[:-4])
    with pytest.raises(EOFError):
        list(iter_bsparse(trunc))


def test_sample_iterator_factory(tmp_path):
    from multiverso_tpu.apps.lr_reader import sample_iterator, write_bsparse

    a = tmp_path / "a.txt"
    b = tmp_path / "b.txt"
    a.write_text("1 3:0.5\n")
    b.write_text("0 7:2.0\n")
    # comma-separated multi-file list, read in order
    out = list(sample_iterator("default", f"{a},{b}", True, 10))
    assert [s[0] for s in out] == [1.0, 0.0]

    out = list(sample_iterator("weight", f"{a}", True, 10))
    assert out[0][0] == 1.0

    bs = str(tmp_path / "c.bsparse")
    write_bsparse(bs, out)
    out2 = list(sample_iterator("bsparse", bs, True, 10))
    np.testing.assert_array_equal(out2[0][1], out[0][1])


def test_async_reader_keyset_windows():
    from multiverso_tpu.apps.lr_reader import AsyncSampleReader

    def gen():
        for i in range(10):
            yield float(i % 2), np.asarray([i, i + 100], np.int64), np.ones(2)

    reader = AsyncSampleReader(gen(), window_size=4, bias_key=999)
    seen = list(reader)
    assert len(seen) == 10
    ks1 = reader.next_keyset()
    ks2 = reader.next_keyset()
    ks3 = reader.next_keyset()
    assert reader.next_keyset(timeout=0.5) is None
    # windows of 4, 4, 2 samples; bias key in every keyset
    np.testing.assert_array_equal(
        ks1, sorted({0, 1, 2, 3, 100, 101, 102, 103, 999}))
    np.testing.assert_array_equal(
        ks2, sorted({4, 5, 6, 7, 104, 105, 106, 107, 999}))
    np.testing.assert_array_equal(ks3, sorted({8, 9, 108, 109, 999}))


def test_async_reader_propagates_errors():
    from multiverso_tpu.apps.lr_reader import AsyncSampleReader

    def gen():
        yield 1.0, np.asarray([1], np.int64), np.ones(1)
        raise ValueError("boom")

    reader = AsyncSampleReader(gen(), window_size=4)
    with pytest.raises(ValueError, match="boom"):
        list(reader)


def test_sparse_pipeline_end_to_end(mv_session, tmp_path):
    """Pipelined sparse training (bsparse reader + keyset prefetch) learns."""
    from multiverso_tpu.apps import logreg as app
    from multiverso_tpu.models.logreg import LogRegConfig

    rng = np.random.default_rng(3)
    dim = 60
    w = np.zeros(dim)
    w[:8] = rng.standard_normal(8) * 2
    lines = []
    for _ in range(400):
        keys = np.sort(rng.choice(dim, size=6, replace=False))
        vals = rng.standard_normal(6)
        label = int(w[keys] @ vals > 0)
        lines.append(f"{label} " + " ".join(
            f"{k}:{v:.5f}" for k, v in zip(keys, vals)))
    train = tmp_path / "train.txt"
    train.write_text("\n".join(lines) + "\n")

    cfg = LogRegConfig(input_size=dim, sparse=True, pipeline=True,
                       sync_frequency=2, minibatch_size=32,
                       learning_rate=0.5, learning_rate_coef=0.001)
    model = app.build_model(cfg)
    for _ in range(12):
        app.train_file(model, cfg, str(train), epochs=1, log_every=0)
    acc = app.test_file(model, cfg, str(train))
    assert acc > 0.85


def test_weight_reader_end_to_end(mv_session, tmp_path):
    """weight reader: zero-weight samples must not move the model."""
    from multiverso_tpu.apps import logreg as app
    from multiverso_tpu.models.logreg import LogRegConfig

    # all-zero-weight samples -> zero feature values -> only bias learns
    lines = ["1:0.0 1:5.0 2:5.0"] * 16
    train = tmp_path / "train.txt"
    train.write_text("\n".join(lines) + "\n")
    cfg = LogRegConfig(input_size=4, sparse=True, reader_type="weight",
                       minibatch_size=8, learning_rate=0.5)
    model = app.build_model(cfg)
    app.train_file(model, cfg, str(train), epochs=1, log_every=0)
    weights = model.table.get_keys(np.asarray([1, 2], np.int64))
    np.testing.assert_allclose(np.asarray(weights), 0.0, atol=1e-12)
