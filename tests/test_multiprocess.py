"""Real multi-process integration driver (SURVEY §4: "a small set of real
multi-host drivers" alongside the single-process virtual-mesh tests).

Launches two actual OS processes that join one JAX coordination service
over localhost (the MV_COORDINATOR_ADDRESS control plane that replaces
MPI_Init + rank-0 registration) and checks the cross-process contracts:

* topology: both ranks agree on size and see each other;
* barrier: rendezvous completes;
* aggregate (model averaging): psum across processes;
* sync table adds: the SyncServer invariant value == sum over workers.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    sys.path.insert(0, %r)
    import multiverso_tpu as mv

    rank = int(os.environ["MV_PROCESS_ID"])
    mv.init(["worker", "-sync=true"])
    assert mv.size() == 2, mv.size()
    assert mv.rank() == rank, (mv.rank(), rank)
    mv.barrier()

    # model averaging: psum over DCN/ICI (MV_Aggregate)
    agg = mv.aggregate(np.full(4, float(rank + 1), np.float32))
    assert np.allclose(agg, 3.0), agg          # 1 + 2

    # sync-mode whole-table add: every replica folds every worker's delta
    t = mv.create_table("array", 16)
    t.add(np.full(16, float(rank + 1), np.float32))
    got = t.get()
    assert np.allclose(got, 3.0), got          # SyncServer invariant

    mv.barrier()
    mv.shutdown()
    print(f"RANK{rank}_OK", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_sync_contracts(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER % _REPO)
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "MV_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "MV_NUM_PROCESSES": "2",
            "MV_PROCESS_ID": str(rank),
            # one CPU device per process keeps the mesh worker=2, server=1
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"rank {rank} timed out (coordination stalled)")
        outs.append(out)
    for rank, (proc, out) in enumerate(zip(procs, outs)):
        assert proc.returncode == 0, f"rank {rank}:\n{out[-3000:]}"
        assert f"RANK{rank}_OK" in out
